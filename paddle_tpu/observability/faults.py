"""Fault injection: hooks for the forensics tests and seeded, deterministic
fault *plans* for the chaos harness (:mod:`paddle_tpu.resilience.chaos`).

Instrumented sites call :func:`maybe` with their site name; when a
matching fault is armed the site hangs there (a sleep that releases early
when the fault is cleared) and/or runs an injected callable (which may
raise — that's how chaos tests turn a real code path into a crash).
Disarmed, :func:`maybe` is one module-flag check — the hooks are free in
production.

Arming spellings:

- :func:`inject` — one fault, imperative (the PR-3 tests' API, unchanged),
  now with *scheduled* (``at_trips={3}``, ``every=5``) and *probabilistic*
  (``probability=0.2, seed=7`` — seeded rng, deterministic replay)
  firing on top of the existing ``seconds``/``fn``/``times``;
- :class:`FaultPlan` — a reusable, seeded set of faults with scoped
  arming (``with plan: ...`` guarantees disarm), the chaos suite's unit of
  reproducibility: same seed, same workload → same trips.

Sites wired so far:

- ``collective_hang`` — inside every eager collective's watchdog bracket
  (:mod:`paddle_tpu.distributed.communication`);
- ``serving.scheduler_wedge`` — top of the serving scheduler loop;
- ``serving.step_crash`` — immediately before the batched decode dispatch
  (:meth:`paddle_tpu.serving.engine.ServingEngine._step_once`);
- ``chaos.train_step`` — the chaos harness's train-loop site;
- ``memory.leak`` — grows the synthetic ``fault.memory_leak`` ledger
  owner by 8 MiB per trip (:mod:`.memory`; exercised by the
  :class:`~.memory.MemoryWatchdog` tests — no real allocation);
- ``numerics.nan_inject`` — each trip turns the next
  :func:`paddle_tpu.observability.numerics.consume_nan_inject` call into
  a NaN scalar that probed train-step / guarded serving programs add at
  a configurable tensor site, driving the detect → dump → rollback loop
  without a real numerical bug (:mod:`.numerics`);
- ``serving.traffic_spike`` — top of :meth:`ServingEngine.submit`: arm
  with an ``fn`` that submits a burst of extra requests to drive
  deterministic overload for the QoS brownout/autoscaler drills (the
  injected submits recurse through the site while it is mid-trip, so use
  ``times=``/``at_trips=`` to bound the burst);
- ``serving.replica-scoped sites`` — every engine also polls
  ``serving.scheduler_wedge@<replica>``, ``serving.step_crash@<replica>``
  and ``cluster.replica_preempt@<replica>``; the last kills exactly that
  replica FATALLY (its abort message avoids every transient pattern), so
  chaos runs can take one pool member down and watch the cluster reroute
  and the :class:`~paddle_tpu.serving.qos.AutoScaler` reap + replace it.

Armed faults are listed on the telemetry ``/statusz`` page
(:func:`describe`).
"""

from __future__ import annotations

import random
import threading
from time import monotonic, sleep

_ARMED = False  # fast-path flag, mirrors bool(_FAULTS)
_FAULTS: dict[str, dict] = {}
# specs popped by times=/schedule exhaustion whose sleep may still be in
# flight — clear() must be able to cancel these too (one entry per name)
_EXHAUSTED: dict[str, dict] = {}
_LOCK = threading.Lock()


def inject(name, seconds=None, fn=None, times=None, probability=None,
           at_trips=None, every=None, seed=None):
    """Arm fault ``name``: a hang of ``seconds`` (released early by
    :func:`clear`) and/or a callable ``fn`` (exceptions propagate into the
    instrumented site — injected crashes are real crashes).

    Firing discipline (evaluated per :func:`maybe` call, in order):

    - ``at_trips``: fire only on these 1-based call numbers (a *schedule*;
      self-disarms once the last scheduled call has passed);
    - ``every``: fire on every Nth call;
    - ``probability``: additionally gate each firing on a seeded rng draw
      (``seed`` defaults to a stable hash of the site name, so replays are
      deterministic without ceremony);
    - ``times``: total firings before self-disarm (None = until cleared).
    """
    global _ARMED
    if at_trips is not None:
        at_trips = frozenset(int(t) for t in at_trips)
        if not at_trips or min(at_trips) < 1:
            raise ValueError("at_trips must be 1-based call numbers")
    rng = None
    if probability is not None:
        if seed is None:
            from ..resilience.retry import derive_seed

            seed = derive_seed("fault", name)
        rng = random.Random(seed)
    with _LOCK:
        _FAULTS[name] = {"seconds": seconds, "fn": fn, "times": times,
                         "probability": probability, "at_trips": at_trips,
                         "every": int(every) if every else None, "rng": rng,
                         "calls": 0, "trips": 0, "cancelled": False}
        _ARMED = True


def clear(name=None):
    """Disarm one fault (or all).  A site currently hanging in it wakes up
    within one poll tick."""
    global _ARMED
    with _LOCK:
        if name is None:
            for spec in _FAULTS.values():
                spec["cancelled"] = True
            for spec in _EXHAUSTED.values():
                spec["cancelled"] = True
            _FAULTS.clear()
            _EXHAUSTED.clear()
        else:
            for spec in (_FAULTS.pop(name, None),
                         _EXHAUSTED.pop(name, None)):
                if spec is not None:
                    spec["cancelled"] = True
        _ARMED = bool(_FAULTS)


def armed(name) -> bool:
    return name in _FAULTS


def trip_count(name) -> int:
    spec = _FAULTS.get(name) or _EXHAUSTED.get(name)
    return spec["trips"] if spec else 0


def describe() -> list:
    """Currently-armed faults as JSON-able rows (the ``/statusz`` view)."""
    with _LOCK:
        return [{"site": name, "calls": s["calls"], "trips": s["trips"],
                 "seconds": s["seconds"], "times": s["times"],
                 "probability": s["probability"],
                 "at_trips": sorted(s["at_trips"]) if s["at_trips"] else None,
                 "every": s["every"], "fn": s["fn"] is not None}
                for name, s in _FAULTS.items()]


def maybe(name):
    """Trip fault ``name`` if armed and its schedule/probability says fire
    (called by instrumented sites)."""
    global _ARMED
    if not _ARMED:
        return
    with _LOCK:
        spec = _FAULTS.get(name)
        if spec is None:
            return
        spec["calls"] += 1
        if spec["at_trips"] is not None:
            fire = spec["calls"] in spec["at_trips"]
        elif spec["every"]:
            fire = spec["calls"] % spec["every"] == 0
        else:
            fire = True
        if fire and spec["probability"] is not None:
            fire = spec["rng"].random() < spec["probability"]
        exhausted = (spec["at_trips"] is not None
                     and spec["calls"] >= max(spec["at_trips"]))
        if fire:
            spec["trips"] += 1
            if spec["times"] is not None and spec["trips"] >= spec["times"]:
                exhausted = True
        if exhausted:
            _FAULTS.pop(name, None)
            _EXHAUSTED[name] = spec  # clear() can still cancel the sleep
            _ARMED = bool(_FAULTS)
        if not fire:
            return
    if spec["fn"] is not None:
        spec["fn"]()
    if spec["seconds"]:
        end = monotonic() + float(spec["seconds"])
        # poll so clear() releases a hanging site promptly
        while monotonic() < end and not spec["cancelled"]:
            sleep(0.01)


class FaultPlan:
    """A seeded, reusable set of faults with scoped arming.

    .. code-block:: python

        plan = (FaultPlan(seed=7)
                .add("serving.step_crash", fn=boom, at_trips={3})
                .add("collective_hang", seconds=0.5, probability=0.1))
        with plan:          # arm on enter, disarm (and wake hangs) on exit
            run_workload()
        plan.describe()     # what was armed + how often each site tripped

    Determinism: each entry's probabilistic rng is seeded from
    ``(plan seed, entry index, site)``, so the same plan over the same
    workload trips at the same calls — a failing chaos run replays
    exactly.  One entry per site (a later ``add`` for the same site
    overrides the earlier one at arm time, matching :func:`inject`).
    """

    def __init__(self, seed=0):
        self.seed = int(seed)
        self._entries: list[dict] = []

    def add(self, site, seconds=None, fn=None, times=None, probability=None,
            at_trips=None, every=None):
        self._entries.append({
            "site": site, "seconds": seconds, "fn": fn, "times": times,
            "probability": probability, "at_trips": at_trips, "every": every,
        })
        return self

    @property
    def sites(self):
        return [e["site"] for e in self._entries]

    def arm(self):
        from ..resilience.retry import derive_seed

        for i, e in enumerate(self._entries):
            e["_trips"] = 0  # fresh cycle: drop the previous run's snapshot
            inject(e["site"], seconds=e["seconds"], fn=e["fn"],
                   times=e["times"], probability=e["probability"],
                   at_trips=e["at_trips"], every=e["every"],
                   seed=derive_seed(self.seed, i, e["site"]))
        return self

    def disarm(self):
        for e in self._entries:
            # snapshot the trip count BEFORE clear() drops the spec, so
            # describe() after the with-block still reports how often
            # each site fired (the documented post-run usage)
            e["_trips"] = trip_count(e["site"])
            clear(e["site"])

    def __enter__(self):
        return self.arm()

    def __exit__(self, *exc):
        self.disarm()

    def describe(self):
        armed_sites = {row["site"] for row in describe()}
        # trip_count covers armed AND schedule-exhausted sites; once clear()
        # dropped the spec it reads 0 and the disarm-time snapshot answers
        return [{"site": e["site"], "seconds": e["seconds"],
                 "times": e["times"], "probability": e["probability"],
                 "at_trips": sorted(e["at_trips"]) if e["at_trips"] else None,
                 "every": e["every"], "fn": e["fn"] is not None,
                 "armed": e["site"] in armed_sites,
                 "trips": trip_count(e["site"]) or e.get("_trips", 0)}
                for e in self._entries]
