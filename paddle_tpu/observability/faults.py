"""Fault-injection hooks for the forensics tests.

The watchdogs (:mod:`.watchdog`) are exercised by ARMING a named fault and
driving the real code path: instrumented sites call :func:`maybe` with
their site name and, when a matching fault is armed, hang there (a sleep
that releases early when the fault is cleared) or run an injected callable.
Disarmed, :func:`maybe` is one module-flag check — the hooks are free in
production.

Sites wired in this PR:

- ``collective_hang`` — inside every eager collective's watchdog bracket
  (:mod:`paddle_tpu.distributed.communication`);
- ``serving.scheduler_wedge`` — top of the serving scheduler loop
  (:meth:`paddle_tpu.serving.engine.ServingEngine._loop`).
"""

from __future__ import annotations

import threading
from time import monotonic, sleep

_ARMED = False  # fast-path flag, mirrors bool(_FAULTS)
_FAULTS: dict[str, dict] = {}
# specs popped by times= exhaustion whose sleep may still be in flight —
# clear() must be able to cancel these too (one entry per name, bounded)
_EXHAUSTED: dict[str, dict] = {}
_LOCK = threading.Lock()


def inject(name, seconds=None, fn=None, times=None):
    """Arm fault ``name``: a hang of ``seconds`` (released early by
    :func:`clear`) and/or a callable ``fn``.  ``times`` bounds how many
    trips before self-disarm (None = until cleared)."""
    global _ARMED
    with _LOCK:
        _FAULTS[name] = {"seconds": seconds, "fn": fn, "times": times,
                         "trips": 0, "cancelled": False}
        _ARMED = True


def clear(name=None):
    """Disarm one fault (or all).  A site currently hanging in it wakes up
    within one poll tick."""
    global _ARMED
    with _LOCK:
        if name is None:
            for spec in _FAULTS.values():
                spec["cancelled"] = True
            for spec in _EXHAUSTED.values():
                spec["cancelled"] = True
            _FAULTS.clear()
            _EXHAUSTED.clear()
        else:
            for spec in (_FAULTS.pop(name, None),
                         _EXHAUSTED.pop(name, None)):
                if spec is not None:
                    spec["cancelled"] = True
        _ARMED = bool(_FAULTS)


def armed(name) -> bool:
    return name in _FAULTS


def trip_count(name) -> int:
    spec = _FAULTS.get(name)
    return spec["trips"] if spec else 0


def maybe(name):
    """Trip fault ``name`` if armed (called by instrumented sites)."""
    global _ARMED
    if not _ARMED:
        return
    with _LOCK:
        spec = _FAULTS.get(name)
        if spec is None:
            return
        spec["trips"] += 1
        if spec["times"] is not None and spec["trips"] >= spec["times"]:
            _FAULTS.pop(name, None)
            _EXHAUSTED[name] = spec  # clear() can still cancel the sleep
            _ARMED = bool(_FAULTS)
    if spec["fn"] is not None:
        spec["fn"]()
    if spec["seconds"]:
        end = monotonic() + float(spec["seconds"])
        # poll so clear() releases a hanging site promptly
        while monotonic() < end and not spec["cancelled"]:
            sleep(0.01)
