"""Watchdogs: detect hung collectives and a wedged serving scheduler.

Reference analog: Fleet's collective diagnostics (the NCCL watchdog that
names the stuck op, its communicator and the ranks that never arrived)
— SURVEY.md L12 — rebuilt over the eager shard_map collectives and the
continuous-batching scheduler thread.

- :class:`CollectiveWatchdog` — the eager collectives in
  ``distributed.communication`` bracket every dispatch with
  :func:`collective_begin` / :func:`collective_end` (one global read when
  no watchdog is armed).  A daemon monitor scans the in-flight table; an
  op older than the deadline fires ONCE: a loud log naming the op, group,
  ranks present/missing and age, a flight-record dump, and a
  ``observability.watchdog_fires{kind="collective"}`` counter bump.
- :class:`ServingWatchdog` — monitors one :class:`ServingEngine`: if work
  is pending (queued requests or active slots) and the scheduler loop's
  heartbeat hasn't advanced within the deadline, the scheduler is wedged —
  same fire recipe, plus the engine's stats snapshot in the dump.

Env deadlines (README "Distributed tracing & forensics"):
``PADDLE_COLLECTIVE_TIMEOUT_S`` (default 300),
``PADDLE_SERVING_WATCHDOG_S`` (engine watchdog; unset = off).
"""

from __future__ import annotations

import itertools
import logging
import os
import threading
from time import monotonic

from ..profiler import metrics as _metrics
from . import flight_recorder as _flight
from . import programs as _programs

logger = logging.getLogger("paddle_tpu.observability")

_COLLECTIVE_WD: "CollectiveWatchdog | None" = None


def _fires_counter():
    return _metrics.counter(
        "observability.watchdog_fires", "watchdog triggers by kind/op")


# Fire listeners: detection-to-recovery wiring.  The resilience layer
# (paddle_tpu.resilience.emergency) registers here so a watchdog fire can
# trigger an emergency checkpoint, not just a dump.  Listeners run on the
# monitor thread and must never raise into the fire path.
_FIRE_LISTENERS: list = []


def add_fire_listener(fn):
    """Register ``fn(kind, record)`` called on every watchdog fire
    (``kind`` is ``"collective"`` or ``"serving"``)."""
    if fn not in _FIRE_LISTENERS:
        _FIRE_LISTENERS.append(fn)


def remove_fire_listener(fn):
    try:
        _FIRE_LISTENERS.remove(fn)
    except ValueError:
        pass


def _notify_fire(kind, record):
    for fn in list(_FIRE_LISTENERS):
        try:
            fn(kind, record)
        except Exception:
            logger.exception("watchdog fire listener failed (kind=%s)", kind)


class CollectiveWatchdog:
    """Deadline monitor over in-flight eager collectives."""

    def __init__(self, deadline_s=None, poll_s=None, recorder=None):
        if deadline_s is None:
            deadline_s = float(os.environ.get(
                "PADDLE_COLLECTIVE_TIMEOUT_S", "300"))
        self.deadline_s = float(deadline_s)
        self.poll_s = float(poll_s) if poll_s is not None \
            else max(min(self.deadline_s / 4, 5.0), 0.02)
        self._recorder = recorder
        self._inflight: dict[int, dict] = {}
        self._lock = threading.Lock()
        self._seq = itertools.count()
        self._stop = threading.Event()
        self._thread = None
        self.fired: list[dict] = []
        self._m_fires = _fires_counter()

    # ------------------------------------------------------------ lifecycle
    def start(self):
        global _COLLECTIVE_WD
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._monitor, name="paddle-collective-watchdog",
                daemon=True)
            self._thread.start()
        _COLLECTIVE_WD = self
        return self

    def stop(self):
        global _COLLECTIVE_WD
        if _COLLECTIVE_WD is self:
            _COLLECTIVE_WD = None
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        return self

    # ------------------------------------------------------------- bracket
    def begin(self, op, group):
        token = {"id": next(self._seq), "op": op, "group_id": group.id,
                 "nranks": group.nranks, "ranks": list(group.ranks),
                 # single-controller: this process drives every rank it
                 # launched, so "present" is the process rank; in a
                 # multi-process launch the missing set is the ranks whose
                 # processes never logged an entry for this op
                 "ranks_present": [group.rank],
                 "t0": monotonic(), "tid": threading.get_ident(),
                 "fired": False}
        with self._lock:
            self._inflight[token["id"]] = token
        return token

    def end(self, token):
        with self._lock:
            self._inflight.pop(token["id"], None)

    def inflight(self):
        with self._lock:
            return [{k: v for k, v in t.items() if not k.startswith("_")}
                    for t in self._inflight.values()]

    # ------------------------------------------------------------- monitor
    def _monitor(self):
        while not self._stop.wait(self.poll_s):
            now = monotonic()
            stuck = []
            with self._lock:
                for t in self._inflight.values():
                    if not t["fired"] and now - t["t0"] > self.deadline_s:
                        t["fired"] = True
                        stuck.append(dict(t))
            for t in stuck:
                self._fire(t, now)

    def _fire(self, t, now):
        missing = [r for r in t["ranks"] if r not in t["ranks_present"]]
        age = now - t["t0"]
        record = {"op": t["op"], "group_id": t["group_id"],
                  "nranks": t["nranks"], "ranks": t["ranks"],
                  "ranks_present": t["ranks_present"],
                  "ranks_missing": missing, "age_s": age, "tid": t["tid"]}
        logger.error(
            "COLLECTIVE WATCHDOG: op %r on group %d (%d ranks) stuck for "
            "%.1fs (deadline %.1fs) — ranks present %s, missing %s; dumping "
            "flight record", t["op"], t["group_id"], t["nranks"], age,
            self.deadline_s, t["ranks_present"], missing)
        rec = self._recorder or _flight.get_flight_recorder()
        rec.record("watchdog", "collective_stuck", **record)
        record["dump_path"] = rec.dump("collective_watchdog", extra=record)
        self._m_fires.inc(kind="collective", op=t["op"])
        self.fired.append(record)
        _notify_fire("collective", record)


# Module-level bracket: ONE global read when no watchdog is armed — the
# shape of every fast-path hook in this codebase (events._ACTIVE et al).
def collective_begin(op, group):
    wd = _COLLECTIVE_WD
    if wd is None:
        return None
    token = wd.begin(op, group)
    token["_wd"] = wd
    return token


def collective_end(token):
    if token is not None:
        token["_wd"].end(token)


def get_collective_watchdog():
    return _COLLECTIVE_WD


class ServingWatchdog:
    """Wedged-scheduler detector for one :class:`ServingEngine`.

    Fires when the engine has pending work (queued requests or occupied
    slots) but the scheduler loop's heartbeat (``engine._progress_t``,
    stamped once per iteration) is older than the deadline.  Re-arms after
    progress resumes, so a second wedge fires again.
    """

    def __init__(self, engine, deadline_s=None, poll_s=None, recorder=None):
        if deadline_s is None:
            deadline_s = float(os.environ.get(
                "PADDLE_SERVING_WATCHDOG_S", "60"))
        self.engine = engine
        self.deadline_s = float(deadline_s)
        self.poll_s = float(poll_s) if poll_s is not None \
            else max(min(self.deadline_s / 4, 5.0), 0.02)
        self._recorder = recorder
        self._stop = threading.Event()
        self._thread = None
        self._fired_at_stamp = None  # heartbeat value already reported
        self.fired: list[dict] = []
        self._m_fires = _fires_counter()

    def start(self):
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._monitor, name="paddle-serving-watchdog",
                daemon=True)
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        return self

    def _busy(self):
        e = self.engine
        try:
            return bool(e._queue) or any(s is not None for s in e._slots)
        except Exception:
            return False

    def _monitor(self):
        while not self._stop.wait(self.poll_s):
            e = self.engine
            stamp = getattr(e, "_progress_t", None)
            if stamp is None or not getattr(e, "_started", False):
                continue
            if _programs.ledger().compiling(e):
                # the program ledger holds an OPEN compile window for this
                # engine: first dispatch = XLA compile, slow but not stuck.
                # The ledger (not an engine flag someone forgot to clear)
                # is the authority, and its compile_in_progress gauge keeps
                # the stall visible on /statusz while we stay quiet.
                continue
            age = monotonic() - stamp
            if age <= self.deadline_s or not self._busy():
                if stamp != self._fired_at_stamp:
                    self._fired_at_stamp = None  # progress resumed: re-arm
                continue
            if self._fired_at_stamp == stamp:
                continue  # already reported this wedge
            self._fired_at_stamp = stamp
            self._fire(age)

    def _fire(self, age):
        e = self.engine
        try:
            stats = e.stats()
        except Exception:
            stats = {}
        record = {"age_s": age,
                  "iteration": getattr(e, "_iteration", None),
                  "stats": stats}
        logger.error(
            "SERVING WATCHDOG: scheduler thread made no progress for %.1fs "
            "(deadline %.1fs) with work pending — iteration=%s queue=%s "
            "active=%s; dumping flight record", age, self.deadline_s,
            record["iteration"], stats.get("queue_depth"),
            stats.get("active_slots"))
        rec = self._recorder or _flight.get_flight_recorder()
        rec.record("watchdog", "serving_scheduler_wedge", **record)
        record["dump_path"] = rec.dump("serving_watchdog", extra=record)
        self._m_fires.inc(kind="serving", op="scheduler_wedge")
        self.fired.append(record)
        _notify_fire("serving", record)
