"""Distributed span tracing over the host RecordEvent tree.

Reference analog: the profiler's cross-rank timeline correlation (the
reference merges per-rank chrome traces by aligned wall clocks) plus the
trace-id plumbing production serving stacks thread from request admission
through every decode iteration.

Design (PR-1/PR-2 discipline: one attribute load when disabled):

- :func:`span` is the instrumentation primitive.  With no sink armed it
  returns a module-level no-op singleton — instrumented hot paths
  (``ServingEngine`` decode step, eager collectives, ``TrainStep``) pay a
  single ``if _ACTIVE`` check and nothing else.
- A :class:`Tracer` collects finished :class:`Span` objects (bounded) for
  export; the armed :class:`~.flight_recorder.FlightRecorder` additionally
  receives every finished span into its crash ring.  Either sink flips the
  shared ``_ACTIVE`` flag.
- Trace context is a thread-local span stack.  A span started with an
  explicit ``trace_id=`` (the serving engine passes the request's id from
  ``submit()``) roots a new trace on that id; otherwise the parent's trace
  id is inherited, so traced-phase collectives recorded inside a
  ``TrainStep`` trace land in the step's trace automatically.
- IDs follow OTLP conventions: 16-byte hex trace ids, 8-byte hex span ids.

Cross-rank story: every exporter stamps its file with the process rank and
a wall-clock anchor (``unix_time`` at the perf-counter origin all span
timestamps are relative to).  :func:`merge_rank_traces` reads any number of
per-rank chrome-trace files (from :meth:`Tracer.export_chrome` or
``profiler.Profiler.export``), shifts each rank onto the earliest rank's
clock, and writes one merged, monotonically sorted timeline.
"""

from __future__ import annotations

import json
import os
import threading
from time import perf_counter, time as _wall

import jax

from ..profiler import events as _events
from ..profiler import metrics as _metrics

# Fast-path flag: True while a Tracer and/or a FlightRecorder is armed.
_ACTIVE = False
_LOCK = threading.Lock()
_TRACER = None   # the single active Tracer, if any
_FLIGHT = None   # the armed FlightRecorder (set by flight_recorder.enable)

_ctx = threading.local()  # per-thread stack of open Spans
_OPEN: dict[int, "Span"] = {}  # every open span, for /statusz + flight dumps


def _refresh_active():
    global _ACTIVE
    _ACTIVE = (_TRACER is not None) or (_FLIGHT is not None)


def new_trace_id() -> str:
    return os.urandom(16).hex()


def new_span_id() -> str:
    return os.urandom(8).hex()


def current_trace_id():
    """Trace id of the innermost open span on this thread (or None)."""
    stack = getattr(_ctx, "stack", None)
    return stack[-1].trace_id if stack else None


def current_span():
    stack = getattr(_ctx, "stack", None)
    return stack[-1] if stack else None


def enabled() -> bool:
    return _ACTIVE


class Span:
    """One timed region with distributed-tracing identity."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "t0", "t1",
                 "wall_t0", "attrs", "rank", "tid", "_ev", "_col")

    def __init__(self, name, attrs):
        self.name = name
        self.attrs = attrs
        self.trace_id = None
        self.span_id = new_span_id()
        self.parent_id = None
        self.t0 = self.t1 = None
        self.wall_t0 = None
        self.rank = jax.process_index()
        self.tid = threading.get_ident()
        self._ev = None
        self._col = None

    @property
    def duration(self):
        return (self.t1 - self.t0) if self.t1 is not None else None

    def __enter__(self):
        explicit = self.attrs.pop("trace_id", None)
        stack = getattr(_ctx, "stack", None)
        if stack is None:
            stack = _ctx.stack = []
        parent = stack[-1] if stack else None
        if explicit is not None:
            self.trace_id = explicit
            # an explicit id roots its own trace: only a same-trace parent
            # is a structural parent
            if parent is not None and parent.trace_id == explicit:
                self.parent_id = parent.span_id
        elif parent is not None:
            self.trace_id = parent.trace_id
            self.parent_id = parent.span_id
        else:
            self.trace_id = new_trace_id()
        self.t0 = perf_counter()
        self.wall_t0 = _wall()
        stack.append(self)
        with _LOCK:
            _OPEN[id(self)] = self
        # wrap the RecordEvent tree: spans show up in Profiler.summary()
        col = _events._COLLECTOR if _events._ACTIVE else None
        if col is not None:
            self._col = col
            self._ev = col.push(self.name)
        return self

    def __exit__(self, exc_type, exc, tb):
        self.t1 = perf_counter()
        if exc_type is not None:
            self.attrs = dict(self.attrs, error=repr(exc))
        if self._ev is not None:
            self._col.pop(self._ev)
            self._ev = self._col = None
        stack = getattr(_ctx, "stack", None)
        if stack and stack[-1] is self:
            stack.pop()
        with _LOCK:
            _OPEN.pop(id(self), None)
        tracer, flight = _TRACER, _FLIGHT
        if tracer is not None:
            tracer._deliver(self)
        if flight is not None:
            flight.record_span(self)
        return False

    def to_dict(self):
        return {"name": self.name, "trace_id": self.trace_id,
                "span_id": self.span_id, "parent_id": self.parent_id,
                "t0": self.t0, "duration": self.duration,
                "wall_t0": self.wall_t0, "rank": self.rank, "tid": self.tid,
                "attrs": dict(self.attrs)}

    def __repr__(self):
        dur = self.duration
        return (f"Span({self.name!r}, trace={self.trace_id[:8]}…, "
                f"{'open' if dur is None else f'{dur * 1e3:.3f} ms'})")


class _NoopSpan:
    """Returned by span() when no sink is armed — zero-allocation path."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


NOOP = _NoopSpan()


def span(name, **attrs):
    """Open a traced region: ``with span("serving.prefill", trace_id=t): …``

    Pass ``trace_id=`` to root the span on an existing trace (cross-thread
    propagation — the serving engine hands the scheduler thread each
    request's id this way); otherwise the innermost open span's trace id is
    inherited, or a fresh one is minted.
    """
    if not _ACTIVE:
        return NOOP
    return Span(name, attrs)


def event(name, **attrs):
    """Record an instantaneous span (entry == exit) in the current trace
    context — the cheap spelling for point events like traced-phase
    collective registrations."""
    if not _ACTIVE:
        return None
    s = Span(name, attrs)
    s.__enter__()
    s.__exit__(None, None, None)
    return s


def open_spans(lock_timeout=None):
    """Snapshot of every in-flight span (any thread) — /statusz + flight
    dumps read this to name what was running when things went wrong.

    ``lock_timeout`` bounds the lock wait for crash-time callers: a signal
    handler runs ON the interrupted thread, which may be holding the
    (non-reentrant) registry lock inside a span enter/exit — blocking
    there would deadlock the dump.  On timeout the copy proceeds without
    the lock, best-effort (concurrent mutation can at worst drop a span).
    """
    acquired = _LOCK.acquire(timeout=lock_timeout) \
        if lock_timeout is not None else _LOCK.acquire()
    try:
        try:
            spans = list(_OPEN.values())
        except RuntimeError:  # lockless copy raced a resize
            spans = []
    finally:
        if acquired:
            _LOCK.release()
    return [s.to_dict() for s in spans]


def safe_rank():
    """jax.process_index(), 0 when the backend isn't up yet (crash paths
    and telemetry must not die on an uninitialized runtime)."""
    try:
        return jax.process_index()
    except Exception:
        return 0


class Tracer:
    """Collects finished spans for export (one per process; rank-stamped).

    ::

        tr = Tracer().start()
        with span("step"):
            ...
        tr.stop()
        tr.export_chrome("/tmp/trace/rank0_spans_chrome_trace.json")
    """

    def __init__(self, rank=None, max_spans=100_000):
        self.rank = jax.process_index() if rank is None else int(rank)
        self.max_spans = int(max_spans)
        self.spans: list[Span] = []
        self.dropped = 0
        self._lock = threading.Lock()
        # wall-clock anchor: unix time at the perf_counter origin every
        # exported timestamp is relative to (the clock-alignment handle)
        self.clock_perf = perf_counter()
        self.clock_unix = _wall()
        self._m_spans = _metrics.counter(
            "observability.spans_recorded", "finished spans kept by tracers")

    # ------------------------------------------------------------- control
    def start(self):
        global _TRACER
        with _LOCK:
            _TRACER = self
            _refresh_active()
        return self

    def stop(self):
        global _TRACER
        with _LOCK:
            if _TRACER is self:
                _TRACER = None
                _refresh_active()
        return self

    def _deliver(self, sp):
        with self._lock:
            if len(self.spans) >= self.max_spans:
                self.dropped += 1
                return
            self.spans.append(sp)
        self._m_spans.inc()

    def find(self, name=None, trace_id=None):
        return [s for s in self.spans
                if (name is None or s.name == name)
                and (trace_id is None or s.trace_id == trace_id)]

    # ------------------------------------------------------------- export
    def _clock_meta(self):
        return {"unix_time": self.clock_unix, "perf_counter": self.clock_perf}

    def export_chrome(self, path):
        """Chrome-trace JSON, one file per rank.  ``ts`` is microseconds
        from this tracer's perf origin; the metadata clock anchor lets
        :func:`merge_rank_traces` put every rank on one absolute axis."""
        evs = []
        with self._lock:
            spans = list(self.spans)
        for s in spans:
            args = {"trace_id": s.trace_id, "span_id": s.span_id}
            if s.parent_id:
                args["parent_id"] = s.parent_id
            args.update({k: v for k, v in s.attrs.items()
                         if isinstance(v, (str, int, float, bool, list))})
            evs.append({"name": s.name, "ph": "X", "cat": "span",
                        "ts": (s.t0 - self.clock_perf) * 1e6,
                        "dur": (s.duration or 0.0) * 1e6,
                        "pid": s.rank, "tid": s.tid, "args": args})
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump({"traceEvents": evs, "displayTimeUnit": "ms",
                       "metadata": {"rank": self.rank,
                                    "clock": self._clock_meta(),
                                    "dropped_spans": self.dropped}}, f)
        return path

    def export_otlp(self, path):
        """OTLP-shaped JSON (ExportTraceServiceRequest layout) so the spans
        feed any OpenTelemetry pipeline without a collector-side shim."""
        with self._lock:
            spans = list(self.spans)
        otlp_spans = []
        for s in spans:
            start_ns = int((self.clock_unix + (s.t0 - self.clock_perf)) * 1e9)
            end_ns = start_ns + int((s.duration or 0.0) * 1e9)
            span_attrs = dict(s.attrs)
            # a 'links' attribute of trace ids (decode steps serving many
            # requests) is the OTLP Span.links field, not a generic attr —
            # viewers only navigate real links
            link_ids = span_attrs.pop("links", None)
            attrs = [{"key": k, "value": _otlp_value(v)}
                     for k, v in span_attrs.items()]
            attrs.append({"key": "rank", "value": {"intValue": str(s.rank)}})
            rec = {
                "traceId": s.trace_id, "spanId": s.span_id,
                "parentSpanId": s.parent_id or "",
                "name": s.name, "kind": 1,
                "startTimeUnixNano": str(start_ns),
                "endTimeUnixNano": str(end_ns),
                "attributes": attrs,
            }
            if link_ids:
                rec["links"] = [{"traceId": str(t), "spanId": ""}
                                for t in link_ids]
            otlp_spans.append(rec)
        doc = {"resourceSpans": [{
            "resource": {"attributes": [
                {"key": "service.name",
                 "value": {"stringValue": "paddle_tpu"}},
                {"key": "process.rank",
                 "value": {"intValue": str(self.rank)}},
            ]},
            "scopeSpans": [{
                "scope": {"name": "paddle_tpu.observability"},
                "spans": otlp_spans,
            }],
        }]}
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(doc, f)
        return path

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


def _otlp_value(v):
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}
    if isinstance(v, float):
        return {"doubleValue": v}
    if isinstance(v, (list, tuple)):
        return {"arrayValue": {"values": [_otlp_value(x) for x in v]}}
    return {"stringValue": str(v)}


def get_tracer():
    return _TRACER


# ------------------------------------------------------- cross-rank merging
def merge_rank_traces(inputs, out_path=None):
    """Merge per-rank chrome-trace files into ONE clock-aligned timeline.

    ``inputs``: a directory (every ``*.json`` with ``traceEvents`` inside)
    or an explicit list of file paths.  Each file carries a metadata clock
    anchor (``{"rank": r, "clock": {"unix_time": u}}``) written by
    :meth:`Tracer.export_chrome` and ``profiler.Profiler.export``; event
    timestamps are shifted by the anchor delta to the EARLIEST rank's
    clock, pids are rewritten to the rank, and the merged stream is sorted
    so timestamps are monotonic.  Returns the merged dict (and writes it to
    ``out_path`` when given).
    """
    if isinstance(inputs, (str, os.PathLike)):
        if os.path.isdir(inputs):
            paths = sorted(
                os.path.join(inputs, f) for f in os.listdir(inputs)
                if f.endswith(".json"))
        elif os.path.isfile(inputs):
            paths = [os.fspath(inputs)]
        else:
            raise FileNotFoundError(
                f"merge_rank_traces: {os.fspath(inputs)!r} is neither a "
                "directory of trace files nor a trace file")
    else:
        paths = [os.fspath(p) for p in inputs]
    loaded = []
    for p in paths:
        with open(p) as f:
            data = json.load(f)
        if isinstance(data, list):
            data = {"traceEvents": data, "metadata": {}}
        if "traceEvents" not in data:
            continue
        meta = data.get("metadata") or {}
        clock = meta.get("clock") or {}
        loaded.append((p, data, meta.get("rank"), clock.get("unix_time")))
    if not loaded:
        raise ValueError(f"merge_rank_traces: no trace files in {inputs!r}")
    anchors = [u for _, _, _, u in loaded if u is not None]
    base = min(anchors) if anchors else 0.0
    unaligned = [p for p, _, _, u in loaded if u is None]
    if unaligned and anchors:
        import warnings

        warnings.warn(
            f"merge_rank_traces: {len(unaligned)} source(s) carry no clock "
            f"anchor and merge UNALIGNED (raw timestamps): {unaligned} — "
            "re-export them with a current Tracer/Profiler for a "
            "clock-aligned timeline", stacklevel=2)
    merged, ranks = [], []
    for i, (p, data, rank, unix) in enumerate(loaded):
        rank = rank if rank is not None else i
        ranks.append(rank)
        off_us = ((unix - base) * 1e6) if unix is not None else 0.0
        for ev in data["traceEvents"]:
            if ev.get("ph") == "M":
                continue
            ev = dict(ev)
            ev["ts"] = ev.get("ts", 0.0) + off_us
            ev["pid"] = rank
            merged.append(ev)
    merged.sort(key=lambda e: e["ts"])
    out = {"traceEvents": (
        [{"ph": "M", "name": "process_name", "pid": r, "ts": 0.0,
          "args": {"name": f"rank{r}"}} for r in sorted(set(ranks))]
        + merged),
        "displayTimeUnit": "ms",
        "metadata": {"merged_ranks": sorted(set(ranks)),
                     "clock_base_unix_time": base,
                     "sources": [p for p, _, _, _ in loaded],
                     "unaligned_sources": unaligned}}
    if out_path is not None:
        d = os.path.dirname(os.path.abspath(out_path))
        os.makedirs(d, exist_ok=True)
        with open(out_path, "w") as f:
            json.dump(out, f)
    return out
