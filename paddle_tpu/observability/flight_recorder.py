"""Hang/crash flight recorder: a fixed-size ring of recent spans/events
that dumps to ``PADDLE_FLIGHT_DIR`` when the process dies or wedges.

This is the post-mortem story a multi-host serving deployment needs (the
standard failure mode: the scheduler thread wedges or a rank SIGTERMs and
there are zero forensics).  Three triggers, all writing the same JSON
schema:

- **signals** — :func:`install_crash_handlers` chains SIGTERM/SIGABRT (and
  any extra) handlers that dump before re-delivering the signal;
- **unhandled exceptions** — ``sys.excepthook`` / ``threading.excepthook``
  wrappers dump with the traceback attached;
- **watchdogs** — :mod:`.watchdog` calls :meth:`FlightRecorder.dump` when
  a collective or the serving scheduler exceeds its deadline.

Enabling (:func:`enable`, or automatically at import when
``PADDLE_FLIGHT_DIR`` is set) arms the recorder as a tracing sink: every
finished span lands in the ring, so the dump shows the last N operations
before the event plus every span still open (the stuck one included).
"""

from __future__ import annotations

import collections
import itertools
import json
import os
import signal as _signal
import sys
import threading
import traceback
from time import time as _wall

from ..profiler import metrics as _metrics
from . import tracing as _tracing

_DEFAULT_CAPACITY = 4096

_RECORDER: "FlightRecorder | None" = None
_LOCK = threading.Lock()
# tracked separately: a first call from a worker thread installs the
# exception hooks but must NOT mark the signal handlers done (they can only
# install from the main thread; a later main-thread call retries them).
# Signals are tracked by NAME so a later call can chain additional ones.
_EXC_HOOKS_INSTALLED = False
_INSTALLED_SIGNALS: set = set()


class FlightRecorder:
    """Fixed-size ring of recent events + the dump recipe."""

    def __init__(self, dir=None, capacity=_DEFAULT_CAPACITY):
        self.dir = dir or os.environ.get("PADDLE_FLIGHT_DIR")
        self._ring = collections.deque(maxlen=int(capacity))
        self._lock = threading.Lock()
        self._seq = itertools.count()
        self.last_dump_path = None
        self._m_dumps = _metrics.counter(
            "observability.flight_dumps", "flight-record dumps by reason")

    # ----------------------------------------------------------- recording
    # the ring lock covers append vs snapshot: deque appends are atomic,
    # but list(deque) during a concurrent append raises 'mutated during
    # iteration' — and a dump that silently loses that race is a dump
    # that's missing at exactly the moment spans are flowing
    def record(self, kind, name, **data):
        """Append one event to the ring (cheap: a locked deque append)."""
        with self._lock:
            self._ring.append({"time": _wall(), "kind": kind, "name": name,
                               "data": data})

    def record_span(self, sp):
        entry = {"time": sp.wall_t0, "kind": "span", "name": sp.name,
                 "data": {"trace_id": sp.trace_id,
                          "span_id": sp.span_id,
                          "duration": sp.duration,
                          "tid": sp.tid,
                          "attrs": {k: v for k, v in sp.attrs.items()
                                    if isinstance(v, (str, int, float, bool,
                                                      list))}}}
        with self._lock:
            self._ring.append(entry)

    def snapshot(self, lock_timeout=None):
        """Ring copy; ``lock_timeout`` bounds the wait on the crash path
        (the interrupted thread may hold the lock mid-append)."""
        acquired = self._lock.acquire(timeout=lock_timeout) \
            if lock_timeout is not None else self._lock.acquire()
        try:
            try:
                return list(self._ring)
            except RuntimeError:  # lockless copy raced an append
                return []
        finally:
            if acquired:
                self._lock.release()

    # --------------------------------------------------------------- dump
    def dump(self, reason, extra=None, path=None, from_signal=False):
        """Write the ring + every in-flight span as one JSON file.  Never
        raises — a dump failing must not mask the original crash.

        ``from_signal``: the handler runs ON the interrupted thread, which
        may hold any non-reentrant lock (tracing registry, a metric child)
        mid-critical-section — so the signal path bounds the span-registry
        lock wait and skips the metric increment entirely; blocking there
        would deadlock the dying process."""
        try:
            d = self.dir or os.path.join("/tmp", "paddle_flight")
            os.makedirs(d, exist_ok=True)
            if path is None:
                n = next(self._seq)
                path = os.path.join(
                    d, f"flight_pid{os.getpid()}_{reason}_{n}.json")
            doc = {
                "schema": "paddle_tpu.observability.flight.v1",
                "reason": reason,
                "time": _wall(),
                "pid": os.getpid(),
                "rank": _tracing.safe_rank(),
                "open_spans": _tracing.open_spans(
                    lock_timeout=0.25 if from_signal else None),
                "events": self.snapshot(
                    lock_timeout=0.25 if from_signal else None),
            }
            if extra:
                doc["extra"] = extra
            with open(path, "w") as f:
                json.dump(doc, f, default=repr)
            self.last_dump_path = path
            if not from_signal:
                self._m_dumps.inc(reason=reason)
            return path
        except Exception:
            return None


# ------------------------------------------------------------ global wiring
def get_flight_recorder() -> FlightRecorder:
    """The process recorder (created unarmed on first use)."""
    global _RECORDER
    if _RECORDER is None:
        with _LOCK:
            if _RECORDER is None:
                _RECORDER = FlightRecorder()
    return _RECORDER


def enable(dir=None, capacity=None) -> FlightRecorder:
    """Arm the recorder as a tracing sink (spans start filling the ring)."""
    rec = get_flight_recorder()
    if dir is not None:
        rec.dir = dir
    if capacity is not None:
        with rec._lock:
            rec._ring = collections.deque(rec._ring, maxlen=int(capacity))
    with _tracing._LOCK:
        _tracing._FLIGHT = rec
        _tracing._refresh_active()
    return rec


def disable():
    with _tracing._LOCK:
        _tracing._FLIGHT = None
        _tracing._refresh_active()


def enabled() -> bool:
    return _tracing._FLIGHT is not None


def maybe_enable_from_env():
    """Arm + install crash handlers when ``PADDLE_FLIGHT_DIR`` is set (the
    production spelling: export one env var, get forensics)."""
    if not os.environ.get("PADDLE_FLIGHT_DIR"):
        return None
    rec = enable()
    install_crash_handlers()
    return rec


# -------------------------------------------------------- crash-time hooks
def handle_exception(exc_type, exc, tb):
    """Dump an unhandled exception (the excepthook body, callable directly
    by embedders that own their own hook chain)."""
    rec = get_flight_recorder()
    rec.record("exception", getattr(exc_type, "__name__", str(exc_type)),
               message=str(exc))
    return rec.dump("unhandled_exception", extra={
        "exception": "".join(
            traceback.format_exception(exc_type, exc, tb))[-20000:]})


def install_crash_handlers(signals=("SIGTERM", "SIGABRT"), exceptions=True):
    """Chain dump-then-continue handlers.  Idempotent per hook family;
    signal handlers can only be installed from the main thread, so a first
    call from a worker thread installs just the exception hooks and a
    later main-thread call (e.g. the next maybe_enable_from_env) still
    gets to install the signal handlers.  Returns True if anything new
    was installed."""
    global _EXC_HOOKS_INSTALLED
    installed = False
    with _LOCK:
        do_exc = exceptions and not _EXC_HOOKS_INSTALLED
        if do_exc:
            _EXC_HOOKS_INSTALLED = True
        if threading.current_thread() is threading.main_thread():
            todo_signals = [n for n in signals if n not in _INSTALLED_SIGNALS]
            _INSTALLED_SIGNALS.update(todo_signals)
        else:
            todo_signals = []

    if do_exc:
        installed = True
        prev_sys = sys.excepthook

        def _sys_hook(exc_type, exc, tb):
            handle_exception(exc_type, exc, tb)
            prev_sys(exc_type, exc, tb)

        sys.excepthook = _sys_hook

        prev_thread = threading.excepthook

        def _thread_hook(args):
            handle_exception(args.exc_type, args.exc_value, args.exc_traceback)
            prev_thread(args)

        threading.excepthook = _thread_hook

    if todo_signals:
        installed = True
        for name in todo_signals:
            sig = getattr(_signal, name, None)
            if sig is None:
                continue
            try:
                prev = _signal.getsignal(sig)

                def _handler(signum, frame, _prev=prev):
                    get_flight_recorder().dump(
                        f"signal_{_signal.Signals(signum).name}",
                        from_signal=True)
                    if _prev == _signal.SIG_IGN:
                        return  # deliberately ignored signal: dump, survive
                    if callable(_prev) and _prev != _signal.SIG_DFL:
                        _prev(signum, frame)
                    else:
                        # restore the default disposition and re-deliver so
                        # the process still dies with the right signal
                        _signal.signal(signum, _signal.SIG_DFL)
                        os.kill(os.getpid(), signum)

                _signal.signal(sig, _handler)
            except (ValueError, OSError):
                pass
    return installed
