"""Request-level SLO accounting for the serving stack.

The serving engine measures TTFT/ITL *distributions* (PR-2 histograms),
but a latency SLO is a per-REQUEST promise: "first token within X, every
subsequent token within Y, done within Z".  This module closes that gap:

- :class:`SLOPolicy` — the targets (any subset of TTFT / ITL / e2e) plus
  the attainment ``objective`` the burn rate is judged against;
- :class:`RequestTimeline` / :func:`timeline_of` — the token-level
  timeline of one request, built from the timestamps the engine already
  stamps on its handles (``submitted_at``, per-token ``token_times``);
- :class:`SLOAccountant` — evaluates each finished request, keeps a
  rolling window, and exports ``serving.slo.requests{met=}``,
  ``serving.slo.{good_tokens,tokens}`` counters and
  ``serving.slo.{attainment,burn_rate,goodput_tokens_per_sec,
  tokens_per_sec}`` gauges.  Goodput follows the serving-literature
  definition: tokens of requests that MET their SLO, per second — a
  replica decoding fast but blowing TTFT scores zero goodput, which raw
  tokens/sec hides.

Wiring: ``ServingEngine(slo=SLOPolicy(...))`` accounts per replica
(``replica=`` label), ``ServingCluster(slo=...)`` additionally accounts
the caller-visible outer handles cluster-wide (``cluster=`` label) —
failover legs and reroute overhead land in the cluster's numbers, not the
replicas'.  Every derived gauge is an exact function of the per-request
timelines (the window), so tests can recompute them byte-for-byte.
"""

from __future__ import annotations

import collections
import dataclasses
import threading


@dataclasses.dataclass(frozen=True)
class SLOPolicy:
    """Latency targets (seconds).  ``None`` disables a check.  A request
    MEETS the SLO iff every configured check passes: TTFT <= ttft_s,
    every inter-token gap <= itl_s, finish - submit <= e2e_s.

    ``objective`` is the attainment target the burn rate is judged
    against: burn_rate = (1 - attainment) / (1 - objective) — 1.0 means
    the error budget burns exactly as fast as it refills, >1 is an
    incident in progress.  ``window`` is the rolling-request window the
    attainment/goodput gauges are computed over."""

    ttft_s: float | None = None
    itl_s: float | None = None
    e2e_s: float | None = None
    objective: float = 0.99
    window: int = 256

    def evaluate(self, tl: "RequestTimeline") -> "SLOReport":
        ttft = tl.ttft
        ttft_ok = (self.ttft_s is None or ttft is None
                   or ttft <= self.ttft_s)
        gaps = tl.itl_gaps
        viol = (sum(1 for g in gaps if g > self.itl_s)
                if self.itl_s is not None else 0)
        e2e = tl.e2e
        e2e_ok = (self.e2e_s is None or e2e is None or e2e <= self.e2e_s)
        met = bool(ttft_ok and e2e_ok and viol == 0 and tl.tokens > 0)
        return SLOReport(ttft=ttft, ttft_ok=ttft_ok,
                         itl_max=max(gaps) if gaps else None,
                         itl_violations=viol, e2e=e2e, e2e_ok=e2e_ok,
                         tokens=tl.tokens,
                         good_tokens=tl.tokens if met else 0, met=met)

    def to_dict(self):
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class RequestTimeline:
    """One request's token-level timeline (absolute wall-clock seconds):
    admission, each token emission, completion."""

    submitted_at: float
    token_times: tuple
    finished_at: float | None = None

    @property
    def tokens(self):
        return len(self.token_times)

    @property
    def ttft(self):
        if not self.token_times:
            return None
        return self.token_times[0] - self.submitted_at

    @property
    def itl_gaps(self):
        ts = self.token_times
        return [ts[i] - ts[i - 1] for i in range(1, len(ts))]

    @property
    def e2e(self):
        end = self.finished_at if self.finished_at is not None \
            else (self.token_times[-1] if self.token_times else None)
        return None if end is None else end - self.submitted_at


@dataclasses.dataclass(frozen=True)
class SLOReport:
    ttft: float | None
    ttft_ok: bool
    itl_max: float | None
    itl_violations: int
    e2e: float | None
    e2e_ok: bool
    tokens: int
    good_tokens: int
    met: bool


def timeline_of(handle) -> RequestTimeline:
    """Timeline from a serving ``RequestHandle`` / ``ClusterHandle`` (the
    engine stamps ``submitted_at`` at submit, appends to ``token_times``
    at every emission, sets ``finished_at`` at retirement)."""
    return RequestTimeline(
        submitted_at=handle.submitted_at,
        token_times=tuple(getattr(handle, "token_times", ())),
        finished_at=handle.finished_at)


class SLOAccountant:
    """Evaluates finished requests against one policy and keeps the
    rolling gauges current.  ``labels`` pre-merge into every series
    (``replica=`` for engines, ``cluster=`` for the cluster fold)."""

    def __init__(self, policy: SLOPolicy, registry=None, **labels):
        from ..profiler import metrics as _metrics

        self.policy = policy
        reg = registry if registry is not None else _metrics.get_registry()

        def _b(m):
            return _metrics.bind(m, **labels) if labels else m

        self._m_requests = _b(reg.counter(
            "serving.slo.requests", "finished requests by SLO outcome"))
        self._m_good_tokens = _b(reg.counter(
            "serving.slo.good_tokens",
            "tokens of requests that met their SLO (goodput numerator)"))
        self._m_tokens = _b(reg.counter(
            "serving.slo.tokens", "tokens of all SLO-evaluated requests"))
        self._m_attainment = _b(reg.gauge(
            "serving.slo.attainment",
            "SLO-met fraction over the rolling request window"))
        self._m_burn = _b(reg.gauge(
            "serving.slo.burn_rate",
            "(1 - attainment) / (1 - objective); >1 burns error budget"))
        self._m_goodput = _b(reg.gauge(
            "serving.slo.goodput_tokens_per_sec",
            "SLO-met tokens/sec over the rolling window"))
        self._m_tps = _b(reg.gauge(
            "serving.slo.tokens_per_sec",
            "all tokens/sec over the same window (goodput's denominator "
            "twin: the gap between the two is SLO-missed throughput)"))
        # window rows: (submitted_at, finished_at, tokens, good_tokens, met)
        self._window = collections.deque(maxlen=int(policy.window))
        self._lock = threading.Lock()
        self._evaluated = 0
        self._met = 0

    # ---------------------------------------------------------------- feed
    def observe(self, handle, met_override=None) -> SLOReport:
        """Evaluate one finished request and refresh counters/gauges.
        ``met_override=False`` forces a miss regardless of the timeline
        (deadline-expired requests missed by definition).

        Cold-start forensics (PR 16): a miss that would have been a MET
        had the request not waited out a program compile (the engine's
        ledger windows accumulate ``handle.compile_s``) is labeled
        ``cause=cold_start`` — a distinct child of the same counter, so
        existing ``met=`` series stay untouched and total misses remain
        the sum across causes."""
        tl = timeline_of(handle)
        rep = self.policy.evaluate(tl)
        if met_override is not None and rep.met != bool(met_override):
            rep = dataclasses.replace(
                rep, met=bool(met_override),
                good_tokens=rep.tokens if met_override else 0)
        cause = None
        compile_s = float(getattr(handle, "compile_s", 0.0) or 0.0)
        if not rep.met and met_override is None and compile_s > 0.0:
            # re-evaluate the counterfactual timeline with the compile
            # stall subtracted from every stamp after submission
            warm = RequestTimeline(
                submitted_at=tl.submitted_at,
                token_times=tuple(t - compile_s for t in tl.token_times),
                finished_at=None if tl.finished_at is None
                else tl.finished_at - compile_s)
            if self.policy.evaluate(warm).met:
                cause = "cold_start"
        end = tl.finished_at if tl.finished_at is not None \
            else tl.submitted_at
        with self._lock:
            self._window.append(
                (tl.submitted_at, end, rep.tokens, rep.good_tokens, rep.met))
            self._evaluated += 1
            self._met += 1 if rep.met else 0
            rows = list(self._window)
        if cause is not None:
            self._m_requests.inc(met="false", cause=cause)
        else:
            self._m_requests.inc(met="true" if rep.met else "false")
        self._m_tokens.inc(rep.tokens)
        if rep.good_tokens:
            self._m_good_tokens.inc(rep.good_tokens)
        self._refresh(rows)
        return rep

    @staticmethod
    def window_rates(rows, objective):
        """The derived gauges as an exact, reproducible function of the
        window rows — tests recompute this from the raw handle timelines
        and assert equality with the exported gauges."""
        if not rows:
            return None
        met = sum(1 for r in rows if r[4])
        attainment = met / len(rows)
        burn = (1.0 - attainment) / max(1.0 - objective, 1e-9)
        span = max(r[1] for r in rows) - min(r[0] for r in rows)
        tokens = sum(r[2] for r in rows)
        good = sum(r[3] for r in rows)
        tps = tokens / span if span > 0 else 0.0
        goodput = good / span if span > 0 else 0.0
        return {"attainment": attainment, "burn_rate": burn,
                "tokens_per_sec": tps, "goodput_tokens_per_sec": goodput,
                "window": len(rows), "met": met, "tokens": tokens,
                "good_tokens": good, "window_span_s": span}

    def _refresh(self, rows):
        rates = self.window_rates(rows, self.policy.objective)
        if rates is None:
            return
        self._m_attainment.set(rates["attainment"])
        self._m_burn.set(rates["burn_rate"])
        self._m_goodput.set(rates["goodput_tokens_per_sec"])
        self._m_tps.set(rates["tokens_per_sec"])

    # -------------------------------------------------------------- insight
    def current(self):
        """The current window's derived rates (the :func:`window_rates`
        dict), or None before any request finished — the burn-rate scalar
        the QoS brownout ladder and autoscaler poll without scraping the
        metric registry."""
        with self._lock:
            rows = list(self._window)
        return self.window_rates(rows, self.policy.objective)

    def summary(self):
        """/statusz section: policy + the current window's derived rates
        + lifetime counts."""
        with self._lock:
            rows = list(self._window)
            evaluated, met = self._evaluated, self._met
        out = {"policy": self.policy.to_dict(),
               "evaluated": evaluated, "met": met,
               "lifetime_attainment": met / evaluated if evaluated else None}
        rates = self.window_rates(rows, self.policy.objective)
        if rates is not None:
            out["window"] = rates
        return out


def slo_histogram_buckets(default_buckets, *targets):
    """Histogram edges aligned with SLO thresholds: the default latency
    buckets plus each configured target and its half/double — so "what
    fraction of samples beat the target" is answerable from the
    ``_bucket`` series alone (the PR-7 bucket-alignment satellite)."""
    edges = set(default_buckets)
    for t in targets:
        if t:
            edges.update((round(t * 0.5, 9), round(float(t), 9),
                          round(t * 2.0, 9)))
    return tuple(sorted(edges))
