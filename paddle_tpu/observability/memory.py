"""Device-memory ledger — WHO owns the HBM bytes, reconciled against JAX.

The perf layer (PR 7) attributes device *time* per compiled program; this
module attributes device *bytes* per owner.  Every long-lived device
allocation in the serving/training stack registers here with an owner
label from a fixed taxonomy:

- ``kv.pages`` — paged KV payload pools (the engine's donated pool tuple);
- ``kv.scales`` — the quantized engine's parallel f32 scale pools;
- ``model.params`` — model parameters + buffers (minus int8 weights);
- ``model.weights_int8`` — converted ``Int8Linear`` weight/scale buffers;
- ``lora.r<r>`` — the LoRAStore's A/B pools for rank bucket ``r``;
- ``checkpoint.snapshot`` — in-flight async-checkpoint snapshots (HOST
  bytes: ``device="host"``, excluded from device reconciliation);
- ``fault.memory_leak`` — the synthetic owner the ``memory.leak`` fault
  site grows (watchdog tests);
- ``untracked`` — the reconciliation remainder: live ``jax.Array`` bytes
  no registration claims.

Registrations are *sources*, not snapshots: a zero-arg callable returning
the CURRENT arrays (or an int byte count), usually closed over a weakref
to the owning object so a dead engine's rows evict themselves on the next
read — the ledger never pins pools or params.

:meth:`MemoryLedger.report` reconciles the tracked set against
``jax.live_arrays()`` by array identity (``.nbytes`` is metadata — no
device sync), so unaccounted bytes surface as an explicit
``owner="untracked"`` row instead of silently missing.  Arrays shared
between registrations (cluster replicas over one model) are deduplicated
for the reconciled total; each owner row still reports its full view.

Exported three ways: ``memory.device_bytes{owner=,replica=,device=}`` /
``memory.untracked_bytes`` / ``memory.total_bytes`` gauges in the PR-1
registry, a ``memory`` section on ``/statusz`` (owner table sorted by
bytes, KV capacity math folded in from the registrations' metadata), and
:meth:`report` for programmatic use (bench, tests, OOM forensics).

On top of the ledger:

- :class:`MemoryWatchdog` — snapshots owner totals on a cadence and fires
  ONE PR-3 flight-recorder dump per episode when an owner grows
  monotonically across N windows (``reason="memory_leak"``, the dump
  names the leaking owner and carries the full owner table) or when the
  reconciled total exceeds ``PADDLE_HBM_BUDGET_BYTES``
  (``reason="hbm_budget"``).  The ``memory.leak`` fault site
  (:mod:`.faults`) grows the synthetic ``fault.memory_leak`` owner by
  8 MiB per trip, so the whole alarm path is exercisable without leaking
  anything real.
- OOM forensics — :func:`is_oom_error` recognizes ``RESOURCE_EXHAUSTED``
  device allocation failures and :func:`oom_dump` writes a flight record
  carrying the owner table plus per-program peak bytes from
  :mod:`.perf`; the serving scheduler calls both from its failure path.
- Admission pre-flight — :func:`hbm_budget_bytes` reads the budget env;
  ``ServingEngine.submit`` sheds with
  ``RequestRejectedError(reason="hbm_budget")`` when a request's
  projected pages would not fit the remaining budget (see the engine).
"""

from __future__ import annotations

import os
import threading
import weakref

from ..profiler import metrics as _metrics
from . import faults as _faults

#: synthetic growth per ``memory.leak`` fault trip (bytes)
FAULT_LEAK_STEP_BYTES = 8 * 1024 * 1024


def hbm_budget_bytes():
    """The configured HBM budget (``PADDLE_HBM_BUDGET_BYTES``), or None.
    Read dynamically — tests and operators flip it without rebuilds."""
    v = os.environ.get("PADDLE_HBM_BUDGET_BYTES")
    if not v:
        return None
    try:
        return int(float(v))
    except ValueError:
        return None  # malformed override must not kill admission


_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Resource exhausted", "out of memory",
                "Out of memory", "OOM: ", "failed to allocate")


def is_oom_error(exc) -> bool:
    """True when an exception smells like a device allocation failure
    (XLA spells it RESOURCE_EXHAUSTED; jaxlib sometimes 'out of
    memory')."""
    s = f"{type(exc).__name__}: {exc}"
    return any(m in s for m in _OOM_MARKERS)


def oom_dump(exc, replica=None):
    """OOM forensics: one flight-recorder dump carrying the full owner
    table and every known per-program memory_analysis row — the answer to
    'who had the bytes when the allocator gave up'.  Never raises, never
    compiles (pending perf costs stay pending)."""
    from . import flight_recorder as _flight
    from . import perf as _perf

    try:
        extra = {"error": f"{type(exc).__name__}: {exc}"[:4000],
                 "replica": replica,
                 "memory": ledger().statusz(),
                 "programs": [
                     {k: r.get(k) for k in
                      ("program", "calls", "argument_bytes", "output_bytes",
                       "temp_bytes", "peak_bytes")}
                     for r in _perf.snapshot(resolve=False)]}
    except Exception:
        extra = {"error": repr(exc)[:4000], "replica": replica}
    return _flight.get_flight_recorder().dump("oom", extra=extra)


class _Registration:
    """One owner's byte source.  ``source()`` returns the CURRENT arrays
    (list/tuple), an int byte count, or None once the owning object died
    (the ledger evicts the row)."""

    __slots__ = ("owner", "replica", "device", "source", "meta", "_ledger")

    def __init__(self, owner, source, replica, device, meta, led):
        self.owner = str(owner)
        self.replica = str(replica)
        self.device = device
        self.source = source
        self.meta = dict(meta) if meta else {}
        self._ledger = weakref.ref(led)

    def unregister(self):
        led = self._ledger()
        if led is not None:
            led.unregister(self)


def _array_device(arr):
    try:
        devs = arr.devices()
        for d in devs:
            return str(d)
    except Exception:
        pass
    return "device0"


class MemoryLedger:
    """The process-wide owner table (one per process — :func:`ledger`).
    Registration is cheap (a locked list append); all byte math happens
    at read time from the sources, so rows are never stale."""

    def __init__(self, registry=None):
        reg = registry if registry is not None else _metrics.get_registry()
        self._regs: list[_Registration] = []
        self._lock = threading.Lock()
        self._m_bytes = reg.gauge(
            "memory.device_bytes",
            "resident device bytes by owner (ledger view; owner="
            "'untracked' is the jax.live_arrays remainder)")
        self._m_untracked = reg.gauge(
            "memory.untracked_bytes",
            "live jax.Array bytes no ledger registration claims")
        self._m_total = reg.gauge(
            "memory.total_bytes",
            "tracked (deduplicated) + untracked device bytes")

    # ---------------------------------------------------------- registration
    def register(self, owner, source=None, *, nbytes=None, replica="0",
                 device=None, meta=None) -> _Registration:
        """Register an owner.  ``source`` is a zero-arg callable returning
        the current arrays / an int / None-when-dead; ``nbytes`` registers
        a fixed count instead.  ``device="host"`` rows are bookkeeping
        only — excluded from the jax.live_arrays reconciliation."""
        if source is None:
            if nbytes is None:
                raise ValueError("register needs source= or nbytes=")
            fixed = int(nbytes)
            source = lambda: fixed  # noqa: E731
        reg = _Registration(owner, source, replica, device, meta, self)
        with self._lock:
            self._regs.append(reg)
        _ensure_provider()
        return reg

    def unregister(self, reg):
        with self._lock:
            try:
                self._regs.remove(reg)
            except ValueError:
                pass

    def reset(self):
        """Tests: drop every registration (the gauges' already-rendered
        series stay, like any labelled metric's)."""
        with self._lock:
            self._regs.clear()

    # --------------------------------------------------------------- reading
    def _rows(self):
        """Resolve every source: (registration, bytes, arrays) rows, dead
        registrations evicted.  No jax involvement unless sources hold
        jax arrays — never takes any engine lock."""
        with self._lock:
            regs = list(self._regs)
        rows, dead = [], []
        for reg in regs:
            try:
                val = reg.source()
            except Exception:
                val = None
            if val is None:
                dead.append(reg)
                continue
            if isinstance(val, (int, float)):
                rows.append((reg, int(val), ()))
            else:
                arrs = tuple(val)
                rows.append((reg, sum(int(a.nbytes) for a in arrs), arrs))
        if dead:
            with self._lock:
                for reg in dead:
                    try:
                        self._regs.remove(reg)
                    except ValueError:
                        pass
        return rows

    def owner_rows(self, replica=None):
        """Owner table WITHOUT the live-array reconciliation (cheap: no
        walk of the whole process heap).  Optionally filtered by
        replica — the cluster's per-replica rollup."""
        out = []
        for reg, nbytes, arrs in self._rows():
            if replica is not None and reg.replica != str(replica):
                continue
            dev = reg.device or (_array_device(arrs[0]) if arrs else "device0")
            row = {"owner": reg.owner, "replica": reg.replica, "device": dev,
                   "bytes": nbytes, "arrays": len(arrs)}
            if reg.meta:
                row["meta"] = dict(reg.meta)
            out.append(row)
        out.sort(key=lambda r: -r["bytes"])
        return out

    def owner_totals(self):
        """{owner: bytes} summed across replicas/devices (the watchdog's
        leak-detection unit)."""
        totals = {}
        for reg, nbytes, _ in self._rows():
            totals[reg.owner] = totals.get(reg.owner, 0) + nbytes
        return totals

    def kv_pool_bytes(self):
        """Total bytes under the KV owners (payload + scale pools) — the
        denominator perf's chunk-the-prefill hint compares peak temp
        bytes against."""
        return sum(b for reg, b, _ in self._rows()
                   if reg.owner in ("kv.pages", "kv.scales"))

    def replica_rollup(self, replicas):
        """Per-replica owner totals for the cluster's ``stats()`` — a
        lockless diagnostic: {replica: {"bytes": total, "owners":
        {owner: bytes}}}."""
        out = {str(r): {"bytes": 0, "owners": {}} for r in replicas}
        for reg, nbytes, _ in self._rows():
            ent = out.get(reg.replica)
            if ent is None:
                continue
            ent["bytes"] += nbytes
            ent["owners"][reg.owner] = \
                ent["owners"].get(reg.owner, 0) + nbytes
        return out

    def report(self):
        """The reconciled ledger: owner rows (sorted by bytes, an explicit
        ``untracked`` row last), the deduplicated tracked total, and the
        ``jax.live_arrays()`` comparison.  Refreshes the ``memory.*``
        gauges.  Reads array *metadata* only — no device sync, no engine
        lock — so it is safe from a telemetry scrape."""
        import jax

        rows = self._rows()
        tracked_ids = set()
        tracked_bytes = 0          # deduplicated across registrations
        out_rows = []
        for reg, nbytes, arrs in rows:
            for a in arrs:
                if id(a) not in tracked_ids:
                    tracked_ids.add(id(a))
                    if reg.device != "host":
                        tracked_bytes += int(a.nbytes)
            if not arrs and reg.device != "host":
                tracked_bytes += nbytes   # synthetic/int rows: no dedup key
            dev = reg.device or (_array_device(arrs[0]) if arrs else "device0")
            row = {"owner": reg.owner, "replica": reg.replica, "device": dev,
                   "bytes": nbytes, "arrays": len(arrs)}
            if reg.meta:
                row["meta"] = dict(reg.meta)
            out_rows.append(row)
            self._m_bytes.set(float(nbytes), owner=reg.owner,
                              replica=reg.replica, device=dev)
        try:
            live = jax.live_arrays()
        except Exception:
            live = []
        live_bytes = 0
        untracked = 0
        for a in live:
            try:
                nb = int(a.nbytes)
            except Exception:
                continue
            live_bytes += nb
            if id(a) not in tracked_ids:
                untracked += nb
        out_rows.sort(key=lambda r: -r["bytes"])
        out_rows.append({"owner": "untracked", "replica": "-",
                         "device": "-", "bytes": untracked, "arrays": None})
        self._m_bytes.set(float(untracked), owner="untracked",
                          replica="-", device="-")
        self._m_untracked.set(float(untracked))
        self._m_total.set(float(tracked_bytes + untracked))
        return {
            "owners": out_rows,
            "tracked_bytes": tracked_bytes,
            "untracked_bytes": untracked,
            "live_bytes": live_bytes,
            "total_bytes": tracked_bytes + untracked,
            "untracked_frac": untracked / live_bytes if live_bytes else 0.0,
        }

    def statusz(self):
        """/statusz ``memory`` section: the reconciled owner table, the
        budget, and the KV capacity math folded in from the pool
        registrations' metadata (bytes/page, pool pages, max resident
        slots at the engine's max_model_len — the
        ``BlockManager.max_resident_sequences`` numbers)."""
        rep = self.report()
        budget = hbm_budget_bytes()
        capacity = []
        for row in rep["owners"]:
            meta = row.get("meta") or {}
            if meta.get("kind") != "kv":
                continue
            cap = {
                "replica": row["replica"],
                "bytes_per_page": meta.get("bytes_per_page"),
                "page_size": meta.get("page_size"),
                "num_pages": meta.get("num_pages"),
                "max_model_len": meta.get("max_model_len"),
                "max_resident_slots": meta.get("max_resident_slots"),
            }
            # mesh-sharded pools: bytes_per_page above is PER SHARD (the
            # per-chip cost admission runs on); surface the split so the
            # capacity table reads unambiguously next to the global-bytes
            # owner rows
            if meta.get("shard"):
                cap["shard"] = meta["shard"]
            capacity.append(cap)
        rep["budget_bytes"] = budget
        if budget:
            rep["budget_used_frac"] = rep["total_bytes"] / budget
        rep["kv_capacity"] = capacity
        return rep


# ------------------------------------------------------------ process state
_LEDGER: MemoryLedger | None = None
_LOCK = threading.Lock()
_PROVIDER_REGISTERED = False

# synthetic fault.memory_leak owner state (the ``memory.leak`` site)
_fault_leak_bytes = 0
_fault_leak_trips_seen = 0
_fault_leak_registered = False


def ledger() -> MemoryLedger:
    global _LEDGER
    if _LEDGER is None:
        with _LOCK:
            if _LEDGER is None:
                _LEDGER = MemoryLedger()
    return _LEDGER


def _ensure_provider():
    """Register the /statusz ``memory`` section once, lazily on first
    registration — a process that never registers never grows the key.
    The provider renders :meth:`MemoryLedger.statusz` — array metadata
    only, no engine locks (the PR-3 signal-path rule)."""
    global _PROVIDER_REGISTERED
    if _PROVIDER_REGISTERED:
        return
    with _LOCK:
        if _PROVIDER_REGISTERED:
            return
        from . import telemetry as _telemetry

        _telemetry.add_status_provider("memory", lambda: ledger().statusz())
        _PROVIDER_REGISTERED = True


def reset():
    """Tests: drop registrations, watchdog episodes and synthetic fault
    bytes (the ledger object and its provider survive)."""
    global _fault_leak_bytes, _fault_leak_trips_seen
    if _LEDGER is not None:
        _LEDGER.reset()
    with _LOCK:
        _fault_leak_bytes = 0
        _fault_leak_trips_seen = 0
        # a reset ledger dropped the synthetic row with everything else;
        # the next trip re-registers it
        global _fault_leak_registered
        _fault_leak_registered = False


def _tick_fault_leak():
    """The ``memory.leak`` fault site: each armed trip grows the synthetic
    ``fault.memory_leak`` owner by :data:`FAULT_LEAK_STEP_BYTES`, so the
    watchdog's whole alarm path runs against a deterministic 'leak'
    without allocating anything."""
    global _fault_leak_bytes, _fault_leak_trips_seen, _fault_leak_registered
    _faults.maybe("memory.leak")
    trips = _faults.trip_count("memory.leak")
    with _LOCK:
        if trips < _fault_leak_trips_seen:   # faults.clear() reset the site
            _fault_leak_trips_seen = 0
        new = trips - _fault_leak_trips_seen
        if new > 0:
            _fault_leak_trips_seen = trips
            _fault_leak_bytes += new * FAULT_LEAK_STEP_BYTES
        grown = _fault_leak_bytes
        need_reg = grown and not _fault_leak_registered
        if need_reg:
            _fault_leak_registered = True
    if need_reg:
        ledger().register("fault.memory_leak",
                          lambda: _fault_leak_bytes or None,
                          replica="-", meta={"kind": "fault"})
    return grown


class MemoryWatchdog:
    """Leak + budget watchdog over the ledger: snapshot owner totals each
    tick; an owner that grew on ``windows`` CONSECUTIVE ticks fires one
    flight-recorder dump per episode (``reason="memory_leak"``, the full
    owner table attached, the leaking owner named); a reconciled total
    over ``PADDLE_HBM_BUDGET_BYTES`` fires one ``reason="hbm_budget"``
    dump per excursion.  ``tick()`` is callable directly (tests, cron);
    ``start()`` runs it on a daemon cadence."""

    def __init__(self, led=None, interval_s=5.0, windows=3,
                 min_growth_bytes=1):
        self._ledger = led if led is not None else ledger()
        self.interval_s = float(interval_s)
        self.windows = int(windows)
        self.min_growth_bytes = int(min_growth_bytes)
        self._last: dict[str, int] = {}
        self._streak: dict[str, int] = {}
        self._fired: set[str] = set()
        self._budget_fired = False
        self._thread = None
        self._stop = threading.Event()
        self._m_alerts = _metrics.counter(
            "memory.leak_alerts",
            "watchdog leak/budget episodes that dumped a flight record")

    # ------------------------------------------------------------------ tick
    def tick(self):
        """One watchdog pass; returns the flight-dump paths it fired
        (usually empty)."""
        from . import flight_recorder as _flight

        _tick_fault_leak()
        totals = self._ledger.owner_totals()
        fired = []
        for owner, nbytes in totals.items():
            prev = self._last.get(owner)
            if prev is None:
                continue  # first sighting: a baseline, not growth
            if nbytes >= prev + self.min_growth_bytes:
                self._streak[owner] = self._streak.get(owner, 0) + 1
            else:
                self._streak[owner] = 0
                self._fired.discard(owner)   # episode over: re-arm
        for owner in list(self._streak):
            if self._streak.get(owner, 0) >= self.windows \
                    and owner not in self._fired:
                self._fired.add(owner)
                self._m_alerts.inc()
                path = _flight.get_flight_recorder().dump(
                    "memory_leak", extra={
                        "leaking_owner": owner,
                        "grew_windows": self._streak[owner],
                        "owner_bytes": totals.get(owner),
                        "owners": self._ledger.owner_rows(),
                    })
                if path:
                    fired.append(path)
        self._last = dict(totals)
        budget = hbm_budget_bytes()
        if budget:
            total = sum(totals.values())
            if total > budget and not self._budget_fired:
                self._budget_fired = True
                self._m_alerts.inc()
                path = _flight.get_flight_recorder().dump(
                    "hbm_budget", extra={
                        "budget_bytes": budget,
                        "total_bytes": total,
                        "owners": self._ledger.owner_rows(),
                    })
                if path:
                    fired.append(path)
            elif total <= budget:
                self._budget_fired = False
        return fired

    # ------------------------------------------------------------- lifecycle
    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.tick()
                except Exception:
                    pass  # the watchdog must never kill its host

        self._thread = threading.Thread(
            target=loop, name="paddle-memory-watchdog", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
