"""Per-program roofline attribution — WHO spent the device time, and WHY.

The PR-1/PR-3 layers can see wall time (op timers, spans) but cannot say
which *compiled program* spent it, nor whether that program is HBM-bound
or compute-bound — exactly the information ROADMAP item 3 (close the
0.255/0.379 MFU gap, beat 0.958x paged decode) needs to pick kernel
targets.  This module turns BENCH_r04's one-off roofline numbers into a
live table:

- every compiled-program family (the ``program_store`` families:
  ``prefill/<bucket>``, ``decode``, ``verify/k<k>`` — with an ``@int8``
  suffix when the engine serves quantized KV pools — ``generate.decode``,
  ``train_step/t<n>.v<i>`` — ``t<n>`` scopes per TrainStep instance, so
  two models training in one process never fold into one family)
  accumulates **calls** and **device seconds** as the dispatch sites
  record them (engine step/prefill/verify timers, ``decode_loop``,
  ``TrainStep.__call__``).  Engine families are deliberately COARSE:
  replicas over one model share compiled programs and should share a
  family; heterogeneous engines in one process (different models or pool
  shapes) fold together — pair such engines with their own process, or
  read the per-replica serving.* histograms instead;
- each family lazily attaches **XLA cost_analysis** flops/bytes (a
  re-lower+compile, so it runs on demand or on a background thread —
  never on the dispatch path, never inside a telemetry scrape);
- the table derives achieved TFLOP/s, achieved GB/s, arithmetic
  intensity, the **roofline regime** (bandwidth- vs compute-bound against
  ``PADDLE_PEAK_FLOPS`` and a measured-or-configured HBM ceiling,
  ``PADDLE_HBM_GBS``), and fraction-of-the-binding-peak.

Exported three ways: ``perf.program.*`` metrics in the PR-1 registry, a
``perf_programs`` section on ``/statusz`` (sorted by total device time),
and :func:`report` — a ``Profiler.summary()``-style text table naming the
top fusion/kernel candidates.

"Device seconds" here are host-observed dispatch-to-sync walls at the
recording sites (the engine syncs every iteration; ``decode_loop`` syncs
once per generate call) — the same convention every BENCH number uses, so
fractions-of-peak line up with the bench roofline.

Ceiling resolution order (both axes): explicit :func:`set_hbm_ceiling` /
``PADDLE_HBM_GBS`` env / datasheet-by-device-kind; ``PADDLE_PEAK_FLOPS``
env / bf16 datasheet.  BENCH_r04 measured 456 GB/s and 126.8 TFLOP/s
through this tunnel vs the 819 GB/s / 197 TFLOP/s v5e datasheet lines —
export the measured numbers for honest fractions on tunneled chips.
"""

from __future__ import annotations

import os
import threading
from time import perf_counter  # noqa: F401  (recording sites' clock)

# bf16 datasheet peaks per chip generation (BENCH convention: the v5e int8
# TOPS line is NOT the bf16 peak).  Override with PADDLE_PEAK_FLOPS
# (FLOP/s) — required on the CPU test mesh.  TrainStep's MFU gauge reads
# the same table via peak_flops().
PEAK_BF16_FLOPS = {"v6": 918e12, "v5p": 459e12, "v5 lite": 197e12,
                   "v5e": 197e12, "v4": 275e12, "v3": 123e12, "v2": 45e12}

# HBM bandwidth datasheet lines (bytes/s) by chip generation.  A tunneled
# chip measures well under these (BENCH_r04: 456 GB/s vs 819 datasheet);
# PADDLE_HBM_GBS / set_hbm_ceiling() is the production spelling.
HBM_GBS = {"v6": 1640e9, "v5p": 2765e9, "v5 lite": 819e9, "v5e": 819e9,
           "v4": 1228e9, "v3": 900e9, "v2": 700e9}

_hbm_override = None  # set_hbm_ceiling() value (bytes/s)


def _device_kind():
    import jax

    try:
        return jax.devices()[0].device_kind.lower()
    except Exception:
        return None


def peak_flops():
    """Device peak FLOP/s: PADDLE_PEAK_FLOPS override, else the bf16
    datasheet number for the visible chip kind, else None (CPU mesh)."""
    env = os.environ.get("PADDLE_PEAK_FLOPS")
    if env:
        try:
            return float(env)
        except ValueError:
            return None  # malformed override must not kill the caller
    kind = _device_kind()
    if kind:
        for k, v in PEAK_BF16_FLOPS.items():
            if k in kind:
                return v
    return None


def hbm_ceiling():
    """HBM ceiling in bytes/s: set_hbm_ceiling() > PADDLE_HBM_GBS env >
    datasheet by device kind > None."""
    if _hbm_override is not None:
        return _hbm_override
    env = os.environ.get("PADDLE_HBM_GBS")
    if env:
        try:
            return float(env) * 1e9
        except ValueError:
            return None
    kind = _device_kind()
    if kind:
        for k, v in HBM_GBS.items():
            if k in kind:
                return v
    return None


def set_hbm_ceiling(gbs):
    """Record a MEASURED HBM ceiling (GB/s) — e.g. the bench roofline
    section's number — overriding env/datasheet.  ``None`` clears it."""
    global _hbm_override
    _hbm_override = None if gbs is None else float(gbs) * 1e9


def classify(flops_per_call, bytes_per_call, peak=None, hbm=None):
    """Roofline regime of a program: its arithmetic intensity (FLOP/byte)
    against the machine ridge point ``peak_flops / hbm_bytes_per_s``.
    Below the ridge the program cannot reach peak FLOP/s no matter how
    good the kernels are — HBM feeds it too slowly (bandwidth-bound);
    above it, compute is the wall."""
    peak = peak if peak is not None else peak_flops()
    hbm = hbm if hbm is not None else hbm_ceiling()
    if not flops_per_call or not bytes_per_call or not peak or not hbm:
        return "unknown"
    ridge = peak / hbm
    intensity = flops_per_call / bytes_per_call
    return "bandwidth-bound" if intensity < ridge else "compute-bound"


#: serving-engine program families whose bytes are dominated by the paged
#: KV cache — the ones int8 pools (kv_dtype="int8") directly shrink
_KV_BOUND_FAMILIES = ("decode", "prefill/", "prefill_chunk/", "verify/")


def is_quantized_family(family):
    """True for the quantized serving program families — the engine
    attributes its int8-pool programs as ``decode@int8``,
    ``prefill/<bucket>@int8``, ``verify/k<k>@int8``."""
    return "@int8" in family


def is_lora_family(family):
    """True for the multi-tenant LoRA program families — the engine
    attributes them as ``decode@lora-r<r>``, ``prefill/<bucket>@lora-r<r>``
    (rank-bucket suffix; adapter count never appears)."""
    return "@lora-r" in family


def is_encode_family(family):
    """True for the embed/score passthrough families
    (``prefill/<bucket>@embed`` / ``@score``)."""
    return "@embed" in family or "@score" in family


def is_flash_family(family):
    """True for the length-bounded flash-decode families — on a TPU
    backend the engine attributes its decode programs as ``decode@flash``
    (``decode@flash@int8`` when quantized): the page sweep is clamped per
    row by the prefetched seq_lens, so dead-page DMA is already gone."""
    return "@flash" in family


def is_mp_family(family):
    """True for the tensor-parallel serving families — a mesh-sharded
    engine attributes its programs as ``decode@mp<N>``,
    ``prefill/<bucket>@mp<N>``, ``verify/k<k>@mp<N>`` (the suffix composes
    after ``@flash``/``@int8``: one SPMD program per family, dispatched
    over the ``model`` axis)."""
    return "@mp" in family


def mp_degree(family):
    """Model-parallel degree parsed from the ``@mp<N>`` suffix (1 when
    the family is unsharded)."""
    for part in family.split("@"):
        if part.startswith("mp") and part[2:].isdigit():
            return int(part[2:])
    return 1


def is_cached_prefill_family(family):
    """True for the prefix-cached prefill/encode families — the engine
    attributes a dispatch that reused ``p`` resident radix pages as
    ``prefill/<bucket>@cached<p>`` (``prefill/<bucket>@embed@cached<p>``
    for passthrough encodes): the family rides the chunked-prefill
    program shape but starts at the cached token offset, so its
    device-time per prompt token is already the minimum the cache can
    buy."""
    return "@cached" in family


def is_chunked_prefill_family(family):
    """True for the chunked-prefill ingestion families — the engine
    attributes them as ``prefill_chunk/<chunk_tokens>`` (plus the usual
    ``@int8`` / ``@lora-r<r>`` suffixes).  NOT a ``prefill/`` family:
    scratch is already O(chunk), so the 'chunk the prefill' capacity hint
    must never fire for these."""
    return family.split("@")[0].startswith("prefill_chunk/")


def _multi_chip_host():
    """More than one accelerator visible — an unsharded serving family
    here is leaving mesh capacity on the table, which flips the
    bandwidth-bound hint toward ``ServingEngine(mesh=...)``."""
    try:
        import jax

        return jax.device_count() > 1
    except Exception:
        return False


def candidate_hint(family, regime, temp_bytes=None, pool_bytes=None,
                   prefix_stats=None):
    """The regime-driven recommendation :meth:`ProgramTable.report` prints
    for a top device-time program.  Recognizes the quantized serving
    families: a bandwidth-bound UNQUANTIZED serving program's first lever
    is int8 KV pools (dequant fuses into the paged kernel — the
    serving.quant subsystem); an ``@int8`` family has already pulled it,
    so the hint points at the remaining byte traffic instead.  Also the
    multi-tenant families: ``@lora-r<r>`` programs carry the per-row
    paged adapter gather, ``@embed``/``@score`` are prefill-shaped
    one-shot encodes.

    Memory attribution (``temp_bytes`` from the family's
    ``memory_analysis``, ``pool_bytes`` = the ledger's KV pool total):
    a prefill family whose peak scratch dwarfs the whole paged cache is
    capacity-bound before it is time-bound — the hint becomes 'chunk the
    prefill', whatever the roofline regime says.

    Prefix-cache attribution (``prefix_stats`` = the registry's
    ``serving.prefix_cache_*`` / ``serving.kv_spill_*`` totals): a plain
    prefill family dominating device time while sharable pages mostly
    MISS means the workload recomputes prefixes the radix index would
    have kept resident — skipping the compute beats any bytes/flops
    lever, so that hint wins; a spill tier resurrecting pages about as
    fast as the cache hits is thrashing host<->device and wants a bigger
    ``PADDLE_KV_SPILL_BUDGET_BYTES``."""
    quant = is_quantized_family(family)
    flash = is_flash_family(family)
    mp = is_mp_family(family)
    serving = family.split("@")[0].startswith(_KV_BOUND_FAMILIES)
    if temp_bytes and pool_bytes \
            and is_chunked_prefill_family(family) \
            and temp_bytes > pool_bytes:
        return ("chunked prefill already active, yet peak temp bytes "
                f"({temp_bytes / 1e6:.1f} MB) still dwarf the paged KV "
                f"pools ({pool_bytes / 1e6:.1f} MB): lower "
                "prefill_chunk_tokens so per-chunk scratch shrinks "
                "further")
    if temp_bytes and pool_bytes \
            and family.split("@")[0].startswith("prefill/") \
            and temp_bytes > pool_bytes:
        return (f"prefill peak temp bytes ({temp_bytes / 1e6:.1f} MB) dwarf "
                f"the paged KV pools ({pool_bytes / 1e6:.1f} MB): chunk the "
                "prefill — ServingEngine(prefill_chunk_tokens=N) runs the "
                "prompt through the chunked cache variant in N-token "
                "slices so scratch stays O(chunk), and long prompts stop "
                "spiking HBM at admission")
    if prefix_stats:
        hits = int(prefix_stats.get("hits") or 0)
        misses = int(prefix_stats.get("misses") or 0)
        res = int(prefix_stats.get("resurrections") or 0)
        prefill_like = family.split("@")[0].startswith(
            ("prefill/", "prefill_chunk/"))
        if prefill_like and not is_cached_prefill_family(family) \
                and misses >= 8 and misses > 4 * max(hits, 1):
            return ("prefill dominates while sharable prefix pages miss "
                    f"{misses}:{hits} against the cache: enable the radix "
                    "prefix index (ServingEngine(prefix_cache=\"radix\")) "
                    "— partial-prefix matches reuse the longest shared "
                    "page run and prefill starts past the cached tokens, "
                    "skipping that compute entirely")
        if res >= 8 and res * 2 >= max(hits, 1):
            return ("KV spill tier is thrashing: "
                    f"{res} resurrections against {hits} cache hits "
                    "means hot prefix pages keep falling to host and "
                    "re-paging back — raise PADDLE_KV_SPILL_BUDGET_BYTES "
                    "(or shrink the working set) so resident prefixes "
                    "stay on-device")
    if regime == "bandwidth-bound":
        if is_lora_family(family):
            if quant:
                return ("HBM-bound int8 multi-LoRA program: KV dequant "
                        "fused; the remaining levers are the adapter "
                        "pools — fewer/lower rank buckets, fewer LoRA "
                        "targets, or bf16 adapter pools")
            return ("HBM-bound multi-LoRA serving program: the per-row "
                    "adapter gather rides the decode bytes — shrink rank "
                    "buckets / targets, then quantize the KV pools "
                    "(kv_dtype=\"int8\")")
        if is_encode_family(family):
            return ("HBM-bound embed/score encode: prefill-shaped one-shot "
                    "— batch more rows per dispatch or share prefix "
                    "compute with generate admissions")
        if mp and serving:
            n = mp_degree(family)
            if quant:
                return (f"HBM-bound mp{n} int8 serving program: KV pools "
                        "sharded over the model axis AND dequant fused — "
                        "per-shard bytes are the floor; remaining levers "
                        "are int8 weights (weight_dtype=\"int8\") and "
                        "batch occupancy")
            return (f"HBM-bound mp{n} serving program: already sharded "
                    "over the model axis, so each chip sweeps 1/"
                    f"{n} of the KV heads — cut the per-shard bytes next "
                    "with int8 pools (kv_dtype=\"int8\")")
        if flash:
            if quant:
                return ("HBM-bound int8 flash-decode program: the page "
                        "sweep is length-bounded and KV dequant is fused "
                        "— remaining levers are int8 weights "
                        "(weight_dtype=\"int8\") and batch occupancy "
                        "(more live slots per dispatch)")
            return ("HBM-bound flash-decode program: dead-page DMA is "
                    "already clamped by the length-bounded sweep — next "
                    "lever is int8 KV pools (kv_dtype=\"int8\"), then "
                    "int8 weights")
        if quant:
            return ("HBM-bound int8 serving program: KV dequant already "
                    "fused in-kernel — cut the remaining bytes (int8 "
                    "weights via weight_dtype, larger pages, more slots "
                    "per dispatch)")
        if serving and _multi_chip_host():
            return ("HBM-bound serving program with UNSHARDED pools on a "
                    "multi-chip host: shard the KV pools and weights over "
                    "the mesh (ServingEngine(mesh=...)) — each chip then "
                    "sweeps only its KV-head slice, ~1/mp the bytes/call "
                    "— then int8 pools (kv_dtype=\"int8\")")
        if serving:
            return ("HBM-bound serving program: quantize the KV pools "
                    "(kv_dtype=\"int8\" — dequant fuses into the paged "
                    "kernel, ~2x fewer cache bytes/call), fuse producers, "
                    "raise arithmetic intensity")
        return ("HBM-bound: cut bytes/call — fuse producers into the "
                "kernel, quantize operands, raise arithmetic intensity")
    if regime == "compute-bound":
        return ("compute-bound: raise matmul utilization — tile for the "
                "MXU, overlap with transfers")
    if quant:
        return ("regime unknown (resolve cost_analysis first); int8 "
                "serving program — KV dequant already fused in-kernel")
    return "regime unknown: resolve cost_analysis first"


class _ProgStats:
    __slots__ = ("family", "calls", "device_seconds", "flops_per_call",
                 "bytes_per_call", "memory_per_call", "cost_thunk",
                 "cost_error")

    def __init__(self, family):
        self.family = family
        self.calls = 0
        self.device_seconds = 0.0
        self.flops_per_call = None
        self.bytes_per_call = None
        self.memory_per_call = None  # XLA memory_analysis dict (or None)
        self.cost_thunk = None   # lazy () -> (flops, bytes[, memory])
        self.cost_error = None   # last thunk failure (kept, not retried)


class ProgramTable:
    """The live per-program attribution table (one per process by
    default — :func:`table`).  ``record`` is the hot-path entry: one dict
    lookup, two float adds under a per-table lock, two counter bumps."""

    def __init__(self, registry=None):
        from ..profiler import metrics as _metrics

        reg = registry if registry is not None else _metrics.get_registry()
        self._stats: dict[str, _ProgStats] = {}
        self._lock = threading.Lock()
        self._resolver = None
        self._m_calls = reg.counter(
            "perf.program.calls", "compiled-program dispatches, by family")
        self._m_seconds = reg.counter(
            "perf.program.device_seconds",
            "device seconds attributed to the family (dispatch-to-sync)")
        self._m_tflops = reg.gauge(
            "perf.program.achieved_tflops",
            "cost_analysis flops * calls / device seconds")
        self._m_gbs = reg.gauge(
            "perf.program.achieved_gbs",
            "cost_analysis bytes * calls / device seconds")
        self._m_frac = reg.gauge(
            "perf.program.frac_of_peak",
            "achieved rate over the BINDING peak (HBM when "
            "bandwidth-bound, FLOP/s when compute-bound)")
        # per-program memory attribution (memory_analysis, resolved off
        # the dispatch path exactly like the cost thunks)
        self._m_peak_bytes = reg.gauge(
            "perf.program.peak_bytes",
            "XLA memory_analysis peak bytes per call (argument + output "
            "+ temp - aliased)")
        self._m_temp_bytes = reg.gauge(
            "perf.program.temp_bytes",
            "XLA memory_analysis temp (scratch) bytes per call")

    # -------------------------------------------------------------- recording
    def _get(self, family):
        st = self._stats.get(family)
        if st is None:
            with self._lock:
                st = self._stats.setdefault(family, _ProgStats(family))
        return st

    def record(self, family, seconds, calls=1):
        """Attribute ``seconds`` of device time (``calls`` dispatches) to
        a program family.  Recording sites skip compile dispatches — a
        trace+compile wall is not device time."""
        st = self._get(family)
        with self._lock:
            st.calls += calls
            st.device_seconds += seconds
        self._m_calls.inc(calls, program=family)
        self._m_seconds.inc(seconds, program=family)

    def needs_cost(self, family):
        """True while the family has neither cost numbers nor a pending
        thunk — dispatch sites use this to capture arg shapes only once."""
        st = self._stats.get(family)
        return st is None or (st.flops_per_call is None
                              and st.cost_thunk is None
                              and st.cost_error is None)

    def set_cost(self, family, flops_per_call, bytes_per_call, memory=None):
        st = self._get(family)
        with self._lock:
            st.flops_per_call = float(flops_per_call)
            st.bytes_per_call = float(bytes_per_call)
            if memory is not None:
                st.memory_per_call = dict(memory)
            st.cost_thunk = None

    def register_cost_thunk(self, family, thunk):
        """Attach a lazy ``() -> (flops, bytes_accessed)`` (usually an XLA
        re-lower+compile+cost_analysis — seconds of work, so it never runs
        here; see :meth:`resolve_costs`)."""
        st = self._get(family)
        with self._lock:
            if st.flops_per_call is None and st.cost_thunk is None:
                st.cost_thunk = thunk

    def resolve_costs(self):
        """Run every pending cost thunk SYNCHRONOUSLY (tests, report,
        bench).  A failing thunk records its error and is not retried."""
        for st in list(self._stats.values()):
            with self._lock:
                thunk = st.cost_thunk
            if thunk is None:
                continue
            try:
                res = thunk()
                # jit_cost_thunk returns (flops, bytes, memory_analysis);
                # external 2-tuple thunks stay valid
                mem = res[2] if len(res) > 2 else None
                self.set_cost(st.family, res[0], res[1], memory=mem)
            except Exception as e:  # cost analysis is best-effort
                with self._lock:
                    st.cost_error = repr(e)
                    st.cost_thunk = None

    def _resolve_costs_async(self):
        """Kick cost resolution on a daemon thread (telemetry scrapes must
        stay bounded — a scrape never compiles)."""
        with self._lock:
            if self._resolver is not None and self._resolver.is_alive():
                return
            if not any(st.cost_thunk is not None
                       for st in self._stats.values()):
                return
            self._resolver = threading.Thread(
                target=self.resolve_costs, name="paddle-perf-cost-resolver",
                daemon=True)
            self._resolver.start()

    # -------------------------------------------------------------- reading
    def snapshot(self, resolve=False):
        """Table rows sorted by total device time (descending), derived
        rates and roofline regime included; refreshes the ``perf.program``
        gauges.  ``resolve=True`` first runs pending cost thunks (slow —
        never from a scrape; the /statusz provider instead kicks the
        background resolver and shows what is already known)."""
        if resolve:
            self.resolve_costs()
        peak, hbm = peak_flops(), hbm_ceiling()
        rows = []
        with self._lock:
            stats = [(st.family, st.calls, st.device_seconds,
                      st.flops_per_call, st.bytes_per_call, st.cost_error,
                      st.cost_thunk is not None, st.memory_per_call)
                     for st in self._stats.values()]
        for family, calls, secs, flops, nbytes, err, pending, mem in stats:
            row = {"program": family, "calls": calls,
                   "device_seconds": secs,
                   "flops_per_call": flops, "bytes_per_call": nbytes,
                   "achieved_tflops": None, "achieved_gbs": None,
                   "intensity_flop_per_byte": None,
                   "regime": "unknown", "frac_of_peak": None,
                   "argument_bytes": None, "output_bytes": None,
                   "temp_bytes": None, "peak_bytes": None}
            if mem:
                for k in ("argument_bytes", "output_bytes", "temp_bytes",
                          "peak_bytes"):
                    row[k] = mem.get(k)
                if row["peak_bytes"] is not None:
                    self._m_peak_bytes.set(row["peak_bytes"], program=family)
                if row["temp_bytes"] is not None:
                    self._m_temp_bytes.set(row["temp_bytes"], program=family)
            if pending:
                row["cost"] = "pending"
            elif err is not None:
                row["cost"] = f"error: {err}"
            if flops and nbytes and secs > 0 and calls:
                fps = flops * calls / secs
                bps = nbytes * calls / secs
                row["achieved_tflops"] = fps / 1e12
                row["achieved_gbs"] = bps / 1e9
                row["intensity_flop_per_byte"] = flops / nbytes
                row["regime"] = classify(flops, nbytes, peak, hbm)
                if row["regime"] == "bandwidth-bound" and hbm:
                    row["frac_of_peak"] = bps / hbm
                elif row["regime"] == "compute-bound" and peak:
                    row["frac_of_peak"] = fps / peak
                self._m_tflops.set(row["achieved_tflops"], program=family)
                self._m_gbs.set(row["achieved_gbs"], program=family)
                if row["frac_of_peak"] is not None:
                    self._m_frac.set(row["frac_of_peak"], program=family)
            rows.append(row)
        rows.sort(key=lambda r: -r["device_seconds"])
        return rows

    def statusz(self):
        """/statusz ``perf_programs`` provider: the table plus the
        ceilings it was judged against.  A scrape NEVER compiles: with
        ``PADDLE_PERF_COST=1`` pending costs resolve on a background
        thread kicked here; otherwise they stay "pending" until someone
        calls :func:`resolve_costs` / ``report()`` explicitly (a hidden
        background XLA compile per scrape is real CPU stolen from the
        serving process — opt in deliberately)."""
        if os.environ.get("PADDLE_PERF_COST", "").lower() \
                not in ("", "0", "false", "no"):
            self._resolve_costs_async()
        peak, hbm = peak_flops(), hbm_ceiling()
        return {
            "peak_tflops": peak / 1e12 if peak else None,
            "hbm_gbs": hbm / 1e9 if hbm else None,
            "ridge_flop_per_byte": (peak / hbm) if peak and hbm else None,
            "programs": self.snapshot(resolve=False),
        }

    def report(self, top=3, resolve=True):
        """Profiler.summary()-style text table + the top fusion/kernel
        candidates (largest device-time programs, with the roofline-driven
        recommendation: cut bytes when bandwidth-bound, cut/overlap flops
        when compute-bound)."""
        rows = self.snapshot(resolve=resolve)
        head = (f"{'program':<24}{'calls':>8}{'dev s':>10}{'TFLOP/s':>10}"
                f"{'GB/s':>9}{'I(F/B)':>9}{'of peak':>9}{'peak MB':>9}"
                "  regime")
        lines = ["Per-program roofline attribution", head, "-" * len(head)]

        def fmt(v, nd=2):
            return f"{v:.{nd}f}" if v is not None else "-"

        for r in rows:
            peak_mb = r["peak_bytes"] / 1e6 \
                if r.get("peak_bytes") is not None else None
            lines.append(
                f"{r['program']:<24}{r['calls']:>8}"
                f"{r['device_seconds']:>10.3f}"
                f"{fmt(r['achieved_tflops']):>10}{fmt(r['achieved_gbs'], 1):>9}"
                f"{fmt(r['intensity_flop_per_byte'], 1):>9}"
                f"{fmt(r['frac_of_peak'], 3):>9}{fmt(peak_mb, 1):>9}"
                f"  {r['regime']}")
        cands = [r for r in rows if r["device_seconds"] > 0][:top]
        if cands:
            # the memory ledger's KV pool total is the denominator for the
            # chunk-the-prefill hint (best-effort: no ledger, no hint)
            try:
                from . import memory as _memory

                pool_bytes = _memory.ledger().kv_pool_bytes()
            except Exception:
                pool_bytes = None
            # prefix-cache workload evidence for the radix/spill hints
            # (best-effort: zero everywhere -> no evidence -> no hint)
            try:
                from ..profiler import metrics as _pm

                prefix_stats = {
                    "hits": _pm.counter(
                        "serving.prefix_cache_hits").total() or 0,
                    "misses": _pm.counter(
                        "serving.prefix_cache_misses").total() or 0,
                    "resurrections": _pm.counter(
                        "serving.kv_spill_resurrections").total() or 0,
                }
                if not any(prefix_stats.values()):
                    prefix_stats = None
            except Exception:
                prefix_stats = None
            lines.append("")
            lines.append("Top kernel/fusion candidates (by device time):")
            for i, r in enumerate(cands, 1):
                hint = candidate_hint(r["program"], r["regime"],
                                      temp_bytes=r.get("temp_bytes"),
                                      pool_bytes=pool_bytes,
                                      prefix_stats=prefix_stats)
                lines.append(f"  {i}. {r['program']} "
                             f"({r['device_seconds']:.3f}s over "
                             f"{r['calls']} calls) — {hint}")
        return "\n".join(lines)

    def drop_prefix(self, prefix):
        """Evict every family under ``prefix`` (``prefix`` itself or
        ``prefix.*``/``prefix/*``).  TrainStep registers this as a
        weakref finalizer on its per-instance tag, so a process that
        constructs TrainSteps in a loop does not grow the table without
        bound (already-rendered ``perf.program.*`` registry series stay,
        like any labelled metric's)."""
        with self._lock:
            for fam in [f for f in self._stats
                        if f == prefix or f.startswith(prefix + ".")
                        or f.startswith(prefix + "/")]:
                del self._stats[fam]

    def reset(self):
        with self._lock:
            self._stats.clear()


# ------------------------------------------------------- process-wide table
_TABLE = None
_TABLE_LOCK = threading.Lock()
_PROVIDER_REGISTERED = False


def table() -> ProgramTable:
    global _TABLE
    if _TABLE is None:
        with _TABLE_LOCK:
            if _TABLE is None:
                _TABLE = ProgramTable()
    return _TABLE


def _ensure_provider():
    """Register the /statusz ``perf_programs`` section once, lazily on
    first record — a process that never dispatches never grows the key."""
    global _PROVIDER_REGISTERED
    if _PROVIDER_REGISTERED:
        return
    with _TABLE_LOCK:
        if _PROVIDER_REGISTERED:
            return
        from . import telemetry as _telemetry

        _telemetry.add_status_provider("perf_programs",
                                       lambda: table().statusz())
        _PROVIDER_REGISTERED = True


def record(family, seconds, calls=1):
    """Module-level spelling of :meth:`ProgramTable.record` on the process
    table (the one dispatch sites use)."""
    _ensure_provider()
    table().record(family, seconds, calls)


def needs_cost(family):
    return table().needs_cost(family)


def register_cost_thunk(family, thunk):
    table().register_cost_thunk(family, thunk)


def snapshot(resolve=False):
    return table().snapshot(resolve=resolve)


def resolve_costs():
    table().resolve_costs()


def report(top=3, resolve=True):
    return table().report(top=top, resolve=resolve)


def reset():
    """Tests: drop accumulated attribution (the table object and its
    registered provider survive)."""
    if _TABLE is not None:
        _TABLE.reset()


def metric_quantile(name, q, **labels):
    """Reservoir quantile of one registry histogram child, or None when
    the series is absent or empty.  The read half of the latency-SLO
    story (bench arms and the QoS report use it for per-tier TTFT/ITL
    p95s): serving series carry ``replica=`` labels — and on QoS engines
    ``tier=`` — so the child is addressed by exact label match."""
    from ..profiler import metrics as _metrics

    h = _metrics.get_registry().get(name)
    c = h.labels(**labels) if h is not None else None
    return (c.quantile(q) if c is not None and c.count else None)


# ------------------------------------------------- cost-thunk construction
def _shape_struct(v):
    import jax

    shape = getattr(v, "shape", None)
    dtype = getattr(v, "dtype", None)
    if shape is None or dtype is None:
        return v
    return jax.ShapeDtypeStruct(tuple(shape), dtype)


def _memory_analysis_dict(comp):
    """One compiled program's ``memory_analysis()`` as a plain dict
    (argument/output/temp/alias/generated-code bytes + a derived peak:
    XLA's CompiledMemoryStats has no explicit peak field on every
    backend, but arguments + outputs + temp − aliased is the live set a
    dispatch holds at once).  Best-effort: ``None`` when the backend
    doesn't expose it."""
    try:
        ma = comp.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None

    def g(name):
        try:
            return float(getattr(ma, name))
        except Exception:
            return 0.0

    arg = g("argument_size_in_bytes")
    out = g("output_size_in_bytes")
    temp = g("temp_size_in_bytes")
    alias = g("alias_size_in_bytes")
    peak = getattr(ma, "peak_memory_in_bytes", None)
    if peak is None:
        peak = max(0.0, arg + out + temp - alias)
    return {"argument_bytes": arg, "output_bytes": out, "temp_bytes": temp,
            "alias_bytes": alias,
            "generated_code_bytes": g("generated_code_size_in_bytes"),
            "peak_bytes": float(peak)}


def jit_cost_thunk(jitted, args):
    """Build a lazy cost thunk for a ``jax.jit``-ed callable from the
    concrete args of one dispatch: shapes/dtypes are captured NOW (cheap;
    donated buffers keep their metadata), the re-lower+compile+
    cost_analysis+memory_analysis runs only when the table resolves
    costs.

    The program is held by WEAKREF: the process-wide table outlives any
    one engine/model, and a pending thunk must not pin a dead model's
    params (the jitted closure reaches them) until someone happens to
    resolve costs."""
    import weakref

    import jax

    shapes = jax.tree_util.tree_map(_shape_struct, args)
    ref = weakref.ref(jitted)

    def thunk():
        fn = ref()
        if fn is None:
            raise RuntimeError(
                "compiled program was garbage-collected before its "
                "cost_analysis resolved")
        comp = fn.lower(*shapes).compile()
        ca = comp.cost_analysis()
        ca = ca[0] if isinstance(ca, list) else ca
        return (float(ca.get("flops", 0.0)),
                float(ca.get("bytes accessed", 0.0)),
                _memory_analysis_dict(comp))

    return thunk


def jit_analysis_thunk(jitted, args):
    """:func:`jit_cost_thunk` with a lifecycle split for the program
    ledger: the re-lower is timed as a trace-seconds estimate and the
    backend compile separately, alongside flops / bytes-accessed /
    executable size / memory analysis — one dict per program, resolved
    lazily (never on a scrape).  Same weakref discipline as
    :func:`jit_cost_thunk`: a pending thunk must not pin a dead model."""
    import weakref

    import jax

    shapes = jax.tree_util.tree_map(_shape_struct, args)
    ref = weakref.ref(jitted)

    def thunk():
        fn = ref()
        if fn is None:
            raise RuntimeError(
                "compiled program was garbage-collected before its "
                "analysis resolved")
        t0 = perf_counter()
        low = fn.lower(*shapes)
        t1 = perf_counter()
        comp = low.compile()
        t2 = perf_counter()
        ca = comp.cost_analysis()
        ca = ca[0] if isinstance(ca, list) else (ca or {})
        mem = _memory_analysis_dict(comp)
        return {"trace_s": t1 - t0,
                "backend_compile_s": t2 - t1,
                "flops": float(ca.get("flops", 0.0)),
                "bytes_accessed": float(ca.get("bytes accessed", 0.0)),
                "executable_bytes": (mem or {}).get("generated_code_bytes"),
                "memory": mem}

    return thunk
