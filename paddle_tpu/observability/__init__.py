"""paddle_tpu.observability — distributed tracing + forensics (this PR's
tentpole; ROADMAP: the first thing a real multi-host deployment needs).

Three pillars over the PR-1 profiler/metrics layer:

- :mod:`.tracing` — ``span()`` with OTLP-convention trace/span ids,
  per-rank :class:`Tracer` collection wrapping the RecordEvent tree,
  chrome-trace + OTLP-JSON export, and :func:`merge_rank_traces` to fold
  per-rank files into one clock-aligned timeline.  Trace ids propagate
  from ``ServingEngine.submit()`` through prefill/decode iterations and
  from ``jit.TrainStep`` through the collective wrappers.
- :mod:`.flight_recorder` + :mod:`.watchdog` — a fixed-size ring of recent
  spans/events that dumps to ``PADDLE_FLIGHT_DIR`` on SIGTERM/SIGABRT,
  unhandled exceptions and watchdog triggers; a
  :class:`~.watchdog.CollectiveWatchdog` bracketing every eager collective
  and a :class:`~.watchdog.ServingWatchdog` catching a wedged scheduler
  thread.  :mod:`.faults` provides the injection hooks the tests use to
  trip both.
- :mod:`.telemetry` — ``observability.serve(port)``: a stdlib HTTP thread
  exposing ``/metrics`` (Prometheus text), ``/healthz`` and ``/statusz``
  (engine occupancy, queue depth, slot table, page-pool utilization,
  in-flight spans, last flight record).  Also armed by
  ``PADDLE_TELEMETRY_PORT`` via ``ServingEngine.start()``.

Env flags (README "Distributed tracing & forensics"):
``PADDLE_FLIGHT_DIR``, ``PADDLE_TELEMETRY_PORT``,
``PADDLE_COLLECTIVE_TIMEOUT_S``, ``PADDLE_SERVING_WATCHDOG_S``.
"""

from __future__ import annotations

from . import (  # noqa: F401
    faults, flight_recorder, memory, numerics, perf, programs, slo, telemetry,
    tracing, watchdog,
)
from .faults import FaultPlan  # noqa: F401
from .memory import MemoryLedger, MemoryWatchdog  # noqa: F401
from .numerics import (  # noqa: F401
    NumericsMonitor, TensorCheckerConfig, check_numerics,
    collect_operator_stats, disable_tensor_checker, enable_tensor_checker,
)
from .perf import ProgramTable  # noqa: F401
from .programs import ProgramLedger, WarmupManifest  # noqa: F401
from .slo import RequestTimeline, SLOAccountant, SLOPolicy  # noqa: F401
from .flight_recorder import (  # noqa: F401
    FlightRecorder, get_flight_recorder, install_crash_handlers,
)
from .telemetry import (  # noqa: F401
    TelemetryServer, add_health_provider, add_status_provider, serve,
)
from .tracing import (  # noqa: F401
    Span, Tracer, current_trace_id, event, merge_rank_traces, new_trace_id,
    open_spans, span,
)
from .watchdog import (  # noqa: F401
    CollectiveWatchdog, ServingWatchdog, add_fire_listener,
    remove_fire_listener,
)

__all__ = [
    "tracing", "flight_recorder", "watchdog", "telemetry", "faults",
    "perf", "programs", "slo", "memory", "numerics", "NumericsMonitor",
    "TensorCheckerConfig", "enable_tensor_checker", "disable_tensor_checker",
    "check_numerics", "collect_operator_stats", "ProgramTable", "SLOPolicy", "SLOAccountant",
    "RequestTimeline", "MemoryLedger", "MemoryWatchdog",
    "ProgramLedger", "WarmupManifest",
    "Span", "Tracer", "span", "event", "new_trace_id", "current_trace_id",
    "open_spans", "merge_rank_traces",
    "FlightRecorder", "get_flight_recorder", "install_crash_handlers",
    "CollectiveWatchdog", "ServingWatchdog", "add_fire_listener",
    "remove_fire_listener", "FaultPlan",
    "TelemetryServer", "serve", "add_status_provider", "add_health_provider",
]

# production spelling: export PADDLE_FLIGHT_DIR=/some/dir and importing any
# instrumented module arms the crash ring + signal/exception dumps
flight_recorder.maybe_enable_from_env()
