"""Numerics observability — the reproduction's ``check_nan_inf`` axis.

PaddlePaddle ships a numerics-debugging toolkit (``paddle.amp.debugging``'s
``TensorCheckerConfig`` / ``check_numerics`` and the ``FLAGS_check_nan_inf``
runtime check).  This module is its TPU-native home: after time (PR 1-3),
flops/SLO (PR 7) and bytes (PR 12), this closes the last blind axis —
whether the numbers themselves are still numbers.

Four pillars:

- **probe math** — :func:`stats_row` / :func:`tensor_stats`: cheap
  per-tensor reductions (nonfinite count, absmax, rms, zero-frac and
  bf16/fp16 under/overflow fractions) usable both eagerly and inside a
  traced program.
- **in-program probes** — :func:`capture` hooks ``nn.Layer.__call__``
  (one module-global load per call when inactive, the profiler-events
  pattern) so a traced train-step or serving program records one stats
  row per layer output into a small device-side table.  The table is an
  ordinary program OUTPUT: producers call :func:`submit` with the device
  array and :func:`poll` resolves it to host OFF the dispatch path (the
  PR-7 cost-thunk discipline), exporting
  ``numerics.{nonfinite,absmax,rms,underflow_frac}{site=,tensor=}``
  gauges and feeding the anomaly engine.  Probes enter program caches as
  a distinct variant keyed by :func:`probe_token` — disabled, every
  program is byte-identical to an un-probed build.
- **anomaly engine** — :class:`NumericsMonitor`: first-nonfinite
  occurrence, grad-norm explosion and loss spikes (rolling median + MAD
  over the probed loss), ONE flight-recorder dump per episode
  (``reason="numerics"``, first offending layer/tensor named, the full
  stats table attached).  ``poll(raise_on_fault=True)`` (or
  ``level="abort"``) converts a fresh non-finite episode into a
  :class:`~paddle_tpu.resilience.retry.NumericFault` so a
  :class:`~paddle_tpu.resilience.RecoverySupervisor` rolls back to the
  last valid checkpoint instead of blindly retrying the poisoned step.
- **fault site** — ``numerics.nan_inject`` (:mod:`.faults`):
  :func:`consume_nan_inject` turns an armed trip into a NaN scalar that
  probed programs add at a configurable site (default: the first probed
  layer), driving the whole detect → dump → rollback loop in tests
  without a single real numerical bug.

Eager mode rides the same machinery: :func:`check_numerics` (one
tensor), :func:`collect_operator_stats` (per-layer stats over a region,
the ``paddle.amp.debugging`` context-manager shape) and
:func:`enable_tensor_checker` with ``level="warn"|"dump"|"abort"`` and
name filters.

The ``/statusz`` "numerics" section renders the last RESOLVED table only
— scrapes never touch the device (the PR-3 signal-path rule).
"""

from __future__ import annotations

import threading
import time
import warnings
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field

import jax.numpy as jnp
import numpy as np

from ..profiler import metrics as _metrics
from . import faults as _faults

__all__ = [
    "STAT_FIELDS", "TensorCheckerConfig", "enable_tensor_checker",
    "disable_tensor_checker", "check_numerics", "collect_operator_stats",
    "tensor_stats", "stats_row", "capture", "submit", "poll", "maybe_poll",
    "probe_token", "probe_cadence", "checker_enabled", "monitor",
    "consume_nan_inject", "set_nan_inject_row", "latest", "statusz",
    "reset",
]

STAT_FIELDS = ("nonfinite", "absmax", "rms", "zero_frac",
               "underflow_frac", "overflow_frac")
NSTATS = len(STAT_FIELDS)

# normal-range limits the under/overflow fractions measure against: the
# fraction of values a cast to the low-precision grid would flush to zero
# (|x| below the smallest normal) or saturate (|x| above the largest
# finite).  bf16 shares float32's exponent range; fp16 is the narrow one.
_RANGES = {
    "bfloat16": (1.1754944e-38, 3.3895314e38),
    "float16": (6.104e-05, 65504.0),
    "float32": (1.1754944e-38, 3.4028235e38),
}

_LEVELS = ("warn", "dump", "abort")


# ------------------------------------------------------------- probe math
def stats_row(x, low_dtype="bfloat16"):
    """The probe: one ``float32[6]`` row of reductions over ``x`` in
    :data:`STAT_FIELDS` order.  Traceable (returns jnp scalars inside a
    jit) and cheap — two passes over the tensor, O(1) output."""
    v = getattr(x, "_value", x)
    v = jnp.asarray(v)
    f = v.astype(jnp.float32).reshape(-1)
    n = max(int(f.size), 1)
    tiny, huge = _RANGES.get(str(low_dtype), _RANGES["bfloat16"])
    finite = jnp.isfinite(f)
    nonfinite = jnp.sum(~finite).astype(jnp.float32)
    a = jnp.abs(jnp.where(finite, f, 0.0))
    absmax = jnp.max(a) if f.size else jnp.float32(0.0)
    rms = jnp.sqrt(jnp.sum(a * a) / n)
    inv_n = jnp.float32(1.0 / n)
    zero_frac = jnp.sum(a == 0).astype(jnp.float32) * inv_n
    underflow = jnp.sum((a > 0) & (a < tiny)).astype(jnp.float32) * inv_n
    overflow = (jnp.sum(a > huge).astype(jnp.float32) + nonfinite) * inv_n
    return jnp.stack([nonfinite, absmax, rms, zero_frac, underflow,
                      overflow]).astype(jnp.float32)


def tensor_stats(x, low_dtype="bfloat16"):
    """Eager spelling of :func:`stats_row`: a ``{field: float}`` dict."""
    row = np.asarray(stats_row(x, low_dtype=low_dtype))
    return {k: float(v) for k, v in zip(STAT_FIELDS, row)}


# ----------------------------------------------------------- configuration
@dataclass
class TensorCheckerConfig:
    """``paddle.amp.debugging.TensorCheckerConfig``-shaped switchboard.

    ``level`` governs what a detection does: ``"warn"`` warns, ``"dump"``
    also fires one flight-recorder dump per episode, ``"abort"`` also
    raises (``FloatingPointError`` from eager checks,
    :class:`~paddle_tpu.resilience.retry.NumericFault` from
    :func:`poll`).  ``include``/``exclude`` are name-substring filters
    over probe/check sites; ``cadence`` is how often the train step runs
    its probed program variant (every Nth step)."""

    enable: bool = True
    level: str = "dump"
    include: tuple = ()
    exclude: tuple = ()
    cadence: int = 1
    low_dtype: str = "bfloat16"
    serving_guard: bool = False      # default for ServingEngine(numeric_guard=None)
    nan_inject_site: str | None = None   # None = first probed site
    # anomaly-engine knobs: rolling median + MAD over `window` samples,
    # spike = value > median + mad_threshold * MAD after `min_history`
    window: int = 64
    mad_threshold: float = 10.0
    min_history: int = 8

    def __post_init__(self):
        if self.level not in _LEVELS:
            raise ValueError(f"level must be one of {_LEVELS}, got "
                             f"{self.level!r}")
        if isinstance(self.include, str):
            self.include = (self.include,)
        if isinstance(self.exclude, str):
            self.exclude = (self.exclude,)
        self.include = tuple(self.include or ())
        self.exclude = tuple(self.exclude or ())
        self.cadence = max(1, int(self.cadence))

    def match(self, name):
        name = str(name)
        if any(s in name for s in self.exclude):
            return False
        if self.include:
            return any(s in name for s in self.include)
        return True


# ------------------------------------------------------------ process state
_LOCK = threading.Lock()
_CONFIG: TensorCheckerConfig | None = None
_VERSION = 0                     # bumps on enable/disable -> probe_token
_PROVIDER_REGISTERED = False
_TLS = threading.local()
_ACTIVE_CAPTURES = 0

_PENDING: dict = {}              # stream -> (sites, device stats, step)
_LATEST: dict = {}               # stream -> {"sites", "table", "step", "ts"}
_last_poll = 0.0

_nan_trips_seen = 0
_NAN_INJECT_ROW = 0

_MONITOR = None

_g_nonfinite = _metrics.gauge(
    "numerics.nonfinite", "non-finite element count per probed tensor")
_g_absmax = _metrics.gauge(
    "numerics.absmax", "absolute max per probed tensor (finite values)")
_g_rms = _metrics.gauge(
    "numerics.rms", "root-mean-square per probed tensor (finite values)")
_g_underflow = _metrics.gauge(
    "numerics.underflow_frac",
    "fraction of values below the low-precision normal range")
_c_checks = _metrics.counter(
    "numerics.checks", "eager check_numerics calls that found non-finite "
    "values")


def enable_tensor_checker(config=None, **kw):
    """Arm the checker (and the in-program probe variants).  Pass a
    :class:`TensorCheckerConfig` or its fields as keywords; returns the
    active config."""
    global _CONFIG, _VERSION
    cfg = config if config is not None else TensorCheckerConfig(**kw)
    with _LOCK:
        _CONFIG = cfg
        _VERSION += 1
    _ensure_provider()
    return cfg


def disable_tensor_checker():
    """Disarm: probe tokens return 0, program caches fall back to the
    byte-identical un-probed variants."""
    global _CONFIG, _VERSION
    with _LOCK:
        _CONFIG = None
        _VERSION += 1


def config():
    return _CONFIG


def checker_enabled():
    cfg = _CONFIG
    return cfg is not None and cfg.enable


def level():
    cfg = _CONFIG
    return cfg.level if cfg is not None else "warn"


def probe_token():
    """Program-variant key component: 0 when probes are off (producers
    must then build their pre-existing, byte-identical programs), a
    fresh non-zero integer per enable so stale variants never alias."""
    return _VERSION if checker_enabled() else 0


def probe_cadence():
    cfg = _CONFIG
    return cfg.cadence if (cfg is not None and cfg.enable) else 1


def serving_guard_default():
    cfg = _CONFIG
    return bool(cfg is not None and cfg.enable and cfg.serving_guard)


def low_dtype():
    cfg = _CONFIG
    return cfg.low_dtype if cfg is not None else "bfloat16"


def _match(name):
    cfg = _CONFIG
    return cfg.match(name) if cfg is not None else True


# ------------------------------------------------------- capture machinery
class _Capture:
    """Trace- or eager-time collector of (site, stats-row) pairs fed by
    the ``nn.Layer.__call__`` tap.  ``inject`` (a float32 scalar, host or
    traced) is ADDED to the output of the matching site — the
    ``numerics.nan_inject`` poison point; 0.0 is the disarmed value, so
    the probed program's shape never depends on whether a fault is
    armed."""

    def __init__(self, stream="trace", names=None, inject=None,
                 inject_site=None, low_dtype="bfloat16", eager=False):
        self.stream = stream
        self.sites: list = []
        self.rows: list = []
        self.eager = eager
        self.inject = inject
        self.inject_site = inject_site
        self.low = low_dtype
        self._names = names or {}
        self._counts: dict = {}
        self._injected = False

    def _name_for(self, layer):
        name = self._names.get(id(layer))
        if name is None:
            base = getattr(layer, "_name_scope", type(layer).__name__)
            k = self._counts.get(base, 0)
            self._counts[base] = k + 1
            name = base if k == 0 else f"{base}#{k}"
        return name

    def _inject_here(self, name):
        if self.inject is None or self._injected:
            return False
        if self.inject_site is None:
            return True                       # first probed site
        return self.inject_site in name

    def add(self, name, value):
        """Manual probe site (loss, grads, logits)."""
        if not _match(name):
            return
        self.sites.append(str(name))
        self.rows.append(stats_row(value, low_dtype=self.low))

    def tap(self, layer, out):
        arr = _first_array(out)
        if arr is None:
            return out
        name = self._name_for(layer)
        if not _match(name):
            return out
        if self._inject_here(name):
            self._injected = True
            poisoned = arr + jnp.asarray(self.inject).astype(arr.dtype)
            out = _replace_array(out, poisoned)
            arr = poisoned
        self.sites.append(name)
        self.rows.append(stats_row(arr, low_dtype=self.low))
        return out

    def stack(self):
        """(sites, float32[n, 6]) — the device-side stats table a traced
        program returns as an extra output."""
        if not self.rows:
            return (), jnp.zeros((0, NSTATS), jnp.float32)
        return tuple(self.sites), jnp.stack(self.rows)

    def summary(self):
        """Eager: ``{site: {field: float}}`` in call order."""
        out = {}
        for name, row in zip(self.sites, self.rows):
            out[name] = {k: float(v)
                         for k, v in zip(STAT_FIELDS, np.asarray(row))}
        return out


def _first_array(out):
    if hasattr(out, "_value"):
        return out._value
    if isinstance(out, jnp.ndarray):
        return out
    if isinstance(out, (tuple, list)) and out:
        return _first_array(out[0])
    return None


def _replace_array(out, arr):
    if hasattr(out, "_value"):
        out._value = arr
        return out
    if isinstance(out, jnp.ndarray):
        return arr
    if isinstance(out, (tuple, list)) and out:
        head = _replace_array(out[0], arr)
        rest = list(out[1:])
        return type(out)([head] + rest) if isinstance(out, list) \
            else (head,) + tuple(rest)
    return out


def _layer_tap(layer, out):
    stack = getattr(_TLS, "captures", None)
    if not stack:
        return out
    return stack[-1].tap(layer, out)


def _set_hook(active):
    from ..nn import layer as _layer_mod

    _layer_mod._NUMERICS_TAP = _layer_tap if active else None


@contextmanager
def capture(stream="trace", names=None, inject=None, inject_site=None,
            eager=False):
    """Activate the layer tap on this thread; yields the
    :class:`_Capture` whose ``stack()``/``summary()`` the caller reads
    after the region."""
    global _ACTIVE_CAPTURES
    cap = _Capture(stream=stream, names=names, inject=inject,
                   inject_site=inject_site, low_dtype=low_dtype(),
                   eager=eager)
    stack = getattr(_TLS, "captures", None)
    if stack is None:
        stack = _TLS.captures = []
    stack.append(cap)
    with _LOCK:
        _ACTIVE_CAPTURES += 1
        _set_hook(True)
    try:
        yield cap
    finally:
        stack.pop()
        with _LOCK:
            _ACTIVE_CAPTURES -= 1
            if _ACTIVE_CAPTURES == 0:
                _set_hook(False)


def layer_names(model):
    """``{id(sublayer): qualified_name}`` for capture naming — producers
    build this once per model so probe sites carry real parameter paths
    instead of bare class names."""
    out = {id(model): getattr(model, "_name_scope",
                              type(model).__name__)}
    try:
        for name, sub in model.named_sublayers(include_self=False):
            out[id(sub)] = name
    except Exception:
        pass
    return out


# ------------------------------------------------- device table lifecycle
def submit(stream, sites, dev_stats, step=0):
    """Producer side: park the latest device stats table for ``stream``.
    Never syncs — resolution happens in :func:`poll`, off the dispatch
    path (the PR-7 cost-thunk discipline).  Only the newest table per
    stream is kept."""
    if not sites:
        return
    with _LOCK:
        _PENDING[stream] = (tuple(sites), dev_stats, int(step))


def poll(stream=None, raise_on_fault=None):
    """Resolve pending device tables to host (the one sync), export the
    ``numerics.*`` gauges and run the anomaly engine.  Returns the list
    of NEW anomaly episodes.  ``raise_on_fault=True`` (or
    ``level="abort"``) raises
    :class:`~paddle_tpu.resilience.retry.NumericFault` on a fresh
    non-finite episode."""
    with _LOCK:
        if stream is None:
            items = list(_PENDING.items())
            _PENDING.clear()
        else:
            items = [(stream, _PENDING.pop(stream))] \
                if stream in _PENDING else []
    episodes = []
    for strm, (sites, dev, step) in items:
        table = np.asarray(dev, dtype=np.float32)
        with _LOCK:
            _LATEST[strm] = {"sites": sites, "table": table,
                             "step": step, "ts": time.time()}
        _export_gauges(strm, sites, table)
        episodes.extend(monitor().observe(strm, sites, table, step))
    if raise_on_fault is None:
        raise_on_fault = level() == "abort"
    if raise_on_fault:
        for ep in episodes:
            if ep.kind == "nonfinite":
                from ..resilience.retry import NumericFault

                raise NumericFault(
                    f"non-finite values at {ep.site!r} "
                    f"(stream={ep.stream}, step={ep.step})",
                    site=ep.site, stream=ep.stream, step=ep.step)
    return episodes


def maybe_poll(min_interval_s=0.5):
    """Throttled :func:`poll` for hot loops: at most one resolve per
    ``min_interval_s``, nothing to do when no table is pending."""
    global _last_poll
    if not _PENDING:
        return []
    now = time.monotonic()
    if now - _last_poll < min_interval_s:
        return []
    _last_poll = now
    return poll()


def latest(stream=None):
    """Last resolved stats: the whole dict, or one stream's entry."""
    with _LOCK:
        if stream is not None:
            return _LATEST.get(stream)
        return dict(_LATEST)


def _export_gauges(stream, sites, table):
    for i, site in enumerate(sites):
        labels = {"site": stream, "tensor": site}
        _g_nonfinite.set(float(table[i, 0]), **labels)
        _g_absmax.set(float(table[i, 1]), **labels)
        _g_rms.set(float(table[i, 2]), **labels)
        _g_underflow.set(float(table[i, 4]), **labels)


# ------------------------------------------------------------ fault site
def consume_nan_inject():
    """The ``numerics.nan_inject`` site: returns ``float32("nan")`` when
    an armed fault tripped since the last call, else ``0.0`` — producers
    feed the value straight into their probed program's inject argument,
    so arming a fault never changes a program's shape."""
    global _nan_trips_seen
    with _LOCK:
        # baseline BEFORE tripping: a re-armed site starts a fresh spec at
        # trips=0, so reading only after maybe() would swallow its first
        # trip (1 == the stale seen-count from the exhausted spec)
        before = _faults.trip_count("numerics.nan_inject")
        if before < _nan_trips_seen:       # faults.clear()/re-arm reset
            _nan_trips_seen = before
    _faults.maybe("numerics.nan_inject")
    trips = _faults.trip_count("numerics.nan_inject")
    with _LOCK:
        fired = trips > _nan_trips_seen
        _nan_trips_seen = trips
    return np.float32("nan") if fired else np.float32(0.0)


def set_nan_inject_row(row):
    """Serving: which batch lane the next tripped ``nan_inject`` poisons
    (default 0)."""
    global _NAN_INJECT_ROW
    _NAN_INJECT_ROW = int(row)


def nan_inject_row():
    return _NAN_INJECT_ROW


# ---------------------------------------------------------- anomaly engine
@dataclass
class Anomaly:
    kind: str                    # nonfinite | grad_explosion | loss_spike
    stream: str
    step: int
    site: str
    value: float
    dump: str | None = None


class NumericsMonitor:
    """First-nonfinite, grad-norm-explosion and loss-spike detection over
    resolved stats tables; one flight-recorder dump per EPISODE (an
    episode re-arms when the stream goes clean again)."""

    def __init__(self):
        self._hist: dict = {}            # (stream, kind) -> deque
        self._active: set = set()        # (stream, kind) in-episode
        self._episodes: deque = deque(maxlen=32)
        self._m_anomalies = _metrics.counter(
            "numerics.anomalies", "numeric anomaly episodes by kind")

    # ------------------------------------------------------------ observe
    def observe(self, stream, sites, table, step):
        cfg = _CONFIG or TensorCheckerConfig(enable=False)
        out = []
        nf = np.flatnonzero(table[:, 0] > 0) if len(table) else np.array([])
        key = (stream, "nonfinite")
        if nf.size:
            if key not in self._active:
                self._active.add(key)
                i = int(nf[0])
                out.append(self._fire("nonfinite", stream, step, sites[i],
                                      float(table[i, 0]), sites, table))
        else:
            self._active.discard(key)

        gi = [i for i, s in enumerate(sites) if s.startswith("grad")]
        if gi and not np.any(table[gi, 0] > 0):
            gnorm = float(np.sqrt(np.sum(table[gi, 2] ** 2)))
            a = self._spike("grad_explosion", stream, step, "grad_norm",
                            gnorm, cfg, sites, table)
            if a:
                out.append(a)
        if "loss" in sites:
            i = sites.index("loss")
            if not table[i, 0] > 0:
                a = self._spike("loss_spike", stream, step, "loss",
                                float(table[i, 2]), cfg, sites, table)
                if a:
                    out.append(a)
        return out

    def observe_loss(self, value, stream="train", step=0):
        """Host-side loss feed for eager loops without probes."""
        v = float(value)
        if not np.isfinite(v):
            key = (stream, "nonfinite")
            if key in self._active:
                return []
            self._active.add(key)
            return [self._fire("nonfinite", stream, step, "loss", v,
                               ("loss",), np.array([[1.0] + [0.0] * 5]))]
        self._active.discard((stream, "nonfinite"))
        cfg = _CONFIG or TensorCheckerConfig(enable=False)
        a = self._spike("loss_spike", stream, step, "loss", v, cfg,
                        ("loss",), np.zeros((1, NSTATS)))
        return [a] if a else []

    # ------------------------------------------------------------ details
    def _spike(self, kind, stream, step, site, value, cfg, sites, table):
        if not np.isfinite(value):
            return None
        key = (stream, kind)
        hist = self._hist.setdefault(key, deque(maxlen=cfg.window))
        fired = None
        if len(hist) >= cfg.min_history:
            med = float(np.median(hist))
            mad = float(np.median(np.abs(np.asarray(hist) - med)))
            floor = max(abs(med) * 1e-3, 1e-12)
            thresh = med + cfg.mad_threshold * max(mad, floor)
            if value > thresh:
                if key not in self._active:
                    self._active.add(key)
                    fired = self._fire(kind, stream, step, site, value,
                                       sites, table)
            else:
                self._active.discard(key)
        if key not in self._active:
            hist.append(value)           # keep the baseline clean
        return fired

    def _fire(self, kind, stream, step, site, value, sites, table):
        self._m_anomalies.inc(kind=kind)
        lvl = level()
        dump = None
        if lvl in ("dump", "abort"):
            from . import flight_recorder as _flight

            rows = [dict(zip(STAT_FIELDS, (float(x) for x in table[i])),
                         tensor=sites[i]) for i in range(len(sites))]
            dump = _flight.get_flight_recorder().dump(
                "numerics", extra={"kind": kind, "stream": stream,
                                   "step": step, "site": site,
                                   "value": value, "stats": rows})
        else:
            warnings.warn(
                f"numerics: {kind} at {site!r} (stream={stream}, "
                f"step={step}, value={value!r})", RuntimeWarning,
                stacklevel=3)
        ep = Anomaly(kind=kind, stream=stream, step=step, site=site,
                     value=value, dump=dump)
        self._episodes.append(ep)
        return ep

    def episodes(self):
        return list(self._episodes)

    def reset(self):
        self._hist.clear()
        self._active.clear()
        self._episodes.clear()


def monitor() -> NumericsMonitor:
    global _MONITOR
    if _MONITOR is None:
        with _LOCK:
            if _MONITOR is None:
                _MONITOR = NumericsMonitor()
    return _MONITOR


# ------------------------------------------------------------- eager API
def check_numerics(x, name="tensor", stream="eager"):
    """Eager one-shot check (``paddle.amp.debugging.check_numerics``):
    returns the stats dict; on non-finite values acts per the active
    checker level (warn / one dump per episode / raise
    ``FloatingPointError``)."""
    stats = tensor_stats(x, low_dtype=low_dtype())
    if stats["nonfinite"] > 0 and _match(name):
        _c_checks.inc()
        row = np.array([[stats[k] for k in STAT_FIELDS]])
        key = (f"{stream}/{name}", "nonfinite")
        mon = monitor()
        if key not in mon._active:
            mon._active.add(key)
            mon._fire("nonfinite", f"{stream}/{name}", 0, name,
                      stats["nonfinite"], (name,), row)
        if level() == "abort":
            raise FloatingPointError(
                f"non-finite values in {name!r}: "
                f"{int(stats['nonfinite'])} element(s)")
    elif stats["nonfinite"] == 0:
        monitor()._active.discard((f"{stream}/{name}", "nonfinite"))
    return stats


class OperatorStatsCollector:
    """Eager per-layer stats over a region — the
    ``collect_operator_stats`` context manager's payload.  Rides the same
    layer tap the traced probes use."""

    def __init__(self, model=None, stream="eager"):
        self.stream = stream
        self._names = layer_names(model) if model is not None else None
        self._cm = None
        self._cap = None

    def start(self):
        self._cm = capture(stream=self.stream, names=self._names,
                           eager=True)
        self._cap = self._cm.__enter__()

    def stop(self):
        if self._cm is None:
            return
        self._cm.__exit__(None, None, None)
        self._cm = None

    def summary(self):
        return self._cap.summary() if self._cap is not None else {}

    def report(self):
        lines = [" | ".join(["site".ljust(28)] + [f.rjust(14)
                                                  for f in STAT_FIELDS])]
        for site, stats in self.summary().items():
            lines.append(" | ".join(
                [site[:28].ljust(28)]
                + [f"{stats[f]:14.6g}" for f in STAT_FIELDS]))
        return "\n".join(lines)


@contextmanager
def collect_operator_stats(model=None, stream="eager"):
    """``with collect_operator_stats() as col: ...`` — eager per-layer
    tensor stats (``col.summary()`` / ``col.report()``), checking each
    layer output against the active level on exit."""
    col = OperatorStatsCollector(model=model, stream=stream)
    col.start()
    try:
        yield col
    finally:
        col.stop()
        for site, stats in col.summary().items():
            if stats["nonfinite"] > 0:
                check_numerics(np.float32("nan"), name=site, stream=stream)


# ---------------------------------------------------------------- statusz
def _ensure_provider():
    """Register the /statusz ``numerics`` section once, lazily on first
    enable — a process that never arms the checker never grows the key."""
    global _PROVIDER_REGISTERED
    if _PROVIDER_REGISTERED:
        return
    with _LOCK:
        if _PROVIDER_REGISTERED:
            return
        from . import telemetry as _telemetry

        _telemetry.add_status_provider("numerics", statusz)
        _PROVIDER_REGISTERED = True


def statusz():
    """The ``/statusz`` section: config, last RESOLVED tables, recent
    anomaly episodes and the amp scaler gauges.  Never touches the
    device (pending tables are counted, not resolved)."""
    cfg = _CONFIG
    with _LOCK:
        resolved = {
            strm: {"step": ent["step"], "ts": ent["ts"],
                   "tensors": [dict(zip(STAT_FIELDS,
                                        (float(x) for x in ent["table"][i])),
                                    tensor=ent["sites"][i])
                               for i in range(len(ent["sites"]))]}
            for strm, ent in _LATEST.items()}
        pending = sorted(_PENDING)
    eps = [{"kind": e.kind, "stream": e.stream, "step": e.step,
            "site": e.site, "value": e.value, "dump": e.dump}
           for e in monitor().episodes()[-8:]]
    amp = {"loss_scale": _metrics.gauge("amp.loss_scale").get(),
           "found_inf": _metrics.counter("amp.found_inf").get(),
           "scale_decr": _metrics.counter("amp.scale_decr").get()}
    return {
        "enabled": bool(cfg is not None and cfg.enable),
        "level": cfg.level if cfg else None,
        "cadence": cfg.cadence if cfg else None,
        "probe_token": probe_token(),
        "streams": resolved,
        "pending": pending,
        "episodes": eps,
        "amp": amp,
    }


def reset():
    """Tests: disarm the checker, drop pending/resolved tables, anomaly
    history and fault-site bookkeeping (the provider registration
    survives)."""
    global _CONFIG, _VERSION, _nan_trips_seen, _NAN_INJECT_ROW, _last_poll
    with _LOCK:
        _CONFIG = None
        _VERSION += 1
        _PENDING.clear()
        _LATEST.clear()
        _nan_trips_seen = 0
        _NAN_INJECT_ROW = 0
        _last_poll = 0.0
    if _MONITOR is not None:
        _MONITOR.reset()
