"""Eager autograd: an op tape whose backward runs per-node ``jax.vjp``.

Reference analog: the eager engine (paddle/fluid/eager/) — codegen'd
GradNodes recorded per op, topologically executed by backward.cc.  The
TPU-native rebuild records, per differentiable eager op, the *pure jax
function* that produced the outputs plus its tensor inputs; ``backward``
walks the graph in reverse topological order calling ``jax.vjp`` on each
node's function.  No per-op grad kernels exist anywhere — jax derives them.

This is the correctness path for eager mode.  The performance path is
``@to_static``/Model.fit, which traces the whole step and takes ``jax.grad``
of the fused program (see paddle_tpu.jit) — there the tape is bypassed
entirely, exactly like the reference collapses dygraph into a static Program.

Note: per-node ``jax.vjp`` re-executes that node's forward (linearization),
so eager backward costs ~2x forward compute.  The reference pays an
analogous cost in per-op grad-kernel launches; under jit both collapse into
one fused XLA program.
"""

from __future__ import annotations

import weakref
from typing import Any, List, Sequence

import jax
import jax.numpy as jnp


class Node:
    """One recorded differentiable op.

    fn: pure function (jax arrays -> jax array or tuple of arrays)
    inputs: the op's positional args; Tensors are tracked, rest are consts
    kwargs: non-tensor keyword args (closed over at vjp time)
    outputs: weakrefs to produced Tensors (tuple ops have several)
    """

    __slots__ = ("fn", "inputs", "kwargs", "outputs", "name", "__weakref__")

    def __init__(self, fn, inputs: Sequence[Any], kwargs: dict, outputs, name: str = ""):
        self.fn = fn
        self.inputs = list(inputs)
        self.kwargs = kwargs
        self.outputs = [weakref.ref(o) for o in outputs]
        self.name = name or getattr(fn, "__name__", "op")

    def tensor_inputs(self):
        from ..tensor.tensor import Tensor

        return [(i, t) for i, t in enumerate(self.inputs) if isinstance(t, Tensor) and not t.stop_gradient]


def _topo_from(root_node) -> List[Node]:
    """Reverse-postorder (iterative; eager graphs can be deep)."""
    order, seen = [], set()
    stack = [(root_node, False)]
    while stack:
        node, done = stack.pop()
        if done:
            order.append(node)
            continue
        if id(node) in seen:
            continue
        seen.add(id(node))
        stack.append((node, True))
        for _, t in node.tensor_inputs():
            child = t._grad_node
            if child is not None and id(child) not in seen:
                stack.append((child, False))
    return order  # children before parents; iterate reversed for backward


def backward(tensors, grad_tensors=None, retain_graph=False, into=None,
             create_graph=False):
    """Run backward from ``tensors`` (paddle.autograd.backward semantics).

    Accumulates ``.grad`` on every reachable leaf tensor with
    ``stop_gradient=False``.  Non-leaf grads are kept only if the tensor
    called ``retain_grads()``.  If ``into`` (a dict) is given, grads are
    written there keyed by ``id(tensor)`` instead of touching ``.grad`` —
    used by :func:`grad` so it has no side effects on other leaves.

    ``create_graph=True`` runs every vjp THROUGH the dispatch layer, so the
    gradient computation is itself taped and differentiable (double grad —
    reference: paddle.grad(create_graph=True) via double-grad ops).
    """
    from ..tensor.tensor import Tensor

    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]

    # cotangent accumulator keyed by tensor identity; values are raw arrays
    # normally, Tensors when create_graph (so accumulation itself is taped)
    cts: dict[int, Any] = {}
    keep: dict[int, Tensor] = {}  # keep tensors alive during walk
    roots = []
    for t, g in zip(tensors, grad_tensors):
        if t.stop_gradient:
            raise RuntimeError("backward() on a tensor with stop_gradient=True")
        if create_graph:
            seed = g if isinstance(g, Tensor) else Tensor(
                g if g is not None else jnp.ones_like(t._value), stop_gradient=True)
        else:
            seed = g._value if isinstance(g, Tensor) else (
                g if g is not None else jnp.ones_like(t._value))
        cts[id(t)] = cts.get(id(t), 0) + seed
        keep[id(t)] = t
        if t._grad_node is not None:
            roots.append(t._grad_node)

    # merged topological order over all roots
    order, seen = [], set()
    for r in roots:
        for n in _topo_from(r):
            if id(n) not in seen:
                seen.add(id(n))
                order.append(n)

    hooked: set = set()
    _run_nodes(order, cts, keep, create_graph, hooked)

    # store accumulated grads on leaves (and retain_grads tensors), once
    for tid, t in keep.items():
        if tid not in cts:
            continue
        is_leaf = t._grad_node is None
        if (is_leaf and not t.stop_gradient) or getattr(t, "_retain_grads", False):
            g = cts[tid]
            if tid not in hooked:  # mid-walk application already ran once
                g = _apply_hooks(t, g)
            if into is not None:
                into[tid] = into[tid] + g if tid in into else g
            elif isinstance(g, Tensor):
                t.grad = g if t.grad is None else Tensor(t.grad._value + g._value,
                                                         stop_gradient=True)
            elif t.grad is None:
                t.grad = Tensor(g, stop_gradient=True)
            else:
                t.grad = Tensor(t.grad._value + g, stop_gradient=True)


def _apply_hooks(t, g):
    """Run Tensor.register_hook callbacks on a finalized gradient."""
    from ..tensor.tensor import Tensor

    hooks = getattr(t, "_grad_hooks", None)
    if not hooks:
        return g
    gt = g if isinstance(g, Tensor) else Tensor(g, stop_gradient=True)
    for h in hooks:
        out = h(gt)
        if out is not None:
            gt = out if isinstance(out, Tensor) else Tensor(out, stop_gradient=True)
    return gt if isinstance(g, Tensor) else gt._value


def _run_nodes(order, cts, keep, create_graph=False, hooked=None):
    """Execute vjps parents-first; accumulate cotangents into ``cts``.

    create_graph: route every vjp through dispatch.apply so the gradient
    computation is itself recorded on the tape (differentiable grads).
    ``hooked`` records tensors whose hooks ran here, so backward()'s final
    loop doesn't apply them a second time.
    """
    from ..tensor.tensor import Tensor

    for node in reversed(order):
        outs = [r() for r in node.outputs]
        out_cts = []
        have_any = False
        for o in outs:
            if o is not None and id(o) in cts:
                g = cts[id(o)]
                if getattr(o, "_grad_hooks", None) and (
                        o._grad_node is not None or not o.stop_gradient):
                    g = _apply_hooks(o, g)
                    cts[id(o)] = g
                    if hooked is not None:
                        hooked.add(id(o))
                out_cts.append(g)
                have_any = True
            else:
                out_cts.append(None)
        if not have_any:
            continue

        tin = node.tensor_inputs()
        if not tin:
            continue
        idxs = [i for i, _ in tin]

        def primal(*vs, _node=node, _idxs=idxs):
            args = list(_node.inputs)
            for i, v in zip(_idxs, vs):
                args[i] = v
            args = [a._value if isinstance(a, Tensor) else a for a in args]
            return _node.fn(*args, **_node.kwargs)

        n_in = len(tin)

        if create_graph:
            # taped gradient: (inputs..., cotangents...) -> input cotangents,
            # recorded through dispatch.apply so a second backward() works
            from ..tensor.dispatch import apply as _dispatch_apply

            ct_tensors = [c if isinstance(c, Tensor) else
                          (None if c is None else Tensor(c, stop_gradient=True))
                          for c in out_cts]
            present = [i for i, c in enumerate(ct_tensors) if c is not None]

            def grad_fn(*vals, _primal=primal, _present=tuple(present),
                        _n_in=n_in):
                tv = vals[:_n_in]
                cvs = vals[_n_in:]
                p_out, vjp_fn = jax.vjp(_primal, *tv)
                if isinstance(p_out, (tuple, list)):
                    it = iter(cvs)
                    ct_full = tuple(
                        next(it) if i in _present else _zero_cotangent(po)
                        for i, po in enumerate(p_out))
                else:
                    ct_full = cvs[0]
                res = vjp_fn(ct_full)
                return tuple(res) if len(res) > 1 else res[0]

            args = [t for _, t in tin] + [ct_tensors[i] for i in present]
            grads = _dispatch_apply(grad_fn, *args,
                                    op_name=f"grad_{node.name}", n_outs=None)
            in_cts = grads if isinstance(grads, tuple) else (grads,)
            for (_, t), g in zip(tin, in_cts):
                tid = id(t)
                keep[tid] = t
                cts[tid] = cts[tid] + g if tid in cts else g
            continue

        tvals = [t._value for _, t in tin]
        primal_out, vjp_fn = jax.vjp(primal, *tvals)
        if isinstance(primal_out, (tuple, list)):
            ct = tuple(
                c if c is not None else _zero_cotangent(po)
                for c, po in zip(out_cts, primal_out)
            )
        else:
            ct = out_cts[0]
        in_cts = vjp_fn(ct)

        for (_, t), g in zip(tin, in_cts):
            tid = id(t)
            keep[tid] = t
            cts[tid] = cts[tid] + g if tid in cts else g


def _zero_cotangent(po):
    """Zero cotangent matching jax.vjp's contract: float0 for non-inexact
    primal outputs (e.g. topk's index output)."""
    import numpy as np

    if hasattr(po, "dtype") and jnp.issubdtype(po.dtype, jnp.inexact):
        return jnp.zeros_like(po)
    return np.zeros(jnp.shape(po), dtype=jax.dtypes.float0)


def grad(outputs, inputs, grad_outputs=None, retain_graph=False, create_graph=False,
         only_inputs=True, allow_unused=False):
    """paddle.grad: return grads of ``outputs`` w.r.t. ``inputs`` with NO
    side effects on any tensor's ``.grad`` (grads flow into a private sink).
    ``create_graph=True`` returns grads that are themselves on the tape, so
    a second backward()/grad() differentiates through them (double grad).
    """
    from ..tensor.tensor import Tensor

    single_in = isinstance(inputs, Tensor)
    inputs = [inputs] if single_in else list(inputs)
    outputs = [outputs] if isinstance(outputs, Tensor) else list(outputs)
    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    elif isinstance(grad_outputs, Tensor):
        grad_outputs = [grad_outputs]

    retains = []
    for t in inputs:
        if t._grad_node is not None and not getattr(t, "_retain_grads", False):
            t._retain_grads = True
            retains.append(t)
    sink: dict = {}
    try:
        backward(outputs, grad_outputs, retain_graph=retain_graph, into=sink,
                 create_graph=create_graph)
        results = []
        for t in inputs:
            g = sink.get(id(t))
            if g is None:
                if not allow_unused:
                    raise RuntimeError("an input tensor is unused in the graph (allow_unused=False)")
                results.append(None)
            elif isinstance(g, Tensor):
                # create_graph: g is on the tape; keep its node for the
                # second-order backward
                results.append(g)
            else:
                results.append(Tensor(g, stop_gradient=True))
    finally:
        for t in retains:
            t._retain_grads = False
    return results[0] if single_in else results
