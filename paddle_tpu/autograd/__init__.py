"""Autograd public API (reference: python/paddle/autograd/)."""

from __future__ import annotations

import contextlib

from ..framework import state as _state
from .tape import backward, grad, Node  # noqa: F401


class no_grad(contextlib.ContextDecorator):
    """Context manager / decorator disabling gradient recording."""

    def __enter__(self):
        self._prev = _state.set_grad_enabled(False)
        return self

    def __exit__(self, *exc):
        _state.set_grad_enabled(self._prev)
        return False


class enable_grad(contextlib.ContextDecorator):
    def __enter__(self):
        self._prev = _state.set_grad_enabled(True)
        return self

    def __exit__(self, *exc):
        _state.set_grad_enabled(self._prev)
        return False


class set_grad_enabled(contextlib.ContextDecorator):
    def __init__(self, mode: bool):
        self._mode = mode
        self._prev = _state.set_grad_enabled(mode)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        _state.set_grad_enabled(self._prev)
        return False


def is_grad_enabled() -> bool:
    return _state.grad_enabled()


class PyLayerContext:
    """ctx passed to PyLayer.forward/backward (reference:
    python/paddle/autograd/py_layer.py)."""

    def __init__(self):
        self._saved = ()
        self.attrs = {}

    def save_for_backward(self, *tensors):
        self._saved = tensors

    @property
    def saved_tensor(self):
        return self._saved

    def saved_tensors(self):
        return self._saved


class PyLayerMeta(type):
    pass


class PyLayer(metaclass=PyLayerMeta):
    """User-defined autograd function; the TPU-native analog wires the user's
    backward as a custom VJP node on the eager tape (reference PyLayer records
    a GradNodePyLayer).  Subclass and define static ``forward(ctx, ...)`` and
    ``backward(ctx, *grads)``.
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *args):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        from ..tensor.tensor import Tensor
        from .tape import Node
        import jax.numpy as jnp

        ctx = PyLayerContext()
        with no_grad():
            out = cls.forward(ctx, *args, **kwargs)
        multi = isinstance(out, (tuple, list))
        outs = list(out) if multi else [out]

        tracked = [a for a in args if isinstance(a, Tensor) and not a.stop_gradient]
        if _state.grad_enabled() and tracked:
            # wrap the user's backward as the node function's vjp via
            # jax.custom_vjp so the standard tape machinery applies
            import jax

            t_idx = [i for i, a in enumerate(args) if isinstance(a, Tensor) and not a.stop_gradient]

            @jax.custom_vjp
            def fwd_fn(*tvals):
                return tuple(o._value for o in outs) if multi else outs[0]._value

            def fwd_rule(*tvals):
                return fwd_fn(*tvals), None

            def bwd_rule(_, cts):
                g = cts if multi else (cts,)
                gt = cls.backward(ctx, *[Tensor(c) for c in g])
                if not isinstance(gt, (tuple, list)):
                    gt = (gt,)
                vals = []
                for x in gt:
                    vals.append(x._value if isinstance(x, Tensor) else x)
                # align to tracked inputs only
                if len(vals) == len(args):
                    vals = [vals[i] for i in t_idx]
                return tuple(
                    v if v is not None else jnp.zeros_like(args[i]._value)
                    for v, i in zip(vals, t_idx)
                )

            fwd_fn.defvjp(fwd_rule, bwd_rule)

            new_outs = []
            for o in outs:
                t = Tensor(o._value, stop_gradient=False)
                new_outs.append(t)
            node = Node(fwd_fn, [args[i] for i in t_idx], {}, new_outs, name=cls.__name__)
            for t in new_outs:
                t._grad_node = node
            return tuple(new_outs) if multi else new_outs[0]
        return out
