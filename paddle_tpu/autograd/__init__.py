"""Autograd public API (reference: python/paddle/autograd/)."""

from __future__ import annotations

import contextlib

from ..framework import state as _state
from .tape import backward, grad, Node  # noqa: F401


class no_grad(contextlib.ContextDecorator):
    """Context manager / decorator disabling gradient recording."""

    def __enter__(self):
        self._prev = _state.set_grad_enabled(False)
        return self

    def __exit__(self, *exc):
        _state.set_grad_enabled(self._prev)
        return False


class enable_grad(contextlib.ContextDecorator):
    def __enter__(self):
        self._prev = _state.set_grad_enabled(True)
        return self

    def __exit__(self, *exc):
        _state.set_grad_enabled(self._prev)
        return False


class set_grad_enabled(contextlib.ContextDecorator):
    def __init__(self, mode: bool):
        self._mode = mode
        self._prev = _state.set_grad_enabled(mode)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        _state.set_grad_enabled(self._prev)
        return False


def is_grad_enabled() -> bool:
    return _state.grad_enabled()


class PyLayerContext:
    """ctx passed to PyLayer.forward/backward (reference:
    python/paddle/autograd/py_layer.py)."""

    def __init__(self):
        self._saved = ()
        self.attrs = {}

    def save_for_backward(self, *tensors):
        self._saved = tensors

    @property
    def saved_tensor(self):
        return self._saved

    def saved_tensors(self):
        return self._saved


class PyLayerMeta(type):
    pass


class PyLayer(metaclass=PyLayerMeta):
    """User-defined autograd function; the TPU-native analog wires the user's
    backward as a custom VJP node on the eager tape (reference PyLayer records
    a GradNodePyLayer).  Subclass and define static ``forward(ctx, ...)`` and
    ``backward(ctx, *grads)``.
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *args):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        from ..tensor.tensor import Tensor
        from .tape import Node
        import jax.numpy as jnp

        ctx = PyLayerContext()
        with no_grad():
            out = cls.forward(ctx, *args, **kwargs)
        multi = isinstance(out, (tuple, list))
        outs = list(out) if multi else [out]

        tracked = [a for a in args if isinstance(a, Tensor) and not a.stop_gradient]
        if _state.grad_enabled() and tracked:
            # wrap the user's backward as the node function's vjp via
            # jax.custom_vjp so the standard tape machinery applies
            import jax

            t_idx = [i for i, a in enumerate(args) if isinstance(a, Tensor) and not a.stop_gradient]

            @jax.custom_vjp
            def fwd_fn(*tvals):
                return tuple(o._value for o in outs) if multi else outs[0]._value

            def fwd_rule(*tvals):
                return fwd_fn(*tvals), None

            def bwd_rule(_, cts):
                g = cts if multi else (cts,)
                gt = cls.backward(ctx, *[Tensor(c) for c in g])
                if not isinstance(gt, (tuple, list)):
                    gt = (gt,)
                vals = []
                for x in gt:
                    vals.append(x._value if isinstance(x, Tensor) else x)
                # align to tracked inputs only
                if len(vals) == len(args):
                    vals = [vals[i] for i in t_idx]
                return tuple(
                    v if v is not None else jnp.zeros_like(args[i]._value)
                    for v, i in zip(vals, t_idx)
                )

            fwd_fn.defvjp(fwd_rule, bwd_rule)

            new_outs = []
            for o in outs:
                t = Tensor(o._value, stop_gradient=False)
                new_outs.append(t)
            node = Node(fwd_fn, [args[i] for i in t_idx], {}, new_outs, name=cls.__name__)
            for t in new_outs:
                t._grad_node = node
            return tuple(new_outs) if multi else new_outs[0]
        return out


# ---------------------------------------------------- functional autodiff
def _as_jax_fn(func):
    """Wrap a Tensor-in/Tensor-out callable as a jax-array function."""
    from ..tensor.tensor import Tensor

    def fn(*arrays):
        # jax does the differentiation; suppress the eager tape so the
        # trace doesn't record (and immediately discard) a Node per op
        with _state.no_grad_ctx():
            outs = func(*[Tensor(a) for a in arrays])
        if isinstance(outs, (tuple, list)):
            return tuple(o._value if isinstance(o, Tensor) else o
                         for o in outs)
        return outs._value if isinstance(outs, Tensor) else outs

    return fn


def _unwrap_all(xs):
    from ..tensor.tensor import Tensor

    single = not isinstance(xs, (tuple, list))
    vals = [x._value if isinstance(x, Tensor) else x
            for x in ([xs] if single else xs)]
    return vals, single


def _wrap_tree(tree):
    import jax

    from ..tensor.tensor import Tensor

    return jax.tree_util.tree_map(Tensor, tree)


def jacobian(func, xs, create_graph=False, allow_unused=False):
    """d func(xs) / d xs (reference: paddle.autograd's functional jacobian;
    func-based form — jax.jacrev does the work in one traced pass)."""
    import jax

    if create_graph:
        raise NotImplementedError(
            "create_graph=True: compose jax transforms instead (e.g. take "
            "jacobian inside the outer loss function)")
    vals, single = _unwrap_all(xs)
    argnums = 0 if single else tuple(range(len(vals)))
    out = jax.jacrev(_as_jax_fn(func), argnums=argnums)(*vals)
    return _wrap_tree(out)


def hessian(func, xs, create_graph=False, allow_unused=False):
    """d^2 func(xs) / d xs^2 for a scalar-output func (reference:
    functional hessian) — forward-over-reverse, one compiled program."""
    import jax

    if create_graph:
        raise NotImplementedError(
            "create_graph=True: compose jax transforms instead")
    vals, single = _unwrap_all(xs)
    argnums = 0 if single else tuple(range(len(vals)))
    out = jax.hessian(_as_jax_fn(func), argnums=argnums)(*vals)
    return _wrap_tree(out)


def vjp(func, xs, v=None):
    """(outputs, vjp_result): pull ``v`` back through func at xs
    (reference: paddle.autograd.functional.vjp)."""
    import jax
    import jax.numpy as jnp

    vals, single = _unwrap_all(xs)
    outs, pullback = jax.vjp(_as_jax_fn(func), *vals)
    if v is None:
        cot = jax.tree_util.tree_map(jnp.ones_like, outs)
    else:
        cot, _ = _unwrap_all(v)
        cot = cot[0] if not isinstance(outs, tuple) else tuple(cot)
    grads = pullback(cot)
    grads = grads[0] if single else grads
    return _wrap_tree(outs), _wrap_tree(grads)


def jvp(func, xs, v=None):
    """(outputs, jvp_result): push ``v`` forward through func at xs
    (reference: paddle.autograd.functional.jvp)."""
    import jax
    import jax.numpy as jnp

    vals, single = _unwrap_all(xs)
    if v is None:
        tangents = [jnp.ones_like(x) for x in vals]
    else:
        tangents, _ = _unwrap_all(v)
    outs, tangent_out = jax.jvp(_as_jax_fn(func), tuple(vals),
                                tuple(tangents))
    return _wrap_tree(outs), _wrap_tree(tangent_out)
