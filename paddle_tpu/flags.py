"""Typed, env-overridable global flag registry.

TPU-native analog of the reference's gflags-style ``FLAGS_*`` system
(reference: paddle/utils/flags.h, phi/core/flags.cc — ~300 C++ gflags settable
via env or ``paddle.set_flags``).  Here flags are a plain typed registry:
values come from (highest priority first) ``set_flags()`` calls, environment
variables named ``FLAGS_<name>``, then the registered default.  XLA-level
knobs are intentionally NOT mirrored here — they pass through ``XLA_FLAGS``
to the compiler, which is the idiomatic TPU channel.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict


class _Flag:
    __slots__ = ("name", "default", "type", "help", "_value", "_set")

    def __init__(self, name: str, default: Any, typ: Callable, help: str = ""):
        self.name = name
        self.default = default
        self.type = typ
        self.help = help
        self._value = None
        self._set = False

    def get(self):
        if self._set:
            return self._value
        env = os.environ.get("FLAGS_" + self.name)
        if env is not None:
            return self._parse(env)
        return self.default

    def set(self, value):
        self._value = self._parse(value)
        self._set = True

    def _parse(self, value):
        if self.type is bool and isinstance(value, str):
            return value.lower() in ("1", "true", "yes", "on")
        return self.type(value)


_REGISTRY: Dict[str, _Flag] = {}


def define_flag(name: str, default: Any, help: str = "", type: Callable | None = None):
    """Register a flag. ``type`` defaults to ``type(default)``."""
    typ = type or (default.__class__ if default is not None else str)
    _REGISTRY[name] = _Flag(name, default, typ, help)
    return _REGISTRY[name]


def get_flags(names=None) -> Dict[str, Any]:
    """Return {name: value}. ``names`` may be a str, list of str, or None (=all)."""
    if names is None:
        names = list(_REGISTRY)
    if isinstance(names, str):
        names = [names]
    out = {}
    for n in names:
        key = _canon(n)
        if key not in _REGISTRY:
            raise ValueError(f"unknown flag {n!r}")
        out[n] = _REGISTRY[key].get()
    return out


def _canon(name: str) -> str:
    # the reference spells flags both 'FLAGS_foo' (env style) and 'foo'
    return name[6:] if name.startswith("FLAGS_") else name


def set_flags(flags: Dict[str, Any]) -> None:
    """Set flags from a dict, e.g. ``set_flags({'check_nan_inf': True})``."""
    for k, v in flags.items():
        key = _canon(k)
        if key not in _REGISTRY:
            raise ValueError(f"unknown flag {k!r}")
        _REGISTRY[key].set(v)


def get_flag(name: str):
    return _REGISTRY[name].get()


# Core flags (the subset of the reference's ~300 that have TPU meaning).
define_flag("check_nan_inf", False, "check outputs of every op for nan/inf (debug)")
define_flag("cudnn_deterministic", False, "kept for API compat; XLA on TPU is deterministic by default")
define_flag("paddle_tpu_default_matmul_precision", "default",
            "jax matmul precision: default|high|highest")
define_flag("use_donated_buffers", True, "donate input buffers in compiled train steps")
define_flag("allocator_strategy", "xla", "API compat; memory is owned by the XLA runtime")
define_flag("eager_delete_tensor_gb", 0.0, "API compat no-op; XLA owns memory")
define_flag("init_allocated_mem", False, "API compat no-op")
define_flag("benchmark", False, "block on every op for timing (eager mode)")
