"""Recovery supervisor: close the loop from detected failure to resumed
training.

``distributed.elastic.ElasticSupervisor`` (the bare restart loop) restarts
on ANY exception with linear backoff and trusts the newest checkpoint.
This supervisor adds the three things a pod-scale deployment needs:

- **failure classification** (:func:`.retry.classify_failure`) — transient
  failures (preemption, collective timeout) burn a restart budget with
  capped, jittered exponential backoff; fatal ones (traced errors) surface
  immediately by default;
- **valid-checkpoint resume** — restore walks back over corrupt
  checkpoints (checksum manifests, :class:`.checkpoint
  .AsyncCheckpointManager.restore_latest_valid`) instead of crashing again
  on a half-written or bit-flipped newest step;
- **metrics** — ``resilience.restarts{kind=,supervisor=}`` and
  ``resilience.backoff_seconds`` land in the PR-1 registry so a dashboard
  shows a job that is *surviving* failures before anyone greps logs.
"""

from __future__ import annotations

import logging
import time

from ..profiler import metrics as _metrics
from .retry import RetryPolicy, classify_failure

logger = logging.getLogger("paddle_tpu.resilience")


def restart_metrics():
    """The (counter, histogram) pair every supervisor emits through."""
    return (_metrics.counter("resilience.restarts",
                             "supervisor restarts by failure kind"),
            _metrics.histogram("resilience.backoff_seconds",
                               "backoff slept before each restart"))


class RecoverySupervisor:
    """Run a resumable ``train_fn(start_step, state)`` with classified
    restart-on-failure over an :class:`~.checkpoint.AsyncCheckpointManager`.

    ``train_fn`` receives the step to resume from (0 on a fresh start) and
    the restored state (None on a fresh start); it should checkpoint
    through the same manager.  On a transient failure the supervisor backs
    off (jittered exponential, capped), reloads the newest *valid*
    checkpoint — falling back past corrupt ones — and calls it again.
    """

    def __init__(self, manager, policy=None, max_transient_restarts=5,
                 max_fatal_restarts=0, max_numeric_restarts=2,
                 on_restart=None, to_tensors=True):
        self.manager = manager
        self.policy = policy if policy is not None \
            else RetryPolicy(base_delay=1.0, max_delay=30.0, jitter=0.5)
        self.max_transient_restarts = int(max_transient_restarts)
        self.max_fatal_restarts = int(max_fatal_restarts)
        # NumericFault (ISSUE 13): a poisoned step is not transient (a
        # blind retry of the same step replays the NaN) but rollback to
        # the last VALID checkpoint usually is recoverable — its own
        # small budget
        self.max_numeric_restarts = int(max_numeric_restarts)
        self.on_restart = on_restart   # fn(kind, exc, attempt) — test hook
        self.to_tensors = to_tensors
        self.restarts = {"transient": 0, "fatal": 0}
        self._m_restarts, self._m_backoff = restart_metrics()

    def run(self, train_fn):
        while True:
            try:
                # drain the crashed run's still-queued async saves BEFORE
                # choosing the resume point: a save committing after the
                # restore would plant a newer checkpoint from the abandoned
                # timeline, and a later failure would resume past the
                # segment just retrained (non-monotonic resume)
                if hasattr(self.manager, "wait_until_finished"):
                    try:
                        self.manager.wait_until_finished()
                    except Exception:
                        pass  # writer failure: restore falls back anyway
                step, state = self.manager.restore_latest_valid(
                    to_tensors=self.to_tensors)
                return train_fn(int(step) if step is not None else 0, state)
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException as e:
                kind = classify_failure(e)
                # "numeric" appears lazily so pre-existing call sites that
                # compare the dict literally keep seeing {transient, fatal}
                self.restarts[kind] = self.restarts.get(kind, 0) + 1
                budget = {"transient": self.max_transient_restarts,
                          "numeric": self.max_numeric_restarts,
                          }.get(kind, self.max_fatal_restarts)
                if self.restarts[kind] > budget:
                    logger.error(
                        "[resilience] %s failure #%d exceeds budget %d; "
                        "surfacing", kind, self.restarts[kind], budget)
                    raise
                attempt = sum(self.restarts.values())
                delay = self.policy.delay(attempt)
                self._m_restarts.inc(kind=kind, supervisor="recovery")
                self._m_backoff.observe(delay)
                logger.warning(
                    "[resilience] %s failure (%r): restart %d/%d after "
                    "%.2fs backoff, resuming from latest valid checkpoint",
                    kind, e, self.restarts[kind], budget, delay)
                if self.on_restart is not None:
                    self.on_restart(kind, e, attempt)
                time.sleep(delay)
