"""Async checkpointing with atomic commit, checksum manifests, and
corruption fallback — the save half of the recovery loop.

Layered on :mod:`paddle_tpu.io.checkpoint`'s manifest protocol
(``write_manifest`` / ``verify_manifest``): each checkpoint is a directory

.. code-block:: text

    <dir>/step_00000012/
        tree.json      # pytree structure + scalar leaves
        arrays.npz     # every array leaf, host-side
        manifest.json  # sha256 + byte counts over both (written LAST)

written under a ``.tmp-<pid>`` name and renamed into place only after the
manifest is fsynced — a crash mid-save leaves a ``.tmp`` orphan (garbage-
collected, never restored from), and a committed directory that later
fails its checksums is QUARANTINED and restore falls back to the previous
valid step instead of feeding corrupt weights to the optimizer.

``save()`` snapshots the state to host numpy immediately (the training
loop may donate/mutate device arrays right after) and hands the disk work
to one background writer thread, so steady-state checkpointing costs the
train loop a host copy, not an fsync.  ``save_emergency()`` is the
synchronous spelling the SIGTERM / watchdog hooks use
(:mod:`.emergency`).
"""

from __future__ import annotations

import json
import logging
import os
import re
import shutil
import threading
import time

import numpy as np

from ..io.checkpoint import verify_manifest, write_manifest
from ..profiler import metrics as _metrics
from ..tensor.tensor import Tensor

logger = logging.getLogger("paddle_tpu.resilience")

_STEP_RE = re.compile(r"^step_(\d{8})$")
_TREE_SCHEMA = "paddle_tpu.resilience.checkpoint.v1"


# ----------------------------------------------------------- pytree <-> disk
def _snapshot(tree, arrays):
    """State pytree -> JSON-able structure; array leaves become host numpy
    copies keyed into ``arrays`` (the copy is the async-safety boundary:
    the caller may mutate/donate its arrays the moment save() returns)."""
    if isinstance(tree, Tensor):
        tree = tree._value
    if hasattr(tree, "shape") and hasattr(tree, "dtype") \
            and not isinstance(tree, (np.generic,)):
        key = f"a{len(arrays)}"
        arrays[key] = np.array(tree)  # np.array copies; np.asarray may alias
        return {"__array__": key}
    if isinstance(tree, np.generic):
        return {"__scalar__": tree.item(), "__dtype__": str(tree.dtype)}
    if isinstance(tree, dict):
        bad = [k for k in tree if not isinstance(k, str)]
        if bad:
            raise TypeError(
                f"checkpoint dict keys must be str (JSON round-trip would "
                f"silently stringify {bad[:3]!r}); convert keys explicitly")
        return {"__dict__": {k: _snapshot(v, arrays)
                             for k, v in tree.items()}}
    if isinstance(tree, list):
        return {"__list__": [_snapshot(v, arrays) for v in tree]}
    if isinstance(tree, tuple):
        return {"__tuple__": [_snapshot(v, arrays) for v in tree]}
    if isinstance(tree, (bool, int, float, str, type(None))):
        return tree
    raise TypeError(
        f"unsupported checkpoint leaf {type(tree).__name__}; state must be "
        "a pytree of Tensors/arrays/scalars/str (nest dicts/lists/tuples)")


def _rebuild(node, arrays, to_tensors):
    if isinstance(node, dict):
        if "__array__" in node:
            v = arrays[node["__array__"]]
            return Tensor(v) if to_tensors else v
        if "__scalar__" in node:
            return np.dtype(node["__dtype__"]).type(node["__scalar__"])
        if "__dict__" in node:
            return {k: _rebuild(v, arrays, to_tensors)
                    for k, v in node["__dict__"].items()}
        if "__list__" in node:
            return [_rebuild(v, arrays, to_tensors) for v in node["__list__"]]
        if "__tuple__" in node:
            return tuple(_rebuild(v, arrays, to_tensors)
                         for v in node["__tuple__"])
    return node


class CheckpointCorruptionError(RuntimeError):
    """Every on-disk checkpoint failed its checksum manifest."""


class AsyncCheckpointManager:
    """Background-writing, checksum-verified checkpoint rotation.

    API mirrors :class:`paddle_tpu.io.checkpoint.CheckpointManager` (save
    every K steps, keep the last N, resume from the latest) with the
    resilience extensions: ``save`` returns before the disk write,
    ``restore_latest_valid`` skips — and quarantines — corrupt steps, and
    ``save_emergency`` is the synchronous crash-path spelling.
    """

    def __init__(self, directory, max_to_keep=5, save_interval_steps=1,
                 queue_depth=2):
        self.directory = os.path.abspath(str(directory))
        os.makedirs(self.directory, exist_ok=True)
        self.max_to_keep = int(max_to_keep) if max_to_keep else None
        self.save_interval_steps = max(int(save_interval_steps), 1)
        self._queue_depth = max(int(queue_depth), 1)
        self._pending = []           # [(step, structure, arrays)]
        self._cv = threading.Condition()
        self._busy = False           # writer mid-checkpoint
        self._stop = False
        self._error = None           # first writer failure, surfaced on wait
        self._write_lock = threading.Lock()   # writer thread vs emergency
        self._thread = None
        self._m_saves = _metrics.counter(
            "resilience.checkpoint_saves", "committed checkpoints by kind")
        self._m_save_seconds = _metrics.histogram(
            "resilience.checkpoint_save_seconds",
            "snapshot-to-commit latency of one checkpoint")
        self._m_dropped = _metrics.counter(
            "resilience.checkpoint_saves_dropped",
            "queued saves dropped because the writer fell behind")
        self._m_corrupt = _metrics.counter(
            "resilience.checkpoint_corruptions",
            "checkpoints quarantined after failing their manifest")
        self._register_memory()
        self._gc_partials()

    def _register_memory(self):
        """Ledger owner ``checkpoint.snapshot`` (observability/memory.py):
        queued-but-unwritten snapshots are host numpy, not HBM, so the row
        registers with ``device="host"`` — visible in the owner table,
        excluded from the ``jax.live_arrays()`` reconciliation."""
        import weakref

        from ..observability import memory as _obs_memory

        ref = weakref.ref(self)

        def src():
            mgr = ref()
            if mgr is None:
                return None
            return sum(int(a.nbytes) for _, _, arrays in mgr._pending
                       for a in arrays)
        _obs_memory.ledger().register(
            "checkpoint.snapshot", src, replica="-", device="host",
            meta={"kind": "checkpoint"})

    # ------------------------------------------------------------- locations
    def _step_dir(self, step):
        return os.path.join(self.directory, f"step_{int(step):08d}")

    def all_steps(self):
        """Committed steps (ascending).  Commit = the directory rename
        happened; validity (checksums) is checked at restore time."""
        out = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        for n in names:
            m = _STEP_RE.match(n)
            if m and os.path.isdir(os.path.join(self.directory, n)):
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_step(self):
        steps = self.all_steps()
        return steps[-1] if steps else None

    def verify(self, step):
        """(ok, problems) for one committed step's manifest."""
        return verify_manifest(self._step_dir(step))

    def valid_steps(self):
        return [s for s in self.all_steps() if self.verify(s)[0]]

    # ------------------------------------------------------------------ save
    def save(self, step, state, force=False, block=False):
        """Snapshot ``state`` to host and queue the disk write.  Returns
        True when a save was scheduled (False: off-interval step, or an
        older queued save was superseded by this one under backlog)."""
        step = int(step)
        if not force and step % self.save_interval_steps:
            return False
        arrays = {}
        structure = _snapshot(state, arrays)
        with self._cv:
            if self._error is not None:
                err, self._error = self._error, None
                raise RuntimeError("previous async checkpoint failed") from err
            while len(self._pending) >= self._queue_depth:
                # writer fell behind: the OLDEST queued save is the least
                # useful one — drop it rather than stall the train loop
                dropped_step, _, _ = self._pending.pop(0)
                self._m_dropped.inc()
                logger.warning(
                    "async checkpoint writer behind: dropped queued save of "
                    "step %d (step %d supersedes it)", dropped_step, step)
            self._pending.append((step, structure, arrays))
            self._ensure_thread()
            self._cv.notify_all()
        if block:
            self.wait_until_finished()
        return True

    def save_emergency(self, step, state, reason="emergency",
                       from_signal=False):
        """Synchronous save on the crash path (SIGTERM, watchdog fire):
        snapshot + write + commit before returning, bypassing the queue.
        Never raises — the emergency path must not mask the original
        failure — and BOUNDS its wait on the writer lock (the caller may
        be a signal handler; waiting forever on a wedged writer thread
        would keep the dying process alive).  ``from_signal`` additionally
        skips logging and metric locks: the interrupted frame may hold
        them, and blocking there would deadlock the dying process (the
        PR-3 flight-recorder signal-path rule).  Returns the committed
        path or None."""
        try:
            arrays = {}
            structure = _snapshot(state, arrays)
            path = self._write(int(step), structure, arrays, kind=reason,
                               lock_timeout=10.0,
                               record_metrics=not from_signal)
            return path
        except Exception:
            if not from_signal:
                logger.exception("emergency checkpoint of step %s failed",
                                 step)
            return None

    def wait_until_finished(self):
        """Block until every queued save committed; re-raise the first
        writer failure if one happened."""
        with self._cv:
            while self._pending or self._busy:
                self._cv.wait(timeout=0.05)
            if self._error is not None:
                err, self._error = self._error, None
                raise RuntimeError("async checkpoint failed") from err

    def close(self):
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        try:
            self.wait_until_finished()
        finally:
            self.close()

    # ---------------------------------------------------------------- writer
    def _ensure_thread(self):
        if self._thread is None or not self._thread.is_alive():
            self._stop = False
            self._thread = threading.Thread(
                target=self._writer_loop, name="paddle-ckpt-writer",
                daemon=True)
            self._thread.start()

    def _writer_loop(self):
        while True:
            with self._cv:
                while not self._pending and not self._stop:
                    self._cv.wait(timeout=0.1)
                if self._stop and not self._pending:
                    return
                step, structure, arrays = self._pending.pop(0)
                self._busy = True
            try:
                self._write(step, structure, arrays, kind="async")
            except Exception as e:
                logger.exception("async checkpoint of step %d failed", step)
                with self._cv:
                    self._error = e
            finally:
                with self._cv:
                    self._busy = False
                    self._cv.notify_all()

    def _write(self, step, structure, arrays, kind, lock_timeout=None,
               record_metrics=True):
        t0 = time.perf_counter()
        final = self._step_dir(step)
        tmp = f"{final}.tmp-{os.getpid()}-{threading.get_ident()}"
        if lock_timeout is not None:
            if not self._write_lock.acquire(timeout=lock_timeout):
                raise TimeoutError(
                    f"checkpoint writer lock not acquired within "
                    f"{lock_timeout}s (emergency save path)")
        else:
            self._write_lock.acquire()
        try:
            if os.path.isdir(tmp):
                shutil.rmtree(tmp, ignore_errors=True)
            os.makedirs(tmp)
            with open(os.path.join(tmp, "arrays.npz"), "wb") as f:
                np.savez(f, **arrays)
                f.flush()
                os.fsync(f.fileno())
            with open(os.path.join(tmp, "tree.json"), "w") as f:
                json.dump({"schema": _TREE_SCHEMA, "step": step,
                           "tree": structure}, f)
                f.flush()
                os.fsync(f.fileno())
            # manifest last: its presence certifies a complete write
            write_manifest(tmp, step=step, kind=kind, time=time.time())
            if os.path.isdir(final):
                shutil.rmtree(final, ignore_errors=True)
            os.replace(tmp, final)
            self._fsync_dir(self.directory)
        finally:
            self._write_lock.release()
        if record_metrics:  # skipped on the signal path: no metric locks
            self._m_saves.inc(kind=kind)
            self._m_save_seconds.observe(time.perf_counter() - t0)
        self._gc()
        return final

    @staticmethod
    def _fsync_dir(path):
        try:
            fd = os.open(path, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        except OSError:
            pass  # not all filesystems allow directory fsync

    # ------------------------------------------------------------------- gc
    def _gc_partials(self):
        """Drop orphaned partial saves (``step_*.tmp-*``) — a previous
        process died mid-write; these were never committed and must never
        shadow a real checkpoint or leak disk.  Called ONLY at manager
        startup, when no writer can be mid-save: a post-commit sweep would
        race a concurrent emergency save's in-progress tmp directory and
        delete the checkpoint at exactly the moment it was needed."""
        try:
            names = os.listdir(self.directory)
        except OSError:
            return
        for n in names:
            if ".tmp-" in n and n.startswith("step_"):
                shutil.rmtree(os.path.join(self.directory, n),
                              ignore_errors=True)

    def _gc(self):
        if not self.max_to_keep:
            return
        steps = self.all_steps()
        for s in steps[:-self.max_to_keep]:
            shutil.rmtree(self._step_dir(s), ignore_errors=True)

    # --------------------------------------------------------------- restore
    def _quarantine(self, step, problems):
        src = self._step_dir(step)
        dst = f"{src}.corrupt-{int(time.time())}"
        logger.error(
            "checkpoint step %d failed its manifest (%s); quarantined to %s",
            step, "; ".join(problems), dst)
        try:
            os.replace(src, dst)
        except OSError:
            shutil.rmtree(src, ignore_errors=True)
        self._m_corrupt.inc()

    def _read(self, step, to_tensors):
        d = self._step_dir(step)
        with open(os.path.join(d, "tree.json")) as f:
            doc = json.load(f)
        with np.load(os.path.join(d, "arrays.npz")) as z:
            arrays = {k: z[k] for k in z.files}
        return _rebuild(doc["tree"], arrays, to_tensors)

    def restore(self, step=None, to_tensors=True):
        """Restore one step (default latest committed), verifying its
        manifest first.  Raises :class:`CheckpointCorruptionError` if that
        step is corrupt — use :meth:`restore_latest_valid` for automatic
        fallback.  Returns None when no checkpoint exists."""
        if step is None:
            step = self.latest_step()
            if step is None:
                return None
        ok, problems = self.verify(step)
        if not ok:
            raise CheckpointCorruptionError(
                f"checkpoint step {step} failed verification: "
                f"{'; '.join(problems)}")
        return self._read(int(step), to_tensors)

    def restore_latest_valid(self, to_tensors=True):
        """Newest checkpoint that passes its checksum manifest, walking
        backwards over corrupt ones (each is quarantined so the next
        attempt doesn't re-verify it).  Returns ``(step, state)`` or
        ``(None, None)`` when nothing restorable exists."""
        for step in reversed(self.all_steps()):
            ok, problems = self.verify(step)
            if not ok:
                self._quarantine(step, problems)
                continue
            try:
                return step, self._read(step, to_tensors)
            except Exception as e:  # unreadable despite manifest: quarantine
                self._quarantine(step, [repr(e)])
        return None, None
