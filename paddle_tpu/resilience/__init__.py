"""paddle_tpu.resilience — close the loop from detected failure to
recovery (this PR's tentpole; PR-3 gave the system eyes, this gives it
reflexes).

Four pillars:

- :mod:`.checkpoint` — :class:`AsyncCheckpointManager`: background save
  thread, atomic directory commit with a sha256 manifest
  (:func:`paddle_tpu.io.checkpoint.write_manifest`), partial-save garbage
  collection, quarantine-and-fall-back restore
  (:meth:`~.checkpoint.AsyncCheckpointManager.restore_latest_valid`);
- :mod:`.retry` + :mod:`.supervisor` — failure classification
  (transient preemption/collective-timeout vs fatal traced error),
  capped + jittered exponential backoff with a retry budget, and
  :class:`RecoverySupervisor` resuming from the newest *valid* checkpoint;
- :mod:`.emergency` — :func:`arm_emergency_checkpoint`: synchronous
  save triggered by SIGTERM (preemption notice) or a PR-3 watchdog fire;
- :mod:`.chaos` — the chaos harness: :func:`corrupt_checkpoint` (real
  on-disk damage for the manifest fallback path) and :func:`run_smoke`
  (the ``bench.py --chaos-smoke`` run), driving
  :class:`paddle_tpu.observability.faults.FaultPlan` fault plans.

Serving-side resilience (health state machine, load shedding, engine
auto-restart with in-flight requeue) lives in
:mod:`paddle_tpu.serving.engine` and reuses :mod:`.retry`'s
classification.  Metrics: ``resilience.restarts``,
``resilience.backoff_seconds``, ``resilience.checkpoint_saves``,
``resilience.checkpoint_corruptions``, ``resilience.emergency_saves``.
"""

from __future__ import annotations

from . import chaos, checkpoint, emergency, retry, supervisor  # noqa: F401
from .checkpoint import (  # noqa: F401
    AsyncCheckpointManager, CheckpointCorruptionError,
)
from .chaos import corrupt_checkpoint, run_smoke  # noqa: F401
from .emergency import arm_emergency_checkpoint  # noqa: F401
from .retry import (  # noqa: F401
    CollectiveTimeoutError, EngineStoppedError, NumericFault, PreemptionError,
    RetryPolicy, TransientError, classify_failure,
)
from .supervisor import RecoverySupervisor  # noqa: F401

__all__ = [
    "checkpoint", "retry", "supervisor", "emergency", "chaos",
    "AsyncCheckpointManager", "CheckpointCorruptionError",
    "RecoverySupervisor", "RetryPolicy", "classify_failure",
    "TransientError", "PreemptionError", "CollectiveTimeoutError",
    "EngineStoppedError", "NumericFault", "arm_emergency_checkpoint",
    "corrupt_checkpoint",
    "run_smoke",
]
