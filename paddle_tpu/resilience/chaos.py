"""Chaos harness: drive real workloads through injected failures and
measure that they recover.

Building blocks:

- :class:`paddle_tpu.observability.faults.FaultPlan` — seeded,
  deterministic fault plans (probabilistic + scheduled injection, scoped
  arming) over the instrumented sites (``collective_hang``,
  ``serving.scheduler_wedge``, ``serving.step_crash``, ``chaos.train_step``);
- :func:`corrupt_checkpoint` — flip or truncate bytes in a committed
  checkpoint so the checksum-manifest fallback path is exercised with real
  on-disk damage, not a mocked verifier;
- :func:`run_smoke` — the ``bench.py --chaos-smoke`` body: a short
  deterministic train loop that takes a transient failure mid-run *and* a
  corrupted newest checkpoint, recovers through
  :class:`~.supervisor.RecoverySupervisor`, and reports what happened.

The chaos test suite (``tests/test_chaos.py``, marker ``chaos``) drives
the same machinery plus a serving workload; ``run_smoke`` keeps a
single-command reproduction around for benches and operators.
"""

from __future__ import annotations

import os
import tempfile
import time

from .checkpoint import _STEP_RE  # the checkpoint-dir naming scheme


def corrupt_checkpoint(directory, step=None, mode="flip", nbytes=64,
                       filename="arrays.npz"):
    """Damage a committed checkpoint in place (chaos testing only).

    ``directory`` is a checkpoint root (or an ``AsyncCheckpointManager`` —
    a bare path is scanned directly, NOT wrapped in a new manager: a
    manager's startup partial-save GC would race a live writer's
    in-flight tmp directory).  ``mode="flip"`` XORs ``nbytes`` bytes in
    the middle of ``filename``; ``mode="truncate"`` cuts the file in
    half.  Either way the manifest checksum no longer matches, which is
    exactly what ``restore_latest_valid`` must detect.  Returns the
    damaged file path."""
    root = getattr(directory, "directory", None) or os.path.abspath(
        str(directory))
    if step is None:
        steps = [int(m.group(1)) for m in map(_STEP_RE.match,
                                              os.listdir(root)) if m]
        if not steps:
            raise FileNotFoundError(f"no committed checkpoint under {root}")
        step = max(steps)
    path = os.path.join(root, f"step_{int(step):08d}", filename)
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        if mode == "truncate":
            f.truncate(max(size // 2, 1))
        elif mode == "flip":
            off = max(size // 2 - nbytes // 2, 0)
            f.seek(off)
            chunk = f.read(min(nbytes, size - off))
            f.seek(off)
            f.write(bytes(b ^ 0xFF for b in chunk))
        else:
            raise ValueError(f"unknown corruption mode {mode!r}")
        f.flush()
        os.fsync(f.fileno())
    return path


def run_smoke(total_steps=6, fail_at=3, directory=None, seed=0):
    """Short end-to-end chaos run (the ``bench.py --chaos-smoke`` section).

    Trains a tiny deterministic MLP, checkpointing every step through
    :class:`~.checkpoint.AsyncCheckpointManager`.  A seeded
    :class:`FaultPlan` raises a :class:`~.retry.CollectiveTimeoutError` at
    step ``fail_at`` AND corrupts the newest on-disk checkpoint first, so
    recovery must classify the failure as transient, detect the corruption
    via the checksum manifest, fall back to the previous valid step, and
    still reach ``total_steps``.  Returns a JSON-able report; raises if
    any recovery invariant fails (a bench run with a broken resilience
    stack should fail loudly, not report a green smoke)."""
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as opt
    from ..observability import faults
    from .checkpoint import AsyncCheckpointManager
    from .retry import CollectiveTimeoutError, RetryPolicy
    from .supervisor import RecoverySupervisor

    tmp = None
    if directory is None:
        tmp = tempfile.TemporaryDirectory(prefix="paddle_chaos_smoke_")
        directory = tmp.name
    t_start = time.perf_counter()
    mgr = None
    try:
        mgr = AsyncCheckpointManager(directory, max_to_keep=3)
        losses = {}

        def build():
            paddle.seed(0)
            m = nn.Sequential(nn.Linear(16, 32), nn.Tanh(), nn.Linear(32, 4))
            o = opt.Momentum(learning_rate=0.05, momentum=0.9,
                             parameters=m.parameters())
            return m, o

        rs = np.random.RandomState(7)
        x = paddle.to_tensor(rs.randn(32, 16).astype("float32"))
        y = paddle.to_tensor(rs.randint(0, 4, (32,)).astype("int64"))
        lossf = nn.CrossEntropyLoss()

        def train_fn(start, state):
            m, o = build()
            if state is not None:
                m.set_state_dict(state["model"])
                o.set_state_dict(state["opt"])
            for step in range(start, total_steps):
                faults.maybe("chaos.train_step")
                loss = lossf(m(x), y)
                loss.backward()
                o.step()
                o.clear_grad()
                losses[step] = float(loss)
                mgr.save(step + 1,
                         {"model": m.state_dict(), "opt": o.state_dict()},
                         block=True)
            return losses

        def sabotage():
            # damage the newest committed checkpoint, then die "transiently"
            corrupt_checkpoint(mgr)
            raise CollectiveTimeoutError(
                f"chaos-smoke: injected collective timeout at step {fail_at}")

        plan = faults.FaultPlan(seed=seed).add(
            "chaos.train_step", fn=sabotage, at_trips={fail_at + 1})
        sup = RecoverySupervisor(
            mgr, policy=RetryPolicy(base_delay=0.01, max_delay=0.05, seed=seed),
            max_transient_restarts=2)
        with plan:
            sup.run(train_fn)
        mgr.wait_until_finished()

        fallback_step = fail_at - 1  # corrupt step quarantined, resumed 1 back
        if sorted(losses) != list(range(total_steps)):
            raise RuntimeError(f"chaos smoke did not cover every step: "
                               f"{sorted(losses)}")
        if sup.restarts["transient"] != 1:
            raise RuntimeError(
                f"expected exactly 1 transient restart, got {sup.restarts}")
        # the invariant this smoke exists to guard: the damaged checkpoint
        # was caught by its MANIFEST and quarantined (measured, not assumed)
        quarantined = sum(1 for n in os.listdir(directory)
                          if ".corrupt-" in n)
        if quarantined != 1:
            raise RuntimeError(
                f"expected exactly 1 quarantined corrupt checkpoint, found "
                f"{quarantined} under {directory}")
        from ..profiler import metrics as _metrics

        return {
            "completed_steps": total_steps,
            "injected_failure_at_step": fail_at,
            "transient_restarts": sup.restarts["transient"],
            "resumed_from_step": fallback_step,
            "corrupt_checkpoints_quarantined": quarantined,
            "final_loss": losses[total_steps - 1],
            "checkpoint_saves": _metrics.counter(
                "resilience.checkpoint_saves").total(),
            "elapsed_s": round(time.perf_counter() - t_start, 3),
        }
    finally:
        if mgr is not None:
            mgr.close()  # writer thread must not outlive the smoke
        if tmp is not None:
            tmp.cleanup()
