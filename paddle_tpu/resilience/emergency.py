"""Emergency checkpointing: save state the moment the process learns it is
dying or wedged.

Two triggers, both landing in :meth:`AsyncCheckpointManager.save_emergency`
(synchronous, bypassing the writer queue, never raising):

- **SIGTERM** (preemption notice) — a chained handler in the style of the
  flight recorder's crash handlers: save, then re-deliver to the previous
  disposition so the process still dies with the right signal;
- **watchdog fires** — :mod:`paddle_tpu.observability.watchdog` notifies
  registered fire listeners when a collective or the serving scheduler
  exceeds its deadline; a hung job's last good state gets persisted while
  the flight recorder captures the forensics.

``arm_emergency_checkpoint(manager, state_fn)`` wires both and returns a
``disarm()`` callable.  ``state_fn() -> (step, state)`` is called at
trigger time, so it must be cheap and must not touch the device (host-side
mirrors of the state, e.g. the pytree the train loop last checkpointed).
"""

from __future__ import annotations

import logging
import os
import signal as _signal
import threading

from ..profiler import metrics as _metrics

logger = logging.getLogger("paddle_tpu.resilience")


def _saves_counter():
    return _metrics.counter("resilience.emergency_saves",
                            "emergency checkpoints by trigger")


def arm_emergency_checkpoint(manager, state_fn, signals=("SIGTERM",),
                             on_watchdog=True):
    """Arm emergency checkpointing.  Returns ``disarm()``.

    ``signals`` chain handlers (main thread only — from a worker thread the
    signal leg is skipped, matching the flight recorder's contract);
    ``on_watchdog`` registers a watchdog fire listener.  Each trigger saves
    at most once per (trigger, step): a watchdog re-firing on the same
    wedge doesn't rewrite the same checkpoint forever."""
    from ..observability import watchdog as _watchdog

    m_saves = _saves_counter()
    seen: set = set()
    lock = threading.Lock()
    disarmed = threading.Event()

    def save(trigger, from_signal=False):
        if disarmed.is_set():
            return None
        try:
            step, state = state_fn()
        except Exception:
            if not from_signal:  # logging locks are off-limits in a handler
                logger.exception("emergency state_fn failed (trigger=%s)",
                                 trigger)
            return None
        # signal-path discipline (the PR-3 flight-recorder rule): this may
        # run INSIDE a signal handler on the main thread, where blocking on
        # a lock the interrupted frame holds would deadlock the dying
        # process.  Non-blocking: a nested re-delivered signal (or a
        # concurrent watchdog fire) just skips — the holder is already
        # saving.
        if not lock.acquire(blocking=False):
            return None
        try:
            key = (trigger, int(step))
            if key in seen:
                return None
            seen.add(key)
        finally:
            lock.release()
        path = manager.save_emergency(step, state, reason=trigger,
                                      from_signal=from_signal)
        if path is not None and not from_signal:
            # metric + logging locks only OFF the signal path — the
            # interrupted frame may hold either (PR-3 signal-path rule)
            m_saves.inc(trigger=trigger)
            logger.error("emergency checkpoint (trigger=%s) committed: %s",
                         trigger, path)
        return path

    listener = None
    if on_watchdog:
        def listener(kind, record):  # noqa: F811 — the armed closure
            save(f"watchdog_{kind}")

        _watchdog.add_fire_listener(listener)

    installed = []
    if threading.current_thread() is threading.main_thread():
        for name in signals:
            sig = getattr(_signal, name, None)
            if sig is None:
                continue
            try:
                prev = _signal.getsignal(sig)

                def _handler(signum, frame, _prev=prev):
                    save(f"signal_{_signal.Signals(signum).name}",
                         from_signal=True)
                    if _prev == _signal.SIG_IGN:
                        return
                    if callable(_prev) and _prev != _signal.SIG_DFL:
                        _prev(signum, frame)
                    else:
                        _signal.signal(signum, _signal.SIG_DFL)
                        os.kill(os.getpid(), signum)

                _signal.signal(sig, _handler)
                installed.append((sig, prev))
            except (ValueError, OSError):
                pass
    elif signals:
        logger.warning(
            "arm_emergency_checkpoint called off the main thread: signal "
            "handlers skipped (watchdog trigger still armed)")

    def disarm():
        disarmed.set()
        if listener is not None:
            _watchdog.remove_fire_listener(listener)
        for sig, prev in installed:
            try:
                _signal.signal(sig, prev)
            except (ValueError, OSError):
                pass

    return disarm
