"""Failure classification + retry backoff (the recovery supervisor's policy
layer).

Large TPU deployments live with two very different failure populations:

- **transient** — a preempted host, a collective that timed out because a
  neighbor was being rescheduled, a dropped coordination-service socket.
  The correct response is restart-from-checkpoint with backoff; the job is
  healthy, the world briefly wasn't.
- **fatal** — a traced shape error, a NaN guard, an assertion in user
  code.  Restarting replays the same crash forever; the correct response
  is to surface it immediately.

:func:`classify_failure` encodes that split (type-based for our own error
hierarchy, message-pattern-based for errors that bubble out of the jax
runtime), and :class:`RetryPolicy` is exponential backoff with a max-delay
cap and seeded jitter — deterministic under test, decorrelated in a real
pod where every host restarting on the same beat would thundering-herd the
coordination service.
"""

from __future__ import annotations

import random
import zlib


class TransientError(RuntimeError):
    """Base for failures worth an automatic restart (preemption, flaky
    host, collective timeout).  Raise (or wrap into) one of these to tell
    the supervisors a retry is expected to succeed."""


class PreemptionError(TransientError):
    """The scheduler is taking the host/slice back (SIGTERM with notice,
    maintenance event)."""


class CollectiveTimeoutError(TransientError):
    """A collective exceeded its deadline — the canonical symptom of one
    rank dying mid-allreduce (the watchdog names the op; this error is what
    recovery acts on)."""


class EngineStoppedError(RuntimeError):
    """A serving request failed because its engine was stopped with the
    request still in flight (``ServingEngine.stop()`` without drain)."""


class NumericFault(RuntimeError):
    """Non-finite values detected by the numerics observability layer
    (:mod:`paddle_tpu.observability.numerics`).  Neither transient nor
    fatal: retrying the SAME step replays the NaN, but the job is
    recoverable — supervisors classify this as ``"numeric"`` and roll
    back to the last VALID checkpoint instead of blindly retrying or
    surfacing it."""

    def __init__(self, msg="non-finite values detected", site=None,
                 stream=None, step=None):
        super().__init__(msg)
        self.site = site
        self.stream = stream
        self.step = step


# substrings (lowercased) in errors from the jax/XLA runtime and the
# coordination service that indicate the WORLD failed, not the program
_TRANSIENT_PATTERNS = (
    "deadline exceeded",
    "preempt",
    "unavailable",
    "socket closed",
    "connection reset",
    "connection refused",
    "broken pipe",
    "coordination service",
    "heartbeat",
    "barrier timed out",
    "peer down",
)

_TRANSIENT_TYPES = (TransientError, TimeoutError, ConnectionError,
                    BrokenPipeError)


def classify_failure(exc) -> str:
    """``"transient"`` (restart-worthy), ``"numeric"`` (roll back to the
    last valid checkpoint) or ``"fatal"`` (surface it)."""
    if isinstance(exc, NumericFault):
        return "numeric"
    if isinstance(exc, FloatingPointError):
        return "numeric"
    if isinstance(exc, _TRANSIENT_TYPES):
        return "transient"
    msg = str(exc).lower()
    if any(p in msg for p in _TRANSIENT_PATTERNS):
        return "transient"
    return "fatal"


class RetryPolicy:
    """Exponential backoff with a cap and seeded jitter.

    ``delay(attempt)`` for attempt 1, 2, 3, … is
    ``min(base * 2**(attempt-1), max_delay)`` scaled by a uniform jitter in
    ``[1-jitter, 1+jitter]`` and re-capped — so delays grow, never exceed
    the cap, and don't synchronize across hosts.  A given ``seed`` makes
    the jitter stream reproducible (the chaos tests assert exact delays).
    """

    def __init__(self, base_delay=1.0, max_delay=30.0, jitter=0.5,
                 seed=None):
        if not 0.0 <= float(jitter) <= 1.0:
            raise ValueError(f"jitter must be in [0, 1], got {jitter}")
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.jitter = float(jitter)
        self._rng = random.Random(seed)

    def delay(self, attempt) -> float:
        d = min(self.base_delay * (2.0 ** max(int(attempt) - 1, 0)),
                self.max_delay)
        if self.jitter:
            d *= 1.0 + self.jitter * (2.0 * self._rng.random() - 1.0)
        return max(min(d, self.max_delay), 0.0)


def derive_seed(*parts) -> int:
    """Stable small seed from arbitrary parts (fault plans, per-site rngs):
    crc32 of the repr-joined parts — reproducible across processes, unlike
    ``hash()`` under PYTHONHASHSEED randomization."""
    return zlib.crc32(":".join(repr(p) for p in parts).encode())
