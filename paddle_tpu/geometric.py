"""paddle.geometric (reference: python/paddle/geometric/) — graph message
passing and segment reductions.

TPU-native: every primitive lowers to gather + ``jax.ops.segment_*`` /
scatter-reduce, which XLA turns into vectorized dynamic-slice/scatter —
no per-edge loops.  ``out_size``/num_segments must be static under jit
(pass it explicitly inside traced code; eager infers from the data).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .tensor.dispatch import apply

__all__ = ["segment_sum", "segment_mean", "segment_max", "segment_min",
           "send_u_recv", "send_ue_recv", "send_uv"]


def _mask_empty_segments(out, ids, n, ndim):
    """Reference semantics: EMPTY segments read 0, not the reduce identity
    (+-inf for floats, INT_MIN/INT_MAX for ints)."""
    cnt = jax.ops.segment_sum(jnp.ones_like(ids), ids, num_segments=n)
    empty = (cnt == 0).reshape((n,) + (1,) * (ndim - 1))
    return jnp.where(empty, jnp.zeros_like(out), out)


def _num_segments(ids, out_size):
    if out_size is not None:
        return int(out_size)
    return int(jnp.max(ids)) + 1 if ids.size else 0


def _segment(data, segment_ids, out_size, kind):
    def fn(d, ids):
        n = _num_segments(ids, out_size)
        if kind == "sum":
            return jax.ops.segment_sum(d, ids, num_segments=n)
        if kind == "mean":
            tot = jax.ops.segment_sum(d, ids, num_segments=n)
            cnt = jax.ops.segment_sum(jnp.ones_like(ids, d.dtype), ids,
                                      num_segments=n)
            shape = (n,) + (1,) * (d.ndim - 1)
            return tot / jnp.maximum(cnt.reshape(shape), 1)
        if kind == "max":
            out = jax.ops.segment_max(d, ids, num_segments=n)
        else:
            out = jax.ops.segment_min(d, ids, num_segments=n)
        return _mask_empty_segments(out, ids, n, d.ndim)

    return apply(fn, data, segment_ids, op_name=f"segment_{kind}")


def segment_sum(data, segment_ids, name=None, out_size=None):
    """Sum rows of ``data`` per segment id (reference:
    paddle.geometric.segment_sum; ids must be sorted there — here any
    order works, matching ids still reduce together)."""
    return _segment(data, segment_ids, out_size, "sum")


def segment_mean(data, segment_ids, name=None, out_size=None):
    return _segment(data, segment_ids, out_size, "mean")


def segment_max(data, segment_ids, name=None, out_size=None):
    """Per-segment max; empty segments read 0 (reference semantics)."""
    return _segment(data, segment_ids, out_size, "max")


def segment_min(data, segment_ids, name=None, out_size=None):
    return _segment(data, segment_ids, out_size, "min")


_MSG = {
    "add": lambda u, e: u + e,
    "sub": lambda u, e: u - e,
    "mul": lambda u, e: u * e,
    "div": lambda u, e: u / e,
}


def _reduce_edges(msgs, dst, n, reduce_op):
    if reduce_op in ("sum", "mean"):
        out = jax.ops.segment_sum(msgs, dst, num_segments=n)
        if reduce_op == "mean":
            cnt = jax.ops.segment_sum(jnp.ones_like(dst, msgs.dtype), dst,
                                      num_segments=n)
            out = out / jnp.maximum(cnt.reshape((n,) + (1,) * (msgs.ndim - 1)),
                                    1)
        return out
    if reduce_op == "max":
        out = jax.ops.segment_max(msgs, dst, num_segments=n)
    elif reduce_op == "min":
        out = jax.ops.segment_min(msgs, dst, num_segments=n)
    else:
        raise ValueError(f"unknown reduce_op {reduce_op!r}")
    return _mask_empty_segments(out, dst, n, msgs.ndim)


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather source-node features along edges and reduce at destinations
    (reference: paddle.geometric.send_u_recv)."""
    def fn(xv, src, dst):
        n = _num_segments(dst, out_size) if out_size is not None \
            else xv.shape[0]
        return _reduce_edges(xv[src], dst, n, reduce_op)

    return apply(fn, x, src_index, dst_index, op_name="send_u_recv")


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Combine source-node features with edge features, reduce at
    destinations (reference: paddle.geometric.send_ue_recv)."""
    if message_op not in _MSG:
        raise ValueError(f"unknown message_op {message_op!r}")

    def fn(xv, yv, src, dst):
        n = _num_segments(dst, out_size) if out_size is not None \
            else xv.shape[0]
        msgs = _MSG[message_op](xv[src], yv)
        return _reduce_edges(msgs, dst, n, reduce_op)

    return apply(fn, x, y, src_index, dst_index, op_name="send_ue_recv")


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Per-edge messages from source and destination node features
    (reference: paddle.geometric.send_uv): out[e] = x[src[e]] op y[dst[e]]."""
    if message_op not in _MSG:
        raise ValueError(f"unknown message_op {message_op!r}")

    def fn(xv, yv, src, dst):
        return _MSG[message_op](xv[src], yv[dst])

    return apply(fn, x, y, src_index, dst_index, op_name="send_uv")
