"""Deployment-facing surface of the serving engine.

:class:`ContinuousBatchingPredictor` bridges the reference
``paddle.inference`` Config/Predictor API (named input/output handles,
``copy_from_cpu`` / ``run()`` / ``copy_to_cpu``) onto the
:class:`~.engine.ServingEngine`: every row of the staged ``input_ids``
batch becomes an independent request, so concurrent ``run()`` callers (and
the rows within one call) share the engine's iteration-level batch instead
of serializing behind each other — the drop-in upgrade path from the
single-request :class:`paddle_tpu.inference.Predictor`.
"""

from __future__ import annotations

import numpy as np

from .engine import ServingEngine


class ContinuousBatchingPredictor:
    """Predictor-shaped facade over a :class:`ServingEngine`.

    ``model``: a causal LM the engine's adapter understands (GPT-family).
    ``config``: optional ``paddle.inference.Config`` — accepted for script
    compatibility (device/flags recorded; the engine executes via its own
    compiled programs, not the StableHLO artifact, because serving needs
    the KV-cache decode path the artifact does not carry).

    Input handle ``input_ids``: int64 ``[B, S]``, rows right-padded with
    ``pad_token_id``.  Output handle ``output_0``: int64
    ``[B, S + max_new_tokens]`` — prompt + generated ids, right-padded.
    """

    def __init__(self, model, config=None, max_new_tokens=32,
                 temperature=0.0, eos_token_id=None, pad_token_id=0,
                 engine=None, **engine_kwargs):
        from ..inference import PredictorTensor

        self._engine = engine if engine is not None \
            else ServingEngine(model, **engine_kwargs)
        self._config = config
        self._max_new_tokens = int(max_new_tokens)
        self._temperature = float(temperature)
        self._eos = eos_token_id
        self._pad = int(pad_token_id)
        self._input = PredictorTensor("input_ids", [None, None], "int64")
        self._output = PredictorTensor("output_0", None, "int64")

    # --------------------------------------------------- reference surface
    def get_input_names(self):
        return ["input_ids"]

    def get_input_handle(self, name):
        if name != "input_ids":
            raise KeyError(f"unknown input {name!r}; valid: ['input_ids']")
        return self._input

    def get_output_names(self):
        return ["output_0"]

    def get_output_handle(self, name):
        if name != "output_0":
            raise KeyError(f"unknown output {name!r}; valid: ['output_0']")
        return self._output

    def run(self, inputs=None):
        """Fan the staged batch out as one request per row, wait for all,
        refill the output handle.  Functional spelling
        ``run([ids_batch])`` returns ``[np.ndarray]`` like the reference."""
        if inputs is not None:
            if len(inputs) != 1:
                raise ValueError(f"run() takes one input batch, "
                                 f"got {len(inputs)}")
            self._input.copy_from_cpu(np.asarray(inputs[0]))
        ids = self._input.copy_to_cpu()
        if ids is None or ids.ndim != 2:
            raise RuntimeError("input_ids not set (or not [B, S]); call "
                               "copy_from_cpu first")
        ids = ids.astype(np.int64)
        handles = []
        try:
            for row in ids:
                # strip TRAILING padding only (pad_token_id may be a real
                # token mid-prompt); all-pad rows keep one token
                nz = np.nonzero(row != self._pad)[0]
                prompt = row[:nz[-1] + 1] if nz.size else row[:1]
                handles.append(self._engine.submit(
                    prompt, max_new_tokens=self._max_new_tokens,
                    temperature=self._temperature, eos_token_id=self._eos))
        except Exception:
            # a mid-batch rejection must not leave earlier rows decoding
            # unobserved (burning slots/pages with nobody collecting them)
            for h in handles:
                h.cancel()
            raise
        B, S = ids.shape
        out = np.full((B, S + self._max_new_tokens), self._pad, np.int64)
        out[:, :S] = ids
        for b, h in enumerate(handles):
            new = h.result()
            out[b, S:S + len(new)] = new
        self._output.copy_from_cpu(out)
        if inputs is not None:
            return [out.copy()]
        return True

    # ------------------------------------------------------------- passthru
    def submit(self, prompt_ids, **kw):
        kw.setdefault("max_new_tokens", self._max_new_tokens)
        kw.setdefault("temperature", self._temperature)
        kw.setdefault("eos_token_id", self._eos)
        return self._engine.submit(prompt_ids, **kw)

    def stream(self, prompt_ids, **kw):
        return self.submit(prompt_ids, **kw).stream()

    @property
    def engine(self):
        return self._engine

    def close(self):
        self._engine.stop()

    def __enter__(self):
        self._engine.start()
        return self

    def __exit__(self, *exc):
        self.close()
