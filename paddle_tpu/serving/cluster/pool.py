"""ReplicaPool — N :class:`~paddle_tpu.serving.engine.ServingEngine`
replicas over one model (dp for inference).

Each replica is a full engine: its own scheduler thread, its own
:class:`~paddle_tpu.serving.block_manager.BlockManager` and page pools,
its own ``replica=`` metric label and keyed ``/statusz`` provider.  The
model (and its compiled-program store) is SHARED — the engines key the
``program_store`` by (phase, batch-shape, sampler), so N same-shaped
replicas reuse one traced prefill/step/verify family instead of minting N.

Device placement is configured from ``jax.devices()`` with an explicit
dp-replica count: ``devices="auto"`` round-robins replicas over the
visible devices and commits each replica's params/buffers/pools to its
device (the engine's uncommitted per-step host arrays follow); an
explicit device LIST pins the round-robin order; the default
``devices=None`` leaves placement to jax (all replicas on the default
device — the single-host dryrun shape, where replicas still overlap
host-side scheduling with device dispatch).

Tensor-parallel replicas (dp x mp topologies behind the same router):
``mp=N`` carves the device list into contiguous N-sized submeshes — one
mp engine per carve, each sharding its pools/weights over its own
``"model"`` axis (``ServingEngine(mesh=...)``) — or pass ``devices=`` as
an explicit list of submeshes (each entry a device list / jax Mesh).
Count divisibility is validated with a clear error either way.
"""

from __future__ import annotations

import os
import threading

import jax


def _is_device(d):
    """A jax device object (vs a submesh list/Mesh)."""
    return hasattr(d, "platform") and not isinstance(d, (list, tuple))


class ReplicaPool:
    """Build and own N serving-engine replicas.

    ``replicas=None`` defaults to one per carve when ``devices``/``mp``
    select placement, else 1.  ``replica_prefix`` namespaces the replica
    ids (metric labels / provider keys) when several pools share a
    process.  Remaining ``engine_kwargs`` go to every engine verbatim.
    """

    def __init__(self, model, replicas=None, devices=None, replica_prefix="",
                 engine_cls=None, mp=None, warmup=None, **engine_kwargs):
        from ..engine import ServingEngine

        if engine_cls is None:
            # multi-tenant kwargs (shared LoRAStore) pick the multi-tenant
            # engine automatically; an explicit engine_cls= overrides
            if "lora_store" in engine_kwargs:
                from ..multitenant import MultiTenantEngine

                engine_cls = MultiTenantEngine
            else:
                engine_cls = ServingEngine
        mp = int(mp) if mp else None
        if mp is not None and mp < 1:
            raise ValueError(f"mp must be >= 1, got {mp}")
        if devices == "auto":
            devices = list(jax.devices())
        elif devices is not None:
            devices = list(devices)
        if devices is not None and not devices:
            raise ValueError("devices must be non-empty (or None/'auto')")
        # submesh placement: either the caller hands explicit submeshes
        # (list entries that are themselves device lists / meshes), or
        # mp= carves the flat device list into contiguous mp-sized groups
        meshes = None
        if devices is not None and not all(_is_device(d) for d in devices):
            if mp is not None:
                raise ValueError(
                    "pass EITHER mp=N (carve a flat device list) OR "
                    "devices= as explicit submeshes, not both")
            if any(_is_device(d) for d in devices):
                raise ValueError(
                    "devices= mixes single devices and submeshes — use "
                    "1-element lists for single-device replicas")
            meshes = [list(m) if isinstance(m, (list, tuple)) else m
                      for m in devices]
            sizes = {len(m) if isinstance(m, list)
                     else int(m.devices.size) for m in meshes}
            if len(sizes) > 1:
                raise ValueError(
                    f"submeshes must be same-sized (one SPMD program per "
                    f"family across replicas), got sizes {sorted(sizes)}")
        elif mp is not None and mp > 1:
            if devices is None:
                devices = list(jax.devices())
            if len(devices) % mp:
                raise ValueError(
                    f"{len(devices)} devices not divisible by mp={mp}: a "
                    f"dp x mp pool needs len(devices) == replicas * mp "
                    f"(force host devices with "
                    f"XLA_FLAGS=--xla_force_host_platform_device_count=N "
                    f"for CPU tests)")
            meshes = [devices[i:i + mp] for i in range(0, len(devices), mp)]
        if meshes is not None:
            if replicas is None:
                replicas = len(meshes)
            replicas = int(replicas)
            if replicas < 1:
                raise ValueError(f"replicas must be >= 1, got {replicas}")
            if replicas > len(meshes):
                raise ValueError(
                    f"replicas={replicas} exceeds the {len(meshes)} "
                    f"available submeshes (need replicas * mp devices)")
        else:
            if replicas is None:
                replicas = len(devices) if devices is not None else 1
            replicas = int(replicas)
            if replicas < 1:
                raise ValueError(f"replicas must be >= 1, got {replicas}")
        self.model = model
        self.devices = devices
        self.meshes = meshes
        # warm replica spin-up: a WarmupManifest (object or saved path)
        # replayed by every engine BEFORE its scheduler starts, so a fresh
        # pool's first real request on any replica mints zero traces.
        # The model (and program store) is shared: replica 0's replay
        # warms same-shaped siblings for free, and each engine skips keys
        # its store already holds traced.
        if isinstance(warmup, (str, os.PathLike)):
            from ...observability.programs import WarmupManifest

            warmup = WarmupManifest.load(warmup)
        self.warmup_manifest = warmup
        # elastic membership (AutoScaler): the build recipe is kept so
        # replicas can be added after construction; ids are monotonic
        # (never reused) so a replaced replica's metric labels and
        # /statusz keys stay distinct from its predecessor's
        self._engine_cls = engine_cls
        self._engine_kwargs = engine_kwargs
        self._replica_prefix = replica_prefix
        self._mut = threading.RLock()
        self._next_idx = replicas
        self._pool_started = False
        self.engines = [self._build_engine(i) for i in range(replicas)]

    def _build_engine(self, i):
        place = {}
        if self.meshes is not None:
            place["mesh"] = self.meshes[i % len(self.meshes)]
        elif self.devices is not None:
            place["device"] = self.devices[i % len(self.devices)]
        return self._engine_cls(
            self.model, replica=f"{self._replica_prefix}{i}", **place,
            **self._engine_kwargs)

    # ------------------------------------------------------------ lifecycle
    def start(self):
        with self._mut:
            self._pool_started = True
            engines = list(self.engines)
        for e in engines:
            if self.warmup_manifest is not None and not e._started:
                e.warmup(self.warmup_manifest)
            e.start()
        return self

    def drain(self, timeout=600):
        for e in self.engines:
            e.drain(timeout=timeout)
        return True

    def stop(self, drain=False, drain_timeout=600):
        errors = []
        for e in list(self.engines):
            try:
                e.stop(drain=drain, drain_timeout=drain_timeout)
            except Exception as exc:  # stop the REST before surfacing
                errors.append(exc)
        if errors:
            raise errors[0]

    # --------------------------------------------------- elastic membership
    def add_replica(self):
        """Grow the pool by one engine (autoscaler scale-up) and return
        it.  Spin-up is WARM when the pool has a ``warmup=`` manifest —
        the new engine replays it before its scheduler starts, and since
        the model's program store is shared it skips every key a sibling
        already traced, so elastic growth mints nothing on a warmed
        fleet.  Started iff the pool is started."""
        with self._mut:
            i = self._next_idx
            self._next_idx += 1
            e = self._build_engine(i)
            started = self._pool_started
            # list REPLACEMENT (not append): readers iterate a consistent
            # snapshot without holding the pool lock
            self.engines = self.engines + [e]
        if started:
            if self.warmup_manifest is not None:
                e.warmup(self.warmup_manifest)
            e.start()
        return e

    def remove_replica(self, engine):
        """Forget a retired/dead engine (the autoscaler stops it first;
        removal here only changes membership)."""
        with self._mut:
            self.engines = [e for e in self.engines if e is not engine]

    def snapshot_states(self):
        """One atomic ``(engines, states)`` pair: row ``i`` of ``states``
        describes ``engines[i]`` even if the pool resizes concurrently —
        the router/autoscaler contract under elastic membership."""
        with self._mut:
            engines = list(self.engines)
        return engines, self._states_of(engines)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    def __len__(self):
        return len(self.engines)

    # -------------------------------------------------------------- insight
    @property
    def replica_ids(self):
        return [e.replica for e in self.engines]

    def states(self):
        """Router-input snapshots, one per replica (reads race the
        scheduler threads benignly — routing is a heuristic, not a
        transaction)."""
        return self._states_of(list(self.engines))

    @staticmethod
    def _states_of(engines):
        out = []
        for e in engines:
            hs = e.health_state()
            # radix-index export for cross-replica prefix placement: the
            # router matches an incoming prompt's page-boundary digests
            # against each replica's resident set (None outside radix
            # mode, or for non-engine stand-ins in tests)
            try:
                summ = e.prefix_index_summary()
            except AttributeError:
                summ = None
            out.append({
                "replica": e.replica,
                "state": hs["state"],
                "reasons": hs.get("reasons", []),
                "stalled": any("scheduler_stalled" in r
                               for r in hs.get("reasons", [])),
                "queue_depth": len(e._queue),
                "active": sum(1 for s in e._slots if s is not None),
                "num_slots": e.num_slots,
                "prefix_index": summ,
            })
        return out

    def stats(self):
        return {e.replica: e.stats() for e in self.engines}
