"""ServingCluster — the replicated, routed serving service ("Fleet for
inference"): a :class:`~.pool.ReplicaPool` of engines behind a
:class:`~.router.PrefixAffinityRouter`, with cross-replica resilience.

Request lifecycle::

    cluster.submit(prompt)                      (caller thread)
      └─ router.route(prompt, pool.states())    health-aware decision
         └─ engines[i].submit(...)              one "leg" on replica i
    monitor thread (one per cluster, poll-driven):
      forwards each leg's tokens to the caller-facing ClusterHandle;
      when a leg dies WITH its replica (engine stopped / fatal error),
      re-routes the request onto a surviving replica as prompt +
      tokens-so-far with the remaining budget — the PR-4 in-flight
      requeue invariant lifted across the replica boundary, so a greedy
      request's final ids are exactly the uninterrupted single-engine
      ones.  Tokens already streamed stay streamed.

A replica's own transient-failure auto-restart (PR-4) is invisible here —
the engine re-queues its own in-flight work and the leg's handle never
finishes.  The cluster path engages only when the replica is LOST:
fatal classification, restart budget burned, or a plain ``stop()``.

Health-aware admission: replicas reporting ``draining`` / ``stopped`` /
``error`` receive no traffic; if none is routable the submit sheds with
:class:`~paddle_tpu.serving.engine.RequestRejectedError` (reason
``no_routable_replica``, or ``draining`` when every replica is draining).
A leg rejected by a saturated engine (bounded queue, deadline shed) spills
to the next-best routable replica before giving up.

Observability: ``cluster.requests{replica=}``, ``cluster.affinity{result=
hit|miss}``, ``cluster.affinity_hit_rate``, ``cluster.rerouted_requests``,
``cluster.rejected{reason=}``, ``cluster.routable_replicas``,
``cluster.in_flight`` in the PR-1 registry; a ``cluster`` section on
``/statusz`` (per-replica occupancy / queue depth / health, hit rate,
reroute counter) and a ``cluster`` component on ``/healthz`` (healthy
while ANY replica is routable — a load balancer should keep sending);
``cluster.route`` spans carry the decision and parent each leg's
``serving.submit`` span (PR-3 trace propagation).
"""

from __future__ import annotations

import itertools
import logging
import queue as _queue
import threading
import time

from ...observability import tracing as _tracing
from ...profiler import metrics as _metrics
from ..engine import (EngineStoppedError, RequestHandle,
                      RequestRejectedError, SamplingParams, ServingEngine)
from .pool import ReplicaPool
from .router import ROUTABLE_STATES, PrefixAffinityRouter

#: leg terminal statuses that mean "the replica died under the request",
#: not "the request reached its own end"
_REPLICA_LOST = ("stopped", "error")

_logger = logging.getLogger(__name__)


class ClusterHandle(RequestHandle):
    """Caller-side view of a cluster request — the same ``result()`` /
    ``stream()`` / ``cancel()`` surface as the engine's
    :class:`RequestHandle`, accumulated across however many replica legs
    the request needed.  ``replica_history`` lists the replicas that
    served it (length > 1 ⇒ it survived a replica loss)."""

    def __init__(self, request_id, prompt, max_new_tokens, sampling,
                 eos_token_id, deadline, adapter=None, grammar=None,
                 mode="generate", pooling="mean", tier=None):
        super().__init__(request_id, len(prompt))
        self.prompt = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        self.sampling = sampling
        self.eos_token_id = eos_token_id
        self.deadline = deadline            # absolute time.time(), or None
        # multi-tenant fields ride the outer handle so failover legs
        # re-submit with the same tenant/grammar/mode — and the QoS tier
        # rides the same way, so a rerouted leg keeps its priority
        self.adapter = adapter
        self.grammar = grammar
        self.mode = mode
        self.pooling = pooling
        self.tier = tier
        self.replica_history = []
        self._inner = None                  # current leg's engine handle
        self._legs = 0

    def cancel(self):
        super().cancel()
        inner = self._inner
        if inner is not None:
            inner.cancel()


class ServingCluster:
    """See module docstring.  Typical use::

        cluster = ServingCluster(model, replicas=2, prefix_sharing=True)
        with cluster:
            h = cluster.submit(prompt, max_new_tokens=64)
            ids = h.result(timeout=120)

    ``**engine_kwargs`` configure every replica (num_slots, page_size,
    prefix_sharing, ...) — including ``warmup=`` (a
    :class:`~paddle_tpu.observability.programs.WarmupManifest` or saved
    path), which the pool replays on every replica before its scheduler
    starts so the cluster's first request on any replica mints zero
    traces.  Pass a prebuilt ``pool=`` / ``router=`` to override
    construction; ``policy`` picks the routing policy (``affinity``
    default, ``random`` / ``round_robin`` / ``least_loaded`` as
    controls).  With ``prefix_cache="radix"`` replicas the affinity
    policy routes to the replica holding the DEEPEST resident prefix
    match (pool states carry each radix index's digest summary),
    falling back to rendezvous for cold prefixes; ``prefix_match=False``
    restores pure rendezvous placement."""

    def __init__(self, model=None, replicas=2, devices=None, pool=None,
                 router=None, policy="affinity", affinity_tokens=None,
                 saturation_queue=None, seed=0, prefix_match=True,
                 max_reroutes=None, poll_s=0.002, replica_prefix="",
                 name=None, slo=None, qos=None, autoscale=None,
                 **engine_kwargs):
        if pool is None:
            if model is None:
                raise ValueError("need a model (or a prebuilt pool=)")
            # replicas report on /healthz but don't gate it — this
            # cluster's own any-replica-routable component does
            engine_kwargs.setdefault("health_gating", False)
            if slo is not None:
                # per-replica accounting too: each engine evaluates the
                # legs it served under its replica= label (a prebuilt
                # pool= configures its own engines)
                engine_kwargs.setdefault("slo", slo)
            if qos is not None:
                # one QoSConfig is immutable and safely shared: every
                # replica gets the same tier table (queues stay per-engine)
                engine_kwargs.setdefault("qos", qos)
            pool = ReplicaPool(model, replicas=replicas, devices=devices,
                               replica_prefix=replica_prefix,
                               **engine_kwargs)
        self._pool = pool
        n = len(pool)
        if router is None:
            if affinity_tokens is None:
                # page-aligned default: two BlockManager prefix pages —
                # prompts sharing this window share at least those pages
                affinity_tokens = 2 * pool.engines[0].page_size
            router = PrefixAffinityRouter(
                n, affinity_tokens=affinity_tokens, policy=policy,
                saturation_queue=saturation_queue, seed=seed,
                prefix_match=prefix_match)
        if router.n_replicas != n:
            raise ValueError(f"router built for {router.n_replicas} "
                             f"replicas, pool has {n}")
        self._router = router
        # cluster identity, mirroring the engines' replica= fix: two pools
        # in one process (replica_prefix) must not share cluster.* series
        # or the "cluster" provider key.  Default "0" keeps the provider
        # key at plain "cluster".
        self.name = str(name) if name is not None \
            else (replica_prefix.strip("/") or "0")
        self._provider_key = "cluster" if self.name == "0" \
            else f"cluster/{self.name}"
        self._max_reroutes = int(max_reroutes) if max_reroutes is not None \
            else n
        self._poll_s = float(poll_s)
        self._lock = threading.Lock()
        # elastic membership: routing decisions and router resizes are
        # serialized so a route never runs against a half-applied resize
        self._route_lock = threading.Lock()
        self._autoscaler = None
        if autoscale:
            from ..qos import AutoScaler

            if isinstance(autoscale, AutoScaler):
                self._autoscaler = autoscale
            else:
                kw = dict(autoscale) if isinstance(autoscale, dict) else {}
                kw.setdefault("cluster", self.name)
                # the scale-up burn signal: the worst protected-tier burn
                # across the fleet (0.0 on non-QoS engines)
                kw.setdefault("burn_source", self._qos_burn)
                self._autoscaler = AutoScaler(pool, **kw)
        self._inflight: set[ClusterHandle] = set()
        self._rid = itertools.count()
        self._started = False
        self._stopping = False
        self._mon_stop = threading.Event()
        self._mon_thread = None
        self._status_provider = None
        self._health_provider = None
        self._aff_hits = 0
        self._aff_misses = 0
        self._rerouted_total = 0
        # cluster-wide SLO accounting over the OUTER handles: failover
        # legs and reroute overhead land here, not in any one replica's
        # numbers (serving.slo.* series carry cluster=<name>)
        self._slo = None
        if slo is not None:
            from ...observability.slo import SLOAccountant, SLOPolicy

            if not isinstance(slo, SLOPolicy):
                raise TypeError(f"slo must be an SLOPolicy, got {slo!r}")
            self._slo = SLOAccountant(slo, cluster=self.name)

        # every cluster.* series carries cluster=<name> (default "0") so
        # two pools in one process keep distinct series, mirroring the
        # engines' replica= label
        def _c(mname, help):
            return _metrics.bind(_metrics.counter(mname, help),
                                 cluster=self.name)

        def _g(mname, help):
            return _metrics.bind(_metrics.gauge(mname, help),
                                 cluster=self.name)

        self._m_requests = _c(
            "cluster.requests", "request legs routed, by replica")
        self._m_affinity = _c(
            "cluster.affinity", "routing decisions by result=hit|miss "
            "(hit = landed on the prefix's affine replica)")
        self._m_hit_rate = _g(
            "cluster.affinity_hit_rate",
            "affinity hits / routing decisions, lifetime")
        self._m_rerouted = _c(
            "cluster.rerouted_requests",
            "in-flight requests re-routed off a lost replica")
        self._m_rejected = _c(
            "cluster.rejected", "cluster-level submit rejections, by reason")
        self._m_routable = _g(
            "cluster.routable_replicas", "replicas accepting traffic now")
        self._m_inflight = _g(
            "cluster.in_flight", "cluster requests not yet terminal")

    # ------------------------------------------------------------ lifecycle
    def start(self):
        if self._started:
            return self
        self._pool.start()
        self._stopping = False
        self._mon_stop.clear()
        self._mon_thread = threading.Thread(
            target=self._monitor, name="paddle-serving-cluster", daemon=True)
        self._started = True
        self._mon_thread.start()
        from ...observability import telemetry as _telemetry

        self._status_provider = self._statusz
        _telemetry.add_status_provider(self._provider_key,
                                       self._status_provider)
        self._health_provider = self.health_state
        _telemetry.add_health_provider(self._provider_key,
                                       self._health_provider)
        return self

    def drain(self, timeout=600):
        """Graceful rundown: every replica drains (no new admissions),
        then wait for the monitor to propagate the last terminal events."""
        self._pool.drain(timeout=timeout)
        deadline = time.monotonic() + float(timeout)
        while time.monotonic() < deadline:
            with self._lock:
                if not self._inflight:
                    return True
            time.sleep(self._poll_s)
        raise TimeoutError(f"cluster did not drain within {timeout}s")

    def stop(self, drain=False, drain_timeout=600):
        """Stop every replica and the monitor.  ``drain=True`` finishes
        in-flight work first; without it, in-flight requests fail fast
        with :class:`EngineStoppedError` (never re-routed — a cluster
        shutdown is not a replica failure)."""
        if not self._started:
            return
        if drain:
            self.drain(timeout=drain_timeout)
        with self._lock:  # submits registered after this are rejected
            self._stopping = True
        try:
            self._pool.stop()
            # the engines just failed any remaining handles; let the
            # monitor forward those terminal events to the outer handles
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                with self._lock:
                    if not self._inflight:
                        break
                time.sleep(self._poll_s)
            with self._lock:
                leftovers = list(self._inflight)
                self._inflight.clear()
            for h in leftovers:  # belt and braces: never leave a waiter
                h._error = EngineStoppedError(
                    f"request {h.request_id} still unresolved at cluster "
                    "stop()")
                self._finish_outer(h, "stopped")
        finally:
            self._mon_stop.set()
            if self._mon_thread is not None:
                self._mon_thread.join(timeout=30)
                self._mon_thread = None
            from ...observability import telemetry as _telemetry

            _telemetry.remove_providers_if_owner(
                self._provider_key, self._status_provider,
                self._health_provider)
            self._status_provider = None
            self._health_provider = None
            self._started = False
            self._stopping = False

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # ------------------------------------------------------------------ api
    def submit(self, prompt_ids, max_new_tokens=32, temperature=0.0,
               eos_token_id=None, deadline_s=None, sampling=None,
               adapter=None, grammar=None, mode="generate", pooling="mean",
               tier=None):
        """Route one request onto a replica; returns a
        :class:`ClusterHandle` immediately.  ``adapter`` (LoRA tenant),
        ``grammar`` (constrained decoding) and ``mode`` (generate | embed
        | score) forward to the replica engines — multi-tenant pools only
        (``ReplicaPool(lora_store=...)``); adapter-named requests route by
        ADAPTER affinity so a tenant's weights page into one replica.
        ``tier`` names the request's QoS tier (QoS-enabled pools only)
        and rides every failover leg."""
        prompt = ServingEngine._normalize_prompt(prompt_ids)
        if not prompt:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        self.start()
        sampling = sampling if sampling is not None \
            else SamplingParams(temperature=temperature)
        deadline = time.time() + deadline_s if deadline_s is not None \
            else None
        if grammar is not None and eos_token_id is None:
            eos_token_id = grammar.eos_token_id
        h = ClusterHandle(f"c{next(self._rid)}", prompt,
                          int(max_new_tokens), sampling, eos_token_id,
                          deadline, adapter=adapter, grammar=grammar,
                          mode=mode, pooling=pooling, tier=tier)
        # register BEFORE the leg, atomically with the stopping check: a
        # submit racing stop() either rejects here or its handle is seen
        # by stop()'s leftover sweep — never a live handle nobody pumps
        with self._lock:
            if self._stopping:
                raise EngineStoppedError(
                    f"cluster {self.name} is stopping; request "
                    f"{h.request_id} not admitted")
            self._inflight.add(h)
            self._m_inflight.set(len(self._inflight))
        try:
            self._submit_leg(h, prompt, h.max_new_tokens, deadline_s)
        except BaseException as e:
            # EVERY failed first leg must unregister the handle — not
            # just engine rejections: a multi-tenant validation error
            # (ValueError/KeyError from an unknown adapter or a
            # mismatched grammar) would otherwise leave a never-finished
            # handle in _inflight for the monitor to pump forever
            with self._lock:
                self._inflight.discard(h)
                self._m_inflight.set(len(self._inflight))
            if isinstance(e, RequestRejectedError):
                self._m_rejected.inc(reason=e.reason)
            raise
        return h

    def generate(self, prompt_ids, max_new_tokens=32, timeout=None, **kw):
        return self.submit(prompt_ids, max_new_tokens, **kw).result(timeout)

    def stream(self, prompt_ids, max_new_tokens=32, **kw):
        return self.submit(prompt_ids, max_new_tokens, **kw).stream()

    # ------------------------------------------------------------- routing
    def _submit_leg(self, h, prompt, max_new, deadline_s):
        """Route + submit one leg (caller OR monitor thread).  A rejection
        from the chosen engine (bounded queue, deadline shed) spills to
        the next-best routable replica before surfacing."""
        # one atomic (engines, states) snapshot: with an autoscaler the
        # membership can change between routing and submission, so every
        # index below is into THIS snapshot, never the live pool list
        engines, states = self._pool.snapshot_states()
        with self._route_lock:
            if self._router.n_replicas != len(states):
                self._router.resize(len(states))
            dec = self._router.route(prompt, states, adapter=h.adapter)
        self._m_routable.set(sum(1 for st in states
                                 if st["state"] in ROUTABLE_STATES))
        if dec is None:
            reason = "draining" if states and all(
                st["state"] == "draining" for st in states) \
                else "no_routable_replica"
            raise RequestRejectedError(
                f"no routable replica for request {h.request_id} "
                f"(states: {[st['state'] for st in states]})", reason=reason)
        order = [dec.replica] + sorted(
            (i for i, st in enumerate(states)
             if i != dec.replica and st["state"] in ROUTABLE_STATES),
            key=lambda i: states[i]["queue_depth"] + states[i]["active"])
        last_rejection = None
        for idx in order:
            eng = engines[idx]
            # the full RouteDecision rides the span as REAL attributes
            # (OTLP/chrome export them as-is), so failover forensics read
            # affine/hit/reason off the trace instead of grepping logs
            with _tracing.span("cluster.route", trace_id=h.trace_id,
                               request_id=h.request_id, replica=eng.replica,
                               affine=engines[dec.affine].replica,
                               hit=idx == dec.affine, policy=dec.policy,
                               reason=dec.reason, leg=h._legs + 1):
                try:
                    fsm_state = None
                    if h.grammar is not None and h.token_ids:
                        # failover resume: replay the emitted tokens so
                        # the new leg's FSM starts mid-document, exactly
                        # where the lost replica left the grammar
                        fsm_state = h.grammar.advance_seq(
                            h.grammar.start, h.token_ids)
                    inner = eng.submit(
                        prompt, max_new_tokens=max_new,
                        eos_token_id=h.eos_token_id, deadline_s=deadline_s,
                        sampling=h.sampling, adapter=h.adapter,
                        grammar=h.grammar, mode=h.mode, pooling=h.pooling,
                        tier=h.tier, _fsm_state=fsm_state,
                        _autostart=False)
                except (RequestRejectedError, RuntimeError) as e:
                    # RequestRejectedError: engine shed it (bounded queue,
                    # deadline, draining).  RuntimeError (incl. Engine-
                    # StoppedError): the engine died or stopped between the
                    # states() snapshot and this submit — _autostart=False
                    # keeps a leg from resurrecting a stopped replica.
                    # Either way: spill to the next-best replica.
                    last_rejection = e
                    continue
            h._inner = inner
            if h.cancelled:  # cancel raced the leg hand-off: chase it
                inner.cancel()
            h._legs += 1
            h.replica_history.append(eng.replica)
            hit = idx == dec.affine
            self._m_requests.inc(replica=eng.replica)
            self._m_affinity.inc(result="hit" if hit else "miss")
            with self._lock:  # callers and the monitor both submit legs
                if hit:
                    self._aff_hits += 1
                else:
                    self._aff_misses += 1
                total = self._aff_hits + self._aff_misses
                self._m_hit_rate.set(self._aff_hits / total)
            return
        if isinstance(last_rejection, RequestRejectedError):
            raise last_rejection  # every routable replica rejected it
        raise RequestRejectedError(
            f"every routable replica failed request {h.request_id}: "
            f"{last_rejection!r}", reason="no_routable_replica")

    # ------------------------------------------------------------- monitor
    def _monitor(self):
        while not self._mon_stop.is_set():
            self._pump()
            if self._autoscaler is not None and not self._stopping:
                try:
                    self._autoscaler.tick()
                except Exception:
                    # a scaling hiccup (replica ctor raced a device error,
                    # say) must never kill the monitor: requests in flight
                    # depend on this thread pumping their tokens
                    _logger.exception("autoscaler tick failed")
            self._mon_stop.wait(self._poll_s)
        self._pump()  # final sweep so stop()-time events still land

    def _pump(self):
        with self._lock:
            entries = list(self._inflight)
        for h in entries:
            inner = h._inner
            if inner is None:
                continue
            try:
                while True:
                    try:
                        kind, val = inner._events.get_nowait()
                    except _queue.Empty:
                        break
                    if kind == "token":
                        self._forward_token(h, val)
                    else:
                        self._on_leg_done(h, inner, val)
                        break
            except BaseException as e:  # a broken handle must not hang the
                h._inner = None         # rest of the fleet's monitoring
                h._error = e
                self._finish_outer(h, "error")

    def _forward_token(self, h, tok):
        now = time.time()
        if h.first_token_at is None:
            h.first_token_at = now
        h.token_ids.append(tok)
        # outer token timeline: what the CALLER observed, including any
        # cross-replica failover gap (the cluster's SLO truth)
        h.token_times.append(now)
        h._events.put(("token", tok))

    def _on_leg_done(self, h, inner, status):
        # fold the leg's QoS eviction count into the caller-visible total
        # BEFORE deciding on reroute — a rerouted leg's preemptions count
        h.preemptions += getattr(inner, "preemptions", 0)
        if status in _REPLICA_LOST and not self._stopping \
                and not h.cancelled and self._try_reroute(h):
            return
        h._inner = None
        h._error = inner._error
        h.value = inner.value           # embed vector / score list
        self._finish_outer(h, status)

    def _try_reroute(self, h):
        """The replica under ``h`` is gone: re-queue the request on a
        surviving replica as prompt + tokens-so-far with the remaining
        budget (greedy ids stay exactly the uninterrupted ones — the PR-4
        invariant across the replica boundary).  Returns False when the
        request can't be re-routed (reroute budget burned, nothing
        routable, every survivor rejected it)."""
        if h._legs > self._max_reroutes:
            return False
        remaining = h.max_new_tokens - len(h.token_ids) \
            if h.mode == "generate" else 1    # embed/score: just re-run
        if remaining <= 0:   # it had finished; the loss beat the retire
            h._inner = None
            self._finish_outer(h, "completed")
            return True
        deadline_s = None
        if h.deadline is not None:
            deadline_s = h.deadline - time.time()
            if deadline_s <= 0:
                h._inner = None
                self._finish_outer(h, "expired")
                return True
        prompt = h.prompt + [int(t) for t in h.token_ids]
        try:
            self._submit_leg(h, prompt, remaining, deadline_s)
        except RequestRejectedError:
            return False
        with self._lock:
            self._rerouted_total += 1
        self._m_rerouted.inc()
        return True

    def _finish_outer(self, h, status):
        h.status = status
        h.finished_at = time.time()
        if self._slo is not None and status in ("completed", "expired") \
                and h.mode == "generate":
            self._slo.observe(h, met_override=False
                              if status == "expired" else None)
        with self._lock:
            self._inflight.discard(h)
            self._m_inflight.set(len(self._inflight))
        h._events.put(("done", status))
        h._done.set()

    # --------------------------------------------------------------- health
    def health_state(self):
        """Cluster-level health for a load balancer: ``healthy`` while any
        replica is healthy, ``degraded`` while any is at least routable,
        ``draining`` when every replica is draining, else ``error`` —
        the OPPOSITE fold of /healthz's worst-component rule, because one
        lost replica must not 503 the whole cluster."""
        states = [st["state"] for st in self._pool.states()]
        if any(s == "healthy" for s in states):
            return {"state": "healthy", "reasons": []}
        if any(s == "degraded" for s in states):
            return {"state": "degraded",
                    "reasons": [f"replica_states:{states}"]}
        if states and all(s == "draining" for s in states):
            return {"state": "draining", "reasons": ["all replicas draining"]}
        if states and all(s == "stopped" for s in states):
            return {"state": "stopped", "reasons": []}
        return {"state": "error",
                "reasons": [f"no routable replica: {states}"]}

    @property
    def health(self):
        return self.health_state()["state"]

    def _qos_burn(self):
        """Autoscaler burn signal: the WORST protected-tier burn rate
        across the fleet (one hot replica is an incident even when its
        siblings are idle); None when no engine accounts a tier SLO."""
        rates = [e.qos_burn_rate() for e in list(self._pool.engines)
                 if hasattr(e, "qos_burn_rate")]
        return max(rates) if rates else None

    # -------------------------------------------------------------- insight
    @property
    def pool(self):
        return self._pool

    @property
    def router(self):
        return self._router

    @property
    def autoscaler(self):
        """The cluster's :class:`~paddle_tpu.serving.qos.AutoScaler`
        (None unless ``autoscale=`` was set)."""
        return self._autoscaler

    @property
    def slo_accountant(self):
        """Cluster-wide SLO accountant (None unless ``slo=`` was set)."""
        return self._slo

    @property
    def engines(self):
        return self._pool.engines

    def register_adapter(self, adapter):
        """Register a LoRA adapter on every distinct store behind the
        fleet (one shared store registers once) — multi-tenant pools
        only."""
        stores = []
        for e in self._pool.engines:
            store = getattr(e, "lora_store", None)
            if store is None:
                raise ValueError(
                    f"replica {e.replica} has no lora_store; build the "
                    "cluster with ReplicaPool(lora_store=...)")
            if not any(store is s for s in stores):
                stores.append(store)
        for store in stores:
            store.register(adapter)
        return adapter.name

    def affinity_hit_rate(self):
        total = self._aff_hits + self._aff_misses
        return self._aff_hits / total if total else None

    def stats(self):
        # LOCKLESS snapshot (len() is atomic enough for a diagnostic):
        # /statusz renders this while callers and the monitor churn, and a
        # scrape must never queue behind — or hold — the cluster lock
        # (PR-3 signal-path rule, asserted by the telemetry-under-load
        # test)
        inflight = len(self._inflight)
        from ...observability import memory as _obs_memory

        return {
            "replicas": self._pool.stats(),
            "policy": self._router.policy,
            "affinity_tokens": self._router.affinity_tokens,
            "in_flight": inflight,
            "rerouted_requests": self._rerouted_total,
            "affinity": {"hits": self._aff_hits,
                         "misses": self._aff_misses,
                         "hit_rate": self.affinity_hit_rate()},
            # per-replica device-memory rollup off the process ledger —
            # owner_rows only, no live-array walk, still lockless
            "memory": _obs_memory.ledger().replica_rollup(
                [e.replica for e in self._pool.engines]),
        }

    def _statusz(self):
        """/statusz ``cluster`` section: the router's view of the fleet."""
        st = self.stats()
        st["started"] = self._started
        st["health"] = self.health_state()
        if self._slo is not None:
            st["slo"] = self._slo.summary()
        if self._autoscaler is not None:
            sc = self._autoscaler
            st["autoscaler"] = {
                "min_replicas": sc.min_replicas,
                "max_replicas": sc.max_replicas,
                "replicas": len(self._pool),
                "retiring": sc.retiring.replica
                if sc.retiring is not None else None,
                "timeline": sc.timeline(),
            }
        per = {}
        engines, states = self._pool.snapshot_states()
        for snap, e in zip(states, engines):
            per[e.replica] = {
                "state": snap["state"],
                "reasons": snap["reasons"],
                "queue_depth": snap["queue_depth"],
                "active_slots": snap["active"],
                "occupancy": snap["active"] / max(snap["num_slots"], 1),
                "page_utilization": e.block_manager.utilization(),
            }
        st["replica_health"] = per
        return st
