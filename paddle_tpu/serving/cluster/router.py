"""Prefix-affinity request router — the front end of the serving cluster.

The BlockManager's refcounted prefix sharing only pays off when requests
with a common prompt prefix land on the SAME replica: a prefix page cached
on replica 2 is invisible to replica 5.  The router therefore maps the
first ``affinity_tokens`` prompt tokens (aligned with the page-granular
prefix keys BlockManager uses) to a replica by **rendezvous hashing**
(highest-random-weight): every (prefix, replica) pair gets a stable score,
the prefix's *affine replica* is the top scorer, and when a replica leaves
the routable set only ITS prefixes move — everyone else's cache stays warm
(the stability property consistent hashing exists for).

Health-aware fallback: replicas whose :meth:`ServingEngine.health_state`
reports ``draining`` / ``stopped`` / ``error`` are not routable at all;
a routable-but-*saturated* affine replica (deep queue, or a scheduler
stalled past its degraded threshold) falls back to the **least-loaded**
routable replica, trading a prefix-cache hit for latency only when the
affine replica could not serve promptly anyway.

**Deepest-match placement** (the hierarchical-KV-cache tier of routing):
when replicas run ``prefix_cache="radix"``, their state snapshots carry a
radix-index summary — :func:`~paddle_tpu.serving.prefix_index
.prefix_digest` of every resident page-boundary prefix.  The affinity
policy digests the incoming prompt the same way and routes to the
unsaturated replica with the DEEPEST matching resident run (most cached
pages, i.e. most prefill compute skipped), falling back to rendezvous
when no replica has any match — so cold prefixes still spread by the
stable hash, and a prefix that went warm on a non-affine replica (e.g.
after a saturation fallback) keeps landing where its pages actually
live.  Equal-depth ties break by rendezvous score, keeping the choice
stable per prefix.  ``prefix_match=False`` restores pure rendezvous
(the bench's control arm).

Control policies for benchmarking the affinity win (``bench.py --serving
--replicas N``): ``random`` (seeded uniform over routable replicas) and
``round_robin`` and ``least_loaded``.  Every decision still records the
affine replica, so the *affinity hit rate* — fraction of requests that
landed on their affine replica — is comparable across policies.

The router is pure host-side policy: it sees a list of replica state
snapshots (built by :class:`~.service.ServingCluster` from the live
engines) and returns a :class:`RouteDecision`; it never touches an engine.
"""

from __future__ import annotations

import dataclasses
import hashlib
import itertools
import random

from ..prefix_index import prefix_digest

#: health states a replica may receive traffic in
ROUTABLE_STATES = ("healthy", "degraded")

POLICIES = ("affinity", "least_loaded", "random", "round_robin")


@dataclasses.dataclass
class RouteDecision:
    """One routing decision.  ``replica`` is the chosen index into the
    states list; ``affine`` the prefix's rendezvous winner over ALL
    replicas (dead or alive — it says where the prefix's pages would
    accumulate in a fully healthy pool); ``hit`` whether they coincide;
    ``reason`` the machine-readable branch taken."""

    replica: int
    affine: int
    hit: bool
    reason: str
    policy: str
    #: resident radix pages the chosen replica already holds for this
    #: prompt (0 outside deepest-match routing) — the placement win in
    #: pages, observable per decision
    prefix_pages: int = 0


def prefix_key(prompt_ids, affinity_tokens):
    """Canonical bytes for a prompt's routing prefix (its first
    ``affinity_tokens`` ids).  Prompts shorter than the window key on what
    they have — two prompts only share a key when one's window is a prefix
    the other matches exactly."""
    head = [int(t) for t in list(prompt_ids)[:max(int(affinity_tokens), 1)]]
    return (",".join(map(str, head))).encode()


def routing_key(prompt_ids, affinity_tokens, adapter=None):
    """The rendezvous key a request hashes on: **adapter affinity** when
    the request names a LoRA tenant (multi-tenant serving — same-tenant
    requests land together so the adapter is paged into ONE replica's
    pools instead of occupying a slot on all of them), else the prompt's
    prefix key (prefix-page affinity).  The two namespaces cannot
    collide: adapter keys carry a ``adapter|`` prefix no token spelling
    produces."""
    if adapter is not None:
        return b"adapter|" + str(adapter).encode()
    return prefix_key(prompt_ids, affinity_tokens)


class PrefixAffinityRouter:
    """See module docstring.

    ``saturation_queue``: a replica with this many queued requests no
    longer receives affine traffic (``None`` = its ``num_slots``, i.e. a
    full extra batch already waiting).  A replica whose health reasons
    include a stalled scheduler is treated as saturated regardless of
    queue depth — a wedged replica's queue may be short AND hopeless.
    """

    def __init__(self, n_replicas, affinity_tokens=16, policy="affinity",
                 saturation_queue=None, seed=0, prefix_match=True):
        if n_replicas < 1:
            raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
        if policy not in POLICIES:
            raise ValueError(f"policy must be one of {POLICIES}, "
                             f"got {policy!r}")
        self.n_replicas = int(n_replicas)
        self.affinity_tokens = int(affinity_tokens)
        self.policy = policy
        self.saturation_queue = None if saturation_queue is None \
            else int(saturation_queue)
        self.prefix_match = bool(prefix_match)
        self._rng = random.Random(seed)
        self._rr = itertools.count()

    def resize(self, n_replicas):
        """Retarget the router at ``n_replicas`` (elastic pools — the
        autoscaler grew or shrank membership).  Rendezvous hashing is
        stateless over the index range, so this is exactly the stability
        property the scheme exists for: when the pool shrinks only the
        removed index's prefixes move; when it grows only the prefixes
        the new index wins migrate to it."""
        n_replicas = int(n_replicas)
        if n_replicas < 1:
            raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
        self.n_replicas = n_replicas

    # ------------------------------------------------------------- hashing
    def _score(self, key, idx):
        h = hashlib.sha1(key + b"|" + str(idx).encode()).digest()
        return int.from_bytes(h[:8], "big")

    def _affine_for_key(self, key):
        return max(range(self.n_replicas),
                   key=lambda i: self._score(key, i))

    def affine_index(self, prompt_ids, adapter=None):
        """The request's rendezvous winner over ALL replica indices
        (adapter affinity when ``adapter`` names a tenant)."""
        return self._affine_for_key(
            routing_key(prompt_ids, self.affinity_tokens, adapter))

    # -------------------------------------------------------------- policy
    @staticmethod
    def _load(st):
        return st.get("queue_depth", 0) + st.get("active", 0)

    def _saturated(self, st):
        if st.get("stalled"):
            return True
        cap = self.saturation_queue if self.saturation_queue is not None \
            else max(1, int(st.get("num_slots", 1)))
        return st.get("queue_depth", 0) >= cap

    def _match_depth(self, prompt_ids, st):
        """Resident radix pages this replica already holds for the
        prompt: walk the prompt's page-boundary digests against the
        replica's exported summary until the first miss (a resident run
        exports every boundary along its path, so matches are contiguous
        from the root).  0 without a summary — non-radix replicas never
        attract deepest-match traffic."""
        summ = st.get("prefix_index") or {}
        digests = summ.get("digests")
        ps = int(summ.get("page_size") or 0)
        if not digests or ps < 1:
            return 0
        dig = set(digests)
        toks = [int(t) for t in list(prompt_ids)]
        depth = 0
        for k in range(1, len(toks) // ps + 1):
            if prefix_digest(toks[:k * ps]) not in dig:
                break
            depth = k
        return depth

    def _least_loaded(self, key, candidates, states):
        # rendezvous score as the tie-break so equal-load choices are
        # stable per prefix instead of always index 0
        return min(candidates,
                   key=lambda i: (self._load(states[i]),
                                  -self._score(key, i)))

    def route(self, prompt_ids, states, adapter=None):
        """Pick a replica for this prompt given live state snapshots
        (dicts with ``state``/``stalled``/``queue_depth``/``active``/
        ``num_slots``).  ``adapter`` switches the rendezvous key to the
        tenant's (see :func:`routing_key`).  Returns ``None`` when no
        replica is routable — the caller sheds the request."""
        if len(states) != self.n_replicas:
            raise ValueError(f"router built for {self.n_replicas} replicas, "
                             f"got {len(states)} states")
        key = routing_key(prompt_ids, self.affinity_tokens, adapter)
        affine = self._affine_for_key(key)
        routable = [i for i, st in enumerate(states)
                    if st.get("state") in ROUTABLE_STATES]
        if not routable:
            return None
        pages = 0
        if self.policy == "random":
            chosen = self._rng.choice(routable)
            reason = "random"
        elif self.policy == "round_robin":
            chosen = routable[next(self._rr) % len(routable)]
            reason = "round_robin"
        elif self.policy == "least_loaded":
            chosen = self._least_loaded(key, routable, states)
            reason = "least_loaded"
        else:
            # affinity: deepest resident radix match first (adapter
            # affinity keeps tenant keys on the rendezvous path — the
            # LoRA pools, not the KV pages, are the scarce resource
            # there), then the rendezvous winner, then fallback
            chosen = None
            if self.prefix_match and adapter is None:
                unsat = [i for i in routable
                         if not self._saturated(states[i])]
                depths = {i: self._match_depth(prompt_ids, states[i])
                          for i in unsat}
                best = max(depths.values(), default=0)
                if best > 0:
                    chosen = max(
                        (i for i in unsat if depths[i] == best),
                        key=lambda i: self._score(key, i))
                    reason, pages = "prefix_match", best
            if chosen is None:
                if affine in routable \
                        and not self._saturated(states[affine]):
                    chosen, reason = affine, "affinity"
                else:
                    # affine replica down or saturated: least-loaded
                    # fallback, preferring unsaturated replicas so a
                    # wedged replica's queue doesn't keep accreting
                    unsat = [i for i in routable
                             if not self._saturated(states[i])]
                    chosen = self._least_loaded(key, unsat or routable,
                                                states)
                    reason = "fallback_unroutable" \
                        if affine not in routable else "fallback_saturated"
        return RouteDecision(replica=chosen, affine=affine,
                             hit=chosen == affine, reason=reason,
                             policy=self.policy, prefix_pages=pages)
