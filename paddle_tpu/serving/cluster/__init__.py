"""paddle_tpu.serving.cluster — multi-replica serving with a
prefix-affinity router ("Fleet for inference", README "Cluster serving").

- :mod:`.pool` — :class:`ReplicaPool`: N :class:`ServingEngine` replicas
  over one shared model (dp for inference), each with its own scheduler /
  BlockManager / page pools / ``replica=`` metric label, optionally placed
  one-per-device from ``jax.devices()``.
- :mod:`.router` — :class:`PrefixAffinityRouter`: rendezvous-hash mapping
  from prompt prefixes to replicas so BlockManager prefix sharing keeps
  paying off under fan-out; health-aware, with least-loaded fallback when
  the affine replica is saturated, plus random / round-robin / least-loaded
  control policies.
- :mod:`.service` — :class:`ServingCluster`: the routed, resilient facade —
  submit/generate/stream across the pool, cross-replica in-flight requeue
  when a replica is lost (greedy ids byte-identical to an uninterrupted
  run), cluster-level /statusz section, /healthz component and ``cluster.*``
  metrics.
"""

from .pool import ReplicaPool  # noqa: F401
from .router import (  # noqa: F401
    POLICIES, ROUTABLE_STATES, PrefixAffinityRouter, RouteDecision,
    prefix_key,
)
from .service import ClusterHandle, ServingCluster  # noqa: F401

__all__ = [
    "ReplicaPool", "PrefixAffinityRouter", "RouteDecision", "prefix_key",
    "POLICIES", "ROUTABLE_STATES", "ServingCluster", "ClusterHandle",
]
