"""Page-granular radix prefix index — longest-shared-run prefix matching.

The SGLang-RadixAttention analog over this repo's paged KV pools: where
the legacy :class:`~paddle_tpu.serving.block_manager.BlockManager` cache
content-addresses each page by its FULL token prefix (so a prompt that
diverges one token past a 100-page shared prefix still matches, but only
because every shorter key happens to be registered), the radix index
stores resident prefixes as a compressed tree over page-sized token
blocks.  ``acquire`` walks the tree and returns the *longest shared page
run* — an arbitrary partial match, refcounted as a unit — and the caller
allocates fresh pages only for the divergent tail.  Because K/V at
position p is a pure function of tokens 0..p and the weights, every page
on a matched run already holds byte-exact K/V, which is what lets the
engine skip prefill compute for ``matched_pages * page_size`` tokens
(``PageAllocation.cached_pages``).

Structure: each node carries a RUN of ``(block, page)`` pairs — ``block``
a ``page_size``-token tuple, ``page`` the pool row encoding it — plus one
refcount for the whole run.  Matching that ends mid-run SPLITS the node
at the boundary so refcounts stay uniform per node (the radix-tree
discipline); refcounts are therefore non-increasing with depth, so a
node with ``refs == 0`` roots an entirely-idle subtree.  Idle nodes park
in an LRU order; eviction takes the least-recently-idled subtree and
frees its pages tail-first (deepest node, last block first), preserving
prefix contiguity — an interior page is never dropped while a descendant
survives.  Evicted pages are handed to the caller's spill hook before
the row is reused (serving/kv_spill.py re-pages them later).

Everything here is host-side Python over plain ints/tuples; the only
consumer is BlockManager under the engine lock, but all public methods
are safe to call under a single external mutex (BlockManager provides
one — the ``pfx`` concurrency tests hammer allocate/free from threads).
"""

from __future__ import annotations

import collections
import hashlib


def prefix_digest(token_ids):
    """Stable short digest of a token prefix — the currency the
    cross-replica placement speaks: :meth:`RadixPrefixIndex.summary`
    exports digests of every resident page-boundary prefix, and the
    PrefixAffinityRouter digests the incoming prompt the same way to find
    the replica with the deepest resident run (cluster/router.py)."""
    raw = ",".join(str(int(t)) for t in token_ids).encode()
    return hashlib.sha1(raw).hexdigest()[:16]


class _Node:
    __slots__ = ("blocks", "pages", "refs", "children", "parent", "ckey")

    def __init__(self, blocks, pages, refs, parent):
        self.blocks = list(blocks)   # page-sized token tuples, in order
        self.pages = list(pages)     # pool rows, parallel to blocks
        self.refs = int(refs)        # holders of THIS run (uniform per node)
        self.children = {}           # first-block tuple -> _Node
        self.parent = parent
        self.ckey = self.blocks[0] if self.blocks else None

    def depth_pages(self):
        return len(self.blocks)


class RadixPrefixIndex:
    def __init__(self, page_size):
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.page_size = int(page_size)
        self._root = _Node((), (), 0, None)
        self._idle = collections.OrderedDict()   # _Node -> None, LRU order
        self._idle_pages = 0
        self._resident_pages = 0
        self._nodes = 0
        self._splits = 0
        self._summary_cache = None

    # ------------------------------------------------------------- inspection
    @property
    def idle_pages(self):
        """Pages in refs==0 runs — evictable without touching a live
        sequence (the BlockManager's free_pages includes them)."""
        return self._idle_pages

    @property
    def resident_pages(self):
        return self._resident_pages

    def blocks_of(self, prompt_ids, limit):
        """The first ``limit`` page-sized token blocks of a prompt."""
        ps = self.page_size
        return [tuple(int(t) for t in prompt_ids[i * ps:(i + 1) * ps])
                for i in range(limit)]

    def _walk(self, blocks):
        """Longest resident match: list of ``(node, k)`` pairs — ``k``
        blocks matched inside each node (only the last pair may be
        partial) — without mutating the tree."""
        path, i, node = [], 0, self._root
        while i < len(blocks):
            child = node.children.get(blocks[i])
            if child is None:
                break
            k = 1
            while (k < len(child.blocks) and i + k < len(blocks)
                   and child.blocks[k] == blocks[i + k]):
                k += 1
            path.append((child, k))
            if k < len(child.blocks):
                break
            i += k
            node = child
        return path

    def match_depth(self, prompt_ids, limit):
        """(matched pages, matched pages currently idle) for a prompt,
        without acquiring — the BlockManager's admission plan uses the
        idle count to know how many evictable pages a hit would pin."""
        path = self._walk(self.blocks_of(prompt_ids, limit))
        depth = sum(k for _, k in path)
        idle = sum(k for node, k in path if node.refs == 0)
        return depth, idle

    # --------------------------------------------------------------- mutation
    def _split(self, node, k):
        """Split ``node`` after its k-th block; the suffix becomes a child
        carrying the original's children and refcount."""
        suf = _Node(node.blocks[k:], node.pages[k:], node.refs, node)
        suf.children = node.children
        for ch in suf.children.values():
            ch.parent = suf
        node.blocks = node.blocks[:k]
        node.pages = node.pages[:k]
        node.children = {suf.ckey: suf}
        self._nodes += 1
        self._splits += 1
        if node.refs == 0:
            # both halves stay idle and individually evictable
            self._idle[suf] = None
        self._summary_cache = None

    def acquire(self, blocks):
        """Pin the longest resident run covering ``blocks``: bump every
        node on the matched path (splitting the last node if the match
        ends mid-run) and return ``(pages, idle_reactivated, tip)`` —
        the matched pages in prefix order, how many came out of the idle
        cache, and the deepest matched node (:meth:`insert`'s attachment
        point; the root when nothing matched)."""
        path = self._walk(blocks)
        if path and path[-1][1] < len(path[-1][0].blocks):
            self._split(path[-1][0], path[-1][1])
        pages, reactivated = [], 0
        tip = self._root
        for node, k in path:
            if node.refs == 0:
                self._idle.pop(node, None)
                self._idle_pages -= len(node.pages)
                reactivated += len(node.pages)
            node.refs += 1
            pages.extend(node.pages)
            tip = node
        return pages, reactivated, tip

    def insert(self, tip, blocks, pages):
        """Register a fresh run of ``blocks``/``pages`` under ``tip`` (the
        node :meth:`acquire` returned) with refs=1.  The caller has
        already pinned the path above, so the child-refs <= parent-refs
        invariant holds by construction."""
        if not blocks:
            return tip
        if len(blocks) != len(pages):
            raise ValueError("insert needs one page per block")
        node = _Node(blocks, pages, 1, tip)
        tip.children[node.ckey] = node
        self._nodes += 1
        self._resident_pages += len(pages)
        self._summary_cache = None
        return node

    def release(self, blocks):
        """Unpin a full path (the exact depth a prior acquire+insert
        covered — always a node boundary, since boundaries are only ever
        added).  Runs whose refcount hits zero park in the idle LRU."""
        path = self._walk(blocks)
        depth = sum(k for _, k in path)
        if depth != len(blocks):
            raise KeyError(
                f"release of unregistered prefix: matched {depth} of "
                f"{len(blocks)} pages")
        last, k = path[-1] if path else (self._root, 0)
        if path and k < len(last.blocks):
            raise KeyError("release depth falls mid-run")
        for node, _ in path:
            if node.refs <= 0:
                raise RuntimeError("refcount underflow in prefix index")
            node.refs -= 1
            if node.refs == 0:
                self._idle[node] = None
                self._idle_pages += len(node.pages)

    def evict_one(self):
        """Reclaim ONE page from the least-recently-idled subtree,
        tail-first: descend to the deepest idle descendant and pop its
        last ``(block, page)`` pair.  Returns ``(key_tokens, page)`` —
        the full token prefix the page encodes (the spill tier's content
        address) — or ``None`` when nothing is idle."""
        if not self._idle:
            return None
        node = next(iter(self._idle))
        while node.children:
            node = next(iter(node.children.values()))
        block = node.blocks.pop()
        page = node.pages.pop()
        self._idle_pages -= 1
        self._resident_pages -= 1
        # content address: every block from the root down to (and
        # including) the one this page encoded
        toks = list(block)
        cur = node
        while cur is not None:
            for b in reversed(cur.blocks):
                toks[:0] = b
            cur = cur.parent
        if not node.blocks:
            if node.parent is not None:
                node.parent.children.pop(node.ckey, None)
            self._idle.pop(node, None)
            self._nodes -= 1
        self._summary_cache = None
        return tuple(toks), page

    def clear(self):
        self._root = _Node((), (), 0, None)
        self._idle.clear()
        self._idle_pages = 0
        self._resident_pages = 0
        self._nodes = 0
        self._summary_cache = None

    # ---------------------------------------------------------------- export
    def stats(self):
        return {
            "nodes": self._nodes,
            "resident_pages": self._resident_pages,
            "idle_pages": self._idle_pages,
            "splits": self._splits,
        }

    def summary(self, max_depth=16, max_entries=512):
        """Resident-prefix digest set for cross-replica placement: one
        :func:`prefix_digest` per resident page-boundary prefix, depth
        capped (routing only needs the head of the tree) and entry
        capped (states snapshots stay JSON-small).  Cached until the
        tree's structure changes — routers snapshot this on every
        route, eviction/insert is the rare event."""
        if self._summary_cache is not None:
            return self._summary_cache
        digests = []
        stack = [(self._root, [])]
        while stack and len(digests) < max_entries:
            node, toks = stack.pop()
            for b in node.blocks:
                toks = toks + list(b)
                if len(toks) // self.page_size > max_depth:
                    break
                digests.append(prefix_digest(toks))
                if len(digests) >= max_entries:
                    break
            if len(toks) // self.page_size <= max_depth:
                for ch in node.children.values():
                    stack.append((ch, toks))
        self._summary_cache = {
            "page_size": self.page_size,
            "digests": digests,
            "resident_pages": self._resident_pages,
        }
        return self._summary_cache
