"""Grammar-constrained decoding: JSON-schema / regex -> token FSM.

Outlines-style construction (Willard & Louf, "Efficient Guided Generation
for Large Language Models"): a regular expression is compiled to a
character-level DFA, then lifted to a TOKEN-level FSM against the serving
vocabulary — state ``s`` admits token ``t`` iff walking ``t``'s characters
from ``s`` stays inside the live DFA.  The engine keeps one FSM state per
constrained request and, each step, applies the state's precomputed
``allowed [V]`` boolean mask inside the compiled batched sampler
(``make_masked_batched_sampler``) — schema-valid output becomes a per-row
property of the one shared decode program instead of a second engine.

The regex dialect is the practical subset JSON grammars need: literals,
escapes (``\\d \\w \\s`` + escaped specials), character classes with
ranges and negation, ``.``, ``* + ?``, bounded ``{m}``/``{m,n}``/
``{m,}``, alternation and groups.  :func:`json_schema_to_regex` lowers a
JSON-schema subset (object/array/string/integer/number/boolean/null/enum,
properties emitted in declaration order, compact separators) onto it, so
``compile_json_schema(schema, vocab, eos)`` guarantees every completed
row parses as schema-valid JSON.

EOS semantics: the EOS token is allowed exactly in ACCEPTING states (the
match is complete there), so a constrained row can only stop on a fully
valid document; :class:`~.engine.MultiTenantEngine` defaults the row's
``eos_token_id`` to the FSM's.
"""

from __future__ import annotations

import json

import numpy as np

#: hard cap on discovered token-FSM states — a loud failure beats an
#: unbounded subset construction on a pathological pattern
MAX_STATES = 20000

_EPS = None  # epsilon edge marker in the NFA

_CLASSES = {
    "d": (False, frozenset("0123456789")),
    "w": (False, frozenset(
        "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_")),
    "s": (False, frozenset(" \t\n\r\f\v")),
    "D": (True, frozenset("0123456789")),
    "W": (True, frozenset(
        "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_")),
    "S": (True, frozenset(" \t\n\r\f\v")),
}

_ESCAPABLE = frozenset("\\.^$*+?{}[]()|/-\"'")


# ---------------------------------------------------------------- regex AST
class _Parser:
    """Recursive-descent regex -> AST.  Nodes: ('lit', matcher),
    ('cat', [..]), ('alt', [..]), ('rep', node, m, n|None) where a
    matcher is ``(negated, frozenset_of_chars)``."""

    def __init__(self, pattern):
        self.p = pattern
        self.i = 0

    def error(self, msg):
        raise ValueError(f"regex error at {self.i} in {self.p!r}: {msg}")

    def peek(self):
        return self.p[self.i] if self.i < len(self.p) else None

    def take(self):
        c = self.peek()
        if c is None:
            self.error("unexpected end of pattern")
        self.i += 1
        return c

    def parse(self):
        node = self.alt()
        if self.i != len(self.p):
            self.error(f"unexpected {self.p[self.i]!r}")
        return node

    def alt(self):
        branches = [self.cat()]
        while self.peek() == "|":
            self.take()
            branches.append(self.cat())
        return branches[0] if len(branches) == 1 else ("alt", branches)

    def cat(self):
        items = []
        while self.peek() is not None and self.peek() not in "|)":
            items.append(self.repeat())
        if not items:
            return ("cat", [])      # empty branch: matches ""
        return items[0] if len(items) == 1 else ("cat", items)

    def repeat(self):
        node = self.atom()
        while True:
            c = self.peek()
            if c == "*":
                self.take()
                node = ("rep", node, 0, None)
            elif c == "+":
                self.take()
                node = ("rep", node, 1, None)
            elif c == "?":
                self.take()
                node = ("rep", node, 0, 1)
            elif c == "{":
                self.take()
                m = self._int()
                n = m
                if self.peek() == ",":
                    self.take()
                    n = self._int() if self.peek() != "}" else None
                if self.take() != "}":
                    self.error("expected '}'")
                if n is not None and n < m:
                    self.error(f"bad bound {{{m},{n}}}")
                node = ("rep", node, m, n)
            else:
                return node

    def _int(self):
        ds = ""
        while self.peek() is not None and self.peek().isdigit():
            ds += self.take()
        if not ds:
            self.error("expected integer")
        return int(ds)

    def atom(self):
        c = self.take()
        if c == "(":
            node = self.alt()
            if self.take() != ")":
                self.error("expected ')'")
            return node
        if c == "[":
            return ("lit", self._char_class())
        if c == ".":
            return ("lit", (True, frozenset("\n")))    # any but newline
        if c == "\\":
            return ("lit", self._escape())
        if c in "*+?{}|)":
            self.error(f"dangling {c!r}")
        return ("lit", (False, frozenset(c)))

    def _escape(self):
        e = self.take()
        if e in _CLASSES:
            return _CLASSES[e]
        if e == "n":
            return (False, frozenset("\n"))
        if e == "t":
            return (False, frozenset("\t"))
        if e == "r":
            return (False, frozenset("\r"))
        if e in _ESCAPABLE:
            return (False, frozenset(e))
        self.error(f"unsupported escape \\{e}")

    def _char_class(self):
        neg = False
        if self.peek() == "^":
            self.take()
            neg = True
        chars = set()
        first = True
        while True:
            c = self.peek()
            if c is None:
                self.error("unterminated character class")
            if c == "]" and not first:
                self.take()
                break
            c = self.take()
            first = False
            if c == "\\":
                n, cs = self._escape()
                if n:
                    self.error("negated class escape inside [...]")
                chars |= cs
                continue
            if self.peek() == "-" and self.i + 1 < len(self.p) \
                    and self.p[self.i + 1] != "]":
                self.take()
                hi = self.take()
                if hi == "\\":
                    _, cs = self._escape()
                    hi = min(cs)
                if ord(hi) < ord(c):
                    self.error(f"bad range {c}-{hi}")
                chars |= {chr(o) for o in range(ord(c), ord(hi) + 1)}
            else:
                chars.add(c)
        return (neg, frozenset(chars))


# ------------------------------------------------------------- NFA / DFA
class _NFA:
    """Thompson construction over the AST.  Edges: node -> list of
    (matcher | None, target); matcher None is epsilon."""

    def __init__(self, ast):
        self.edges = []
        self.start = self._node()
        self.accept = self._node()
        self._build(ast, self.start, self.accept)

    def _node(self):
        self.edges.append([])
        return len(self.edges) - 1

    def _edge(self, a, b, matcher=_EPS):
        self.edges[a].append((matcher, b))

    def _build(self, ast, s, a):
        kind = ast[0]
        if kind == "lit":
            self._edge(s, a, ast[1])
        elif kind == "cat":
            cur = s
            for i, item in enumerate(ast[1]):
                nxt = a if i == len(ast[1]) - 1 else self._node()
                self._build(item, cur, nxt)
                cur = nxt
            if not ast[1]:
                self._edge(s, a)
        elif kind == "alt":
            for branch in ast[1]:
                bs, ba = self._node(), self._node()
                self._edge(s, bs)
                self._build(branch, bs, ba)
                self._edge(ba, a)
        elif kind == "rep":
            _, inner, m, n = ast
            cur = s
            for _ in range(m):              # mandatory copies
                nxt = self._node()
                self._build(inner, cur, nxt)
                cur = nxt
            if n is None:                   # x{m,}: Kleene tail
                ls, la = self._node(), self._node()
                self._edge(cur, ls)
                self._build(inner, ls, la)
                self._edge(la, ls)
                self._edge(cur, a)
                self._edge(la, a)
            else:
                for _ in range(n - m):      # optional copies
                    nxt = self._node()
                    self._build(inner, cur, nxt)
                    self._edge(cur, a)
                    cur = nxt
                self._edge(cur, a)
        else:  # pragma: no cover - parser emits only the kinds above
            raise AssertionError(kind)

    def closure(self, states):
        out = set(states)
        stack = list(states)
        while stack:
            s = stack.pop()
            for matcher, t in self.edges[s]:
                if matcher is _EPS and t not in out:
                    out.add(t)
                    stack.append(t)
        return frozenset(out)

    def move(self, states, ch):
        out = set()
        for s in states:
            for matcher, t in self.edges[s]:
                if matcher is _EPS:
                    continue
                neg, chars = matcher
                if (ch in chars) != neg:
                    out.add(t)
        return self.closure(out) if out else None


class CompiledGrammar:
    """The token-level FSM the engine walks (one integer state per
    constrained request).  Built lazily over token-level reachability:
    only states an actual generation can visit are materialized.

    - ``start`` — initial state id;
    - ``allowed(state)`` — ``np.bool_ [V]`` mask of legal next tokens
      (EOS legal iff the state is accepting);
    - ``advance(state, token_id)`` — next state (``None`` for EOS / an
      illegal token);
    - ``is_final(state)`` — the matched prefix is a complete document.
    """

    def __init__(self, pattern, vocab, eos_token_id):
        if eos_token_id is None:
            raise ValueError("a grammar needs an eos_token_id: EOS is how "
                             "a constrained row says 'document complete'")
        self.pattern = str(pattern)
        self.vocab = list(vocab)
        self.vocab_size = len(self.vocab)
        self.eos_token_id = int(eos_token_id)
        if not 0 <= self.eos_token_id < self.vocab_size:
            raise ValueError(f"eos_token_id {eos_token_id} outside the "
                             f"{self.vocab_size}-token vocab")
        self._nfa = _NFA(_Parser(self.pattern).parse())
        # one grammar may be shared by many requests across several engine
        # scheduler threads (cluster replicas): lazy expansion is locked
        import threading

        self._lock = threading.RLock()
        self._char_trans = {}           # frozenset -> {ch -> frozenset|None}
        self._ids = {}                  # frozenset -> dense state id
        self._sets = []                 # dense id -> frozenset
        self._tok_trans = []            # dense id -> {tok -> dense id}
        self._masks = []                # dense id -> np.bool_ [V]
        self._final = []                # dense id -> bool
        # dead-end pruning: a token is only legal when its walk ends in a
        # LIVE char-DFA state (an accepting state stays reachable through
        # characters the vocab can actually spell).  Without this, a mask
        # could admit a token whose continuation no vocab token covers and
        # strand the row mid-document — masks are one-token lookahead.
        self._alphabet = sorted({ch for i, s in enumerate(self.vocab)
                                 if i != self.eos_token_id for ch in s})
        self._live = self._compute_live()
        self.start = self._intern(self._nfa.closure({self._nfa.start}))
        if self._sets[self.start] not in self._live:
            raise ValueError(
                f"grammar {self.pattern!r} has no completion spellable in "
                "this vocabulary (missing characters?)")

    def _compute_live(self):
        """Explore the full char-DFA over the vocab alphabet, then walk
        the edges backwards from the accepting states: the surviving set
        is every state from which a complete match is still spellable."""
        start = self._nfa.closure({self._nfa.start})
        seen = {start}
        order = [start]
        back = {}                       # state -> set of predecessors
        i = 0
        while i < len(order):
            cur = order[i]
            i += 1
            if len(seen) > MAX_STATES:
                raise ValueError(
                    f"grammar {self.pattern!r} exceeded {MAX_STATES} "
                    "char-DFA states; simplify the pattern")
            for ch in self._alphabet:
                nxt = self._char_step(cur, ch)
                if nxt is None:
                    continue
                back.setdefault(nxt, set()).add(cur)
                if nxt not in seen:
                    seen.add(nxt)
                    order.append(nxt)
        live = {s for s in seen if self._nfa.accept in s}
        stack = list(live)
        while stack:
            s = stack.pop()
            for p in back.get(s, ()):
                if p not in live:
                    live.add(p)
                    stack.append(p)
        return live

    # ------------------------------------------------------------ internals
    def _intern(self, nfa_set):
        sid = self._ids.get(nfa_set)
        if sid is not None:
            return sid
        if len(self._sets) >= MAX_STATES:
            raise ValueError(
                f"grammar {self.pattern!r} exceeded {MAX_STATES} token-FSM "
                "states; simplify the pattern (tighter bounds on {m,n} "
                "repetitions usually do it)")
        sid = len(self._sets)
        self._ids[nfa_set] = sid
        self._sets.append(nfa_set)
        self._tok_trans.append(None)    # computed lazily
        self._masks.append(None)
        self._final.append(self._nfa.accept in nfa_set)
        return sid

    def _char_step(self, nfa_set, ch):
        row = self._char_trans.setdefault(nfa_set, {})
        if ch not in row:
            row[ch] = self._nfa.move(nfa_set, ch)
        return row[ch]

    def _expand(self, sid):
        if self._tok_trans[sid] is not None:
            return
        with self._lock:
            self._expand_locked(sid)

    def _expand_locked(self, sid):
        if self._tok_trans[sid] is not None:
            return
        trans = {}
        mask = np.zeros((self.vocab_size,), np.bool_)
        src = self._sets[sid]
        for tok, s in enumerate(self.vocab):
            if tok == self.eos_token_id or not s:
                continue            # EOS handled below; empty tokens never
            cur = src
            for ch in s:
                cur = self._char_step(cur, ch)
                if cur is None:
                    break
            if cur is not None and cur in self._live:
                trans[tok] = self._intern(cur)
                mask[tok] = True
        mask[self.eos_token_id] = self._final[sid]
        # masks first, the trans dict last: _tok_trans doubles as the
        # "expanded" flag the unlocked fast path reads
        self._masks[sid] = mask
        self._tok_trans[sid] = trans

    # ----------------------------------------------------------------- api
    def allowed(self, state):
        self._expand(state)
        mask = self._masks[state]
        if not mask.any():
            # char-liveness says a completion is spellable, but no single
            # vocab TOKEN tiles the next step (pathological vocabs only —
            # BPE vocabs carry all single bytes).  Fail the request loudly
            # instead of letting an unmasked sampler emit junk.
            raise ValueError(
                f"grammar {self.pattern!r} reached a state no vocab token "
                "can continue; the vocabulary cannot tile this pattern")
        return mask

    def advance(self, state, token_id):
        self._expand(state)
        return self._tok_trans[state].get(int(token_id))

    def advance_seq(self, state, token_ids):
        """Fold :meth:`advance` over already-emitted tokens — how a
        re-admitted request (engine restart, cluster failover) resumes
        its grammar state from prompt + tokens-so-far."""
        for t in token_ids:
            if int(t) == self.eos_token_id:
                break
            state = self.advance(state, t)
            if state is None:
                raise ValueError(
                    f"token {int(t)} is not reachable in grammar "
                    f"{self.pattern!r} from the replayed state")
        return state

    def is_final(self, state):
        return self._final[state]

    def matches(self, token_ids):
        """Host-side oracle: do these generated ids (EOS-terminated or
        not) spell a COMPLETE document of the grammar?"""
        state = self.start
        for t in token_ids:
            if int(t) == self.eos_token_id:
                break
            state = self.advance(state, t)
            if state is None:
                return False
        return self.is_final(state)

    @property
    def num_states(self):
        """Token-FSM states materialized so far (lazy expansion)."""
        return len(self._sets)

    def __repr__(self):
        return (f"CompiledGrammar({self.pattern!r}, V={self.vocab_size}, "
                f"eos={self.eos_token_id}, states={self.num_states})")


# ------------------------------------------------------------ JSON schemas
def _regex_escape(text):
    return "".join("\\" + c if c in _ESCAPABLE and c != "'" else c
                   for c in str(text))


_STRING_CHARS = "[A-Za-z0-9_\\- ]"


def json_schema_to_regex(schema, max_string=16, max_items=4, max_digits=6):
    """Lower a JSON-schema subset to the regex dialect above (compact
    separators, no insignificant whitespace — what a sampler should emit).

    Supported: ``enum``/``const`` (JSON-encoded alternation), ``type`` in
    string (``pattern`` honored verbatim as the in-quote body,
    ``maxLength`` bounds the default body), integer, number, boolean,
    null, array (``items``/``minItems``/``maxItems``), object
    (``properties`` emitted in declaration order; every declared property
    is emitted — optionality would need backtracking budgets that belong
    to a future PR and is rejected loudly via ``required`` mismatch)."""
    if not isinstance(schema, dict):
        raise TypeError(f"schema must be a dict, got {type(schema).__name__}")
    if "enum" in schema or "const" in schema:
        options = schema.get("enum", [schema.get("const")])
        return "(" + "|".join(
            _regex_escape(json.dumps(o, separators=(",", ":")))
            for o in options) + ")"
    t = schema.get("type")
    if t == "string":
        if "pattern" in schema:
            return f"\"({schema['pattern']})\""
        n = int(schema.get("maxLength", max_string))
        lo = int(schema.get("minLength", 0))
        return f"\"{_STRING_CHARS}{{{lo},{n}}}\""
    if t == "integer":
        return f"(-?(0|[1-9][0-9]{{0,{max_digits - 1}}}))"
    if t == "number":
        return (f"(-?(0|[1-9][0-9]{{0,{max_digits - 1}}})"
                f"(\\.[0-9]{{1,{max_digits}}})?)")
    if t == "boolean":
        return "(true|false)"
    if t == "null":
        return "null"
    if t == "array":
        item = json_schema_to_regex(schema.get("items", {"type": "integer"}),
                                    max_string, max_items, max_digits)
        lo = int(schema.get("minItems", 0))
        hi = int(schema.get("maxItems", max_items))
        if hi < 1 or hi < lo:
            raise ValueError(f"bad array bounds [{lo}, {hi}]")
        if lo == 0:
            return f"\\[({item}(,{item}){{0,{hi - 1}}})?\\]"
        return f"\\[{item}(,{item}){{{lo - 1},{hi - 1}}}\\]"
    if t == "object":
        props = schema.get("properties", {})
        if not props:
            return "\\{\\}"
        required = schema.get("required")
        if required is not None and set(required) != set(props):
            raise ValueError(
                "optional properties are not supported: every declared "
                f"property is emitted (properties {sorted(props)} vs "
                f"required {sorted(required)})")
        parts = []
        for name, sub in props.items():
            key = _regex_escape(json.dumps(str(name)))
            parts.append(f"{key}:" + json_schema_to_regex(
                sub, max_string, max_items, max_digits))
        return "\\{" + ",".join(parts) + "\\}"
    raise ValueError(f"unsupported schema: {schema!r}")


def compile_regex(pattern, vocab, eos_token_id):
    """Regex -> :class:`CompiledGrammar` over ``vocab`` (token id ->
    string).  Precompile ONCE per (grammar, vocab) and share across
    requests — the FSM is read-mostly (lazy state expansion is guarded by
    the engine's scheduler thread ownership)."""
    return CompiledGrammar(pattern, vocab, eos_token_id)


def compile_json_schema(schema, vocab, eos_token_id, **bounds):
    """JSON schema -> :class:`CompiledGrammar` (see
    :func:`json_schema_to_regex` for the supported subset)."""
    g = compile_regex(json_schema_to_regex(schema, **bounds), vocab,
                      eos_token_id)
    g.schema = schema
    return g
