"""Paged multi-LoRA: adapter definitions, the rank-bucketed LoRAStore,
and the LoRA-aware engine adapters.

S-LoRA-style serving (Sheng et al.): every registered fine-tune's low-rank
pairs live in GLOBAL rank-bucketed device pools — one ``A [L, C+1, d_in,
r]`` / ``B [L, C+1, r, d_out]`` pair per (decoder Linear target, rank
bucket) — and each batch row gathers ITS adapter by slot id INSIDE the
compiled prefill/decode/verify programs (:mod:`paddle_tpu.ops.lora`).
The compiled-program count is a function of the CONFIGURED rank buckets,
never of the adapter population: registering, evicting or hot-swapping
adapters at runtime changes pool *contents* (same shapes), so no program
is ever re-traced for it.

Slot management follows the BlockManager pattern at adapter granularity
(:class:`_SlotAllocator` = refcounted active set + idle-LRU cache +
free list): an adapter is *registered* host-side (cheap), *paged in* to a
device slot on first acquire, refcounted while any live request uses it,
parked idle on release, and evicted LRU when the pool needs the slot —
an idle re-acquire is a pure refcount bump, no device write.  Slot row 0
of every pool is the reserved NULL adapter (zeros): base-model rows gather
exact-zero deltas, so one batch freely mixes tenants and the base model.

Composition: pools default to the MODEL dtype but can pin ``dtype=``
(e.g. bf16 adapters over an int8-weight base — the bypass runs on the
Int8Linear's output, see ``GPTDecoderLayer._lin``), and
:class:`LoRAQuantizedGPTAdapter` runs the same gathers over int8 KV
pools, so quantized serving and multi-LoRA stack.
"""

from __future__ import annotations

import collections
import threading

import jax.numpy as jnp
import numpy as np

from ...ops.lora import gather_adapter
from ..adapter import GPTAdapter
from ..quant.adapter import QuantizedGPTAdapter

#: decoder Linear targets a LoRA pair may attach to, in pool order
TARGETS = ("qkv", "out_proj", "ffn1", "ffn2")


class LoRAAdapter:
    """One tenant's fine-tune: per-(layer, target) low-rank pairs.

    ``weights[(layer_idx, target)] = (A [d_in, rank], B [rank, d_out])``
    host arrays; targets may cover any subset of :data:`TARGETS` (missing
    (layer, target) pairs contribute nothing — their pool rows stay the
    null zeros).  ``scaling`` (the classic alpha/rank) is folded into B
    when the adapter is paged in."""

    def __init__(self, name, rank, weights, scaling=1.0):
        self.name = str(name)
        self.rank = int(rank)
        if self.rank < 1:
            raise ValueError(f"rank must be >= 1, got {rank}")
        self.scaling = float(scaling)
        self.weights = {}
        for (layer, target), (a, b) in weights.items():
            if target not in TARGETS:
                raise ValueError(f"unknown LoRA target {target!r} "
                                 f"(expected one of {TARGETS})")
            a = np.asarray(a)
            b = np.asarray(b)
            if a.shape[1] != self.rank or b.shape[0] != self.rank:
                raise ValueError(
                    f"({layer}, {target}): A {a.shape} / B {b.shape} do not "
                    f"carry rank {self.rank}")
            self.weights[(int(layer), target)] = (a, b)

    @classmethod
    def random(cls, model, name, rank, targets=("qkv", "out_proj"),
               seed=0, scale=0.02, scaling=1.0):
        """A seeded random adapter over every decoder layer — the test /
        example / bench stand-in for a real fine-tune."""
        rng = np.random.RandomState(seed)
        shapes = target_shapes(model)
        weights = {}
        for layer in range(num_decoder_layers(model)):
            for t in targets:
                d_in, d_out = shapes[t]
                weights[(layer, t)] = (
                    rng.normal(0, scale, (d_in, rank)),
                    rng.normal(0, scale, (rank, d_out)))
        return cls(name, rank, weights, scaling=scaling)

    def __repr__(self):
        return (f"LoRAAdapter({self.name!r}, rank={self.rank}, "
                f"pairs={len(self.weights)})")


def _linear_shape(blk, target):
    lin = getattr(blk, target)
    w = getattr(lin, "weight", None)
    if w is None:                       # Int8Linear (weight_dtype="int8")
        w = lin.weight_int8
    return (int(w.shape[0]), int(w.shape[1]))


def target_shapes(model):
    """(d_in, d_out) per LoRA target for this model's decoder blocks."""
    blk = model.gpt.layers[0]
    return {t: _linear_shape(blk, t) for t in TARGETS}


def num_decoder_layers(model):
    return len(model.gpt.layers)


class _SlotAllocator:
    """BlockManager's allocation pattern at adapter-slot granularity:
    refcounted active rows, an idle LRU of resident-but-unused rows, and
    a free list.  Rows are 0-based; the store maps them to pool row+1
    (pool row 0 is the null adapter)."""

    def __init__(self, capacity):
        self.capacity = int(capacity)
        self._free = collections.deque(range(self.capacity))
        self._active = {}                       # name -> [row, refs]
        self._idle = collections.OrderedDict()  # name -> row (LRU)

    def acquire(self, name):
        """-> (row, resident, evicted_name) or None when every slot is
        pinned by live requests."""
        ent = self._active.get(name)
        if ent is not None:
            ent[1] += 1
            return ent[0], True, None
        if name in self._idle:
            row = self._idle.pop(name)
            self._active[name] = [row, 1]
            return row, True, None
        evicted = None
        if self._free:
            row = self._free.popleft()
        elif self._idle:
            evicted, row = self._idle.popitem(last=False)
        else:
            return None                 # all slots pinned by live requests
        self._active[name] = [row, 1]
        return row, False, evicted

    def release(self, name):
        ent = self._active[name]
        ent[1] -= 1
        if ent[1] == 0:
            del self._active[name]
            self._idle[name] = ent[0]

    def forget(self, name):
        """Drop an idle residency (explicit evict)."""
        if name in self._idle:
            self._free.append(self._idle.pop(name))

    def refs(self, name):
        ent = self._active.get(name)
        return ent[1] if ent is not None else 0

    def resident(self, name):
        return name in self._active or name in self._idle

    def reset(self):
        self._free = collections.deque(range(self.capacity))
        self._active.clear()
        self._idle.clear()


class TenantLease:
    """One live request's hold on a paged-in adapter (released at
    retirement; refcounts are per-request, mirroring prefix pages)."""

    __slots__ = ("name", "bucket", "row")

    def __init__(self, name, bucket, row):
        self.name = name
        self.bucket = int(bucket)
        self.row = int(row)             # pool row (null row 0 excluded)


class LoRAStore:
    """See module docstring.  ``ranks`` fixes the bucket set (and with it
    every compiled program's signature) up front; ``capacity`` is adapter
    slots PER bucket; ``targets`` the decoder Linears carrying pairs.

    Thread model: ``register``/``evict`` run on caller threads (host
    registry only); ``acquire``/``release``/device writes run on engine
    scheduler threads.  One lock covers both — a shared store serves
    several cluster replicas."""

    def __init__(self, model, capacity=8, ranks=(8,), targets=None,
                 dtype=None):
        self.model = model
        self.capacity = int(capacity)
        if self.capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.ranks = tuple(sorted(int(r) for r in ranks))
        if not self.ranks or any(r < 1 for r in self.ranks):
            raise ValueError(f"ranks must be positive, got {ranks}")
        self.targets = tuple(targets) if targets is not None \
            else ("qkv", "out_proj")
        for t in self.targets:
            if t not in TARGETS:
                raise ValueError(f"unknown target {t!r}")
        self.num_layers = num_decoder_layers(model)
        self._shapes = target_shapes(model)
        if dtype is None:
            dtype = model.gpt.word_embeddings.weight._value.dtype
        self.dtype = jnp.dtype(dtype)
        self._lock = threading.RLock()
        self._registry = {}     # name -> (bucket_idx, padded host {t: (A,B)})
        self._alloc = [_SlotAllocator(self.capacity) for _ in self.ranks]
        self._row_owner = [dict() for _ in self.ranks]  # row -> name
        self._pools = self._init_pools()
        from ...profiler import metrics as _metrics

        self._m_swaps = _metrics.counter(
            "serving.lora_swaps",
            "adapter page-ins (device pool writes); an idle re-acquire is "
            "a refcount bump, not a swap")
        self._m_resident = _metrics.gauge(
            "serving.lora_resident", "adapters resident in the device pools")
        self._m_registered = _metrics.gauge(
            "serving.lora_registered", "adapters in the host registry")
        self._register_memory()

    def _register_memory(self):
        """Per-rank-bucket ledger owners ``lora.r<r>`` (observability/
        memory.py): each bucket's A/B pool slice registers as one owner
        so the /statusz owner table shows where multi-tenant HBM goes by
        rank.  Sources close over a weakref — the ledger never pins the
        store.  replica="shared": one store serves N cluster replicas."""
        import weakref

        from ...observability import memory as _obs_memory

        led = _obs_memory.ledger()
        ref = weakref.ref(self)
        per_bucket = 2 * len(self.targets)
        for bi, r in enumerate(self.ranks):
            def src(bi=bi):
                st = ref()
                if st is None:
                    return None
                return list(
                    st._pools[bi * per_bucket:(bi + 1) * per_bucket])
            led.register(f"lora.r{r}", src, replica="shared",
                         meta={"kind": "lora", "rank": r,
                               "capacity": self.capacity,
                               "targets": list(self.targets)})

    # ------------------------------------------------------------- identity
    def signature(self):
        """Static tuple baked into every compiled program key: programs
        depend on pool SHAPES (buckets, capacity, targets, dtype), never
        on which adapters currently occupy them."""
        return (self.ranks, self.capacity, self.targets, str(self.dtype),
                self.num_layers)

    @property
    def n_args(self):
        """Device arrays :meth:`device_args` contributes per dispatch."""
        return 2 * len(self.targets) * len(self.ranks)

    def family_suffix(self):
        """Perf-attribution suffix for the LoRA program families, e.g.
        ``@lora-r8`` / ``@lora-r4+16`` (one decode program per rank-bucket
        SET — adapter count never appears)."""
        return "@lora-r" + "+".join(str(r) for r in self.ranks)

    def _init_pools(self):
        pools = []
        for r in self.ranks:
            for t in self.targets:
                d_in, d_out = self._shapes[t]
                pools.append(jnp.zeros(
                    (self.num_layers, self.capacity + 1, d_in, r),
                    self.dtype))
                pools.append(jnp.zeros(
                    (self.num_layers, self.capacity + 1, r, d_out),
                    self.dtype))
        return tuple(pools)

    def pool_bytes(self):
        return int(sum(int(p.nbytes) for p in self._pools))

    def device_args(self):
        """The flat pool tuple appended to every engine dispatch (read-
        only in the programs — NOT donated; a register/page-in between
        steps swaps array references, never shapes)."""
        return self._pools

    # ------------------------------------------------------------- registry
    def bucket_for(self, rank):
        for i, r in enumerate(self.ranks):
            if rank <= r:
                return i
        raise ValueError(
            f"rank {rank} exceeds every configured bucket {self.ranks}; "
            "rank buckets are fixed at store construction (they define "
            "the compiled-program family)")

    def register(self, adapter: LoRAAdapter):
        """Host-side registration (cheap; device page-in is deferred to
        first acquire).  Re-registering a name replaces its weights: the
        old residency is invalidated, so the NEXT request picks up the
        new weights without an engine restart.  Raises while live
        requests hold the old weights — an in-flight tenant must not see
        its pair swapped mid-decode (release them or use a new name)."""
        bi = self.bucket_for(adapter.rank)
        rb = self.ranks[bi]
        padded = {}
        for t in self.targets:
            d_in, d_out = self._shapes[t]
            a = np.zeros((self.num_layers, d_in, rb), np.float64)
            b = np.zeros((self.num_layers, rb, d_out), np.float64)
            for layer in range(self.num_layers):
                pair = adapter.weights.get((layer, t))
                if pair is None:
                    continue
                a[layer, :, :adapter.rank] = pair[0]
                b[layer, :adapter.rank, :] = pair[1] * adapter.scaling
            padded[t] = (a.astype(self.dtype), b.astype(self.dtype))
        with self._lock:
            old = self._registry.get(adapter.name)
            if old is not None and self._alloc[old[0]].refs(adapter.name):
                raise RuntimeError(
                    f"adapter {adapter.name!r} is held by live request(s); "
                    "re-register after they retire, or use a new name")
            self._invalidate_rows(adapter.name)
            self._registry[adapter.name] = (bi, padded)
            self._m_registered.set(len(self._registry))
        return adapter.name

    def _invalidate_rows(self, name):
        for bi, owners in enumerate(self._row_owner):
            rows = [row for row, n in owners.items() if n == name]
            for row in rows:
                del owners[row]
            self._alloc[bi].forget(name)

    def evict(self, name):
        """Drop an adapter from the registry AND its idle residency.
        Raises while live requests still hold it (release them first —
        an in-flight tenant must not lose its weights mid-decode)."""
        with self._lock:
            if name not in self._registry:
                raise KeyError(f"adapter {name!r} is not registered")
            bi = self._registry[name][0]
            if self._alloc[bi].refs(name):
                raise RuntimeError(
                    f"adapter {name!r} is held by "
                    f"{self._alloc[bi].refs(name)} live request(s)")
            self._invalidate_rows(name)
            del self._registry[name]
            self._m_registered.set(len(self._registry))
            self._update_resident_gauge()

    def registered(self, name):
        return name in self._registry

    @property
    def names(self):
        return sorted(self._registry)

    # ------------------------------------------------------------ residency
    def acquire(self, name):
        """Pin ``name`` into a device slot for one request.  Returns a
        :class:`TenantLease`, or ``None`` when every slot of the bucket is
        pinned by live requests (the engine keeps the request queued —
        the adapter analog of page-pool admission control)."""
        with self._lock:
            ent = self._registry.get(name)
            if ent is None:
                raise KeyError(f"adapter {name!r} is not registered")
            bi, padded = ent
            got = self._alloc[bi].acquire(name)
            if got is None:
                return None
            row, resident, evicted = got
            owners = self._row_owner[bi]
            if evicted is not None and owners.get(row) == evicted:
                del owners[row]
            if not resident or owners.get(row) != name:
                self._page_in(bi, row, padded)
                owners[row] = name
                self._m_swaps.inc()
            self._update_resident_gauge()
            return TenantLease(name, bi, row + 1)

    def release(self, lease: TenantLease):
        with self._lock:
            self._alloc[lease.bucket].release(lease.name)

    def _page_in(self, bi, row, padded):
        pools = list(self._pools)
        base = 2 * len(self.targets) * bi
        for ti, t in enumerate(self.targets):
            a, b = padded[t]
            k = base + 2 * ti
            pools[k] = pools[k].at[:, row + 1].set(jnp.asarray(a))
            pools[k + 1] = pools[k + 1].at[:, row + 1].set(jnp.asarray(b))
        self._pools = tuple(pools)

    def _update_resident_gauge(self):
        self._m_resident.set(sum(
            sum(1 for n in self._registry if al.resident(n))
            for al in self._alloc))

    # NOTE: there is deliberately no reset-on-restart hook.  The adapter
    # pools are read-only in the compiled programs and NEVER donated, so
    # unlike the KV pools they survive an engine crash intact; the
    # engine's recovery path releases every in-flight lease and
    # re-admission re-acquires them (an idle resurrection — no device
    # write), which is what keeps restarted output byte-identical.

    # ----------------------------------------------------------- device side
    def gather_layers(self, aid, lw, dtype=None):
        """Build the per-layer ``lora=`` structure the GPT forward
        consumes, gathering per-row pairs from the dispatch's pool
        arrays.  ``aid [n_buckets, B]`` int32 slot rows (0 = null);
        ``lw`` the flat array tuple in :meth:`device_args` order.  Runs
        INSIDE the compiled programs."""
        n = self.n_args
        if len(lw) != n:
            raise TypeError(f"expected {n} adapter pool arrays, "
                            f"got {len(lw)}")
        out = []
        for layer in range(self.num_layers):
            d = {}
            for ti, t in enumerate(self.targets):
                flat = []
                for bi in range(len(self.ranks)):
                    rows = aid[bi]
                    k = 2 * len(self.targets) * bi + 2 * ti
                    flat.append(gather_adapter(lw[k][layer], rows))
                    flat.append(gather_adapter(lw[k + 1][layer], rows))
                d[t] = tuple(flat)
            out.append(d)
        return out

    # ------------------------------------------------------------- insight
    def stats(self):
        with self._lock:
            tenants = {}
            for name, (bi, _) in self._registry.items():
                al = self._alloc[bi]
                tenants[name] = {
                    "rank_bucket": self.ranks[bi],
                    "resident": al.resident(name),
                    "refs": al.refs(name),
                }
            return {
                "ranks": list(self.ranks),
                "capacity": self.capacity,
                "targets": list(self.targets),
                "dtype": str(self.dtype),
                "pool_bytes": self.pool_bytes(),
                "adapters": tenants,
            }


# ------------------------------------------------------- engine adapters
class _LoRAAdapterMixin:
    """Extends an engine adapter's closures with the trailing multi-LoRA
    args ``(aid [n_buckets, B] int32, *adapter_pools)`` and threads the
    per-row gathered pairs into the GPT forward (``lora=``) — all through
    the base adapter's single ``_split_extra`` hook, so the
    prefill/step/verify/encode closure bodies (and any future fix to
    them) stay in ONE place.  KV pool handling (incl. the quantized
    4-array layout) is inherited untouched."""

    def __init__(self, model, page_size, store: LoRAStore):
        super().__init__(model, page_size)
        self.store = store

    def _split_extra(self, args):
        n = self.n_pools
        want = n + 3 + self.store.n_args
        if len(args) != want:
            raise TypeError(
                f"{type(self).__name__} closures take {n} pools + table + "
                f"lens + aid + {self.store.n_args} adapter pools; got "
                f"{len(args)} trailing args")
        pools, table, lens = self._split(args[:n + 2])
        aid, lw = args[n + 2], args[n + 3:]
        return pools, table, lens, \
            self.store.gather_layers(aid.astype(jnp.int32), lw)


class LoRAGPTAdapter(_LoRAAdapterMixin, GPTAdapter):
    """Multi-LoRA over full-precision paged KV pools."""


class LoRAQuantizedGPTAdapter(_LoRAAdapterMixin, QuantizedGPTAdapter):
    """Multi-LoRA over int8 paged KV pools (+ scale pools): quantized
    serving and multi-tenant LoRA compose — the adapter gathers ride the
    same programs that fuse quant into the pool writes."""
