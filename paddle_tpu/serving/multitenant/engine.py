"""MultiTenantEngine — one continuous-batching engine, many tenants.

Extends :class:`~paddle_tpu.serving.engine.ServingEngine` with the three
multi-tenant workload classes (ROADMAP item 4, README "Multi-tenant
serving"), all riding the SAME iteration-level scheduler and compiled
program families:

- **paged multi-LoRA** (``lora_store=``): each batch row gathers its
  tenant's low-rank pairs by slot id inside the compiled
  prefill/decode/verify programs (:mod:`.lora`); program families are
  keyed by the store's RANK BUCKETS (``decode@lora-r<r>``), so adapter
  register/evict/hot-swap at runtime never re-traces;
- **grammar-constrained decoding** (``submit(grammar=...)``): per-row
  token-FSM masks (:mod:`.grammar`) computed host-side each step and
  applied in the batched sampler before greedy/temperature sampling;
  composes with speculative verification — drafts are pre-trimmed at the
  first grammar-illegal token and the verifier's distribution is masked
  per position, so a draft that exits the grammar is rejected and the
  bonus/resample token is always legal;
- **embed / score requests** (``submit(mode="embed"|"score")``): the
  prompt runs one prefill-family dispatch against the scratch page —
  no decode slot, no KV pages allocated — returning the pooled hidden
  state (``pooling="mean"|"last"``) or per-token prompt logprobs via
  ``handle.result()``.

Per-tenant observability: ``serving.tenant.requests{adapter=}`` /
``serving.tenant.tokens{adapter=}`` counters (label ``base`` = no
adapter) and a ``tenants`` section on /statusz; the new program families
attribute in the perf table as ``decode@lora-r<r>``,
``prefill/<bucket>@embed`` etc. and ``perf.candidate_hint`` recognizes
them.
"""

from __future__ import annotations

import collections
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

from ...observability import perf as _perf
from ...observability import tracing as _tracing
from ..engine import ServingEngine
from .lora import LoRAGPTAdapter, LoRAQuantizedGPTAdapter, LoRAStore


class MultiTenantEngine(ServingEngine):
    """See module docstring.  Typical use::

        store = LoRAStore(model, capacity=8, ranks=(8,))
        store.register(LoRAAdapter.random(model, "tenant-a", rank=4))
        engine = MultiTenantEngine(model, lora_store=store, num_slots=4)
        with engine:
            ha = engine.submit(p, adapter="tenant-a")     # LoRA row
            hb = engine.submit(p, grammar=g)              # schema row
            hc = engine.submit(p, mode="embed")           # embedding row
    """

    def __init__(self, model, lora_store: LoRAStore | None = None, **kw):
        if lora_store is not None and kw.get("adapter") is None:
            kvd = str(kw.get("kv_dtype") or "native").lower()
            cls = LoRAQuantizedGPTAdapter if kvd == "int8" \
                else LoRAGPTAdapter
            kw["adapter"] = cls(model, kw.get("page_size", 16), lora_store)
        self._lora = lora_store
        super().__init__(model, **kw)
        from ...profiler import metrics as _metrics
        from ...text.models._decode import make_masked_batched_sampler

        self._vsize = int(model.gpt.word_embeddings.weight.shape[0])
        self._nb = len(lora_store.ranks) if lora_store is not None else 0
        self._lora_fam = lora_store.family_suffix() \
            if lora_store is not None else ""
        self._mt_sig = ("mt", lora_store.signature()
                        if lora_store is not None else None)
        self._masked_sampler = make_masked_batched_sampler(*self._top)
        self._masked_verifier = None
        if self._spec_k:
            from ..speculative import make_masked_verifier

            self._masked_verifier = make_masked_verifier(*self._top)
        # persistent per-lane host buffers, extending the base set: the
        # grammar masks (all-True = unconstrained — bit-identical to the
        # unmasked sampler) and the per-bucket adapter slot ids (0 = null)
        self._h_allowed = np.ones((self.num_slots, self._vsize), np.bool_)
        self._h_aid = np.zeros((max(self._nb, 1), self.num_slots), np.int32)
        # device-RESIDENT all-True twins: with zero constrained rows live
        # (the common pure-LoRA batch) the dispatch passes these instead
        # of re-uploading num_slots x V host bytes every step — same aval,
        # so the program never re-traces when a grammar row arrives
        self._dev_allowed = jnp.ones((self.num_slots, self._vsize),
                                     jnp.bool_)
        self._n_constrained = 0      # live slots carrying a grammar
        if self._spec_k:
            self._h_allowed3 = np.ones(
                (self.num_slots, self._spec_k + 1, self._vsize), np.bool_)
            self._dev_allowed3 = jnp.ones(
                (self.num_slots, self._spec_k + 1, self._vsize), jnp.bool_)
        self._tenant_live = {}       # adapter name -> live request count
        # score-value memo for prefix-cached scoring: value[j] (the
        # logprob of prompt[j+1] given prompt[:j+1]) is a pure function
        # of prompt[:j+2], so entries up to a page boundary c are reusable
        # by ANY prompt sharing those c tokens — keyed by the boundary
        # prefix, populated at every boundary a score dispatch covers
        self._score_memo = collections.OrderedDict()
        self._score_memo_cap = 128
        self._m_tenant_req = _metrics.bind(_metrics.counter(
            "serving.tenant.requests",
            "submitted requests by tenant (adapter name, or 'base')"),
            replica=self.replica)
        self._m_tenant_tok = _metrics.bind(_metrics.counter(
            "serving.tenant.tokens",
            "tokens emitted by tenant (adapter name, or 'base')"),
            replica=self.replica)
        self._m_lora_blocked = _metrics.bind(_metrics.counter(
            "serving.lora_blocked",
            "admissions deferred: every adapter slot pinned by live "
            "requests"), replica=self.replica)

    # ------------------------------------------------------------ tenancy
    @property
    def lora_store(self):
        return self._lora

    def register_adapter(self, adapter):
        """Hot-swap path: host-registers a LoRA adapter on the live
        engine; it is paged into the device pools at first use.  No
        restart, no re-trace (asserted by the trace counters)."""
        if self._lora is None:
            raise ValueError("engine built without a lora_store")
        return self._lora.register(adapter)

    def _validate_tenant(self, adapter, grammar, mode, pooling,
                         eos_token_id):
        if mode not in ("generate", "embed", "score"):
            raise ValueError(f"mode must be generate|embed|score, "
                             f"got {mode!r}")
        if pooling not in ("mean", "last"):
            raise ValueError(f"pooling must be mean|last, got {pooling!r}")
        if adapter is not None:
            if self._lora is None:
                raise ValueError(f"adapter {adapter!r}: engine built "
                                 "without a lora_store")
            if not self._lora.registered(adapter):
                raise KeyError(f"adapter {adapter!r} is not registered "
                               f"(have {self._lora.names})")
        if grammar is not None:
            if mode != "generate":
                raise ValueError("grammar= only applies to mode='generate'")
            if grammar.vocab_size != self._vsize:
                raise ValueError(
                    f"grammar compiled over {grammar.vocab_size} tokens, "
                    f"model vocabulary is {self._vsize}")
            if eos_token_id is None:
                eos_token_id = grammar.eos_token_id
            elif int(eos_token_id) != grammar.eos_token_id:
                raise ValueError(
                    f"eos_token_id {eos_token_id} != the grammar's "
                    f"{grammar.eos_token_id}")
        return eos_token_id

    def submit(self, prompt_ids, *args, **kw):
        h = super().submit(prompt_ids, *args, **kw)
        # counted AFTER a successful enqueue: rejected/shed submissions
        # must not inflate the per-tenant request series (the base
        # serving.requests counter carries their status=rejected)
        self._m_tenant_req.inc(adapter=h.adapter or "base")
        return h

    def _acquire_tenant(self, req):
        if req.adapter is None or req.lease is not None:
            return True
        lease = self._lora.acquire(req.adapter)
        if lease is None:
            self._m_lora_blocked.inc()
            return False
        req.lease = lease
        self._tenant_live[req.adapter] = \
            self._tenant_live.get(req.adapter, 0) + 1
        return True

    def _release_tenant(self, req):
        if req.lease is not None:
            self._lora.release(req.lease)
            req.lease = None
            n = self._tenant_live.get(req.adapter, 0) - 1
            if n > 0:
                self._tenant_live[req.adapter] = n
            else:
                self._tenant_live.pop(req.adapter, None)

    # --------------------------------------------------- dispatch plumbing
    def _mt_args(self, aid):
        """The trailing (aid, *adapter_pools) the adapter closures take —
        empty without a store (the plain adapter takes no LoRA args)."""
        if self._lora is None:
            return ()
        return (aid,) + self._lora.device_args()

    def _aid_row(self, req):
        aid = np.zeros((max(self._nb, 1), 1), np.int32)
        if req.lease is not None:
            aid[req.lease.bucket, 0] = req.lease.row
        return aid

    def _prefill_family(self, s_pad):
        return f"prefill/{s_pad}{self._fam_suffix}{self._lora_fam}"

    def _decode_family(self):
        return f"decode{self._flash_tag}{self._fam_suffix}{self._lora_fam}"

    def _prefill_chunk_family(self, c):
        return f"prefill_chunk/{c}{self._fam_suffix}{self._lora_fam}"

    def _verify_family(self):
        return f"verify/k{self._spec_k}{self._fam_suffix}{self._lora_fam}"

    def _mask_or_fail(self, handle, g, state):
        """One row's grammar mask, containing pathological failures (a
        mid-document state no vocab token can tile, or a state-count
        blowup) to THE REQUEST: the handle records the error and cancels,
        retiring at the next scheduler check, and the returned all-True
        mask only feeds the dying row's final dispatch — one bad
        (grammar, vocab) pairing must not abort every tenant's work."""
        try:
            return g.allowed(state)
        except ValueError as e:
            if handle._error is None:
                handle._error = e
            handle.cancel()
            return np.ones((self._vsize,), np.bool_)

    def _prefill_extra(self, req):
        allowed = np.ones((1, self._vsize), np.bool_)
        if req.grammar is not None:
            allowed[0] = self._mask_or_fail(req.handle, req.grammar,
                                            req.handle._fsm_state)
        return (allowed,) + self._mt_args(self._aid_row(req))

    def _step_extra(self):
        allowed = self._h_allowed if self._n_constrained \
            else self._dev_allowed
        return (allowed,) + self._mt_args(self._h_aid)

    def _verify_extra(self, active):
        if not self._n_constrained:
            return (self._dev_allowed3,) + self._mt_args(self._h_aid)
        for i in active:
            s = self._slots[i]
            g = s.req.grammar
            if g is None:
                continue
            # per-position masks along the (grammar-filtered) draft chain:
            # position t's mask is the state after accepting drafts < t,
            # so an accepted prefix is legal by construction and the
            # bonus/resample at the first rejection samples a legal token
            st = s.handle._fsm_state
            try:
                self._h_allowed3[i, 0] = g.allowed(st)
                dlen = int(self._h_dlen[i])
                for t in range(dlen):
                    tok = int(self._h_ids[i, 1 + t])
                    if tok == g.eos_token_id:
                        # an accepted EOS draft retires the row
                        # mid-chain; later positions (and their bonus
                        # sample) are discarded, so their masks are
                        # unconstrained — advancing the FSM through EOS
                        # has no next state
                        self._h_allowed3[i, t + 1:] = True
                        break
                    st = g.advance(st, tok)
                    self._h_allowed3[i, t + 1] = g.allowed(st)
                else:
                    self._h_allowed3[i, dlen + 1:] = True
            except ValueError as e:     # same containment as _mask_or_fail
                if s.handle._error is None:
                    s.handle._error = e
                s.handle.cancel()
                self._h_allowed3[i] = True
        return (self._h_allowed3,) + self._mt_args(self._h_aid)

    def _filter_draft(self, i, draft):
        s = self._slots[i]
        g = s.req.grammar
        if g is None or not draft:
            return draft
        st = s.handle._fsm_state
        out = []
        for t in draft:
            if not self._mask_or_fail(s.handle, g, st)[int(t)]:
                break
            if s.handle.cancelled:      # grammar failure: row is dying
                return []
            out.append(t)
            if int(t) == g.eos_token_id:
                break
            st = g.advance(st, t)
        return out

    def _budget_status(self, slot):
        """A constrained row whose token budget ran out mid-document (its
        FSM is not in an accepting state) finishes as ``truncated``, not
        ``completed`` — the schema-validity guarantee only covers rows
        that actually reached a complete document, and the caller must be
        able to tell the difference (size ``max_new_tokens`` to the
        grammar's longest document to avoid it)."""
        g = slot.req.grammar
        if g is not None:
            st = slot.handle._fsm_state
            if st is None or not g.is_final(st):
                return "truncated"
        return "completed"

    def _on_admitted(self, slot, i):
        self._h_aid[:, i] = 0
        if slot.req.lease is not None:
            self._h_aid[slot.req.lease.bucket, i] = slot.req.lease.row
        g = slot.req.grammar
        if g is not None:
            self._n_constrained += 1
            self._h_allowed[i] = self._mask_or_fail(
                slot.handle, g, slot.handle._fsm_state)
        else:
            self._h_allowed[i] = True

    def _emit_token(self, slot, tok):
        super()._emit_token(slot, tok)
        g = slot.req.grammar
        h = slot.handle
        if g is not None and int(tok) != g.eos_token_id \
                and not h.cancelled:
            try:
                h._fsm_state = g.advance(h._fsm_state, tok)
                if h._fsm_state is None:  # unreachable under masking
                    raise RuntimeError(
                        f"constrained request {h.request_id} emitted "
                        f"token {int(tok)} outside its grammar")
                self._h_allowed[slot.idx] = self._mask_or_fail(
                    h, g, h._fsm_state)
            except ValueError as e:     # state blowup: contain to the row
                if h._error is None:
                    h._error = e
                h.cancel()
                self._h_allowed[slot.idx] = True
        self._m_tenant_tok.inc(adapter=slot.req.adapter or "base")

    def _clear_slot_row(self, i, slot):
        super()._clear_slot_row(i, slot)
        self._h_allowed[i] = True
        self._h_aid[:, i] = 0
        if slot.req.grammar is not None:
            self._n_constrained -= 1
        if self._spec_k:
            self._h_allowed3[i] = True

    def _reset_host_buffers(self):
        super()._reset_host_buffers()
        self._h_allowed[:] = True
        self._h_aid[:] = 0
        self._n_constrained = 0
        if self._spec_k:
            self._h_allowed3[:] = True

    # ------------------------------------------------------------ programs
    def _step_program(self):
        key = ("mt_step", self.num_slots, self.table_width,
               self._pools[0].shape, str(self._pools[0].dtype), self._top,
               self._mt_sig)
        n = len(self._pools)

        def build():
            traces = [0]
            adapter, sampler = self._adapter, self._masked_sampler

            @functools.partial(jax.jit,
                               donate_argnums=tuple(range(3, 3 + n)))
            def step(params, bufs, last, *rest):
                traces[0] += 1
                pools = rest[:n]
                table, lens, temps, rkey, allowed = rest[n:n + 5]
                mt = rest[n + 5:]       # (aid, *adapter_pools) or ()
                out = adapter.step(params, bufs, last, *pools, table, lens,
                                   *mt)
                return (sampler(out[0], allowed, temps, rkey),) \
                    + tuple(out[1:])

            return step, traces

        return self._program(key, build)

    def _prefill_program(self, s_pad):
        key = ("mt_prefill", s_pad, self.table_width,
               self._pools[0].shape, str(self._pools[0].dtype), self._top,
               self._mt_sig)
        n = len(self._pools)

        def build():
            traces = [0]
            adapter, sampler = self._adapter, self._masked_sampler

            @functools.partial(jax.jit,
                               donate_argnums=tuple(range(3, 3 + n)))
            def prefill(params, bufs, ids, *rest):
                traces[0] += 1
                pools = rest[:n]
                table, lens, temps, rkey, allowed = rest[n:n + 5]
                mt = rest[n + 5:]
                out = adapter.prefill(params, bufs, ids, *pools, table,
                                      lens, *mt)
                return (sampler(out[0], allowed, temps, rkey),) \
                    + tuple(out[1:])

            return prefill, traces

        return self._program(key, build)

    def _prefill_chunk_program(self, c_pad):
        key = ("mt_prefill_chunk", c_pad, self.table_width,
               self._pools[0].shape, str(self._pools[0].dtype), self._top,
               self._mt_sig)
        n = len(self._pools)

        def build():
            traces = [0]
            adapter, sampler = self._adapter, self._masked_sampler

            @functools.partial(jax.jit,
                               donate_argnums=tuple(range(4, 4 + n)))
            def chunk(params, bufs, ids, nvalid, *rest):
                traces[0] += 1
                pools = rest[:n]
                table, lens, temps, rkey, allowed = rest[n:n + 5]
                mt = rest[n + 5:]
                out = adapter.prefill_chunk(params, bufs, ids, nvalid,
                                            *pools, table, lens, *mt)
                return (sampler(out[0], allowed, temps, rkey),) \
                    + tuple(out[1:])

            return chunk, traces

        return self._program(key, build)

    def _verify_program(self):
        key = ("mt_verify", self._spec_k, self.num_slots, self.table_width,
               self._pools[0].shape, str(self._pools[0].dtype), self._top,
               self._mt_sig)
        n = len(self._pools)

        def build():
            traces = [0]
            adapter, verifier = self._adapter, self._masked_verifier

            @functools.partial(jax.jit,
                               donate_argnums=tuple(range(3, 3 + n)))
            def verify(params, bufs, ids, *rest):
                traces[0] += 1
                pools = rest[:n]
                table, lens, dlen, temps, rkey, allowed3 = rest[n:n + 6]
                mt = rest[n + 6:]
                out = adapter.verify(params, bufs, ids, *pools, table, lens,
                                     *mt)
                targets, accept = verifier(out[0], allowed3, ids[:, 1:],
                                           dlen, temps, rkey)
                return (targets, accept) + tuple(out[1:])

            return verify, traces

        return self._program(key, build)

    def _embed_program(self, s_pad, mode, pooling):
        key = ("mt_encode", mode, pooling, s_pad, self.table_width,
               self._pools[0].shape, str(self._pools[0].dtype),
               self._mt_sig)
        n = len(self._pools)

        def build():
            traces = [0]
            adapter = self._adapter

            @functools.partial(jax.jit,
                               donate_argnums=tuple(range(3, 3 + n)))
            def run(params, bufs, ids, *rest):
                import jax.numpy as jnp

                traces[0] += 1
                pools = rest[:n]
                table, lens = rest[n:n + 2]
                mt = rest[n + 2:]
                x, w, *pools2 = adapter.encode(params, bufs, ids, *pools,
                                               table, lens, *mt)
                S = x.shape[1]
                if mode == "embed":
                    if pooling == "last":
                        idx = (lens.astype(jnp.int32) - 1)[:, None, None]
                        out = jnp.take_along_axis(x, idx, axis=1)[:, 0]
                    else:
                        pos = jnp.arange(S, dtype=jnp.int32)[None, :]
                        m = (pos < lens[:, None]).astype(jnp.float32)
                        out = (x * m[..., None]).sum(axis=1) \
                            / jnp.maximum(
                                lens[:, None].astype(jnp.float32), 1.0)
                else:                   # score: logprob of each prompt
                    logits = x @ w.T                     # token given its
                    lp = jax.nn.log_softmax(logits, -1)  # prefix
                    tgt = ids[:, 1:].astype(jnp.int32)
                    out = jnp.take_along_axis(
                        lp[:, :-1], tgt[..., None], axis=-1)[..., 0]
                return (out,) + tuple(pools2)

            return run, traces

        return self._program(key, build)

    def _embed_chunk_program(self, c_pad, mode, pooling):
        """Prefix-cached encode: :meth:`GPTAdapter.encode_chunk` over the
        UNSHARED tail of an embed/score prompt, attending the resident
        shared-run pages the table addresses.  ``nvalid`` carries the real
        tail length (embed/last selects that lane in-program; score's
        host-side slice uses it)."""
        key = ("mt_encode_chunk", mode, pooling, c_pad, self.table_width,
               self._pools[0].shape, str(self._pools[0].dtype),
               self._mt_sig)
        n = len(self._pools)

        def build():
            traces = [0]
            adapter = self._adapter

            @functools.partial(jax.jit,
                               donate_argnums=tuple(range(4, 4 + n)))
            def run(params, bufs, ids, nvalid, *rest):
                traces[0] += 1
                pools = rest[:n]
                table, lens = rest[n:n + 2]
                mt = rest[n + 2:]
                x, w, *pools2 = adapter.encode_chunk(
                    params, bufs, ids, *pools, table, lens, *mt)
                if mode == "embed":     # pooling == "last" by construction
                    idx = jnp.maximum(
                        nvalid.astype(jnp.int32) - 1, 0)[:, None, None]
                    out = jnp.take_along_axis(x, idx, axis=1)[:, 0]
                else:                   # score: logprob of each tail token
                    logits = x @ w.T    # given its full (cached) prefix
                    lp = jax.nn.log_softmax(logits, -1)
                    tgt = ids[:, 1:].astype(jnp.int32)
                    out = jnp.take_along_axis(
                        lp[:, :-1], tgt[..., None], axis=-1)[..., 0]
                return (out,) + tuple(pools2)

            return run, traces

        return self._program(key, build)

    # --------------------------------------------------------- passthrough
    def _run_passthrough(self, req):
        """One embed/score request: a single prefill-family dispatch with
        every table row pointed at the scratch page — the BlockManager is
        never touched (asserted by the page-accounting test) and no
        decode slot is occupied; the request retires immediately.

        Under ``prefix_cache="radix"``, embed (``pooling="last"``) and
        score requests first pin the longest resident shared run
        (``BlockManager.acquire_run``) and dispatch only the unshared
        tail through :meth:`_embed_chunk_program` — a system-prompt-heavy
        embed flood skips recomputing the cached pages entirely.  The
        scratch-page invariant survives: the table addresses only the
        refcounted shared run plus the scratch page (the sub-page tail's
        K/V lands at distinct in-page scratch offsets), and the run is
        released — parked idle, resident for the next sharer — the moment
        the dispatch returns.  ``pooling="mean"`` stays on the monolithic
        path: mean-pooling reduces over every position, so a cached run
        saves nothing and the full dispatch keeps reduction-order parity
        with the uncached engine."""
        h = req.handle
        S0 = len(req.prompt)
        if self._radix and (req.mode == "score" or (
                req.mode == "embed" and req.pooling == "last")):
            run = self._bm.acquire_run(req.prompt)
            if run is not None and run[0]:
                pages, cached = run
                try:
                    return self._run_passthrough_cached(req, pages, cached)
                finally:
                    self._bm.release_run(req.prompt, len(pages))
        s_pad = self._prefill_bucket(S0)
        ids = np.zeros((1, s_pad), np.int64)
        ids[0, :S0] = req.prompt
        table = np.full((1, self.table_width), self._scratch, np.int32)
        lens = np.asarray([S0], np.int32)
        mt = self._mt_args(self._aid_row(req))
        prog, traces = self._embed_program(s_pad, req.mode, req.pooling)
        n0 = traces[0]
        fam = (f"prefill/{s_pad}@{req.mode}"
               f"{self._fam_suffix}{self._lora_fam}")
        if _perf.needs_cost(fam):
            _perf.register_cost_thunk(fam, _perf.jit_cost_thunk(
                prog, (self._params, self._bufs, ids, *self._pools,
                       table, lens, *mt)))
        self._compiling = n0 == 0
        t0 = time.perf_counter()
        try:
            with _tracing.span(f"serving.{req.mode}", trace_id=h.trace_id,
                               request_id=h.request_id, prompt_len=S0):
                val, *pools = prog(self._params, self._bufs, ids,
                                   *self._pools, table, lens, *mt)
                self._pools = tuple(pools)
                val = np.asarray(val)
        finally:
            self._compiling = False
            self._progress_t = time.monotonic()
        if traces[0] > n0:
            self._m_prefill_traces.inc(traces[0] - n0)
        else:
            _perf.record(fam, time.perf_counter() - t0)
        self._m_prefill_seconds.observe(time.perf_counter() - t0)
        if req.mode == "embed":
            h.value = val[0]                        # [H] f32
        else:
            h.value = [float(v) for v in val[0][:max(S0 - 1, 0)]]
        self._release_tenant(req)
        self._admitting = None
        self._finish(h, "cancelled" if h.cancelled else "completed")

    def _run_passthrough_cached(self, req, pages, cached):
        """The prefix-cached half of :meth:`_run_passthrough`: dispatch
        the tail from offset ``l0`` against the pinned run.

        - embed/last: ``l0 = min(cached * ps, S0 - 1)`` — only the lanes
          needed to reach the last real position are computed (at least
          one, so a fully-covered prompt still recomputes its final
          position against cached K/V).
        - score: value entry j needs logits at position j, so a cached
          boundary ``c`` alone cannot produce entry ``c - 1`` — the
          dispatch starts at ``l0 = c' - 1`` where ``c'`` is the deepest
          page boundary with a score-memo hit (entries ``[:c' - 1]`` come
          from the memo; position ``c' - 1`` is recomputed against cached
          K/V for its logits).  No memo hit means a full-tail dispatch
          (``l0 = 0``) that self-warms both the memo and any freshly
          registered run pages.

        Fresh pages ``acquire_run`` registered start at ``cached * ps``
        >= every possible ``l0``, so the dispatch's pool writes always
        cover them with real K/V before the run is released."""
        h = req.handle
        S0 = len(req.prompt)
        ps = self.page_size
        prefix_vals = None
        if req.mode == "score":
            l0 = 0
            for k in range(min(cached, S0 // ps), 0, -1):
                mkey = tuple(int(t) for t in req.prompt[:k * ps])
                got = self._score_memo.get(mkey)
                if got is not None:
                    self._score_memo.move_to_end(mkey)
                    prefix_vals = list(got)
                    l0 = k * ps - 1
                    break
        else:
            l0 = min(cached * ps, S0 - 1)
        tail = S0 - l0
        c_pad = self._prefill_bucket(tail)
        ids = np.zeros((1, c_pad), np.int64)
        ids[0, :tail] = req.prompt[l0:]
        table = np.full((1, self.table_width), self._scratch, np.int32)
        table[0, :len(pages)] = pages
        lens = np.asarray([l0], np.int32)
        nvalid = np.asarray([tail], np.int32)
        mt = self._mt_args(self._aid_row(req))
        prog, traces = self._embed_chunk_program(c_pad, req.mode,
                                                 req.pooling)
        n0 = traces[0]
        fam = (f"prefill/{c_pad}@{req.mode}@cached{cached}"
               f"{self._fam_suffix}{self._lora_fam}")
        if _perf.needs_cost(fam):
            _perf.register_cost_thunk(fam, _perf.jit_cost_thunk(
                prog, (self._params, self._bufs, ids, nvalid, *self._pools,
                       table, lens, *mt)))
        self._compiling = n0 == 0
        t0 = time.perf_counter()
        try:
            with _tracing.span(f"serving.{req.mode}_cached",
                               trace_id=h.trace_id,
                               request_id=h.request_id, prompt_len=S0,
                               cached_tokens=l0):
                val, *pools = prog(self._params, self._bufs, ids, nvalid,
                                   *self._pools, table, lens, *mt)
                self._pools = tuple(pools)
                val = np.asarray(val)
        finally:
            self._compiling = False
            self._progress_t = time.monotonic()
        if traces[0] > n0:
            self._m_prefill_traces.inc(traces[0] - n0)
        else:
            _perf.record(fam, time.perf_counter() - t0)
        self._m_prefill_seconds.observe(time.perf_counter() - t0)
        if req.mode == "embed":
            h.value = val[0]                    # [H] f32, last-position row
        else:
            vals = [float(v) for v in val[0][:max(tail - 1, 0)]]
            if prefix_vals is not None:
                vals = prefix_vals + vals       # memo covers [:l0]
            h.value = vals
            for k in range(1, S0 // ps + 1):    # warm every boundary
                mkey = tuple(int(t) for t in req.prompt[:k * ps])
                self._score_memo[mkey] = tuple(vals[:k * ps - 1])
                self._score_memo.move_to_end(mkey)
            while len(self._score_memo) > self._score_memo_cap:
                self._score_memo.popitem(last=False)
        self._release_tenant(req)
        self._admitting = None
        self._finish(h, "cancelled" if h.cancelled else "completed")

    # -------------------------------------------------------------- insight
    def stats(self):
        st = super().stats()
        st["multitenant"] = {
            "vocab_size": self._vsize,
            "lora": self._lora.stats() if self._lora is not None else None,
        }
        return st

    def _statusz(self):
        st = super()._statusz()
        tenants = {}
        if self._lora is not None:
            lstats = self._lora.stats()
            for name, info in lstats["adapters"].items():
                tenants[name] = dict(info,
                                     live_requests=self._tenant_live.get(
                                         name, 0))
            st["lora_pools"] = {k: lstats[k] for k in
                                ("ranks", "capacity", "targets", "dtype",
                                 "pool_bytes")}
        st["tenants"] = tenants
        return st
