"""paddle_tpu.serving.multitenant — many tenants, ONE engine (ROADMAP
item 4; README "Multi-tenant serving").

- :mod:`.lora` — paged multi-LoRA: :class:`LoRAStore` (rank-bucketed
  global adapter pools, BlockManager-pattern slot allocation with
  refcounts + idle LRU), :class:`LoRAAdapter` definitions, and the
  LoRA-aware engine adapters.
- :mod:`.grammar` — constrained decoding: regex / JSON-schema ->
  character DFA -> token FSM (:class:`CompiledGrammar`), applied as
  per-row logit masks in the batched sampler and the speculative
  verifier.
- :mod:`.engine` — :class:`MultiTenantEngine`: the ServingEngine
  subclass batching LoRA tenants, schema-constrained rows and
  embed/score requests in one scheduler.
"""

from .engine import MultiTenantEngine  # noqa: F401
from .grammar import (  # noqa: F401
    CompiledGrammar, compile_json_schema, compile_regex,
    json_schema_to_regex,
)
from .lora import (  # noqa: F401
    LoRAAdapter, LoRAGPTAdapter, LoRAQuantizedGPTAdapter, LoRAStore,
    TenantLease,
)

__all__ = [
    "MultiTenantEngine", "LoRAStore", "LoRAAdapter", "TenantLease",
    "LoRAGPTAdapter", "LoRAQuantizedGPTAdapter", "CompiledGrammar",
    "compile_regex", "compile_json_schema", "json_schema_to_regex",
]
