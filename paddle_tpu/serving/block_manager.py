"""Paged KV block manager — the allocator side of the serving engine.

vLLM block-manager analog over this repo's page-pool layout: the engine
owns per-layer GLOBAL page pools ``[L, P, page_size, h, d]`` (see
``ops.paged_attention``); this module owns which of the ``P`` rows belong
to which live sequence.  Everything here is host-side Python — the device
only ever sees the ``[B, NP]`` page table the engine rebuilds from these
allocations.

Capacity-based admission control: :meth:`allocate` returns ``None`` when
the pool cannot cover a sequence's worst case (prompt + max_new_tokens),
and the engine keeps the request queued instead of admitting it — no
mid-flight page exhaustion, so no copy-out preemption path is needed.

Prefix sharing (``prefix_sharing=True``): pages FULLY covered by a prompt
are content-addressed by the token prefix they encode (K/V at position p
is a pure function of tokens 0..p and the weights, so the page for
positions ``[i*ps, (i+1)*ps)`` is keyed by ``prompt[:(i+1)*ps]``).  Two
live sequences with identical prompt prefixes share those physical pages
(refcounted); decode never writes them — a sequence's first generated
token lands at position ``len(prompt)``, which is always past the last
fully-covered page.  When the last holder retires, shared pages park in an
idle cache and are resurrected on the next identical prefix (or evicted
LRU when the free list runs dry).

Hierarchical KV cache (``radix=True``): exact-key matching is replaced by
the page-granular radix tree in :mod:`.prefix_index` — ``allocate``
reuses the *longest shared page run* (partial-prefix matches bump
refcounts on the shared run; only the divergent tail allocates fresh
pages) and reports how many leading pages already hold valid K/V
(``PageAllocation.cached_pages``), which is what lets the engine START
prefill at ``cached_pages * page_size`` tokens instead of recomputing
the shared run.  With a :class:`~paddle_tpu.serving.kv_spill.KVSpillTier`
attached, idle pages evicted to refill the free list spill their bytes to
host DRAM first, and a later allocate whose match ends where a spilled
prefix begins resurrects them into fresh device slots — still cached,
one PCIe copy instead of a forward pass.  In legacy mode memory sharing
is real but prefill compute still runs per sequence.
"""

from __future__ import annotations

import collections
import threading


class PageAllocation:
    """One live sequence's pages, in sequence order.  The first
    ``len(shared_keys)`` entries are refcounted prefix pages; the rest are
    private and return to the free list on :meth:`BlockManager.free`.
    ``cached_pages`` counts the LEADING shared pages whose K/V was already
    valid at allocate time (radix hit or spill resurrection) — the prompt
    tokens they cover need no prefill compute; it is always 0 in legacy
    (exact-key) mode, where sharing saves memory but not compute."""

    __slots__ = ("pages", "shared_keys", "cached_pages")

    def __init__(self, pages, shared_keys=(), cached_pages=0):
        self.pages = list(pages)
        self.shared_keys = tuple(shared_keys)
        self.cached_pages = int(cached_pages)

    @property
    def num_shared(self):
        return len(self.shared_keys)

    def __len__(self):
        return len(self.pages)


class BlockManager:
    def __init__(self, num_pages, page_size, prefix_sharing=False,
                 replica="0", bytes_per_page=None, pool_dtype=None,
                 shards=1, radix=False, spill=None):
        if num_pages < 1:
            raise ValueError(f"num_pages must be >= 1, got {num_pages}")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.radix = bool(radix)
        self.prefix_sharing = bool(prefix_sharing) or self.radix
        self.replica = str(replica)
        # HBM accounting (quantized serving): what one page costs across
        # all layers, K+V, scale pools included, and what the pool rows
        # are made of — the engine fills these in so capacity math and the
        # /statusz slot table talk in bytes, not just page counts.
        # Tensor-parallel serving: ``shards`` records the mesh split of
        # the pools and ``bytes_per_page`` is then the PER-SHARD (per-chip)
        # cost — a 2-way-sharded pool holds 2x the resident sequences at
        # the same per-chip HBM budget, which is exactly what
        # :meth:`max_resident_sequences` with ``budget_bytes`` reports
        self.bytes_per_page = int(bytes_per_page) \
            if bytes_per_page is not None else None
        self.pool_dtype = str(pool_dtype) if pool_dtype is not None else None
        self.shards = int(shards)
        self._free = collections.deque(range(self.num_pages))
        self._active = {}                       # prefix key -> [page, refs]
        self._idle = collections.OrderedDict()  # prefix key -> page (refs 0)
        self._index = None
        self._spill = None
        if self.radix:
            from .prefix_index import RadixPrefixIndex

            self._index = RadixPrefixIndex(self.page_size)
            self._spill = spill  # KVSpillTier or None (radix mode only)
        elif spill is not None:
            raise ValueError("the KV spill tier needs radix=True (spilled "
                             "pages are resurrected through the radix "
                             "index's content addresses)")
        # allocate/free are engine-lock-serialized in normal operation,
        # but the allocator must stay correct for any caller (the pfx
        # concurrency tests hammer it from threads) — one internal mutex
        self._mut = threading.Lock()
        # prefix-cache observability: hits = sharable pages whose key was
        # resident (active refcount bump, idle resurrection, or host-tier
        # re-page), misses = sharable pages allocated fresh, evictions =
        # idle prefix pages reclaimed because the free list ran dry,
        # saved_tokens = hit pages x page_size — the counter that weights
        # a 100-page hit 100x a 1-page hit.  Series carry replica= (the
        # engine's id) so N engines in one process stay distinct.
        from ..profiler import metrics as _metrics

        self._m_hits = _metrics.bind(_metrics.counter(
            "serving.prefix_cache_hits",
            "prefix-sharing pages reused from the active/idle cache"),
            replica=self.replica)
        self._m_misses = _metrics.bind(_metrics.counter(
            "serving.prefix_cache_misses",
            "sharable prefix pages that had to be allocated fresh"),
            replica=self.replica)
        self._m_evictions = _metrics.bind(_metrics.counter(
            "serving.prefix_cache_evictions",
            "idle prefix pages evicted LRU to refill the free list"),
            replica=self.replica)
        self._m_saved = _metrics.bind(_metrics.counter(
            "serving.prefix_cache_saved_tokens",
            "prompt tokens covered by prefix-cache page hits "
            "(hit pages x page_size)"),
            replica=self.replica)
        self._hits = 0
        self._misses = 0
        self._evictions = 0
        self._saved_tokens = 0
        self._resurrections = 0

    # ------------------------------------------------------------ accounting
    def pages_for(self, num_tokens):
        return -(-int(num_tokens) // self.page_size)

    @property
    def _idle_count(self):
        return self._index.idle_pages if self.radix else len(self._idle)

    @property
    def free_pages(self):
        """Pages obtainable right now (free list + evictable idle cache)."""
        return len(self._free) + self._idle_count

    @property
    def used_pages(self):
        return self.num_pages - self.free_pages

    def utilization(self):
        return self.used_pages / self.num_pages

    def stats(self):
        """Allocator snapshot, HBM-denominated when the engine supplied
        ``bytes_per_page``/``pool_dtype`` (quantized serving: the int8
        pool's bytes_per_page is ~half bf16's, which is exactly the
        resident-slot win)."""
        st = {
            "num_pages": self.num_pages,
            "page_size": self.page_size,
            "used_pages": self.used_pages,
            "free_pages": self.free_pages,
            "utilization": self.utilization(),
            "prefix_sharing": self.prefix_sharing,
            "bytes_per_page": self.bytes_per_page,
            "pool_dtype": self.pool_dtype,
            "shards": self.shards,
        }
        if self.bytes_per_page is not None:
            # per-shard (per-chip) bytes when the pools are mesh-sharded
            st["pool_bytes"] = self.num_pages * self.bytes_per_page
            st["used_bytes"] = self.used_pages * self.bytes_per_page
            st["kv_bytes_per_token"] = self.bytes_per_page / self.page_size
        if self.prefix_sharing:
            # hit TOKENS, not just hit counts: saved_tokens is hit pages x
            # page_size, so a 100-page shared-run hit reads as 100x the
            # win of a 1-page hit (the hierarchical-cache satellite fix)
            pc = {
                "hits": self._hits,
                "misses": self._misses,
                "evictions": self._evictions,
                "saved_tokens": self._saved_tokens,
                "mode": "radix" if self.radix else "lru",
            }
            if self.radix:
                pc["resurrections"] = self._resurrections
                pc["index"] = self._index.stats()
                if self._spill is not None:
                    pc["spill"] = self._spill.stats()
            st["prefix_cache"] = pc
        st["fragmentation"] = self.fragmentation()
        return st

    def index_summary(self):
        """Resident-prefix digests for cross-replica placement (None in
        legacy mode) — exported through engine.stats() / ReplicaPool
        states so the PrefixAffinityRouter can find the replica with the
        deepest matching resident run (cluster/router.py)."""
        if not self.radix:
            return None
        with self._mut:
            return self._index.summary()

    def fragmentation(self):
        """Free-list fragmentation snapshot (memory observability): runs
        of CONTIGUOUS free page indices, their largest length, and a
        power-of-two run-length histogram, plus the evictable idle
        prefix pages sitting outside the free list.  Paged attention is
        indifferent to contiguity (any row works), so this is a
        diagnostic for allocator churn and for future contiguous-DMA
        kernels, not an admission input."""
        runs = []
        run = 0
        prev = None
        for p in sorted(self._free):
            if prev is not None and p == prev + 1:
                run += 1
            else:
                if run:
                    runs.append(run)
                run = 1
            prev = p
        if run:
            runs.append(run)
        hist = {}
        for r in runs:
            lo = 1 << (r.bit_length() - 1)
            key = f"{lo}" if lo == 1 else f"{lo}-{2 * lo - 1}"
            hist[key] = hist.get(key, 0) + 1
        return {
            "free_pages": len(self._free),
            "free_runs": len(runs),
            "largest_free_run": max(runs, default=0),
            "run_histogram": hist,
            "evictable_idle_pages": self._idle_count,
        }

    def max_resident_sequences(self, tokens_per_seq, budget_bytes=None):
        """Capacity math: how many sequences of ``tokens_per_seq`` worst
        case fit — in this pool, or in a hypothetical pool of
        ``budget_bytes`` HBM at this manager's bytes_per_page (the
        occupancy comparison the int8 acceptance test and the bench arm
        assert on).  ``budget_bytes`` is PER CHIP: with mesh-sharded
        pools (shards > 1) bytes_per_page is the per-shard cost, so the
        same budget admits ``shards``x the sequences of the unsharded
        engine — the mp HBM-capacity win, asserted by the mp tests."""
        per_seq = self.pages_for(tokens_per_seq)
        pages = self.num_pages
        if budget_bytes is not None:
            if self.bytes_per_page is None:
                raise ValueError("budget_bytes needs bytes_per_page")
            pages = int(budget_bytes) // self.bytes_per_page
        return pages // per_seq

    # ------------------------------------------------------------ allocation
    def _pop_free(self):
        if self._free:
            return self._free.popleft()
        # free list dry: evict the least-recently-idled shared prefix page
        if self.radix:
            ev = self._index.evict_one()
            if ev is None:
                raise RuntimeError("page pool exhausted with nothing idle "
                                   "(admission plan should have refused)")
            key, page = ev
            self._m_evictions.inc()
            self._evictions += 1
            if self._spill is not None:
                # snapshot BEFORE the row is reused — the hierarchical
                # cache's device->host demotion
                self._spill.spill(key, page)
            return page
        _, page = self._idle.popitem(last=False)
        self._m_evictions.inc()
        self._evictions += 1
        return page

    def _prefix_hits(self, prompt_ids, n_sharable):
        """Longest run of already-resident prefix pages (legacy exact-key
        mode).  A miss at page i implies misses after it: whoever
        registered a longer prefix also registered every shorter one."""
        hits = []
        for i in range(n_sharable):
            key = tuple(prompt_ids[:(i + 1) * self.page_size])
            if key in self._active or key in self._idle:
                hits.append(key)
            else:
                break
        return hits

    def can_allocate(self, prompt_ids, num_tokens):
        with self._mut:
            return self._plan(prompt_ids, num_tokens) is not None

    def _plan(self, prompt_ids, num_tokens):
        need = self.pages_for(num_tokens)
        n_sharable = 0
        if self.prefix_sharing:
            # pages fully covered by the prompt; decode's first write goes
            # to position len(prompt), past all of them even when the
            # prompt ends exactly on a page boundary
            n_sharable = min(len(prompt_ids) // self.page_size, need)
        if self.radix:
            blocks = self._index.blocks_of(prompt_ids, n_sharable)
            depth, idle_matched = self._index.match_depth(
                prompt_ids, n_sharable)
            fresh = need - depth
            if fresh > len(self._free) + (self._index.idle_pages
                                          - idle_matched):
                return None
            return need, n_sharable, blocks
        hits = self._prefix_hits(prompt_ids, n_sharable) \
            if n_sharable else []
        fresh = need - len(hits)
        idle_hits = sum(1 for k in hits if k in self._idle)
        if fresh > len(self._free) + (len(self._idle) - idle_hits):
            return None
        return need, n_sharable, hits

    def _record_hits(self, pages, prompt_len):
        self._m_hits.inc(pages)
        self._hits += pages
        saved = pages * self.page_size
        if prompt_len is not None:
            saved = min(saved, max(int(prompt_len) - 1, 0))
        self._m_saved.inc(saved)
        self._saved_tokens += saved

    def allocate(self, prompt_ids, num_tokens):
        """Reserve pages covering ``num_tokens`` for a sequence with this
        prompt; ``None`` when the pool can't satisfy it (caller keeps the
        request queued).  ``num_tokens`` must include the prompt AND every
        token the sequence may generate."""
        prompt_ids = [int(t) for t in prompt_ids]
        if num_tokens < len(prompt_ids):
            raise ValueError("num_tokens must cover the prompt")
        with self._mut:
            plan = self._plan(prompt_ids, num_tokens)
            if plan is None:
                return None
            if self.radix:
                return self._allocate_radix(prompt_ids, plan)
            return self._allocate_legacy(prompt_ids, plan)

    def _allocate_radix(self, prompt_ids, plan):
        need, n_sharable, blocks = plan
        ps = self.page_size
        # tier 1 — device-resident radix match: pin the longest shared
        # run (splitting a mid-run divergence at the page boundary)
        pages, _, tip = self._index.acquire(blocks)
        cached = len(pages)
        # tier 2 — host-tier resurrection: extend the run with spilled
        # pages re-paged into fresh device slots (still valid K/V)
        new_blocks, new_pages = [], []
        while (self._spill is not None and cached < n_sharable
               and len(self._free) + self._index.idle_pages > 0):
            key = tuple(prompt_ids[:(cached + 1) * ps])
            if not self._spill.contains(key):
                break
            page = self._pop_free()
            if not self._spill.resurrect(key, page):
                # raced away (shouldn't happen under the mutex): the slot
                # holds junk — return it and fall through to the fresh
                # loop, which registers it as a to-be-written page
                self._free.appendleft(page)
                break
            new_blocks.append(blocks[cached])
            new_pages.append(page)
            cached += 1
            self._resurrections += 1
        if cached:
            self._record_hits(cached, len(prompt_ids))
        # tier 3 — recompute: fresh sharable pages for the divergent
        # tail (prefill will write them), then private non-sharable pages
        fresh_shar = n_sharable - cached
        if fresh_shar > 0:
            self._m_misses.inc(fresh_shar)
            self._misses += fresh_shar
            for i in range(cached, n_sharable):
                new_blocks.append(blocks[i])
                new_pages.append(self._pop_free())
        self._index.insert(tip, new_blocks, new_pages)
        pages = pages + new_pages
        keys = [tuple(prompt_ids[:(i + 1) * ps]) for i in range(n_sharable)]
        for _ in range(n_sharable, need):
            pages.append(self._pop_free())
        # cached counts pages whose K/V is already byte-valid on device;
        # resurrections included — the engine starts prefill past them
        return PageAllocation(pages, keys,
                              cached_pages=min(cached, n_sharable))

    def _allocate_legacy(self, prompt_ids, plan):
        need, n_sharable, hits = plan
        pages, keys = [], []
        if hits:
            self._record_hits(len(hits), len(prompt_ids))
        for key in hits:
            ent = self._active.get(key)
            if ent is not None:
                ent[1] += 1
            else:
                ent = self._active[key] = [self._idle.pop(key), 1]
            pages.append(ent[0])
            keys.append(key)
        for i in range(len(hits), need):
            key = tuple(prompt_ids[:(i + 1) * self.page_size]) \
                if i < n_sharable else None
            # idle keys are not prefix-closed (LRU eviction drops them
            # independently), so a key past the first hit-miss can still sit
            # idle: claim it here, or free() would later overwrite the idle
            # entry and orphan its page from the pool
            if key is not None and key in self._idle:
                page = self._idle.pop(key)
                self._record_hits(1, len(prompt_ids))
            else:
                page = self._pop_free()
                if key is not None:
                    self._m_misses.inc()
                    self._misses += 1
            pages.append(page)
            if key is not None:  # new shareable prefix page: register it
                self._active[key] = [page, 1]
                keys.append(key)
        # legacy exact-key sharing saves memory, never compute
        return PageAllocation(pages, keys, cached_pages=0)

    def free(self, alloc: PageAllocation):
        """Release a retired sequence's pages: private pages return to the
        free list; shared prefix pages decref and park in the idle cache
        when the last holder leaves."""
        with self._mut:
            if self.radix:
                if alloc.shared_keys:
                    full = alloc.shared_keys[-1]
                    self._index.release(self._index.blocks_of(
                        full, len(alloc.shared_keys)))
            else:
                for key in alloc.shared_keys:
                    ent = self._active[key]
                    ent[1] -= 1
                    if ent[1] == 0:
                        del self._active[key]
                        self._idle[key] = ent[0]
            for page in alloc.pages[alloc.num_shared:]:
                self._free.append(page)
            alloc.pages = []
            alloc.shared_keys = ()
            alloc.cached_pages = 0

    # ----------------------------------------------- passthrough run sharing
    def acquire_run(self, prompt_ids, limit=None):
        """Pin (and extend) the shared run for a PASSTHROUGH dispatch
        (multi-tenant ``mode="embed"|"score"``): the longest resident
        radix match is refcounted, spilled extensions resurrect, and —
        unlike :meth:`allocate` — the remaining sharable blocks register
        fresh pages only while the free list has slack (a passthrough
        warming the cache never evicts someone else's resident prefix).
        Returns ``(pages, cached_pages)`` covering ``len(pages)`` leading
        blocks, or ``None`` outside radix mode / for sub-page prompts.
        The caller MUST :meth:`release_run` the same prompt/depth after
        the dispatch; it holds real refcounts until then."""
        if not self.radix:
            return None
        prompt_ids = [int(t) for t in prompt_ids]
        n = len(prompt_ids) // self.page_size
        if limit is not None:
            n = min(n, int(limit))
        if n <= 0:
            return None
        with self._mut:
            blocks = self._index.blocks_of(prompt_ids, n)
            pages, _, tip = self._index.acquire(blocks)
            cached = len(pages)
            new_blocks, new_pages = [], []
            while (self._spill is not None and cached < n and self._free
                   and self._spill.contains(
                       tuple(prompt_ids[:(cached + 1) * self.page_size]))):
                page = self._free.popleft()
                key = tuple(prompt_ids[:(cached + 1) * self.page_size])
                if not self._spill.resurrect(key, page):
                    # entry raced away between contains and resurrect: the
                    # slot holds junk, so it must join the run as a FRESH
                    # (to-be-written) block, never a cached one
                    self._free.appendleft(page)
                    break
                self._resurrections += 1
                new_blocks.append(blocks[cached])
                new_pages.append(page)
                cached += 1
            if cached:
                self._record_hits(cached, None)
            while len(pages) + len(new_pages) < n and self._free:
                i = len(pages) + len(new_pages)
                new_blocks.append(blocks[i])
                new_pages.append(self._free.popleft())
                self._m_misses.inc()
                self._misses += 1
            self._index.insert(tip, new_blocks, new_pages)
            return pages + new_pages, cached

    def release_run(self, prompt_ids, depth):
        """Unpin a run :meth:`acquire_run` returned (``depth`` =
        ``len(pages)``); the run parks idle and stays resident for the
        next passthrough/generate sharing the prefix."""
        if not self.radix or depth <= 0:
            return
        prompt_ids = [int(t) for t in prompt_ids]
        with self._mut:
            self._index.release(self._index.blocks_of(prompt_ids, depth))
