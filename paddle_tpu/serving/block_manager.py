"""Paged KV block manager — the allocator side of the serving engine.

vLLM block-manager analog over this repo's page-pool layout: the engine
owns per-layer GLOBAL page pools ``[L, P, page_size, h, d]`` (see
``ops.paged_attention``); this module owns which of the ``P`` rows belong
to which live sequence.  Everything here is host-side Python — the device
only ever sees the ``[B, NP]`` page table the engine rebuilds from these
allocations.

Capacity-based admission control: :meth:`allocate` returns ``None`` when
the pool cannot cover a sequence's worst case (prompt + max_new_tokens),
and the engine keeps the request queued instead of admitting it — no
mid-flight page exhaustion, so no copy-out preemption path is needed.

Prefix sharing (``prefix_sharing=True``): pages FULLY covered by a prompt
are content-addressed by the token prefix they encode (K/V at position p
is a pure function of tokens 0..p and the weights, so the page for
positions ``[i*ps, (i+1)*ps)`` is keyed by ``prompt[:(i+1)*ps]``).  Two
live sequences with identical prompt prefixes share those physical pages
(refcounted); decode never writes them — a sequence's first generated
token lands at position ``len(prompt)``, which is always past the last
fully-covered page.  When the last holder retires, shared pages park in an
idle cache and are resurrected on the next identical prefix (or evicted
LRU when the free list runs dry).  Memory sharing is real; prefill compute
still runs per sequence (skipping it is future work).
"""

from __future__ import annotations

import collections


class PageAllocation:
    """One live sequence's pages, in sequence order.  The first
    ``len(shared_keys)`` entries are refcounted prefix pages; the rest are
    private and return to the free list on :meth:`BlockManager.free`."""

    __slots__ = ("pages", "shared_keys")

    def __init__(self, pages, shared_keys=()):
        self.pages = list(pages)
        self.shared_keys = tuple(shared_keys)

    @property
    def num_shared(self):
        return len(self.shared_keys)

    def __len__(self):
        return len(self.pages)


class BlockManager:
    def __init__(self, num_pages, page_size, prefix_sharing=False,
                 replica="0", bytes_per_page=None, pool_dtype=None,
                 shards=1):
        if num_pages < 1:
            raise ValueError(f"num_pages must be >= 1, got {num_pages}")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.prefix_sharing = bool(prefix_sharing)
        self.replica = str(replica)
        # HBM accounting (quantized serving): what one page costs across
        # all layers, K+V, scale pools included, and what the pool rows
        # are made of — the engine fills these in so capacity math and the
        # /statusz slot table talk in bytes, not just page counts.
        # Tensor-parallel serving: ``shards`` records the mesh split of
        # the pools and ``bytes_per_page`` is then the PER-SHARD (per-chip)
        # cost — a 2-way-sharded pool holds 2x the resident sequences at
        # the same per-chip HBM budget, which is exactly what
        # :meth:`max_resident_sequences` with ``budget_bytes`` reports
        self.bytes_per_page = int(bytes_per_page) \
            if bytes_per_page is not None else None
        self.pool_dtype = str(pool_dtype) if pool_dtype is not None else None
        self.shards = int(shards)
        self._free = collections.deque(range(self.num_pages))
        self._active = {}                       # prefix key -> [page, refs]
        self._idle = collections.OrderedDict()  # prefix key -> page (refs 0)
        # prefix-cache observability: hits = sharable pages whose key was
        # resident (active refcount bump or idle resurrection), misses =
        # sharable pages allocated fresh, evictions = idle prefix pages
        # reclaimed because the free list ran dry.  Series carry replica=
        # (the engine's id) so N engines in one process stay distinct.
        from ..profiler import metrics as _metrics

        self._m_hits = _metrics.bind(_metrics.counter(
            "serving.prefix_cache_hits",
            "prefix-sharing pages reused from the active/idle cache"),
            replica=self.replica)
        self._m_misses = _metrics.bind(_metrics.counter(
            "serving.prefix_cache_misses",
            "sharable prefix pages that had to be allocated fresh"),
            replica=self.replica)
        self._m_evictions = _metrics.bind(_metrics.counter(
            "serving.prefix_cache_evictions",
            "idle prefix pages evicted LRU to refill the free list"),
            replica=self.replica)

    # ------------------------------------------------------------ accounting
    def pages_for(self, num_tokens):
        return -(-int(num_tokens) // self.page_size)

    @property
    def free_pages(self):
        """Pages obtainable right now (free list + evictable idle cache)."""
        return len(self._free) + len(self._idle)

    @property
    def used_pages(self):
        return self.num_pages - self.free_pages

    def utilization(self):
        return self.used_pages / self.num_pages

    def stats(self):
        """Allocator snapshot, HBM-denominated when the engine supplied
        ``bytes_per_page``/``pool_dtype`` (quantized serving: the int8
        pool's bytes_per_page is ~half bf16's, which is exactly the
        resident-slot win)."""
        st = {
            "num_pages": self.num_pages,
            "page_size": self.page_size,
            "used_pages": self.used_pages,
            "free_pages": self.free_pages,
            "utilization": self.utilization(),
            "prefix_sharing": self.prefix_sharing,
            "bytes_per_page": self.bytes_per_page,
            "pool_dtype": self.pool_dtype,
            "shards": self.shards,
        }
        if self.bytes_per_page is not None:
            # per-shard (per-chip) bytes when the pools are mesh-sharded
            st["pool_bytes"] = self.num_pages * self.bytes_per_page
            st["used_bytes"] = self.used_pages * self.bytes_per_page
            st["kv_bytes_per_token"] = self.bytes_per_page / self.page_size
        st["fragmentation"] = self.fragmentation()
        return st

    def fragmentation(self):
        """Free-list fragmentation snapshot (memory observability): runs
        of CONTIGUOUS free page indices, their largest length, and a
        power-of-two run-length histogram, plus the evictable idle
        prefix pages sitting outside the free list.  Paged attention is
        indifferent to contiguity (any row works), so this is a
        diagnostic for allocator churn and for future contiguous-DMA
        kernels, not an admission input."""
        runs = []
        run = 0
        prev = None
        for p in sorted(self._free):
            if prev is not None and p == prev + 1:
                run += 1
            else:
                if run:
                    runs.append(run)
                run = 1
            prev = p
        if run:
            runs.append(run)
        hist = {}
        for r in runs:
            lo = 1 << (r.bit_length() - 1)
            key = f"{lo}" if lo == 1 else f"{lo}-{2 * lo - 1}"
            hist[key] = hist.get(key, 0) + 1
        return {
            "free_pages": len(self._free),
            "free_runs": len(runs),
            "largest_free_run": max(runs, default=0),
            "run_histogram": hist,
            "evictable_idle_pages": len(self._idle),
        }

    def max_resident_sequences(self, tokens_per_seq, budget_bytes=None):
        """Capacity math: how many sequences of ``tokens_per_seq`` worst
        case fit — in this pool, or in a hypothetical pool of
        ``budget_bytes`` HBM at this manager's bytes_per_page (the
        occupancy comparison the int8 acceptance test and the bench arm
        assert on).  ``budget_bytes`` is PER CHIP: with mesh-sharded
        pools (shards > 1) bytes_per_page is the per-shard cost, so the
        same budget admits ``shards``x the sequences of the unsharded
        engine — the mp HBM-capacity win, asserted by the mp tests."""
        per_seq = self.pages_for(tokens_per_seq)
        pages = self.num_pages
        if budget_bytes is not None:
            if self.bytes_per_page is None:
                raise ValueError("budget_bytes needs bytes_per_page")
            pages = int(budget_bytes) // self.bytes_per_page
        return pages // per_seq

    # ------------------------------------------------------------ allocation
    def _pop_free(self):
        if self._free:
            return self._free.popleft()
        # free list dry: evict the least-recently-idled shared prefix page
        _, page = self._idle.popitem(last=False)
        self._m_evictions.inc()
        return page

    def _prefix_hits(self, prompt_ids, n_sharable):
        """Longest run of already-resident prefix pages.  A miss at page i
        implies misses after it: whoever registered a longer prefix also
        registered every shorter one."""
        hits = []
        for i in range(n_sharable):
            key = tuple(prompt_ids[:(i + 1) * self.page_size])
            if key in self._active or key in self._idle:
                hits.append(key)
            else:
                break
        return hits

    def can_allocate(self, prompt_ids, num_tokens):
        return self._plan(prompt_ids, num_tokens) is not None

    def _plan(self, prompt_ids, num_tokens):
        need = self.pages_for(num_tokens)
        n_sharable = 0
        if self.prefix_sharing:
            # pages fully covered by the prompt; decode's first write goes
            # to position len(prompt), past all of them even when the
            # prompt ends exactly on a page boundary
            n_sharable = min(len(prompt_ids) // self.page_size, need)
        hits = self._prefix_hits(prompt_ids, n_sharable) \
            if n_sharable else []
        fresh = need - len(hits)
        idle_hits = sum(1 for k in hits if k in self._idle)
        if fresh > len(self._free) + (len(self._idle) - idle_hits):
            return None
        return need, n_sharable, hits

    def allocate(self, prompt_ids, num_tokens):
        """Reserve pages covering ``num_tokens`` for a sequence with this
        prompt; ``None`` when the pool can't satisfy it (caller keeps the
        request queued).  ``num_tokens`` must include the prompt AND every
        token the sequence may generate."""
        prompt_ids = [int(t) for t in prompt_ids]
        if num_tokens < len(prompt_ids):
            raise ValueError("num_tokens must cover the prompt")
        plan = self._plan(prompt_ids, num_tokens)
        if plan is None:
            return None
        need, n_sharable, hits = plan
        pages, keys = [], []
        if hits:
            self._m_hits.inc(len(hits))
        for key in hits:
            ent = self._active.get(key)
            if ent is not None:
                ent[1] += 1
            else:
                ent = self._active[key] = [self._idle.pop(key), 1]
            pages.append(ent[0])
            keys.append(key)
        for i in range(len(hits), need):
            key = tuple(prompt_ids[:(i + 1) * self.page_size]) \
                if i < n_sharable else None
            # idle keys are not prefix-closed (LRU eviction drops them
            # independently), so a key past the first hit-miss can still sit
            # idle: claim it here, or free() would later overwrite the idle
            # entry and orphan its page from the pool
            if key is not None and key in self._idle:
                page = self._idle.pop(key)
                self._m_hits.inc()   # key was resident: still a cache hit
            else:
                page = self._pop_free()
                if key is not None:
                    self._m_misses.inc()
            pages.append(page)
            if key is not None:  # new shareable prefix page: register it
                self._active[key] = [page, 1]
                keys.append(key)
        return PageAllocation(pages, keys)

    def free(self, alloc: PageAllocation):
        """Release a retired sequence's pages: private pages return to the
        free list; shared prefix pages decref and park in the idle cache
        when the last holder leaves."""
        for key in alloc.shared_keys:
            ent = self._active[key]
            ent[1] -= 1
            if ent[1] == 0:
                del self._active[key]
                self._idle[key] = ent[0]
        for page in alloc.pages[alloc.num_shared:]:
            self._free.append(page)
        alloc.pages = []
        alloc.shared_keys = ()
