"""QoS-tiered serving: priority tiers, weighted admission, deliberate
preemption, SLO-aware brownouts and elastic replica autoscaling.

Production traffic is not one class (ROADMAP item 5): an interactive
``realtime`` request, a ``standard`` API call and a ``batch`` eval row
have different latency promises, and under pressure the engine must
degrade the cheap promises first.  This module is the policy half:

- :class:`TierPolicy` / :class:`QoSConfig` — the tier table: priority
  (admission order AND preemption rank), weighted-round-robin admission
  weight, an optional per-tier :class:`~paddle_tpu.observability.slo.
  SLOPolicy`, the burn-rate threshold past which the tier is shed
  (brownout), a per-tier queue bound, and whether running requests of the
  tier may be preempted;
- :class:`TieredQueue` — per-tier deques behind the engine's existing
  ``deque`` surface (``append`` / ``appendleft`` / ``popleft`` / ``[0]``
  / ``len``), so every scheduler call site works unchanged while head
  selection becomes priority-ordered weighted round robin (credits refill
  per cycle: with weights 8/3/1 a saturated engine admits 8 realtime, 3
  standard, 1 batch per cycle — bounded starvation, not strict priority);
- :func:`brownout` — the shed ladder: the protected (highest-priority)
  tier's SLO burn rate decides which lower tiers shed at admission
  (level 1 sheds ``batch``, level 2 also ``standard``, level 3 = the
  engine is actively preempting), surfaced in ``health_state()`` and
  ``/statusz``;
- :class:`AutoScaler` — elastic replica count for a
  :class:`~.cluster.pool.ReplicaPool`: queue-depth / occupancy /
  burn-rate scale-up signals with hysteresis (the signal must hold for
  ``stable_s``) and a cooldown between events, warm spin-up via the
  pool's ``warmup=`` manifest (PR 16 made that ~free), drain-then-retire
  on scale-down so no in-flight request is ever dropped, and reaping of
  dead replicas (a fatal crash or a ``cluster.replica_preempt@<r>``
  fault) with replacement back up to ``min_replicas``.

The mechanism half — eviction, requeue as prompt + tokens-so-far with
the remaining budget — is the engine's PR-4 ``_recover`` machinery
scheduled on purpose, so a preempted greedy request's final ids are
byte-identical to an uninterrupted run.

Metrics: ``serving.tier.{queue_depth,active_slots}{tier=}``,
``serving.preemptions{tier=,reason=}``, ``serving.load_shed{reason=,
tier=}`` (engine side, README "Metrics reference");
``cluster.replicas{state=}`` and ``cluster.scale_events{direction=}``
(autoscaler side).
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time

from ..observability.slo import SLOPolicy

#: brownout rung names for the default three-tier ladder (index = level)
BROWNOUT_LADDER = ("normal", "shed_batch", "shed_standard", "preempt")


@dataclasses.dataclass(frozen=True)
class TierPolicy:
    """One tier's policy.  ``priority`` orders admission and preemption
    (higher = more important — a request preempts only strictly-lower
    tiers); ``weight`` is the tier's credits per weighted-round-robin
    admission cycle; ``slo`` accounts the tier's own attainment/burn
    (``serving.slo.*{tier=}``); ``shed_burn_rate`` is the PROTECTED
    tier's burn rate past which THIS tier sheds at admission (None =
    never brownout-shed — the protected tier itself); ``max_queue``
    bounds the tier's queue (None = unbounded); ``preemptible=False``
    exempts running requests of the tier from QoS eviction."""

    name: str
    priority: int
    weight: int = 1
    slo: SLOPolicy | None = None
    shed_burn_rate: float | None = None
    max_queue: int | None = None
    preemptible: bool = True

    def __post_init__(self):
        if not self.name:
            raise ValueError("tier name must be non-empty")
        if self.weight < 1:
            raise ValueError(
                f"tier {self.name!r}: weight must be >= 1, got {self.weight}")
        if self.max_queue is not None and self.max_queue < 1:
            raise ValueError(
                f"tier {self.name!r}: max_queue must be >= 1 or None")


class QoSConfig:
    """The engine's tier table.  ``tiers`` is an iterable of
    :class:`TierPolicy` (unique names); ``default_tier`` serves
    ``submit(tier=None)``; ``preempt_burn_rate`` is the protected-tier
    burn past which the brownout ladder reports its top rung even before
    demand-driven preemption fires.  Immutable after construction — one
    config is safely shared by every replica of a pool (per-engine
    mutable state lives in :class:`TieredQueue`)."""

    def __init__(self, tiers=None, default_tier=None, preempt_burn_rate=8.0):
        tiers = tuple(tiers) if tiers is not None else self._default_tiers()
        if not tiers:
            raise ValueError("need at least one TierPolicy")
        names = [t.name for t in tiers]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate tier names: {names}")
        if len({t.priority for t in tiers}) != len(tiers):
            raise ValueError("tier priorities must be distinct")
        # priority-descending: index 0 is the protected tier
        self.tiers = tuple(sorted(tiers, key=lambda t: -t.priority))
        self._by_name = {t.name: t for t in self.tiers}
        self.default_tier = default_tier if default_tier is not None \
            else self.tiers[len(self.tiers) // 2].name
        if self.default_tier not in self._by_name:
            raise ValueError(f"default_tier {self.default_tier!r} not in "
                             f"{sorted(self._by_name)}")
        self.preempt_burn_rate = float(preempt_burn_rate)

    @staticmethod
    def _default_tiers():
        """The documented three-tier ladder.  ``realtime`` is protected
        (never brownout-shed, never preempted); ``standard`` sheds when
        realtime burns its error budget 4x too fast, ``batch`` at 2x."""
        return (
            TierPolicy("realtime", priority=2, weight=8, preemptible=False),
            TierPolicy("standard", priority=1, weight=3, shed_burn_rate=4.0),
            TierPolicy("batch", priority=0, weight=1, shed_burn_rate=2.0),
        )

    @property
    def names(self):
        return tuple(t.name for t in self.tiers)

    @property
    def protected(self) -> TierPolicy:
        """The highest-priority tier — whose SLO burn drives the ladder."""
        return self.tiers[0]

    def tier(self, name) -> TierPolicy:
        try:
            return self._by_name[name]
        except KeyError:
            raise ValueError(f"unknown tier {name!r}; configured tiers: "
                             f"{list(self.names)}") from None

    def resolve(self, name):
        """Submit-time tier resolution: ``None`` → the default tier;
        unknown names rejected loudly."""
        if name is None:
            return self.default_tier
        return self.tier(name).name

    def shed_tiers(self, burn_rate):
        """Tiers that shed at admission when the protected tier's burn
        rate is ``burn_rate`` (priority-ascending: batch sheds first)."""
        if burn_rate is None:
            return ()
        return tuple(t.name for t in reversed(self.tiers)
                     if t.shed_burn_rate is not None
                     and burn_rate >= t.shed_burn_rate)

    def to_dict(self):
        return {
            "default_tier": self.default_tier,
            "preempt_burn_rate": self.preempt_burn_rate,
            "tiers": [{
                "name": t.name, "priority": t.priority, "weight": t.weight,
                "preemptible": t.preemptible, "max_queue": t.max_queue,
                "shed_burn_rate": t.shed_burn_rate,
                "slo": t.slo.to_dict() if t.slo is not None else None,
            } for t in self.tiers],
        }


def brownout(config: QoSConfig, burn_rate, preempting=False):
    """The brownout ladder as a JSON-able dict: ``level`` (0 = normal,
    each shed tier adds a rung, preemption is the top rung), ``state``
    (the rung name for the default ladder, generic otherwise), ``shed``
    (tier names currently shed at admission) and the driving
    ``burn_rate``.  ``preempting=True`` — the engine evicted a slot
    recently — forces the top rung regardless of burn."""
    b = float(burn_rate) if burn_rate is not None else 0.0
    shed = config.shed_tiers(b)
    top = len(config.tiers)  # one rung past every sheddable tier
    level = len(shed)
    if preempting or b >= config.preempt_burn_rate:
        level = top
    if level == 0:
        state = "normal"
    elif level >= top:
        state = "preempt"
    else:
        state = f"shed_{shed[-1]}" if len(config.tiers) == 3 else "shed"
    return {"level": level, "state": state, "shed": list(shed),
            "burn_rate": b}


class TieredQueue:
    """Per-tier deques behind the engine's single-deque surface.

    Head selection (``[0]`` / ``popleft``) is priority-ordered weighted
    round robin: each tier holds ``weight`` credits; the head is the
    highest-priority non-empty tier with credit left, and when every
    non-empty tier is out of credits the cycle refills all of them.
    Selection is a pure function of (queues, credits), so a ``[0]`` peek
    and the ``popleft`` that follows it under the scheduler lock agree.
    ``append`` routes by ``req.tier``; ``appendleft`` — the restart /
    preemption requeue path — puts the request at the FRONT of its
    tier's deque so resumed work runs before new same-tier arrivals.
    NOT thread-safe: callers hold the engine lock, same as the plain
    deque it replaces.
    """

    def __init__(self, config: QoSConfig):
        self.config = config
        self._qs = {t.name: collections.deque() for t in config.tiers}
        self._credits = {t.name: t.weight for t in config.tiers}
        self._order = config.names  # priority-descending

    # ------------------------------------------------------- deque surface
    def __len__(self):
        return sum(len(q) for q in self._qs.values())

    def __bool__(self):
        return any(self._qs.values())

    def _head_tier(self):
        avail = [n for n in self._order if self._qs[n]]
        if not avail:
            return None
        with_credit = [n for n in avail if self._credits[n] > 0]
        # no non-empty tier has credit: the refill (done by popleft)
        # gives everyone credit, so the choice is the top-priority tier
        return (with_credit or avail)[0]

    def __getitem__(self, i):
        if i != 0:
            raise IndexError("TieredQueue only exposes the head ([0])")
        t = self._head_tier()
        if t is None:
            raise IndexError("peek from an empty TieredQueue")
        return self._qs[t][0]

    def popleft(self):
        t = self._head_tier()
        if t is None:
            raise IndexError("pop from an empty TieredQueue")
        if self._credits[t] <= 0:  # cycle exhausted: refill everyone
            for name in self._order:
                self._credits[name] = self.config.tier(name).weight
        self._credits[t] -= 1
        return self._qs[t].popleft()

    def pop_exact(self, req):
        """Pop ``req`` — known to be at the head of its tier's deque —
        applying the same credit accounting as :meth:`popleft`.  The
        scheduler peeks ``[0]``, may PREEMPT (which appendlefts victims
        into lower-priority tiers), then pops; popping by identity
        instead of re-running head selection makes that sequence immune
        to any future change in how the head is chosen."""
        t = req.tier
        q = self._qs[t]
        if not q or q[0] is not req:
            raise ValueError(
                f"pop_exact: request is not at the head of tier {t!r}")
        if self._credits[t] <= 0:
            for name in self._order:
                self._credits[name] = self.config.tier(name).weight
        self._credits[t] -= 1
        return q.popleft()

    def append(self, req):
        self._qs[req.tier].append(req)

    def appendleft(self, req):
        self._qs[req.tier].appendleft(req)

    # ------------------------------------------------------------- insight
    def depth(self, tier):
        return len(self._qs[tier])

    def depths(self):
        return {name: len(q) for name, q in self._qs.items()}

    def depth_at_or_above(self, priority):
        """Queued requests whose tier priority is >= ``priority`` — the
        queue-position population a deadline estimate for that tier
        competes with (lower tiers never delay it past one cycle)."""
        return sum(len(self._qs[t.name]) for t in self.config.tiers
                   if t.priority >= priority)


class AutoScaler:
    """Elastic replica count for a :class:`~.cluster.pool.ReplicaPool`.

    Driven by explicit :meth:`tick` calls (the
    :class:`~.cluster.service.ServingCluster` monitor thread calls it
    every poll; ``interval_s`` throttles the actual evaluation).  Scale
    decisions need their signal to hold continuously for ``stable_s``
    (hysteresis) and respect ``cooldown_s`` between events, so a traffic
    blip neither thrashes the fleet up nor collapses it mid-burst.

    - **up**: queued-per-replica >= ``scale_up_queue``, or fleet
      occupancy >= ``scale_up_occupancy``, or the protected tier's SLO
      burn (``burn_source()``) >= ``scale_up_burn_rate`` — and the pool
      is below ``max_replicas``.  Spin-up is warm: the pool replays its
      ``warmup=`` manifest before the new replica's scheduler starts.
    - **down**: empty queues and occupancy <= ``scale_down_occupancy``
      above ``min_replicas`` → the newest replica stops ADMITTING
      (``begin_drain``) and is stopped + removed only once quiescent —
      drain-then-retire, no in-flight request dropped.
    - **reap**: a replica whose health reads ``error``/``stopped`` (fatal
      crash, ``cluster.replica_preempt@<r>``) is removed immediately and
      replaced up to ``min_replicas`` without waiting out the cooldown —
      replacing lost capacity is not a scale decision.

    ``history`` records ``{"t", "replicas", "event"}`` rows (the bench's
    replica-count timeline); ``cluster.replicas{state=}`` and
    ``cluster.scale_events{direction=up|down|reap}`` export the same.
    """

    #: health states counted as serving capacity
    _LIVE = ("healthy", "degraded")
    _DEAD = ("error", "stopped")

    def __init__(self, pool, min_replicas=1, max_replicas=4,
                 scale_up_queue=4.0, scale_up_occupancy=0.85,
                 scale_up_burn_rate=2.0, scale_down_occupancy=0.25,
                 stable_s=2.0, cooldown_s=5.0, interval_s=0.25,
                 burn_source=None, cluster="0"):
        from ..profiler import metrics as _metrics

        if min_replicas < 1:
            raise ValueError(f"min_replicas must be >= 1, got {min_replicas}")
        if max_replicas < min_replicas:
            raise ValueError(f"max_replicas {max_replicas} < min_replicas "
                             f"{min_replicas}")
        self.pool = pool
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.scale_up_queue = float(scale_up_queue)
        self.scale_up_occupancy = float(scale_up_occupancy)
        self.scale_up_burn_rate = None if scale_up_burn_rate is None \
            else float(scale_up_burn_rate)
        self.scale_down_occupancy = float(scale_down_occupancy)
        self.stable_s = float(stable_s)
        self.cooldown_s = float(cooldown_s)
        self.interval_s = float(interval_s)
        self._burn_source = burn_source
        self.history = []                 # [{"t","replicas","event"}]
        self._lock = threading.Lock()
        self._retiring = None             # engine draining toward removal
        self._up_since = None             # hysteresis: signal onset stamps
        self._down_since = None
        self._last_event_t = None
        self._last_tick_t = None
        self._m_replicas = _metrics.bind(
            _metrics.gauge("cluster.replicas",
                           "pool replicas by health state"),
            cluster=str(cluster))
        self._m_events = _metrics.bind(
            _metrics.counter("cluster.scale_events",
                             "autoscaler actions by direction=up|down|reap"),
            cluster=str(cluster))

    # -------------------------------------------------------------- insight
    @property
    def retiring(self):
        return self._retiring

    def timeline(self):
        with self._lock:
            return list(self.history)

    def _record(self, event, n, now):
        self.history.append({"t": now, "replicas": n, "event": event})

    # ----------------------------------------------------------------- tick
    def tick(self, now=None):
        """Evaluate signals and maybe scale; returns the event applied
        this tick (``"up"`` / ``"down"`` / ``"reap"`` / None).  Safe to
        call from any single thread at any rate — evaluation is
        throttled to ``interval_s``."""
        now = time.monotonic() if now is None else float(now)
        with self._lock:
            if self._last_tick_t is not None \
                    and now - self._last_tick_t < self.interval_s:
                return None
            self._last_tick_t = now
            return self._tick_locked(now)

    def _tick_locked(self, now):
        event = None
        engines, states = self.pool.snapshot_states()
        # 1. finish an in-progress retirement (drain-then-retire)
        ret = self._retiring
        if ret is not None:
            hs = ret.health_state()["state"]
            if hs in self._DEAD or ret.quiescent:
                self._stop_quietly(ret)
                self.pool.remove_replica(ret)
                self._retiring = None
                self._last_event_t = now
                event = "down"
                self._m_events.inc(direction="down")
                engines, states = self.pool.snapshot_states()
                self._record("down", len(engines), now)
        # 2. reap dead replicas (fatal crash / injected replica loss) and
        #    replace lost capacity up to min_replicas — no cooldown: this
        #    restores promised capacity, it doesn't change the target
        dead = [e for e, st in zip(engines, states)
                if st["state"] in self._DEAD and e is not self._retiring]
        for e in dead:
            self._stop_quietly(e)
            self.pool.remove_replica(e)
            self._m_events.inc(direction="reap")
            event = event or "reap"
        if dead:
            engines, states = self.pool.snapshot_states()
            self._record("reap", len(engines), now)
        while len(engines) < self.min_replicas:
            self.pool.add_replica()
            self._m_events.inc(direction="up")
            engines, states = self.pool.snapshot_states()
            self._record("up", len(engines), now)
            event = event or "up"
        self._export_gauges(states)
        if self._retiring is not None:
            return event            # one state change in flight at a time
        # 3. signals over the live fleet
        live = [st for st in states if st["state"] in self._LIVE]
        n = len(live)
        if n == 0:
            return event
        queued = sum(st["queue_depth"] for st in live)
        slots = sum(st["num_slots"] for st in live) or 1
        occupancy = sum(st["active"] for st in live) / slots
        burn = None
        if self._burn_source is not None:
            try:
                burn = self._burn_source()
            except Exception:
                burn = None
        up = len(engines) < self.max_replicas and (
            queued / n >= self.scale_up_queue
            or occupancy >= self.scale_up_occupancy
            or (burn is not None and self.scale_up_burn_rate is not None
                and burn >= self.scale_up_burn_rate))
        down = (len(engines) > self.min_replicas and queued == 0
                and occupancy <= self.scale_down_occupancy)
        # hysteresis: the signal must hold since onset for stable_s
        # (explicit None checks — an onset stamp of 0.0 is a valid time)
        self._up_since = None if not up else (
            self._up_since if self._up_since is not None else now)
        self._down_since = None if not down else (
            self._down_since if self._down_since is not None else now)
        in_cooldown = (self._last_event_t is not None
                       and now - self._last_event_t < self.cooldown_s)
        if up and now - self._up_since >= self.stable_s and not in_cooldown:
            self.pool.add_replica()
            self._up_since = None
            self._last_event_t = now
            self._m_events.inc(direction="up")
            engines, states = self.pool.snapshot_states()
            self._export_gauges(states)
            self._record("up", len(engines), now)
            return "up"
        if down and now - self._down_since >= self.stable_s \
                and not in_cooldown:
            victim = engines[-1]          # newest replica retires first
            victim.begin_drain()
            self._retiring = victim
            self._down_since = None
            self._record("drain", len(engines), now)
            return event
        return event

    @staticmethod
    def _stop_quietly(engine):
        try:
            engine.stop()
        except Exception:
            pass                          # a dead engine may refuse; reap on

    def _export_gauges(self, states):
        counts = {s: 0 for s in
                  ("healthy", "degraded", "draining", "stopped", "error")}
        for st in states:
            counts[st["state"]] = counts.get(st["state"], 0) + 1
        for state, c in counts.items():
            self._m_replicas.set(c, state=state)
