"""Model adapters: the pure functions the ServingEngine jit-compiles.

An adapter reduces a causal LM to closures over explicit jax state (the
engine wraps them in ``jax.jit`` with DONATED pools, once per
(batch-shape, sampler) tuple — the ``_decode.py`` discipline).  The KV
state is an adapter-defined POOL TUPLE of ``n_pools`` arrays: the base
:class:`GPTAdapter` carries ``(kp, vp)`` per-layer global page pools; the
quantized :class:`~paddle_tpu.serving.quant.QuantizedGPTAdapter` carries
``(kp, vp, k_scales, v_scales)`` — int8 payloads plus parallel scale
pools.  The engine treats the tuple opaquely (build, donate, rebind), so
one scheduler serves every pool layout.

- ``prefill(params, bufs, ids, *pools, table, lens)`` — run the
  (right-padded) prompts ``ids [B, S]`` densely, write their K/V into the
  global page pools through ``table [B, NP]``, and return the next-token
  logits gathered at each row's true last position ``lens[b] - 1``.
- ``step(params, bufs, last, *pools, table, lens)`` — one decode token per
  slot at each slot's OWN position ``lens[b]`` (iteration-level batching:
  no lock-step scalar pos), attention through the paged kernel.
- ``verify(params, bufs, ids, *pools, table, lens)`` — speculative
  decoding's multi-token step: C tokens per slot at positions
  ``lens[b]..lens[b]+C-1`` through the chunk cache variant, returning
  logits at EVERY position so the engine can accept/reject the drafted
  suffix (serving/speculative.py).
- ``prefill_chunk(params, bufs, ids, nvalid, *pools, table, lens)`` —
  chunked prefill's ingestion step: the next C prompt tokens per slot
  through the same chunk cache variant, logits at each row's last real
  chunk lane (``nvalid[b] - 1``) so the final chunk seeds decode exactly
  like a monolithic prefill.

prefill/step return ``(logits [B, V] f32, *pools)``, verify
``(logits [B, C, V] f32, *pools)``, with each pool a per-layer-stacked
``[L, P, ...]`` array.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


class GPTAdapter:
    """Adapter for :class:`paddle_tpu.text.models.GPTForCausalLM` (and any
    model exposing the same ``.gpt`` decoder structure with the "served"
    cache variant).  Subclasses override the pool hooks (``init_pools`` /
    ``_layer_caches`` / ``_stack_pools``) and the cache tags to change the
    KV storage format without touching the closure shapes."""

    #: GPTDecoderLayer cache-variant tags this adapter drives
    tag = "served"
    chunk_tag = "served_chunk"
    #: number of arrays in the pool tuple (the engine donates all of them)
    n_pools = 2
    #: storage format label ("native" = the model dtype)
    kv_dtype = "native"

    def __init__(self, model, page_size=16):
        self.model = model
        self.gpt = model.gpt
        blk = self.gpt.layers[0]
        self.num_layers = len(self.gpt.layers)
        self.head_dim = blk.head_dim
        # local head count from the actual projection width (TP-safe); an
        # int8-weight model (serving.quant.quantize_model_weights) stores
        # the projection as an Int8Linear whose weight lives in the
        # ``weight_int8`` buffer — same shape, different attribute
        qkv_w = getattr(blk.qkv, "weight", None)
        if qkv_w is None:
            qkv_w = blk.qkv.weight_int8
        self.num_kv_heads = qkv_w.shape[-1] // (3 * blk.head_dim)
        self.dtype = self.gpt.word_embeddings.weight._value.dtype
        self.max_model_len = self.gpt.position_embeddings.weight.shape[0]
        self.page_size = int(page_size)

    #: set by ServingEngine(mesh=...) — the jax Mesh whose "model" axis the
    #: pools/weights are sharded over (None = single-device serving).  The
    #: TPU flash kernels consult it at trace time (mp_shard_scope) so each
    #: shard's Pallas page sweep covers only its local KV heads.
    mp_mesh = None
    mp_axis = "model"

    def params_and_buffers(self):
        # under the bind lock: another replica of this model may be inside
        # a trace-time bind() on its scheduler thread right now
        with self.model.bind_lock():
            params = {k: p._value for k, p in self.model.named_parameters()}
            bufs = {k: b._value for k, b in self.model.named_buffers()}
        return params, bufs

    def signature(self):
        """Static geometry a compiled program is specialized on, as a
        JSON-plain dict.  Stamped into :class:`~paddle_tpu.observability
        .programs.WarmupManifest` metadata so a manifest captured against
        one model is refused by an engine whose replay would only mint
        useless programs."""
        return {"adapter": type(self).__name__,
                "kv_dtype": self.kv_dtype,
                "n_pools": int(self.n_pools),
                "num_layers": int(self.num_layers),
                "num_kv_heads": int(self.num_kv_heads),
                "head_dim": int(self.head_dim),
                "page_size": int(self.page_size),
                "max_model_len": int(self.max_model_len),
                "dtype": str(self.dtype)}

    # --------------------------------------------------------- mp sharding
    def validate_mp(self, mp):
        """Divisibility check for ``ServingEngine(mesh=...)``: the pools
        shard on the KV-head dim and the qkv split is head-granular, so
        every shard must own a whole number of heads."""
        mp = int(mp)
        if self.num_kv_heads % mp:
            raise ValueError(
                f"tensor-parallel serving needs num_kv_heads divisible by "
                f"the mesh's model axis: {self.num_kv_heads} heads % "
                f"mp={mp} != 0")

    def pool_pspecs(self, axis="model"):
        """PartitionSpec per pool array: payload pools [L, P, ps, h, d]
        shard the KV-head dim (page table stays replicated — every shard
        addresses the same page slots, each holding its own heads)."""
        from jax.sharding import PartitionSpec as P

        return (P(None, None, None, axis, None),) * self.n_pools

    def param_pspec(self, name, axis="model"):
        """PartitionSpec for one named parameter/buffer under mp serving:
        the Megatron column/row split from gpt.mp_param_specs, replicated
        for everything outside the decoder matmuls."""
        from jax.sharding import PartitionSpec as P
        from ..text.models.gpt import mp_param_specs

        for suf, spec in mp_param_specs(axis).items():
            if name.endswith(suf):
                return spec
        return P()

    # ----------------------------------------------------------- pool hooks
    def init_pools(self, num_pages):
        """Zeroed per-layer K/V pools ``(kp, vp)``, each [L, P, ps, h, d]."""
        shape = (self.num_layers, int(num_pages), self.page_size,
                 self.num_kv_heads, self.head_dim)
        kp = jnp.zeros(shape, self.dtype)
        return kp, jnp.zeros_like(kp)

    def page_bytes(self):
        """HBM bytes ONE page costs across all layers, K and V (the unit
        BlockManager capacity math and the serving.kv_bytes_per_token
        gauge are denominated in)."""
        return (2 * self.num_layers * self.page_size * self.num_kv_heads
                * self.head_dim * jnp.dtype(self.dtype).itemsize)

    def pool_owners(self):
        """Memory-ledger owner labels over the pool tuple: ``(owner,
        pool-index tuple)`` pairs covering EVERY pool array, so the
        engine's ledger registration attributes payload and scale pools
        separately (observability.memory owner taxonomy)."""
        return (("kv.pages", tuple(range(self.n_pools))),)

    def _layer_caches(self, pools, table, lens, tag):
        """Per-layer GPTDecoderLayer cache tuples from the pool tuple."""
        from ..tensor.tensor import Tensor

        kp, vp = pools
        return [(tag, Tensor(kp[i]), Tensor(vp[i]), Tensor(table),
                 Tensor(lens)) for i in range(self.num_layers)]

    def _stack_pools(self, new_cache):
        """Re-stack the per-layer cache tuples into the pool tuple."""
        return (jnp.stack([c[1]._value for c in new_cache]),
                jnp.stack([c[2]._value for c in new_cache]))

    # ------------------------------------------------------------- closures
    def _run(self, params, bufs, ids, pools, table, lens, pos_ids, tag,
             lora=None):
        from ..framework import random as _rng
        from ..framework.state import no_grad_ctx
        from ..ops.paged_attention import mp_shard_scope
        from ..tensor.tensor import Tensor

        gpt = self.gpt
        with no_grad_ctx(), _rng.rng_scope(jax.random.key(0)), \
                self.model.bind(params, bufs), \
                mp_shard_scope(self.mp_mesh, self.mp_axis):
            lc = self._layer_caches(pools, table, lens, tag)
            x, new_cache = gpt(Tensor(ids), position_ids=Tensor(pos_ids),
                               cache=lc, lora=lora)
            w = gpt.word_embeddings.weight._value
            return x._value, w, self._stack_pools(new_cache)

    def _split(self, args):
        """``(*pools, table, lens)`` -> (pools tuple, table, lens)."""
        if len(args) != self.n_pools + 2:
            raise TypeError(
                f"{type(self).__name__} closures take {self.n_pools} pool "
                f"arrays + table + lens; got {len(args)} trailing args")
        return tuple(args[:self.n_pools]), args[-2], args[-1]

    def _split_extra(self, args):
        """``(pools, table, lens, lora)`` — THE extension hook: an
        adapter carrying extra trailing dispatch args (multi-tenant LoRA:
        per-row adapter ids + the rank-bucketed pools) overrides this one
        method; the prefill/step/verify/encode closure bodies below stay
        single-copy."""
        pools, table, lens = self._split(args)
        return pools, table, lens, None

    def prefill(self, params, bufs, ids, *args):
        pools, table, lens, lora = self._split_extra(args)
        S = ids.shape[1]
        pos_ids = jnp.arange(S, dtype=jnp.int64)[None, :]
        x, w, pools = self._run(params, bufs, ids, pools, table, lens,
                                pos_ids, self.tag, lora=lora)
        # logits at each row's LAST REAL position (rows are right-padded)
        idx = (lens.astype(jnp.int32) - 1)[:, None, None]
        h = jnp.take_along_axis(x, idx, axis=1)[:, 0]
        logits = h.astype(jnp.float32) @ w.T.astype(jnp.float32)
        return (logits,) + pools

    def encode(self, params, bufs, ids, *args):
        """Embedding/scoring forward (multi-tenant serving's
        ``mode="embed"|"score"`` requests): run the (right-padded) prompts
        like :meth:`prefill` but return the FULL hidden states and the
        tied LM-head weights instead of last-position logits — the embed
        program pools them, the score program turns them into per-token
        logprobs.  K/V still flows through the pool writes (the caller
        points every table row at the scratch page, so nothing is
        allocated and the junk is never attended).

        Returns ``(hidden [B, S, H] f32, w [V, H] f32, *pools)``."""
        pools, table, lens, lora = self._split_extra(args)
        S = ids.shape[1]
        pos_ids = jnp.arange(S, dtype=jnp.int64)[None, :]
        x, w, pools = self._run(params, bufs, ids, pools, table, lens,
                                pos_ids, self.tag, lora=lora)
        return (x.astype(jnp.float32), w.astype(jnp.float32)) + pools

    def encode_chunk(self, params, bufs, ids, *args):
        """Prefix-cached embed/score forward: run ``ids [B, C]`` — the
        UNSHARED tail of each prompt — at per-slot positions
        ``lens[b]..lens[b]+C-1`` through the chunk cache variant, attending
        the resident shared-run pages the table points at.  Because K/V at
        position p is a pure function of tokens 0..p, hiddens for the tail
        computed this way are byte-identical to a full-prompt
        :meth:`encode`, which is what lets multi-tenant embed/score skip
        recompute of a cached system prompt.  The tail's own K/V lands in
        the table rows past the shared run — the caller points those at
        the scratch page (tail < page_size means every lane gets a
        DISTINCT in-page offset, so within-dispatch causality still
        holds) or at transient pages for longer tails.

        Returns ``(hidden [B, C, H] f32, w [V, H] f32, *pools)`` — the
        :meth:`encode` contract over tail positions only."""
        pools, table, lens, lora = self._split_extra(args)
        C = ids.shape[1]
        pos_ids = lens[:, None].astype(jnp.int64) \
            + jnp.arange(C, dtype=jnp.int64)[None, :]
        pos_ids = jnp.minimum(pos_ids, self.max_model_len - 1)
        x, w, pools = self._run(params, bufs, ids, pools, table, lens,
                                pos_ids, self.chunk_tag, lora=lora)
        return (x.astype(jnp.float32), w.astype(jnp.float32)) + pools

    def step(self, params, bufs, last, *args):
        pools, table, lens, lora = self._split_extra(args)
        pos_ids = lens[:, None].astype(jnp.int64)
        x, w, pools = self._run(params, bufs, last, pools, table, lens,
                                pos_ids, self.tag, lora=lora)
        logits = x[:, -1].astype(jnp.float32) @ w.T.astype(jnp.float32)
        return (logits,) + pools

    def verify(self, params, bufs, ids, *args):
        """Multi-token verification step (speculative decoding): run
        ``ids [B, C]`` — each row the slot's last sampled token followed by
        C-1 draft tokens — at per-slot positions ``lens[b]..lens[b]+C-1``.
        All C K/V per slot are written into the global pools and attended
        against them in ONE call (the chunk cache variant), and logits
        come back for EVERY position: ``logits[b, t]`` is the next-token
        distribution after ``ids[b, :t+1]``, which is exactly what
        accepting/rejecting draft t+1 needs.

        Returns ``(logits [B, C, V] f32, *pools)``."""
        pools, table, lens, lora = self._split_extra(args)
        C = ids.shape[1]
        pos_ids = lens[:, None].astype(jnp.int64) \
            + jnp.arange(C, dtype=jnp.int64)[None, :]
        # clamp: rows shorter than the padded draft may reach past the
        # position table near the model cap; those positions' logits are
        # junk the engine never reads (draft lengths are capped host-side)
        pos_ids = jnp.minimum(pos_ids, self.max_model_len - 1)
        x, w, pools = self._run(params, bufs, ids, pools, table, lens,
                                pos_ids, self.chunk_tag, lora=lora)
        logits = x.astype(jnp.float32) @ w.T.astype(jnp.float32)
        return (logits,) + pools

    def prefill_chunk(self, params, bufs, ids, nvalid, *args):
        """One CHUNK of a long prompt's prefill: run ``ids [B, C]`` — the
        next C prompt tokens of each row, right-padded past ``nvalid[b]``
        — at per-slot positions ``lens[b]..lens[b]+C-1`` through the chunk
        cache variant (the verify machinery reused for prompt ingestion:
        within-chunk causality and the pool writes come for free), and
        return the next-token logits at each row's last REAL chunk lane
        ``nvalid[b] - 1``.  Pad-lane K/V lands past the row's valid length
        (or in dropped OOB lanes), invisible to seq_lens masking and
        overwritten by the next chunk/decode write — the
        paged_table_chunk_write contract.

        Only the FINAL chunk's logits are consumed (they seed decode);
        intermediate chunks exist for their pool writes.  Returns
        ``(logits [B, V] f32, *pools)`` — the prefill contract, so the
        engine's sampler/guard plumbing is shared."""
        pools, table, lens, lora = self._split_extra(args)
        C = ids.shape[1]
        pos_ids = lens[:, None].astype(jnp.int64) \
            + jnp.arange(C, dtype=jnp.int64)[None, :]
        pos_ids = jnp.minimum(pos_ids, self.max_model_len - 1)
        x, w, pools = self._run(params, bufs, ids, pools, table, lens,
                                pos_ids, self.chunk_tag, lora=lora)
        idx = jnp.maximum(nvalid.astype(jnp.int32) - 1, 0)[:, None, None]
        h = jnp.take_along_axis(x, idx, axis=1)[:, 0]
        logits = h.astype(jnp.float32) @ w.T.astype(jnp.float32)
        return (logits,) + pools
