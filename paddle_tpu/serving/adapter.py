"""Model adapters: the pure functions the ServingEngine jit-compiles.

An adapter reduces a causal LM to two closures over explicit jax state
(the engine wraps them in ``jax.jit`` with DONATED pools, once per
(batch-shape, sampler) tuple — the ``_decode.py`` discipline):

- ``prefill(params, bufs, ids, kp, vp, table, lens)`` — run the
  (right-padded) prompts ``ids [B, S]`` densely, write their K/V into the
  global page pools through ``table [B, NP]``, and return the next-token
  logits gathered at each row's true last position ``lens[b] - 1``.
- ``step(params, bufs, last, kp, vp, table, lens)`` — one decode token per
  slot at each slot's OWN position ``lens[b]`` (iteration-level batching:
  no lock-step scalar pos), attention through the paged kernel.
- ``verify(params, bufs, ids, kp, vp, table, lens)`` — speculative
  decoding's multi-token step: C tokens per slot at positions
  ``lens[b]..lens[b]+C-1`` through the "served_chunk" cache variant,
  returning logits at EVERY position so the engine can accept/reject the
  drafted suffix (serving/speculative.py).

prefill/step return ``(logits [B, V] f32, kp, vp)``, verify
``(logits [B, C, V] f32, kp, vp)``, with ``kp/vp: [L, P, ps, h, d]``
stacked per-layer global pools.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


class GPTAdapter:
    """Adapter for :class:`paddle_tpu.text.models.GPTForCausalLM` (and any
    model exposing the same ``.gpt`` decoder structure with the "served"
    cache variant)."""

    def __init__(self, model, page_size=16):
        self.model = model
        self.gpt = model.gpt
        blk = self.gpt.layers[0]
        self.num_layers = len(self.gpt.layers)
        self.head_dim = blk.head_dim
        # local head count from the actual projection width (TP-safe)
        self.num_kv_heads = blk.qkv.weight.shape[-1] // (3 * blk.head_dim)
        self.dtype = self.gpt.word_embeddings.weight._value.dtype
        self.max_model_len = self.gpt.position_embeddings.weight.shape[0]
        self.page_size = int(page_size)

    def params_and_buffers(self):
        # under the bind lock: another replica of this model may be inside
        # a trace-time bind() on its scheduler thread right now
        with self.model.bind_lock():
            params = {k: p._value for k, p in self.model.named_parameters()}
            bufs = {k: b._value for k, b in self.model.named_buffers()}
        return params, bufs

    def init_pools(self, num_pages):
        """Zeroed per-layer K/V pools [L, P, ps, h, d]."""
        shape = (self.num_layers, int(num_pages), self.page_size,
                 self.num_kv_heads, self.head_dim)
        kp = jnp.zeros(shape, self.dtype)
        return kp, jnp.zeros_like(kp)

    # ------------------------------------------------------------- closures
    def _run(self, params, bufs, ids, kp, vp, table, lens, pos_ids,
             tag="served"):
        from ..framework import random as _rng
        from ..framework.state import no_grad_ctx
        from ..tensor.tensor import Tensor

        gpt = self.gpt
        with no_grad_ctx(), _rng.rng_scope(jax.random.key(0)), \
                self.model.bind(params, bufs):
            lc = [(tag, Tensor(kp[i]), Tensor(vp[i]), Tensor(table),
                   Tensor(lens)) for i in range(self.num_layers)]
            x, new_cache = gpt(Tensor(ids), position_ids=Tensor(pos_ids),
                               cache=lc)
            w = gpt.word_embeddings.weight._value
            kp = jnp.stack([c[1]._value for c in new_cache])
            vp = jnp.stack([c[2]._value for c in new_cache])
            return x._value, w, kp, vp

    def prefill(self, params, bufs, ids, kp, vp, table, lens):
        S = ids.shape[1]
        pos_ids = jnp.arange(S, dtype=jnp.int64)[None, :]
        x, w, kp, vp = self._run(params, bufs, ids, kp, vp, table, lens,
                                 pos_ids)
        # logits at each row's LAST REAL position (rows are right-padded)
        idx = (lens.astype(jnp.int32) - 1)[:, None, None]
        h = jnp.take_along_axis(x, idx, axis=1)[:, 0]
        logits = h.astype(jnp.float32) @ w.T.astype(jnp.float32)
        return logits, kp, vp

    def step(self, params, bufs, last, kp, vp, table, lens):
        pos_ids = lens[:, None].astype(jnp.int64)
        x, w, kp, vp = self._run(params, bufs, last, kp, vp, table, lens,
                                 pos_ids)
        logits = x[:, -1].astype(jnp.float32) @ w.T.astype(jnp.float32)
        return logits, kp, vp

    def verify(self, params, bufs, ids, kp, vp, table, lens):
        """Multi-token verification step (speculative decoding): run
        ``ids [B, C]`` — each row the slot's last sampled token followed by
        C-1 draft tokens — at per-slot positions ``lens[b]..lens[b]+C-1``.
        All C K/V per slot are written into the global pools and attended
        against them in ONE call (the "served_chunk" cache variant), and
        logits come back for EVERY position: ``logits[b, t]`` is the
        next-token distribution after ``ids[b, :t+1]``, which is exactly
        what accepting/rejecting draft t+1 needs.

        Returns ``(logits [B, C, V] f32, kp, vp)``."""
        C = ids.shape[1]
        pos_ids = lens[:, None].astype(jnp.int64) \
            + jnp.arange(C, dtype=jnp.int64)[None, :]
        # clamp: rows shorter than the padded draft may reach past the
        # position table near the model cap; those positions' logits are
        # junk the engine never reads (draft lengths are capped host-side)
        pos_ids = jnp.minimum(pos_ids, self.max_model_len - 1)
        x, w, kp, vp = self._run(params, bufs, ids, kp, vp, table, lens,
                                 pos_ids, tag="served_chunk")
        logits = x.astype(jnp.float32) @ w.T.astype(jnp.float32)
        return logits, kp, vp
