"""Host-DRAM KV spill tier — the middle rung of the hierarchical cache.

Device pages -> host spill -> recompute: when the radix prefix index
(serving/prefix_index.py) evicts an idle page to refill the free list,
the BlockManager hands ``(prefix_key, page)`` here FIRST, and the tier
copies the page's bytes out of every pool array — payload AND scale
pools for int8 serving, since the snapshot walks the whole pool tuple —
into host numpy buffers (the same device->host snapshot discipline as
``resilience.checkpoint``).  A later allocate whose radix match ends
where a spilled prefix begins RESURRECTS it: the engine re-pages the
host bytes into a freshly popped device slot (a ``.at[page].set`` /
device_put per pool) and the page rejoins the resident tree as cached
K/V — the prompt tokens it covers skip prefill compute exactly like a
device hit, at one PCIe round-trip instead of a forward pass.

Budgeted and LRU within the tier: ``PADDLE_KV_SPILL_BUDGET_BYTES`` (or
the ``budget_bytes`` ctor arg) caps host bytes; the least-recently
spilled entries drop when a new spill would overflow.  Every resident
byte is accounted to the MemoryLedger under the ``kv.spilled`` HOST
owner (device="host" rows sit outside jax.live_arrays reconciliation,
like checkpoint.snapshot), so /statusz and the watchdog see the tier.

Spilled bytes stay valid across engine recovery in principle (K/V is a
pure function of tokens + weights), but the engine clears the tier in
``_recover`` anyway: a rebuilt BlockManager starts with an empty radix
tree, and a coherent cold start is worth more than a warm one that
needs cross-checking.
"""

from __future__ import annotations

import collections
import os
import threading

_DEFAULT_BUDGET = 256 << 20  # 256 MiB of host DRAM unless told otherwise


def spill_budget_bytes(budget_bytes=None):
    """Resolve the host-tier budget: explicit arg beats the
    ``PADDLE_KV_SPILL_BUDGET_BYTES`` env (the deploy-time knob the
    perf candidate_hint names when resurrections thrash) beats the
    built-in default."""
    if budget_bytes is not None:
        return int(budget_bytes)
    v = os.environ.get("PADDLE_KV_SPILL_BUDGET_BYTES")
    if v:
        try:
            return int(v)
        except ValueError:
            pass
    return _DEFAULT_BUDGET


class KVSpillTier:
    """Content-addressed host cache of evicted KV pages.

    The tier is transport-agnostic: the engine attaches ``snapshot(page)
    -> tuple[np.ndarray]`` (device->host, one array per pool) and
    ``restore(page, payload)`` (host->device) callables, so one tier
    serves every pool layout — (kp, vp) native or (kp, vp, ks, vs) int8,
    where walking the tuple keeps payload+scale pairs together by
    construction."""

    def __init__(self, replica="0", budget_bytes=None):
        self.replica = str(replica)
        self.budget_bytes = spill_budget_bytes(budget_bytes)
        self._entries = collections.OrderedDict()  # key -> tuple[np arrays]
        self._nbytes = 0
        self._snapshot = None
        self._restore = None
        self._lock = threading.Lock()
        self._spills = 0
        self._resurrections = 0
        self._drops = 0
        from ..profiler import metrics as _metrics

        self._m_spills = _metrics.bind(_metrics.counter(
            "serving.kv_spill_pages",
            "idle KV pages spilled to the host tier instead of dropped"),
            replica=self.replica)
        self._m_resurrections = _metrics.bind(_metrics.counter(
            "serving.kv_spill_resurrections",
            "spilled pages re-paged into device slots on a prefix hit"),
            replica=self.replica)
        self._m_drops = _metrics.bind(_metrics.counter(
            "serving.kv_spill_drops",
            "spilled pages dropped LRU to stay inside the host budget"),
            replica=self.replica)
        self._m_bytes = _metrics.bind(_metrics.gauge(
            "serving.kv_spill_bytes",
            "host DRAM bytes resident in the KV spill tier"),
            replica=self.replica)

    def attach(self, snapshot, restore):
        self._snapshot = snapshot
        self._restore = restore

    # ------------------------------------------------------------- inventory
    def nbytes(self):
        """Resident host bytes — the ``kv.spilled`` ledger owner's
        source (observability.memory; weakref'd by the engine)."""
        return self._nbytes

    def __len__(self):
        return len(self._entries)

    def contains(self, key):
        return key in self._entries

    def stats(self):
        return {
            "entries": len(self._entries),
            "bytes": self._nbytes,
            "budget_bytes": self.budget_bytes,
            "spills": self._spills,
            "resurrections": self._resurrections,
            "drops": self._drops,
        }

    # -------------------------------------------------------------- transfer
    def spill(self, key, page):
        """Copy ``page``'s bytes host-side under ``key`` (the full token
        prefix the page encodes).  Called by the BlockManager at evict
        time, BEFORE the device row is handed back for reuse.  Returns
        False when unattached or the page alone exceeds the budget."""
        if self._snapshot is None:
            return False
        payload = tuple(self._snapshot(page))
        nb = sum(int(a.nbytes) for a in payload)
        with self._lock:
            if nb > self.budget_bytes:
                self._drops += 1
                self._m_drops.inc()
                return False
            old = self._entries.pop(key, None)
            if old is not None:
                self._nbytes -= sum(int(a.nbytes) for a in old)
            while self._entries and self._nbytes + nb > self.budget_bytes:
                _, dropped = self._entries.popitem(last=False)
                self._nbytes -= sum(int(a.nbytes) for a in dropped)
                self._drops += 1
                self._m_drops.inc()
            self._entries[key] = payload
            self._nbytes += nb
            self._spills += 1
            self._m_spills.inc()
            self._m_bytes.set(self._nbytes)
        return True

    def resurrect(self, key, page):
        """Re-page a spilled entry into device slot ``page`` and drop the
        host copy (the page can spill again later).  Returns False when
        the key is absent — the caller falls back to fresh allocation
        plus prefill compute, the bottom rung of the hierarchy."""
        with self._lock:
            payload = self._entries.pop(key, None)
            if payload is None:
                return False
            self._nbytes -= sum(int(a.nbytes) for a in payload)
            self._resurrections += 1
            self._m_resurrections.inc()
            self._m_bytes.set(self._nbytes)
        self._restore(page, payload)
        return True

    def clear(self):
        with self._lock:
            self._entries.clear()
            self._nbytes = 0
            self._m_bytes.set(0)
