"""Calibration / accuracy harness for quantized serving.

``calibrate(model, prompts)`` answers the question an operator asks before
flipping ``kv_dtype="int8"`` in production: *what does the int8 path cost
in accuracy, and what does it buy in HBM?*  It

1. runs the CALIBRATION BATCH through the full-precision engine first
   (greedy), recording every request's token stream — the reference;
2. measures per-layer K/V round-trip error on the calibration prompts
   (dense forward capturing each layer's K/V, quantized onto the pool
   grid and compared back) and per-layer weight round-trip error;
3. picks weight scales (``method="absmax"`` or outlier-robust
   ``"percentile"``) and — when ``weight_dtype="int8"`` — converts the
   model via :func:`~.weights.quantize_model_weights`;
4. runs the SAME prompts through the int8 engine
   (``ServingEngine(kv_dtype="int8")``) and reports **top-1 agreement**:
   the fraction of generated positions whose greedy token matches the
   full-precision stream;
5. reports the occupancy side: bytes per KV token for both layouts and
   the resident-slot ratio at an identical page-pool HBM budget.

The reference runs BEFORE any conversion, so one model object suffices —
weight conversion is in-place (see ``weights.py``).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def choose_scale(x, axis=None, method="absmax", pct=99.9, bits=8, eps=1e-8):
    """Scale selection for a symmetric int grid: ``absmax`` covers every
    value (no clipping, coarser grid); ``percentile`` clips the top
    ``100 - pct`` percent of magnitudes for a finer grid on the bulk —
    the better trade when outliers are rare (asserted in the round-trip
    unit tests).  Returns the scale with ``keepdims`` semantics matching
    :func:`paddle_tpu.quantization.absmax_scale`."""
    from ...quantization import absmax_scale

    if method == "absmax":
        return absmax_scale(x, axis=axis, bits=bits, eps=eps)
    if method != "percentile":
        raise ValueError(f"method must be 'absmax' or 'percentile', "
                         f"got {method!r}")
    qmax = 2.0 ** (bits - 1) - 1
    a = jnp.abs(x.astype(jnp.float32))
    m = jnp.percentile(a, pct) if axis is None \
        else jnp.percentile(a, pct, axis=axis, keepdims=True)
    return jnp.maximum(m, jnp.float32(eps)) / jnp.float32(qmax)


def kv_quant_error(model, prompts, bits=8):
    """Per-layer K/V round-trip error on the calibration prompts.

    Runs each prompt densely through the decoder with the legacy
    concat-cache variant (which hands back every layer's raw K/V — exactly
    the tensors the paged writes would quantize), rounds them onto the
    pool grid (per-position-per-head absmax, ``ops.paged_attention.
    quantize_kv``'s layout) and returns the relative L2 error per layer."""
    from ...framework.state import no_grad_ctx
    from ...ops.quant import dequantize, quantize_absmax
    from ...tensor.tensor import Tensor

    gpt = model.gpt
    L = len(gpt.layers)
    blk = gpt.layers[0]
    qkv_w = getattr(blk.qkv, "weight", None)
    if qkv_w is None:
        qkv_w = blk.qkv.weight_int8
    h = qkv_w.shape[-1] // (3 * blk.head_dim)
    sq_err = np.zeros(L)
    sq_ref = np.zeros(L)
    for p in prompts:
        ids = Tensor(jnp.asarray(np.asarray(p, np.int64)[None, :]))
        empty = jnp.zeros((1, 0, h, blk.head_dim),
                          gpt.word_embeddings.weight._value.dtype)
        lc = [(Tensor(empty), Tensor(empty)) for _ in range(L)]
        with no_grad_ctx():
            _, new_cache = gpt(ids, cache=lc)
        for i, (k, v) in enumerate(new_cache):
            for t in (k._value, v._value):
                t = t.astype(jnp.float32)
                q, scale = quantize_absmax(t, axis=-1, bits=bits)
                d = dequantize(q, scale) - t
                sq_err[i] += float(jnp.sum(d * d))
                sq_ref[i] += float(jnp.sum(t * t))
    return [float(np.sqrt(e / max(r, 1e-12)))
            for e, r in zip(sq_err, sq_ref)]


def _run_engine(model, prompts, max_new_tokens, kv_dtype, page_size,
                num_slots, timeout, engine_kwargs):
    from ..engine import ServingEngine

    max_len = max(len(p) for p in prompts) + max_new_tokens
    eng = ServingEngine(model, num_slots=num_slots, page_size=page_size,
                        max_model_len=max_len, kv_dtype=kv_dtype,
                        **(engine_kwargs or {}))
    with eng:
        handles = [eng.submit(p, max_new_tokens=max_new_tokens)
                   for p in prompts]
        ids = [h.result(timeout=timeout) for h in handles]
        stats = eng.stats()
    return ids, stats


def top1_agreement(ref_ids, got_ids):
    """Fraction of generated positions whose token matches the reference
    stream, over all requests (compared up to the shorter stream)."""
    match = total = 0
    for r, g in zip(ref_ids, got_ids):
        n = min(len(r), len(g))
        total += max(len(r), len(g))
        match += sum(1 for i in range(n) if r[i] == g[i])
    return match / total if total else 1.0


def calibrate(model, prompts, max_new_tokens=32, weight_dtype=None,
              scale_method="absmax", pct=99.9, bits=8, page_size=16,
              num_slots=4, engine_kwargs=None, timeout=600):
    """Run the calibration workflow (module docstring) and return the
    report dict.  ``weight_dtype="int8"`` additionally converts the
    model's Linears in place (reference is captured first)."""
    from ..adapter import GPTAdapter
    from .adapter import QuantizedGPTAdapter
    from .weights import quantize_model_weights, weight_quant_error

    prompts = [[int(t) for t in np.asarray(p).reshape(-1)] for p in prompts]

    # 1. full-precision reference FIRST (weight conversion is in-place)
    ref_ids, ref_stats = _run_engine(
        model, prompts, max_new_tokens, None, page_size, num_slots,
        timeout, engine_kwargs)

    # 2. per-layer round-trip errors on the calibration batch
    per_layer_kv = kv_quant_error(model, prompts, bits=bits)
    per_layer_w = weight_quant_error(model, bits=bits)

    # 3. weight scales (+ optional in-place conversion)
    converted = 0
    scales = None
    if weight_dtype is not None and str(weight_dtype).lower() == "int8":
        from ...nn import Linear

        scales = {}
        for name, sub in model.named_sublayers(include_self=False):
            if isinstance(sub, Linear):
                scales[name] = float(choose_scale(
                    sub.weight._value, method=scale_method, pct=pct,
                    bits=bits))
        converted = quantize_model_weights(model, scales=scales, bits=bits)

    # 4. the int8 engine on the same prompts
    q_ids, q_stats = _run_engine(
        model, prompts, max_new_tokens, "int8", page_size, num_slots,
        timeout, engine_kwargs)
    agreement = top1_agreement(ref_ids, q_ids)

    # 5. occupancy: bytes/token and resident slots at an equal HBM budget
    base = GPTAdapter(model, page_size)
    quant = QuantizedGPTAdapter(model, page_size)
    bpt = {"reference": base.page_bytes() / page_size,
           "int8": quant.page_bytes() / page_size}
    return {
        "requests": len(prompts),
        "max_new_tokens": max_new_tokens,
        "top1_agreement": agreement,
        "per_layer_kv_error": per_layer_kv,
        "per_layer_weight_error": per_layer_w,
        "weight_scales": scales,
        "weights_converted": converted,
        "kv_bytes_per_token": bpt,
        "occupancy_ratio": bpt["reference"] / bpt["int8"],
        "reference_stats": ref_stats,
        "quantized_stats": q_stats,
        "reference_ids": ref_ids,
        "quantized_ids": q_ids,
    }
