"""Int8 weight path for the serving engine.

``quantize_model_weights(model)`` converts every decoder ``nn.Linear`` to
a :class:`paddle_tpu.quantization.Int8Linear` IN PLACE — weights live in
HBM as int8 buffers, matmuls run int8 x int8 -> int32 on the MXU, and the
shared grid (``quantization.quantize`` / ``quantize_absmax``) guarantees
the scales agree with the KV-pool path.  ``ServingEngine(weight_dtype=
"int8")`` calls this before building its adapter; the conversion is
idempotent, so N cluster replicas over one shared model convert it once.

Scales come from, in priority order:

1. an explicit ``scales`` dict ``{sublayer_name: w_scale}`` — e.g. the
   output of :func:`paddle_tpu.quantization.extract_scales` after a
   PTQ/QAT pass, with the ``.weight_quanter`` suffix accepted too, or the
   calibration harness (``serving.quant.calibrate``);
2. per-layer absmax over the current weight values (the PTQ-free default).

Activations quantize dynamically per call (Int8Linear's ``act_scale=None``
path) unless ``scales`` carries ``<name>.act_quanter`` entries.

NOTE: conversion mutates the model the caller passed in — generate() and
every engine sharing it see int8 weights afterwards.  To compare against
the full-precision model, run the reference BEFORE converting (what
``serving.quant.calibrate`` does).
"""

from __future__ import annotations

import jax.numpy as jnp


def _resolve_parent(model, name):
    parent = model
    parts = name.split(".")
    for p in parts[:-1]:
        parent = getattr(parent, p)
    return parent, parts[-1]


def quantize_model_weights(model, scales=None, bits=8):
    """Convert the model's ``nn.Linear`` sublayers to int8 (see module
    docstring).  Returns the number of layers converted this call (0 when
    the model was already converted — the idempotence the cluster's
    shared-model replicas rely on)."""
    from ...nn import Linear
    from ...quantization import Int8Linear, absmax_scale

    scales = scales or {}
    converted = 0
    for name, sub in list(model.named_sublayers(include_self=False)):
        if not isinstance(sub, Linear):
            continue
        w_scale = scales.get(name, scales.get(f"{name}.weight_quanter"))
        if w_scale is None:
            w_scale = float(absmax_scale(sub.weight._value, bits=bits))
        if w_scale <= 1e-7:
            # degenerate scale (un-calibrated observer floor): converting
            # would saturate every weight — leave this layer full precision
            continue
        act_scale = scales.get(f"{name}.act_quanter")
        parent, attr = _resolve_parent(model, name)
        setattr(parent, attr,
                Int8Linear(sub, w_scale, act_scale, bits=bits))
        converted += 1
    return converted


def weight_quant_error(model, bits=8):
    """Per-Linear relative round-trip error ``||deq(q(w)) - w|| / ||w||``
    for every not-yet-converted ``nn.Linear`` — the per-layer accuracy
    preview the calibration report carries."""
    from ...nn import Linear
    from ...quantization import dequantize, quantize_absmax

    out = {}
    for name, sub in model.named_sublayers(include_self=False):
        if not isinstance(sub, Linear):
            continue
        w = sub.weight._value.astype(jnp.float32)
        q, scale = quantize_absmax(w, bits=bits)
        err = jnp.linalg.norm(dequantize(q, scale) - w) \
            / jnp.maximum(jnp.linalg.norm(w), 1e-12)
        out[name] = float(err)
    return out
