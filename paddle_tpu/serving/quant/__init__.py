"""paddle_tpu.serving.quant — quantized serving as a first-class subsystem
(README "Quantized serving").

Int8 paged KV-cache pages and int8 weights for the serving stack:

- :class:`QuantizedGPTAdapter` — int8 page pools + parallel per-(page
  slot, head) float32 scale pools, quant fused into every pool write and
  dequant into the paged-attention kernels (``ops.paged_attention``'s int8
  section).  ``ServingEngine(kv_dtype="int8")`` builds one automatically;
  prefill, decode, speculative verify and chunked writes all run through
  the quantized programs (``prefill/<bucket>@int8``, ``decode@int8``,
  ``verify/k<k>@int8`` families in the perf table).
- :func:`quantize_model_weights` — in-place ``Int8Linear`` conversion of
  the decoder Linears on the shared symmetric grid
  (``quantization.quantize_absmax``); ``ServingEngine(weight_dtype=
  "int8")`` applies it, idempotently, so cluster replicas over one model
  convert once.
- :func:`calibrate` — the accuracy harness: runs a calibration batch
  through the full-precision engine, measures per-layer KV/weight
  round-trip error, picks scales (absmax or percentile), then reports
  top-1 agreement and the occupancy win of the int8 engine.

Why: decode is bandwidth-bound (BENCH_r04 roofline, PR-7 per-program
attribution) — halving cache bytes is both raw inter-token latency AND
~2x resident requests per chip at a fixed page-pool HBM budget.
"""

from .adapter import QuantizedGPTAdapter  # noqa: F401
from .calibrate import (  # noqa: F401
    calibrate, choose_scale, kv_quant_error, top1_agreement,
)
from .weights import quantize_model_weights, weight_quant_error  # noqa: F401

__all__ = [
    "QuantizedGPTAdapter", "quantize_model_weights", "weight_quant_error",
    "calibrate", "choose_scale", "kv_quant_error", "top1_agreement",
]
