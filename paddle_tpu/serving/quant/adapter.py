"""QuantizedGPTAdapter — int8 paged KV pools for the serving engine.

Same closure contract as :class:`~paddle_tpu.serving.adapter.GPTAdapter`
(the engine donates/rebinds the pool tuple opaquely), but the KV state is
four arrays instead of two:

- ``kp, vp``: int8 page pools ``[L, P, ps, h, d]`` — half the bf16 bytes,
  a quarter of f32;
- ``k_scales, v_scales``: float32 scale pools ``[L, P, ps, h]`` — one
  absmax scale per (page slot, kv head), addressed by the SAME page table.

Quantization happens inside the compiled programs: the ``served_q`` /
``served_chunk_q`` cache variants of :class:`GPTDecoderLayer` round K/V
onto the int8 grid on the way into every pool scatter
(``ops.paged_attention.paged_table_*_write_quant``) and the paged
attention consumers dequantize in-kernel
(``paged_attention_quantized`` / ``paged_chunk_attend_quant``), so no
full-precision copy of the cache ever materializes in HBM.  Rollback,
prefix pages, scratch-page masking and the chunk-write drop semantics are
all untouched — the scale pool rides the exact same table addressing.

Chunked prefill (``ServingEngine(prefill_chunk_tokens=N)``) rides the
inherited :meth:`GPTAdapter.prefill_chunk` unchanged: ``chunk_tag`` is
``"served_chunk_q"``, so each chunk quantizes on the way into the pools
and the engine's ``prefill_chunk/<c>@int8`` program family stays
byte-identical to the monolithic int8 prefill.  On TPU the decode side of
the same batch runs the int8 flash kernel (``decode@flash@int8``).

The hierarchical KV cache (``prefix_cache="radix"`` + ``kv_spill=True``)
needs no int8-specific code: the engine's spill snapshot/restore hooks
walk the WHOLE pool tuple, so an evicted page's int8 payload rows and
their float32 absmax scale rows spill to host DRAM — and resurrect into a
device slot — together as one unit.  A re-paged page is byte-identical to
the one evicted (payload and scales both round-trip losslessly), so
partial-prefix reuse stays exact under quantized pools too.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..adapter import GPTAdapter


class QuantizedGPTAdapter(GPTAdapter):
    """``ServingEngine(kv_dtype="int8")`` builds one of these (see module
    docstring).  Drives the ``served_q``/``served_chunk_q`` cache variants
    with a 4-array pool tuple."""

    tag = "served_q"
    chunk_tag = "served_chunk_q"
    n_pools = 4
    kv_dtype = "int8"

    def init_pools(self, num_pages):
        """Zeroed ``(kp, vp, k_scales, v_scales)``: int8 payload pools
        [L, P, ps, h, d] + f32 scale pools [L, P, ps, h]."""
        P = int(num_pages)
        shape = (self.num_layers, P, self.page_size, self.num_kv_heads,
                 self.head_dim)
        kp = jnp.zeros(shape, jnp.int8)
        ks = jnp.zeros(shape[:-1], jnp.float32)
        return kp, jnp.zeros_like(kp), ks, jnp.zeros_like(ks)

    def page_bytes(self):
        """One page across all layers, K and V: int8 payload (d bytes per
        position per head) + f32 scale (4 bytes per position per head) —
        (d + 4) / (2 d) of the bf16 cost, so ~1.9x pages per HBM byte at
        d=64 and ~1.94x at d=128."""
        per_pos_head = self.head_dim * 1 + 4   # int8 payload + f32 scale
        return (2 * self.num_layers * self.page_size * self.num_kv_heads
                * per_pos_head)

    def pool_owners(self):
        """int8 payload pools and f32 scale pools get separate ledger
        owners — the scale pools are real device residency that the
        payload-only view used to hide (ISSUE 12 satellite fix)."""
        return (("kv.pages", (0, 1)), ("kv.scales", (2, 3)))

    def pool_pspecs(self, axis="model"):
        """Payload pools [L, P, ps, h, d] AND scale pools [L, P, ps, h]
        both shard the KV-head dim — a shard dequantizes its heads with
        its own scale columns, no cross-shard traffic."""
        from jax.sharding import PartitionSpec as P

        payload = P(None, None, None, axis, None)
        scales = P(None, None, None, axis)
        return (payload, payload, scales, scales)

    def _layer_caches(self, pools, table, lens, tag):
        from ...tensor.tensor import Tensor

        kp, vp, ks, vs = pools
        return [(tag, Tensor(kp[i]), Tensor(vp[i]), Tensor(ks[i]),
                 Tensor(vs[i]), Tensor(table), Tensor(lens))
                for i in range(self.num_layers)]

    def _stack_pools(self, new_cache):
        return (jnp.stack([c[1]._value for c in new_cache]),
                jnp.stack([c[2]._value for c in new_cache]),
                jnp.stack([c[3]._value for c in new_cache]),
                jnp.stack([c[4]._value for c in new_cache]))
