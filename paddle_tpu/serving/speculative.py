"""Speculative decoding for the serving engine: n-gram drafting +
multi-token paged verification (Leviathan et al., "Fast Inference from
Transformers via Speculative Decoding"; drafts via prompt-lookup / n-gram
matching, so there is no second model).

Split of labor:

- :class:`NgramDrafter` (host side) — per-slot suffix-match over the
  prompt + generated ids.  When the current context's n-token suffix
  occurred earlier in the context, the tokens that followed it are
  proposed as the draft (up to ``k``); no match proposes nothing and the
  slot decodes one token that step, exactly like the non-speculative
  engine.  Pure Python dict lookups — O(ngram sizes) per proposal,
  incremental index updates per emitted token.

- :func:`make_verifier` (device side) — given the verification logits
  ``[B, k+1, V]`` from one compiled multi-token step
  (:meth:`~.adapter.GPTAdapter.verify`), decide per slot how much of the
  draft survives and what token follows the surviving prefix:

  * greedy rows (``temps <= 0``): draft token t is accepted iff it equals
    the argmax after the t-1 prefix — the accepted stream is EXACTLY the
    token-by-token greedy stream, so greedy outputs stay byte-identical
    to the non-speculative engine;
  * temperature rows: standard rejection sampling against the
    temperature/top-k/top-p-filtered distribution p̃.  The n-gram draft
    is a point mass q(d)=1, so draft d is accepted with probability
    p̃(d) and a rejection resamples from the residual
    ``norm(p̃ with d zeroed)`` — the emitted marginal is p̃ exactly, the
    same distribution the non-speculative sampler draws from.

The engine consumes the longest accepted prefix per slot plus the bonus /
resample token, so every verification step yields between 1 and k+1
tokens.  Rejected tail tokens need no explicit undo: their K/V lands past
the slot's valid length, where per-slot ``seq_lens`` masking keeps it
invisible and the next step's chunk write overwrites it (rollback = not
advancing ``lens``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


class NgramDrafter:
    """Prompt-lookup draft model: per-slot n-gram suffix index over the
    full context (prompt + generated ids).

    ``propose(sid)`` scans n-gram sizes from ``max_ngram`` down to
    ``min_ngram``: the first size whose current suffix occurred earlier in
    the context yields the tokens that followed that earlier occurrence
    (most recent occurrence wins — recent structure predicts better on
    structured output).  Returns up to ``k`` tokens; ``[]`` when nothing
    matches (the k=0 fallback — the engine then decodes a single token for
    that slot, paying only the cost of an unused pad lane).
    """

    def __init__(self, k=4, max_ngram=3, min_ngram=1):
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        if min_ngram < 1 or max_ngram < min_ngram:
            raise ValueError(f"need 1 <= min_ngram <= max_ngram, got "
                             f"{min_ngram}..{max_ngram}")
        self.k = int(k)
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)
        self._ctx = {}     # sid -> list[int]
        self._index = {}   # sid -> {n -> {ngram tuple -> start pos}}

    # ---------------------------------------------------------------- slots
    def register(self, sid, context_ids):
        """(Re)build slot ``sid``'s index from a full context (admission:
        the prompt; re-admission after an engine restart: prompt +
        tokens-so-far)."""
        self._ctx[sid] = []
        self._index[sid] = {n: {} for n in
                            range(self.min_ngram, self.max_ngram + 1)}
        self.extend(sid, context_ids)

    def extend(self, sid, tokens):
        """Append newly emitted tokens to slot ``sid``'s context and index.

        An n-gram ending at position i is registered once position i+1
        exists, so a lookup of the context's own suffix can only ever find
        a genuinely EARLIER occurrence (overlap with the suffix is fine —
        that is what makes single-token repetition draftable)."""
        ctx = self._ctx[sid]
        idx = self._index[sid]
        for t in tokens:
            i = len(ctx)          # position the new token will occupy
            e = i - 1             # old last position: now safe to index
            for n in range(self.min_ngram, self.max_ngram + 1):
                if e - n + 1 >= 0:
                    idx[n][tuple(ctx[e - n + 1:e + 1])] = e - n + 1
            ctx.append(int(t))

    def release(self, sid):
        self._ctx.pop(sid, None)
        self._index.pop(sid, None)

    def reset(self):
        self._ctx.clear()
        self._index.clear()

    # ------------------------------------------------------------- proposal
    def propose(self, sid, max_tokens=None):
        """Draft up to ``min(k, max_tokens)`` continuation tokens for slot
        ``sid`` (``[]`` when no suffix matches or the cap is <= 0)."""
        cap = self.k if max_tokens is None else min(self.k, int(max_tokens))
        if cap <= 0:
            return []
        ctx = self._ctx.get(sid)
        if not ctx:
            return []
        idx = self._index[sid]
        L = len(ctx)
        for n in range(self.max_ngram, self.min_ngram - 1, -1):
            if L < n + 1:  # need the suffix plus at least one earlier token
                continue
            j = idx[n].get(tuple(ctx[L - n:]))
            if j is not None:
                return ctx[j + n:j + n + cap]
        return []


def make_verifier(top_k=0, top_p=1.0):
    """Build the traced acceptance/resample function for the engine's
    compiled verify step (one per (top_k, top_p) — static, part of the
    program key, exactly like :func:`.._decode.make_batched_sampler`).

    ``verify(logits, drafts, dlen, temps, key)``:

    - ``logits [B, K+1, V]`` f32 — position t is the next-token
      distribution after the last sampled token + drafts[:t];
    - ``drafts [B, K]`` int — proposed tokens (junk past ``dlen[b]``);
    - ``dlen [B]`` int32 — real draft length per slot (0 = no draft);
    - ``temps [B]`` f32 — per-slot temperature (<= 0 is greedy);

    returns ``(targets [B, K+1], accept [B, K])``: ``accept[b, t]`` says
    draft t+1 survives (always False past ``dlen``), and ``targets[b, a]``
    is the token to emit after accepting ``a`` drafts — the argmax /
    residual resample on rejection, the full sample when every real draft
    survived."""
    from ..text.models._decode import apply_top_k_top_p

    def verify(logits, drafts, dlen, temps, key):
        B, K1, V = logits.shape
        K = K1 - 1
        greedy = jnp.argmax(logits, axis=-1)                     # [B, K1]
        l = logits / jnp.maximum(temps, jnp.float32(1e-6))[:, None, None]
        l = apply_top_k_top_p(l.reshape(B * K1, V), top_k, top_p)
        l = l.reshape(B, K1, V)
        p = jax.nn.softmax(l, axis=-1)
        real = jnp.arange(K, dtype=jnp.int32)[None, :] \
            < dlen.astype(jnp.int32)[:, None]                    # [B, K]
        d32 = drafts.astype(jnp.int32)
        pd = jnp.take_along_axis(p[:, :K], d32[..., None],
                                 axis=-1)[..., 0]                # [B, K]
        ku, ks = jax.random.split(key)
        u = jax.random.uniform(ku, (B, K), dtype=jnp.float32)
        acc_temp = u < pd                       # point-mass q: P(acc)=p̃(d)
        acc_greedy = d32 == greedy[:, :K].astype(jnp.int32)
        is_greedy = (temps <= jnp.float32(0.0))[:, None]
        accept = jnp.where(is_greedy, acc_greedy, acc_temp) & real
        # residual resample: where a REAL draft was verified, zero it out of
        # the distribution (rejection-sampling residual); position K — and
        # short-draft bonus positions — sample the full filtered p̃
        is_draft = jnp.arange(V, dtype=jnp.int32)[None, None, :] \
            == d32[..., None]                                    # [B, K, V]
        lm = jnp.where(is_draft & real[..., None], -jnp.inf, l[:, :K])
        lr = jnp.concatenate([lm, l[:, K:]], axis=1)             # [B, K1, V]
        samp = jax.random.categorical(
            ks, lr.reshape(B * K1, V), axis=-1).reshape(B, K1)
        targets = jnp.where(is_greedy, greedy, samp)
        return targets, accept

    return verify


def make_masked_verifier(top_k=0, top_p=1.0):
    """Constrained-decoding twin of :func:`make_verifier` (multi-tenant
    serving): per-position token-FSM masks ``allowed [B, K+1, V]`` bool
    are applied to the verification logits BEFORE acceptance/resampling,
    so a draft token that exits the grammar is rejected by construction —
    the masked distribution's argmax (greedy) / support (temperature)
    cannot contain it — and the bonus/resample token at the first
    rejection is drawn from the masked distribution, i.e. is always
    grammar-legal.  Unconstrained rows carry all-True masks and verify
    bit-identically to :func:`make_verifier`."""
    inner = make_verifier(top_k, top_p)

    def verify(logits, allowed, drafts, dlen, temps, key):
        return inner(jnp.where(allowed, logits, jnp.float32(-1e30)),
                     drafts, dlen, temps, key)

    return verify
