"""paddle_tpu.serving — continuous-batching LLM serving over the paged KV
cache (ROADMAP north star: "serves heavy traffic from millions of users").

- :mod:`.engine` — :class:`ServingEngine`: iteration-level (Orca-style)
  scheduler over a fixed-shape decode batch; one compiled step per
  iteration, donated page pools, per-slot positions.
- :mod:`.block_manager` — :class:`BlockManager`: vLLM-style paged KV block
  allocation, capacity-based admission control, optional prefix sharing.
- :mod:`.prefix_index` — :class:`RadixPrefixIndex`: page-granular radix
  tree over prompt ids (``ServingEngine(prefix_cache="radix")``) — partial
  prefix matches reuse the longest shared page run and prefill starts
  past the cached tokens (README "Hierarchical KV cache").
- :mod:`.kv_spill` — :class:`KVSpillTier`: host-DRAM middle tier
  (``kv_spill=True``): idle pages evicted by the radix index spill to
  host buffers under ``PADDLE_KV_SPILL_BUDGET_BYTES`` and resurrect into
  free device slots on the next prefix hit, accounted by the MemoryLedger
  as ``kv.spilled``.
- :mod:`.adapter` — model adapters (:class:`GPTAdapter`) reducing a causal
  LM to the prefill/step closures the engine compiles.
- :mod:`.api` — :class:`ContinuousBatchingPredictor`, the
  ``paddle.inference``-shaped deployment facade.
- :mod:`.speculative` — :class:`NgramDrafter` (prompt-lookup drafts) +
  :func:`make_verifier` (multi-token acceptance / rejection sampling) for
  ``ServingEngine(speculative_k=k)`` draft-and-verify decoding.
- :mod:`.cluster` — multi-replica serving: :class:`ReplicaPool` (N engines
  over one model), :class:`PrefixAffinityRouter` (rendezvous prefix
  routing with health-aware least-loaded fallback) and
  :class:`ServingCluster` (the routed facade with cross-replica in-flight
  requeue; README "Cluster serving").
- :mod:`.quant` — quantized serving: int8 paged KV pools with parallel
  scale pools (:class:`QuantizedGPTAdapter`, ``ServingEngine(kv_dtype=
  "int8")``), the :func:`quantize_model_weights` Int8Linear weight path,
  and the :func:`calibrate` accuracy harness (README "Quantized
  serving").
- :mod:`.qos` — QoS-tiered serving: :class:`TierPolicy` /
  :class:`QoSConfig` (priority tiers with weighted admission, per-tier
  SLOs, brownout shed thresholds), :class:`TieredQueue` (the engine's
  per-tier weighted-round-robin queue), :func:`brownout` (the shed
  ladder) and :class:`AutoScaler` (elastic replica count for a
  :class:`ReplicaPool` — README "QoS tiers & autoscaling").
- :mod:`.multitenant` — multi-tenant serving: paged multi-LoRA
  (:class:`LoRAStore` rank-bucketed adapter pools with per-row gather
  inside the compiled programs), grammar-constrained decoding
  (:func:`compile_json_schema` / :func:`compile_regex` token FSMs masking
  the batched sampler), and embed/score request modes — all batched by
  ONE :class:`MultiTenantEngine` (README "Multi-tenant serving").

Metrics (PR-1 registry, README "Serving"): ``serving.*`` histograms /
gauges / counters — TTFT, inter-token latency, queue depth, slot
occupancy, page-pool utilization, admission/preemption/trace counters,
speculative proposal/acceptance, prefix-cache hit/miss/eviction/saved
tokens, KV-spill pages/resurrections/drops/bytes.
"""

from .adapter import GPTAdapter  # noqa: F401
from .api import ContinuousBatchingPredictor  # noqa: F401
from .block_manager import BlockManager, PageAllocation  # noqa: F401
from .prefix_index import RadixPrefixIndex, prefix_digest  # noqa: F401
from .kv_spill import KVSpillTier  # noqa: F401
from .engine import (  # noqa: F401
    EngineStoppedError, Request, RequestHandle, RequestRejectedError,
    SamplingParams, ServingEngine,
)
from ..observability.slo import SLOPolicy  # noqa: F401  (engine/cluster slo=)
from .speculative import NgramDrafter, make_verifier  # noqa: F401
from .cluster import (  # noqa: F401
    ClusterHandle, PrefixAffinityRouter, ReplicaPool, RouteDecision,
    ServingCluster,
)
from .quant import (  # noqa: F401
    QuantizedGPTAdapter, calibrate, quantize_model_weights,
)
from .multitenant import (  # noqa: F401
    CompiledGrammar, LoRAAdapter, LoRAStore, MultiTenantEngine,
    compile_json_schema, compile_regex,
)
from .qos import (  # noqa: F401
    AutoScaler, QoSConfig, TieredQueue, TierPolicy, brownout,
)

__all__ = [
    "ServingEngine", "Request", "RequestHandle", "RequestRejectedError",
    "EngineStoppedError", "SamplingParams", "BlockManager", "PageAllocation",
    "RadixPrefixIndex", "KVSpillTier", "prefix_digest",
    "GPTAdapter", "ContinuousBatchingPredictor", "NgramDrafter",
    "make_verifier", "ServingCluster", "ClusterHandle", "ReplicaPool",
    "PrefixAffinityRouter", "RouteDecision", "SLOPolicy",
    "QuantizedGPTAdapter", "quantize_model_weights", "calibrate",
    "MultiTenantEngine", "LoRAStore", "LoRAAdapter", "CompiledGrammar",
    "compile_regex", "compile_json_schema",
    "QoSConfig", "TierPolicy", "TieredQueue", "AutoScaler", "brownout",
]
