"""Continuous-batching LLM serving engine (Orca iteration-level scheduling
x vLLM paged KV blocks, TPU-native).

One fixed-shape batch of ``num_slots`` decode slots runs against per-layer
GLOBAL page pools; a background scheduler thread executes iterations:

1. retire slots that hit EOS / max_new_tokens / deadline / cancellation
   (pages return to the :class:`~.block_manager.BlockManager` immediately);
2. admit waiting prompts into free slots while the page pool can cover
   their worst case (prompt + max_new) — each admit runs one compiled
   prefill that writes the prompt's K/V into its pages and samples the
   first token;
3. run ONE compiled decode step for the whole batch — every slot at its
   OWN position (per-slot lens / page table rows), inactive slots pointed
   at a scratch page — then sync the sampled tokens to the host.

No caller ever waits for the slowest sequence in the batch: a short
request retires and its slot backfills from the queue while long ones keep
decoding.  The compiled programs follow the ``_decode.py`` discipline —
pools are DONATED into each call and the jitted prefill/step pair is
cached in :func:`~paddle_tpu.text.models._decode.program_store`, so there
is exactly ONE trace per (model, batch-shape, sampler) tuple; trace
counters are exported so tests can assert it.

Observability (PR-1 metrics registry): ``serving.ttft_seconds``,
``serving.inter_token_seconds``, ``serving.step_seconds``,
``serving.prefill_seconds`` histograms; ``serving.queue_depth``,
``serving.active_slots``, ``serving.slot_occupancy``,
``serving.page_utilization``, ``serving.pages_in_use`` gauges;
``serving.requests{status=...}``, ``serving.tokens_generated``,
``serving.admissions_blocked``, ``serving.preemptions``,
``serving.step_traces``, ``serving.prefill_traces`` counters.

Speculative decoding (``speculative_k > 0``, see ``serving/speculative.py``
and README "Speculative decoding"): each iteration drafts up to k tokens
per slot by n-gram suffix match over the slot's own context (prompt-lookup
— no second model) and verifies them in ONE compiled multi-token step (the
``("verify", k_pad, …)`` program family; K/V for all k+1 positions lands
in the page pools through ``ops.paged_attention.paged_table_chunk_write``
/ ``paged_chunk_attend``).  The scheduler consumes the longest accepted
prefix plus the bonus token — 1..k+1 tokens per dispatch — with EOS /
deadline / cancel / budget checks per emitted token.  Greedy rows accept
by exact argmax match, so greedy output is byte-identical to the
non-speculative engine; temperature rows use standard rejection sampling.
Extra metrics: ``serving.spec_proposed``, ``serving.spec_accepted``,
``serving.acceptance_rate`` (also on /statusz), ``serving.verify_traces``.

Resilience (PR-4, README "Resilience & fault tolerance"): a health state
machine (healthy → degraded → draining) surfaced on /healthz and /statusz;
deadline-aware load shedding at submit with distinct rejection reasons
(``RequestRejectedError.reason``); transient scheduler failures trigger an
engine auto-restart that rebuilds the page pools and transparently
re-queues in-flight requests (prompt + tokens-so-far, remaining budget)
instead of failing their handles; ``stop()`` without ``drain=True`` fails
in-flight handles fast with :class:`EngineStoppedError`; ``stop(drain=
True)`` finishes all in-flight work first.  Extra metrics:
``serving.load_shed{reason=}``, ``serving.engine_restarts``,
``serving.requests_requeued``, ``serving.health_state``.

Quantized serving (``kv_dtype="int8"`` / ``weight_dtype="int8"``, see
``serving/quant`` and README "Quantized serving"): the paged KV pools
store int8 payloads with parallel per-(page slot, head) float32 scale
pools — quant fused into every pool write, dequant into the paged
attention kernels, so decode streams half the bf16 cache bytes and the
same HBM budget holds ~2x the resident slots; the model's Linears can
ride along as :class:`~paddle_tpu.quantization.Int8Linear`.  The engine
is layout-agnostic: the adapter defines the pool tuple, every compiled
program donates all of it, and the quantized program families are
attributed separately (``decode@int8`` etc.) in the perf table.  Extra
metrics: ``serving.kv_bytes_per_token``, ``serving.pool_bytes{dtype=}``.
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import itertools
import logging
import os
import queue as _queue
import threading
import time
import weakref

import jax
import jax.numpy as jnp
import numpy as np

from ..observability import faults as _faults
from ..observability import memory as _obs_memory
from ..observability import numerics as _numerics
from ..observability import perf as _perf
from ..observability import programs as _programs
from ..observability import tracing as _tracing
from ..resilience.retry import (EngineStoppedError, NumericFault,  # noqa: F401 — re-exported
                                classify_failure)
from .adapter import GPTAdapter
from .block_manager import BlockManager

_logger = logging.getLogger("paddle_tpu.serving")

_HEALTH_CODE = {"healthy": 0, "degraded": 1, "draining": 2, "stopped": 3,
                "error": 4}

# prefill bucketing: prompts up to this many pages compile one prefill
# program per page count; above it, page counts round up to the next power
# of two so long-prompt traffic stops minting a program per page increment
_PREFILL_POW2_PAGES = 4

#: the mesh axis tensor-parallel serving shards over (pool KV-head dim,
#: Megatron weight splits) — the Fleet mp axis name, serving-side
_MP_AXIS = "model"


def _normalize_mesh(mesh):
    """``ServingEngine(mesh=...)`` input -> ``(jax Mesh | None, mp)``.

    Accepts a :class:`jax.sharding.Mesh` with a ``"model"`` axis, a
    :class:`paddle_tpu.distributed.ProcessMesh` carrying a ``"model"``
    dim, or a flat sequence of devices (meshed over one ``"model"``
    axis).  A 1-sized model axis degrades to unsharded serving on that
    single device (mp=1, plain ``device=`` placement) so a dp pool over
    mp-sized submeshes handles ``mp=1`` carves uniformly.  Returns
    ``(mesh, mp, solo_device)``."""
    if mesh is None:
        return None, 1, None
    if hasattr(mesh, "jax_mesh"):        # distributed ProcessMesh
        if _MP_AXIS not in mesh.dim_names:
            raise ValueError(
                f"ProcessMesh {mesh!r} has no '{_MP_AXIS}' dim — serving "
                f"tensor parallelism shards over a '{_MP_AXIS}' axis")
        mesh = mesh.jax_mesh
    if isinstance(mesh, jax.sharding.Mesh):
        if _MP_AXIS not in mesh.axis_names:
            raise ValueError(
                f"mesh axes {mesh.axis_names} carry no '{_MP_AXIS}' axis "
                f"— serving tensor parallelism shards over '{_MP_AXIS}'")
        mp = int(mesh.shape[_MP_AXIS])
        devs = list(mesh.devices.flat)
    else:                                # flat device sequence
        devs = list(mesh)
        if not devs:
            raise ValueError("mesh= device list must be non-empty")
        mp = len(devs)
        mesh = jax.sharding.Mesh(np.array(devs), (_MP_AXIS,))
    if mp > 1:
        return mesh, mp, None
    return None, 1, devs[0]


class RequestRejectedError(RuntimeError):
    """Raised by submit() for requests the engine can never serve (too long
    for the model/page pool) or that are load-shed.  ``reason`` is the
    machine-readable rejection class: ``unservable`` (exceeds model/pool
    caps), ``queue_full``, ``deadline_unmeetable`` (the request's deadline
    cannot be met given current queue/stall state), or ``draining`` (the
    engine is shutting down gracefully)."""

    def __init__(self, message, reason="rejected"):
        super().__init__(message)
        self.reason = reason


@dataclasses.dataclass
class SamplingParams:
    """Per-request sampling.  ``temperature <= 0`` is greedy; temperature
    rows and greedy rows share ONE compiled step (the batched sampler
    branches per slot).  top_k/top_p are engine-level statics — part of the
    compiled program key, not per-request."""

    temperature: float = 0.0
    seed: int | None = None  # reserved; draws come from the engine stream


@dataclasses.dataclass
class Request:
    prompt: list
    max_new_tokens: int
    sampling: SamplingParams
    eos_token_id: int | None
    deadline: float | None      # absolute time.time() seconds
    handle: "RequestHandle"
    # multi-tenant serving (serving/multitenant; every field defaults to
    # the single-tenant base-model request, so the plain engine's paths
    # are untouched): the tenant's registered LoRA adapter name, the
    # compiled token-FSM constraining this row's output, the request kind
    # (generate | embed | score), the embed pooling, and the store lease
    # held while the request is admitted
    adapter: str | None = None
    grammar: object = None
    mode: str = "generate"
    pooling: str = "mean"
    lease: object = None
    # QoS tier name (serving/qos.py) — None on engines without a tier
    # table; resolved to a configured tier at submit on QoS engines, and
    # carried verbatim across requeues (restart recovery / preemption)
    tier: str | None = None


class RequestHandle:
    """Caller-side view of a submitted request.

    ``result(timeout)`` blocks for the generated ids; ``stream()`` yields
    tokens as the engine produces them (closing the iterator cancels the
    request and frees its pages); ``cancel()`` retires it at the next
    iteration."""

    def __init__(self, request_id, prompt_len):
        self.request_id = request_id
        self.prompt_len = prompt_len
        # multi-tenant surface: request kind, the non-generate result
        # payload (embed vector / score list), the tenant's adapter name,
        # and the constrained row's live FSM state (kept on the HANDLE so
        # an engine restart's re-admission resumes the grammar where the
        # emitted tokens left it)
        self.mode = "generate"
        self.value = None
        self.adapter = None
        self._fsm_state = None
        # QoS surface: the request's resolved tier name (None on non-QoS
        # engines) and how many times a higher tier evicted it from a
        # decode slot (each eviction requeued it as prompt+tokens-so-far,
        # so greedy output is unaffected — only latency is)
        self.tier = None
        self.preemptions = 0
        # distributed-tracing identity: every span this request touches
        # (submit -> prefill -> each decode iteration) carries/links it
        self.trace_id = _tracing.new_trace_id()
        self.token_ids = []            # generated ids (appended by the engine)
        # wall-clock stamp of every emission — the request's token-level
        # timeline (observability.slo evaluates TTFT/ITL/e2e targets on it)
        self.token_times = []
        self.status = "queued"
        self.submitted_at = time.time()
        self.admitted_at = None        # queue -> slot (first dispatch start)
        self.compile_s = 0.0           # compile stalls this request waited out
        self.first_token_at = None
        self.finished_at = None
        self.first_token_iteration = None
        self.finished_iteration = None
        self._events = _queue.Queue()
        self._done = threading.Event()
        self._cancel = threading.Event()
        self._error = None

    # ----------------------------------------------------------------- api
    def cancel(self):
        self._cancel.set()

    @property
    def cancelled(self):
        return self._cancel.is_set()

    @property
    def done(self):
        return self._done.is_set()

    def result(self, timeout=None):
        """Generated token ids (blocks until the request finishes).
        ``mode="embed"`` requests return the pooled hidden-state vector,
        ``mode="score"`` the per-token logprob list."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"request {self.request_id} not finished after {timeout}s")
        if self._error is not None:
            # EngineStoppedError / NumericFault are per-request verdicts
            # (stopped mid-flight; this row's logits went non-finite) —
            # surface them as-is, not as an engine-wide failure
            if isinstance(self._error, (EngineStoppedError, NumericFault)):
                raise self._error
            raise RuntimeError("serving engine failed") from self._error
        if self.mode != "generate":
            return self.value
        return list(self.token_ids)

    def stream(self):
        """Token-at-a-time iterator.  Abandoning the iterator (``close()``
        / ``break`` + GC) cancels the request so its pages free."""
        try:
            while True:
                kind, val = self._events.get()
                if kind == "token":
                    yield val
                else:
                    break
            if self._error is not None:
                if isinstance(self._error,
                              (EngineStoppedError, NumericFault)):
                    raise self._error
                raise RuntimeError("serving engine failed") from self._error
        finally:
            if not self._done.is_set():
                self.cancel()

    __iter__ = stream

    @property
    def ttft(self):
        if self.first_token_at is None:
            return None
        return self.first_token_at - self.submitted_at

    # ------------------------------------------- TTFT decomposition (PR 16)
    @property
    def queue_s(self):
        """Submit -> admission wait (None until admitted)."""
        if self.admitted_at is None:
            return None
        return self.admitted_at - self.submitted_at

    @property
    def prefill_s(self):
        """TTFT minus queueing minus compile stalls — the dispatch work
        itself.  Defined as the remainder so the decomposition sums
        exactly: ``queue_s + compile_s + prefill_s == ttft``."""
        t = self.ttft
        if t is None or self.queue_s is None:
            return None
        return max(0.0, t - self.queue_s - self.compile_s)

    def ttft_breakdown(self):
        """Cold-start forensics: where this request's first token went.
        ``None`` until the first token lands."""
        t = self.ttft
        if t is None:
            return None
        return {"ttft_s": t, "queue_s": self.queue_s,
                "compile_s": self.compile_s, "prefill_s": self.prefill_s,
                "cold": self.compile_s > 0.0, "trace_id": self.trace_id}


class _Slot:
    __slots__ = ("handle", "req", "alloc", "table_row", "length", "last",
                 "produced", "temp", "eos", "max_new", "deadline",
                 "last_token_t", "idx", "prefilled")

    def __init__(self, req, alloc, table_row):
        self.idx = None                     # batch lane (set at admission)
        self.handle = req.handle
        self.req = req
        self.alloc = alloc
        self.table_row = table_row          # np.int32 [<= NP] real pages
        self.length = len(req.prompt)       # tokens whose K/V are in pages
        self.last = 0                       # last sampled token id
        self.produced = 0
        self.temp = float(req.sampling.temperature)
        self.eos = req.eos_token_id
        self.max_new = req.max_new_tokens
        self.deadline = req.deadline
        self.last_token_t = None
        # chunked prefill: prompt tokens whose K/V have landed so far.
        # None = monolithic prefill / ingestion complete (lane decodes);
        # an int means the slot is mid-prefill — its persistent host row
        # stays inert (scratch table, length 0) so decode dispatches skip
        # it, and _advance_prefills drives the next chunk.
        self.prefilled = None


class ServingEngine:
    """See module docstring.  Typical use::

        engine = ServingEngine(model, num_slots=4, page_size=16)
        with engine:
            h = engine.submit([1, 2, 3], max_new_tokens=64)
            for tok in h.stream():
                ...
    """

    def __init__(self, model, num_slots=4, page_size=16, max_model_len=None,
                 num_pages=None, top_k=0, top_p=1.0, prefix_sharing=False,
                 max_queue=None, seed=0, adapter=None, watchdog_s=None,
                 telemetry_port=None, max_engine_restarts=3,
                 degraded_stall_s=2.0, restart_cooldown_s=10.0,
                 speculative_k=0, draft_max_ngram=3, draft_min_ngram=1,
                 replica="0", device=None, health_gating=True, slo=None,
                 kv_dtype=None, weight_dtype=None, numeric_guard=None,
                 prefill_chunk_tokens=None, mesh=None, qos=None,
                 prefix_cache=None, kv_spill=False,
                 kv_spill_budget_bytes=None):
        self._model = model
        # chunked prefill (README "Flash decode & chunked prefill"):
        # prompts longer than N tokens are admitted IMMEDIATELY and
        # ingested N tokens at a time through the chunk cache variant,
        # interleaved with the batch decode dispatch each scheduler
        # iteration — one long prompt stops stalling the whole decode
        # batch for its entire prefill, while greedy outputs stay
        # byte-identical to the monolithic path.  None/0 disables.
        if prefill_chunk_tokens:
            prefill_chunk_tokens = int(prefill_chunk_tokens)
            if prefill_chunk_tokens < 1:
                raise ValueError(f"prefill_chunk_tokens must be >= 1, "
                                 f"got {prefill_chunk_tokens}")
        else:
            prefill_chunk_tokens = None
        self._chunk_tokens = prefill_chunk_tokens
        self._prefill_rr = 0    # round-robin cursor over prefilling slots
        # decode perf-family attribution: on TPU the paged kernels run the
        # length-bounded flash sweep — a different roofline than the
        # full-width legacy sweep, so the family carries an @flash tag
        # (perf.candidate_hint keys remediation advice on it)
        from ..ops.paged_attention import flash_decode_active

        self._flash_tag = "@flash" if flash_decode_active() else ""
        # quantized serving (serving/quant, README "Quantized serving"):
        # kv_dtype="int8" stores the paged KV pools as int8 with parallel
        # per-(page slot, head) scale pools — quant fused into the pool
        # writes, dequant into the paged-attention kernels, ~2x resident
        # slots per HBM byte; weight_dtype="int8" converts the model's
        # Linears to Int8Linear in place (idempotent — cluster replicas
        # over one shared model convert once).  The default (None /
        # "native" / "bf16") is byte-identical to the unquantized engine.
        kv_dtype = str(kv_dtype).lower() if kv_dtype is not None else "native"
        if kv_dtype in ("native", "bf16", "bfloat16", "float32", "fp32"):
            kv_dtype = "native"
        elif kv_dtype != "int8":
            raise ValueError(f"kv_dtype must be None/'native' or 'int8', "
                             f"got {kv_dtype!r}")
        self.kv_dtype = kv_dtype
        self.weight_dtype = str(weight_dtype).lower() \
            if weight_dtype is not None else "native"
        if self.weight_dtype not in ("native", "int8"):
            raise ValueError(f"weight_dtype must be None/'native' or "
                             f"'int8', got {weight_dtype!r}")
        if self.weight_dtype == "int8":
            from .quant.weights import quantize_model_weights

            quantize_model_weights(model)
        # quantized program families get their own perf-attribution names
        # (decode@int8, prefill/<bucket>@int8, verify/k<k>@int8) so the
        # roofline table can judge the dequant-fused programs separately
        self._fam_suffix = "@int8" if kv_dtype == "int8" else ""
        # replica identity (cluster serving): stamps every serving.* metric
        # series with a replica= label so N engines in one process don't
        # overwrite each other, keys the /statusz|/healthz provider
        # registration, and names the per-replica fault sites
        # serving.{step_crash,scheduler_wedge}@<replica>
        self.replica = str(replica)
        self._site_wedge = f"serving.scheduler_wedge@{self.replica}"
        self._site_step_crash = f"serving.step_crash@{self.replica}"
        # replica-loss chaos site (QoS/autoscaling bench): when armed and
        # it fires, the scheduler raises a FATAL error — the replica dies
        # like a reclaimed spot host, the cluster reroutes its in-flight
        # work and the autoscaler reaps + replaces it
        self._site_replica_preempt = f"cluster.replica_preempt@{self.replica}"
        self._provider_key = f"serving/{self.replica}"
        # False for cluster replicas: the replica still shows on /healthz
        # but the ServingCluster's any-replica-routable component gates
        # the 503 fold instead (one dead replica must not fail the fleet)
        self._health_gating = bool(health_gating)
        self._device = device
        # tensor-parallel serving (README "Tensor-parallel serving"):
        # mesh= shards this engine's programs SPMD over a "model" mesh
        # axis — paged KV pools on the KV-head dim, decoder weights
        # Megatron-style (qkv/ffn1 column-, out_proj/ffn2 row-parallel),
        # page table / seq_lens / sampler state host-side and replicated
        # so the scheduler, prefix sharing and admission logic never see
        # the second device axis.  Accepts a jax.sharding.Mesh (an axis
        # named "model"), a distributed ProcessMesh with a "model" dim,
        # or a flat device sequence (meshed over one "model" axis).
        self._mesh, self._mp, solo = _normalize_mesh(mesh)
        if mesh is not None and device is not None:
            raise ValueError(
                "device= and mesh= are mutually exclusive: a dp replica "
                "commits to ONE device, an mp engine to a mesh (compose "
                "them via ReplicaPool(devices=..., mp=...))")
        if solo is not None:    # 1-sized mesh = plain dp placement
            self._device = device = solo
        # mp program families get their own perf-attribution suffix
        # (decode@mp2, prefill/<b>@mp2, ...) and program-store keys, so an
        # mp=1 engine's programs stay byte-identical to pre-mesh builds
        self._mp_suffix = f"@mp{self._mp}" if self._mp > 1 else ""
        if adapter is not None:
            self._adapter = adapter
        elif kv_dtype == "int8":
            from .quant.adapter import QuantizedGPTAdapter

            self._adapter = QuantizedGPTAdapter(model, page_size)
        else:
            self._adapter = GPTAdapter(model, page_size)
        if self._mp > 1:
            self._adapter.validate_mp(self._mp)
            # the adapter carries the mesh so the TPU flash kernels trace
            # under mp_shard_scope (each shard sweeps its local KV heads);
            # off-TPU the jnp reference paths are GSPMD-partitioned from
            # the operand shardings and the scope is a no-op
            self._adapter.mp_mesh = self._mesh
        self.page_size = int(page_size)
        self.num_slots = int(num_slots)
        cap = self._adapter.max_model_len
        self.max_model_len = min(int(max_model_len), cap) if max_model_len \
            else cap
        self.table_width = -(-self.max_model_len // self.page_size)  # NP
        if num_pages is None:
            num_pages = self.num_slots * self.table_width  # full residency
        self._num_pages = int(num_pages)
        # hierarchical KV cache (README "Hierarchical KV cache"):
        # prefix_cache="radix" swaps the BlockManager's exact-key prefix
        # matching for the page-granular radix index (serving/
        # prefix_index.py) — allocate reuses the LONGEST shared page run,
        # and prefill starts past the cached tokens instead of
        # recomputing the run; "lru" is an explicit alias for the legacy
        # exact-key sharing (memory reuse, full recompute).  kv_spill=True
        # adds the host-DRAM tier (serving/kv_spill.py): idle pages
        # evicted off-device re-page on the next matching prefix instead
        # of recomputing, bounded by PADDLE_KV_SPILL_BUDGET_BYTES (or the
        # kv_spill_budget_bytes arg) and accounted to the ledger's
        # kv.spilled host owner.
        if prefix_cache not in (None, "lru", "radix"):
            raise ValueError(f"prefix_cache must be None, 'lru' or "
                             f"'radix', got {prefix_cache!r}")
        self._prefix_cache = prefix_cache
        self._radix = prefix_cache == "radix"
        self._prefix_sharing = bool(prefix_sharing) \
            or prefix_cache is not None
        self._spill = None
        if kv_spill:
            if not self._radix:
                raise ValueError(
                    "kv_spill=True needs prefix_cache='radix': spilled "
                    "pages are content-addressed through the radix index")
            from .kv_spill import KVSpillTier

            self._spill = KVSpillTier(replica=self.replica,
                                      budget_bytes=kv_spill_budget_bytes)
        # HBM accounting (quantized serving satellite): every page costs
        # adapter.page_bytes() across all layers, K+V, scale pools
        # included — BlockManager carries it so capacity math, stats()
        # and /statusz all read one number.  Under mp the pools shard the
        # KV-head dim, so a page costs 1/mp of the global bytes PER CHIP —
        # capacity math (max_resident_sequences, admission pre-flight
        # against PADDLE_HBM_BUDGET_BYTES) is denominated in per-shard
        # bytes: a 2-way-sharded pool holds 2x slots per chip at the same
        # HBM budget.  Exact division: page_bytes is linear in
        # num_kv_heads, which validate_mp pinned divisible by mp.
        self._bytes_per_page = int(self._adapter.page_bytes()) // self._mp
        self._pool_dtype = "int8" if self.kv_dtype == "int8" \
            else str(self._adapter.dtype)
        self._bm = self._new_block_manager()
        # pool row num_pages is the SCRATCH page: inactive decode slots and
        # padded table tails point at it (every table entry must be a valid
        # pool row; junk written there is never attended)
        self._scratch = int(num_pages)
        self._pools = tuple(self._adapter.init_pools(num_pages + 1))
        self._params, self._bufs = self._adapter.params_and_buffers()
        if device is not None:
            # dp-replica placement: commit this replica's params/buffers and
            # page pools to its device — uncommitted per-step host arrays
            # (table/lens/ids) follow the committed operands, so every
            # dispatch of this engine runs there
            self._params = jax.device_put(self._params, device)
            self._bufs = jax.device_put(self._bufs, device)
            self._pools = jax.device_put(self._pools, device)
        elif self._mesh is not None:
            # mp placement: commit weights with their Megatron annotations
            # and pools with the KV-head sharding — GSPMD propagates the
            # layouts through the unchanged adapter closures, so every
            # program family compiles ONCE as a single SPMD program (not
            # per shard), and the uncommitted host arrays (table/lens/
            # ids/temps) replicate onto the mesh automatically
            self._params = self._shard_tree(self._params)
            self._bufs = self._shard_tree(self._bufs)
            self._pools = self._shard_pools(self._pools)
        if self._spill is not None:
            # transport callables close over self: every spill/resurrect
            # reads the CURRENT pool tuple, so donation rebinds and
            # post-crash pool rebuilds need no re-attachment
            self._spill.attach(self._spill_snapshot, self._spill_restore)
        from ..text.models._decode import (make_batched_sampler,
                                           make_guarded_batched_sampler)

        self._sampler = make_batched_sampler(top_k, top_p)
        self._top = (int(top_k), float(top_p))
        # NaN-safe serving (README "Numerics observability"): the guarded
        # program variant returns a per-row non-finite-logits flag (and a
        # logits stats row for the numerics stream) next to the sampled
        # tokens; the scheduler fails exactly the flagged requests with
        # status="error" / NumericFault while finite rows' token math is
        # untouched (the guard wraps the SAME sampler, so greedy output
        # stays byte-identical).  Off — the default, unless the active
        # TensorCheckerConfig asks for serving_guard — every program is
        # the pre-guard one: byte-identical keys, traces and dispatches.
        self._numeric_guard = bool(_numerics.serving_guard_default()
                                   if numeric_guard is None
                                   else numeric_guard)
        self._guard_sampler = make_guarded_batched_sampler(top_k, top_p)
        self._base_key = jax.random.key(int(seed))
        self._key_counter = itertools.count()
        self._rid_counter = itertools.count()

        # speculative decoding (serving/speculative.py): n-gram drafts are
        # verified k+1 tokens at a time by ONE compiled multi-token step —
        # greedy rows accept by exact argmax match (byte-identical output),
        # temperature rows by rejection sampling
        self._spec_k = int(speculative_k)
        if self._spec_k < 0:
            raise ValueError(f"speculative_k must be >= 0, got {speculative_k}")
        self._drafter = None
        self._verifier = None
        if self._spec_k:
            from .speculative import NgramDrafter, make_verifier

            self._drafter = NgramDrafter(self._spec_k, draft_max_ngram,
                                         draft_min_ngram)
            self._verifier = make_verifier(top_k, top_p)
        self._spec_proposed_total = 0
        self._spec_accepted_total = 0

        # QoS tiers (serving/qos.py, README "QoS tiers & autoscaling"):
        # qos=True installs the default realtime/standard/batch table, a
        # QoSConfig a custom one.  The queue becomes per-tier with
        # priority-weighted head selection; submits carry tier=, each
        # tier with an SLOPolicy gets its own accountant (tier= label),
        # admission sheds by the brownout ladder, and high-tier requests
        # preempt lower-tier decode slots instead of waiting.
        self._qos = None
        self._tier_slo = {}
        self._tier_ema = {}          # per-tier completed-duration EMAs
        self._last_preempt_t = None
        self._bo_cache = (0.0, None)  # throttled brownout snapshot
        if qos:
            from .qos import QoSConfig

            if qos is True:
                qos = QoSConfig()
            if not isinstance(qos, QoSConfig):
                raise TypeError(f"qos must be a QoSConfig or True, "
                                f"got {qos!r}")
            self._qos = qos
            from ..observability.slo import SLOAccountant as _TierAcct

            for t in qos.tiers:
                if t.slo is not None:
                    self._tier_slo[t.name] = _TierAcct(
                        t.slo, replica=self.replica, tier=t.name)
        if self._qos is not None:
            from .qos import TieredQueue

            self._queue = TieredQueue(self._qos)
        else:
            self._queue = collections.deque()
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._slots = [None] * self.num_slots
        # persistent per-step host buffers: rows change on admit/retire and
        # per-token advances only, so the hot decode dispatch stops
        # re-allocating and re-filling a fresh [B, NP] table every
        # iteration (measured per-step host overhead on the paged path)
        self._h_last = np.zeros((self.num_slots, 1), np.int64)
        self._h_lens = np.zeros((self.num_slots,), np.int32)
        self._h_temps = np.zeros((self.num_slots,), np.float32)
        self._h_table = np.full((self.num_slots, self.table_width),
                                self._scratch, np.int32)
        if self._spec_k:
            self._h_ids = np.zeros((self.num_slots, self._spec_k + 1),
                                   np.int64)
            self._h_dlen = np.zeros((self.num_slots,), np.int32)
        self._n_temp = 0          # live slots with temperature sampling
        self._gauges_t = 0.0      # last _update_gauges stamp (throttled)
        self._max_queue = max_queue
        self._stop_evt = threading.Event()
        self._thread = None
        self._started = False
        self._modes = None
        self._iteration = 0
        self._error = None
        # observability wiring (PR-3): scheduler heartbeat for the serving
        # watchdog, plus opt-in watchdog/telemetry (ctor arg or env)
        self._progress_t = None
        self._compiling = False  # first dispatch of a program (XLA compile)
        self._watchdog_s = watchdog_s
        self._telemetry_port = telemetry_port
        self._watchdog = None
        self._status_provider = None
        self._health_provider = None
        # resilience wiring (PR-4): health state machine, load shedding,
        # transient-failure auto-restart with in-flight requeue
        self._draining = False
        self._max_engine_restarts = int(max_engine_restarts)
        self._degraded_stall_s = float(degraded_stall_s)
        self._restart_cooldown_s = float(restart_cooldown_s)
        self._engine_restarts = 0
        self._last_restart_t = None
        self._ema_request_s = None   # EMA of completed request durations
        self._admitting = None       # request popped but not yet slotted

        from ..profiler import metrics as _metrics

        # request-level SLO accounting (observability.slo): evaluate every
        # finished request's token timeline against the policy, export
        # rolling attainment/burn-rate/goodput gauges per replica
        self._slo = None
        ttft_buckets = itl_buckets = None
        if slo is not None:
            from ..observability.slo import (SLOAccountant, SLOPolicy,
                                             slo_histogram_buckets)

            if not isinstance(slo, SLOPolicy):
                raise TypeError(f"slo must be an SLOPolicy, got {slo!r}")
            self._slo = SLOAccountant(slo, replica=self.replica)
            # align the latency histogram edges with the SLO thresholds so
            # "fraction of samples under target" reads straight off the
            # Prometheus _bucket series
            if slo.ttft_s:
                ttft_buckets = slo_histogram_buckets(
                    _metrics._DEFAULT_BUCKETS, slo.ttft_s)
            if slo.itl_s:
                itl_buckets = slo_histogram_buckets(
                    _metrics._DEFAULT_BUCKETS, slo.itl_s)

        # every serving.* series carries replica=<id> (default "0") so N
        # engines in one process keep distinct series; per-call labels like
        # status=/reason= merge on top of it (metrics.bind)
        def _h(name, help, buckets=None):
            return _metrics.bind(_metrics.histogram(name, help,
                                                    buckets=buckets),
                                 replica=self.replica)

        def _g(name, help):
            return _metrics.bind(_metrics.gauge(name, help),
                                 replica=self.replica)

        def _c(name, help):
            return _metrics.bind(_metrics.counter(name, help),
                                 replica=self.replica)

        self._m_ttft = _h("serving.ttft_seconds", "submit -> first token",
                          buckets=ttft_buckets)
        self._m_ttft_cold = _h(
            "serving.ttft_cold_seconds",
            "submit -> first token for requests that paid a compile stall "
            "(subset of serving.ttft_seconds)", buckets=ttft_buckets)
        self._m_itl = _h(
            "serving.inter_token_seconds", "per-sequence inter-token latency",
            buckets=itl_buckets)
        self._m_step_seconds = _h(
            "serving.step_seconds", "one batched decode iteration")
        self._m_prefill_seconds = _h(
            "serving.prefill_seconds", "admit-time prefill")
        self._m_queue_depth = _g(
            "serving.queue_depth", "requests waiting for a slot")
        self._m_active = _g(
            "serving.active_slots", "slots decoding this iteration")
        self._m_occupancy = _g(
            "serving.slot_occupancy", "active_slots / num_slots")
        self._m_page_util = _g(
            "serving.page_utilization", "KV pages in use / pool size")
        self._m_pages_used = _g(
            "serving.pages_in_use", "KV pages held by live sequences")
        self._m_tokens = _c(
            "serving.tokens_generated", "tokens emitted to callers")
        self._m_requests = _c(
            "serving.requests", "requests by terminal status")
        self._m_blocked = _c(
            "serving.admissions_blocked",
            "admissions deferred: page pool exhausted")
        self._m_preempt = _c(
            "serving.preemptions",
            "sequences evicted from their decode slot (reason=deadline: "
            "retired expired; reason=qos: requeued for a higher tier)")
        # per-tier pressure gauges (QoS engines set them; registered
        # unconditionally so the metric families are stable)
        self._m_tier_depth = _g(
            "serving.tier.queue_depth", "queued requests per QoS tier")
        self._m_tier_active = _g(
            "serving.tier.active_slots", "decoding slots held per QoS tier")
        self._m_step_traces = _c(
            "serving.step_traces", "decode-step program traces")
        self._m_prefill_traces = _c(
            "serving.prefill_traces", "prefill program traces")
        self._m_prefill_chunk_seconds = _h(
            "serving.prefill_chunk_seconds",
            "one chunked-prefill dispatch (prefill_chunk_tokens tokens)")
        self._m_prefill_chunk_traces = _c(
            "serving.prefill_chunk_traces",
            "chunked-prefill program traces")
        self._m_shed = _c(
            "serving.load_shed", "requests shed at submit, by reason")
        self._m_engine_restarts = _c(
            "serving.engine_restarts",
            "scheduler auto-restarts after transient failures")
        self._m_requeued = _c(
            "serving.requests_requeued",
            "in-flight requests transparently re-queued across a restart")
        self._m_health = _g(
            "serving.health_state",
            "0 healthy, 1 degraded, 2 draining, 3 stopped, 4 error")
        self._m_spec_proposed = _c(
            "serving.spec_proposed", "draft tokens submitted to verification")
        self._m_spec_accepted = _c(
            "serving.spec_accepted", "draft tokens accepted by verification")
        self._m_accept_rate = _g(
            "serving.acceptance_rate",
            "speculative acceptance: spec_accepted / spec_proposed")
        self._m_verify_traces = _c(
            "serving.verify_traces", "verify-step program traces")
        # numerics observability (ISSUE 13): requests retired because the
        # guarded program flagged their logits row non-finite, plus a
        # sampled weight dequant->requant drift gauge for quant engines
        self._m_numeric_faults = _c(
            "serving.numeric_faults",
            "requests failed on non-finite logits (guarded programs)")
        self._m_quant_drift = _g(
            "serving.quant_drift",
            "sampled int8 weight dequant->requant roundtrip error "
            "(relative, one layer per tick)")
        self._drift_idx = 0
        self._drift_t = 0.0
        self._npoll_t = 0.0
        # quantized-serving occupancy gauges: bytes one token position
        # costs in the KV pools (layers x K+V, scale pools included) and
        # the allocated pool HBM, labelled by pool dtype
        self._m_kv_bytes_tok = _g(
            "serving.kv_bytes_per_token",
            "KV-cache HBM bytes per token position (all layers, K+V, "
            "scale pools included)")
        self._m_pool_bytes = _g(
            "serving.pool_bytes",
            "allocated KV page-pool HBM bytes (scratch page included)")
        self._set_pool_gauges()
        # memory observability (observability/memory.py): every long-lived
        # device allocation this engine owns registers with the process
        # ledger, and admission pre-flight projects new requests against
        # PADDLE_HBM_BUDGET_BYTES — fixed bytes (params + buffers) plus
        # pages already committed to admitted-but-unfinished requests
        self._fixed_bytes = int(
            sum(int(v.nbytes) for v in self._params.values())
            + sum(int(v.nbytes) for v in self._bufs.values()))
        self._committed_pages = 0
        self._commit_lock = threading.Lock()
        self._mem_regs = []
        self._register_memory()

    def _register_memory(self):
        """Register this engine's device allocations with the process
        MemoryLedger.  Sources close over a weakref — the ledger never
        pins the engine, and every read resolves the CURRENT pool tuple,
        so a post-crash ``_recover()`` rebuild needs no re-registration."""
        led = _obs_memory.ledger()
        ref = weakref.ref(self)

        def _pools_src(idx):
            def src():
                eng = ref()
                if eng is None:
                    return None
                return [eng._pools[i] for i in idx]
            return src

        for owner, idx in self._adapter.pool_owners():
            meta = None
            if owner == "kv.pages":
                meta = {
                    "kind": "kv",
                    # per-shard when mp > 1 (shard= below): the unit the
                    # per-chip capacity math is denominated in
                    "bytes_per_page": self._bytes_per_page,
                    "page_size": self.page_size,
                    "num_pages": self._num_pages,
                    "max_model_len": self.max_model_len,
                    "max_resident_slots":
                        self._bm.max_resident_sequences(self.max_model_len),
                }
            elif owner == "kv.scales":
                meta = {"kind": "kv_scales"}
            if meta is not None and self._mp > 1:
                # sharded pools: label the owner with the mesh split so
                # ledger.report()'s per-device view can divide the global
                # array bytes by the shard count (live_arrays and the
                # sources both report GLOBAL nbytes, so reconciliation
                # still accounts 100% of live bytes either way)
                meta["shard"] = f"{_MP_AXIS}:{self._mp}"
            self._mem_regs.append(led.register(
                owner, _pools_src(idx), replica=self.replica, meta=meta))

        def _named_src(which, pred):
            def src():
                eng = ref()
                if eng is None:
                    return None
                d = eng._params if which == "params" else eng._bufs
                return [v for k, v in d.items() if pred(k)]
            return src

        # int8-converted weights get their own owner row; everything else
        # (f32/bf16 params, residual buffers, Int8Linear biases) is
        # model.params.  Int8Linear stores its payload in a buffer named
        # ``<sublayer>.weight_int8`` (quantization.Int8Linear).
        is_q = lambda k: k.endswith("weight_int8")  # noqa: E731
        self._mem_regs.append(led.register(
            "model.params", _named_src("params", lambda k: True),
            replica=self.replica, meta={"kind": "weights"}))
        self._mem_regs.append(led.register(
            "model.params", _named_src("bufs", lambda k: not is_q(k)),
            replica=self.replica, meta={"kind": "weights"}))
        if self.weight_dtype == "int8":
            self._mem_regs.append(led.register(
                "model.weights_int8", _named_src("bufs", is_q),
                replica=self.replica, meta={"kind": "weights_int8"}))

        if self._spill is not None:
            sref = weakref.ref(self._spill)

            def _spill_src():
                tier = sref()
                return None if tier is None else tier.nbytes()

            # host-DRAM tier: device="host" rows are bookkeeping only —
            # outside the jax.live_arrays reconciliation, exactly like
            # checkpoint.snapshot's pinned host buffers
            self._mem_regs.append(led.register(
                "kv.spilled", _spill_src, replica=self.replica,
                device="host",
                meta={"kind": "kv-spill",
                      "budget_bytes": self._spill.budget_bytes}))

    # --------------------------------------------------------- mp sharding
    def _shard_tree(self, tree):
        """Commit a params/buffers dict to the mesh with each leaf's
        Megatron annotation (adapter.param_pspec; unmatched leaves
        replicate).  device_put with a NamedSharding — the same
        shard_tensor mechanics as distributed.auto_parallel, minus the
        Tensor wrapper (the engine holds raw jax arrays)."""
        from jax.sharding import NamedSharding

        return {k: jax.device_put(
            v, NamedSharding(self._mesh,
                             self._adapter.param_pspec(k, _MP_AXIS)))
            for k, v in tree.items()}

    def _shard_pools(self, pools):
        """Commit a fresh pool tuple to the mesh on the KV-head dim (the
        adapter owns the per-pool specs — the quantized 4-tuple shards
        its scale pools alongside the payloads)."""
        from jax.sharding import NamedSharding

        specs = self._adapter.pool_pspecs(_MP_AXIS)
        return tuple(jax.device_put(p, NamedSharding(self._mesh, s))
                     for p, s in zip(pools, specs))

    def _new_block_manager(self):
        return BlockManager(self._num_pages, self.page_size,
                            prefix_sharing=self._prefix_sharing,
                            replica=self.replica,
                            bytes_per_page=self._bytes_per_page,
                            pool_dtype=self._pool_dtype,
                            shards=self._mp,
                            radix=self._radix, spill=self._spill)

    # ------------------------------------------------- hierarchical KV cache
    def _spill_snapshot(self, page):
        """Device->host copy of ONE page row across EVERY pool array —
        the KVSpillTier's snapshot callable.  Walking the whole tuple is
        what keeps int8 payload+scale pairs together: the quantized
        adapter's (kp, vp, ks, vs) all slice at the same page index."""
        return tuple(np.asarray(p[:, page]) for p in self._pools)

    def _spill_restore(self, page, payload):
        """Host->device re-page of a resurrected entry into device slot
        ``page``: one scatter per pool (eager ``.at[].set`` — a
        device_put of the host bytes plus a copy that preserves the
        pool's placement/sharding), rebinding the pool tuple like every
        dispatch does."""
        self._pools = tuple(
            p.at[:, page].set(jnp.asarray(a, p.dtype))
            for p, a in zip(self._pools, payload))

    def prefix_index_summary(self):
        """Resident-prefix digests for cross-replica placement (None
        outside radix mode) — ReplicaPool folds this into router states
        and stats() so the PrefixAffinityRouter can send a request to the
        replica with the deepest matching resident run."""
        return self._bm.index_summary()

    def _set_pool_gauges(self):
        self._m_kv_bytes_tok.set(self._bytes_per_page / self.page_size)
        # one series PER POOL DTYPE: the quantized engine's f32 scale
        # pools are real device residency — folding them into the int8
        # series used to make serving.pool_bytes disagree with what the
        # arrays actually occupy (ISSUE 12 satellite fix)
        by_dtype = {}
        for p in self._pools:
            dt = str(p.dtype)
            by_dtype[dt] = by_dtype.get(dt, 0) + int(p.nbytes)
        for dt, b in by_dtype.items():
            self._m_pool_bytes.set(float(b), dtype=dt)

    def pool_bytes_by_dtype(self):
        """Actual pool-tuple device bytes, keyed by array dtype (payload
        AND scale pools — what /statusz and the bench memory section
        reconcile against the ledger)."""
        out = {}
        for p in self._pools:
            dt = str(p.dtype)
            out[dt] = out.get(dt, 0) + int(p.nbytes)
        return out

    # ------------------------------------------------------------ lifecycle
    def start(self):
        # error check FIRST: after a scheduler-thread crash _started may
        # still read True, and submit() must reject loudly, not enqueue
        # onto a dead engine
        if self._error is not None:
            raise RuntimeError("engine previously failed") from self._error
        if self._started:
            return self
        self._modes = [(m, m.training)
                       for m in self._model.sublayers(include_self=True)]
        self._model.eval()
        self._stop_evt.clear()
        self._draining = False
        self._engine_restarts = 0   # a fresh start() is a fresh budget
        self._progress_t = time.monotonic()
        self._thread = threading.Thread(
            target=self._loop,
            name=f"paddle-serving-engine[{self.replica}]", daemon=True)
        self._started = True
        self._thread.start()
        self._start_observability()
        return self

    # ------------------------------------------------------------- warmup
    def warmup(self, manifest):
        """Replay a :class:`~paddle_tpu.observability.programs
        .WarmupManifest` ahead of admission: every engine-owned key in the
        manifest is compiled via an INERT dispatch (all lanes inactive —
        scratch table rows, zero lengths — so the program computes junk
        lanes nobody reads and the donated pools round-trip unchanged in
        meaning).  After warmup the first real request dispatches with
        zero new traces.

        Accepts a manifest object, a saved path, or its JSON dict.  Keys
        whose static axes (slot count, table width, pool shape/dtype,
        sampler, guard, mp) don't match THIS engine are skipped, as are
        keys a subclass's request-dependent extras can't replay.  Must run
        before :meth:`start` — replay donates the live pools, which must
        not race the scheduler thread."""
        if self._started:
            raise RuntimeError(
                "warmup() must run before start(): replay dispatches "
                "donate the live page pools")
        if isinstance(manifest, (str, os.PathLike)):
            manifest = _programs.WarmupManifest.load(manifest)
        elif isinstance(manifest, dict):
            manifest = _programs.WarmupManifest.from_json(manifest)
        want = manifest.meta.get("adapter")
        have = self._adapter_signature()
        if want is not None and want != have:
            raise ValueError(
                f"manifest captured for adapter {want}, this engine is "
                f"{have} — replaying would mint useless programs")
        # replay must trace in eval mode, exactly like the scheduler
        modes = [(m, m.training)
                 for m in self._model.sublayers(include_self=True)]
        self._model.eval()
        t0 = time.perf_counter()
        warmed, skipped = 0, []
        try:
            for key in manifest:
                try:
                    ok = self._warm_one(key)
                except Exception as exc:
                    _logger.warning("warmup: replay of %r failed: %r",
                                    key, exc)
                    ok = False
                if ok:
                    warmed += 1
                    ent = _programs.ledger().entry(key, store=self._store())
                    if ent is not None and ent.trace_id is None:
                        ent.trace_id = "warmup"  # provenance: nobody paid
                else:
                    skipped.append(key)
        finally:
            for m, tr in modes:
                m.training = tr
        info = {"warmed": warmed, "skipped": len(skipped),
                "seconds": round(time.perf_counter() - t0, 3)}
        self._warmed = info
        _logger.info("warmup: %(warmed)d programs in %(seconds).2fs "
                     "(%(skipped)d keys skipped)", info)
        return info

    def capture_manifest(self):
        """Snapshot this model's live program-store key set, stamped with
        the adapter signature so :meth:`warmup` refuses a mismatched
        model geometry."""
        return _programs.WarmupManifest.capture(
            self._model, meta={"adapter": self._adapter_signature()})

    def _adapter_signature(self):
        sig = getattr(self._adapter, "signature", None)
        return sig() if callable(sig) else None

    def _warm_one(self, key):
        """Compile one manifest key if it belongs to this engine's static
        configuration.  Returns True when the key is now warm."""
        kind = key[0] if isinstance(key, tuple) and key else None
        if kind == "serve_step" and key == self._step_store_key():
            self._warm_step()
            return True
        if kind == "serve_prefill" and len(key) > 1 \
                and key == self._prefill_store_key(key[1]):
            self._warm_prefill(key[1])
            return True
        if kind == "serve_prefill_chunk" and len(key) > 1 \
                and key == self._prefill_chunk_store_key(key[1]):
            self._warm_prefill_chunk(key[1])
            return True
        if kind == "verify" and self._spec_k and len(key) > 1 \
                and key == self._verify_store_key(self._spec_k):
            self._warm_verify()
            return True
        return False

    def _warm_step(self):
        prog, traces = self._step_program()
        n0 = traces[0]
        if n0:
            return
        guard = self._numeric_guard
        rkey = self._base_key
        extra = self._step_extra()
        tail = (self._numeric_inject(),) if guard else ()
        args = (self._params, self._bufs, self._h_last, *self._pools,
                self._h_table, self._h_lens, self._h_temps, rkey,
                *extra, *tail)
        win = _programs.ledger().compile_window(
            self._step_store_key(), family=self._decode_family(),
            replica=self.replica, device=self._device_label(),
            store=self._store(), owner=self._model, engine=self)
        win.attach(prog, args)
        try:
            if guard:
                _tok, _bad, _st, *pools = prog(*args)
            else:
                _tok, *pools = prog(*args)
            self._pools = tuple(pools)
        finally:
            win.close(traced=traces[0] > n0)

    def _warm_prefill(self, s_pad):
        prog, traces = self._prefill_program(s_pad)
        n0 = traces[0]
        if n0:
            return
        guard = self._numeric_guard
        ids = np.zeros((1, s_pad), np.int64)
        table = np.full((1, self.table_width), self._scratch, np.int32)
        lens = np.asarray([s_pad], np.int32)   # junk K/V lands in scratch
        temps = np.zeros((1,), np.float32)
        rkey = self._base_key
        extra = self._warmup_prefill_extra()
        tail = (self._numeric_inject(1),) if guard else ()
        args = (self._params, self._bufs, ids, *self._pools, table, lens,
                temps, rkey, *extra, *tail)
        win = _programs.ledger().compile_window(
            self._prefill_store_key(s_pad),
            family=self._prefill_family(s_pad), replica=self.replica,
            device=self._device_label(), store=self._store(),
            owner=self._model, engine=self)
        win.attach(prog, args)
        try:
            if guard:
                _tok, _bad, _st, *pools = prog(*args)
            else:
                _tok, *pools = prog(*args)
            self._pools = tuple(pools)
        finally:
            win.close(traced=traces[0] > n0)

    def _warm_prefill_chunk(self, c_pad):
        prog, traces = self._prefill_chunk_program(c_pad)
        n0 = traces[0]
        if n0:
            return
        guard = self._numeric_guard
        ids = np.zeros((1, c_pad), np.int64)
        nvalid = np.asarray([c_pad], np.int32)
        table = np.full((1, self.table_width), self._scratch, np.int32)
        lens = np.zeros((1,), np.int32)
        temps = np.zeros((1,), np.float32)
        rkey = self._base_key
        extra = self._warmup_prefill_extra()
        tail = (self._numeric_inject(1),) if guard else ()
        args = (self._params, self._bufs, ids, nvalid, *self._pools,
                table, lens, temps, rkey, *extra, *tail)
        win = _programs.ledger().compile_window(
            self._prefill_chunk_store_key(c_pad),
            family=self._prefill_chunk_family(c_pad), replica=self.replica,
            device=self._device_label(), store=self._store(),
            owner=self._model, engine=self)
        win.attach(prog, args)
        try:
            if guard:
                _tok, _bad, _st, *pools = prog(*args)
            else:
                _tok, *pools = prog(*args)
            self._pools = tuple(pools)
        finally:
            win.close(traced=traces[0] > n0)

    def _warm_verify(self):
        prog, traces = self._verify_program()
        n0 = traces[0]
        if n0:
            return
        guard = self._numeric_guard
        rkey = self._base_key
        extra = self._verify_extra([])
        tail = (self._numeric_inject(),) if guard else ()
        args = (self._params, self._bufs, self._h_ids, *self._pools,
                self._h_table, self._h_lens, self._h_dlen, self._h_temps,
                rkey, *extra, *tail)
        win = _programs.ledger().compile_window(
            self._verify_store_key(self._spec_k),
            family=self._verify_family(), replica=self.replica,
            device=self._device_label(), store=self._store(),
            owner=self._model, engine=self)
        win.attach(prog, args)
        try:
            if guard:
                _t, _a, _b, _s, *pools = prog(*args)
            else:
                _t, _a, *pools = prog(*args)
            self._pools = tuple(pools)
        finally:
            win.close(traced=traces[0] > n0)

    def _warmup_prefill_extra(self):
        """Request-independent stand-in for :meth:`_prefill_extra` during
        warmup replay (there is no request).  The base engine's extras
        are empty; subclasses whose extras depend on the request override
        this (or let the per-key try/except skip the key)."""
        return self._prefill_extra(None)

    def program_traces(self):
        """Total trace count across this model's program store (serving
        entries carry a ``[count]`` trace box; generate() pairs don't).
        The warmup invariant — a warmed engine's first request mints
        nothing — is asserted as a zero delta of this sum."""
        total = 0
        for ent in self._store().values():
            if isinstance(ent, tuple) and len(ent) == 2 \
                    and isinstance(ent[1], list) and ent[1] \
                    and isinstance(ent[1][0], int):
                total += ent[1][0]
        return total

    def drain(self, timeout=600):
        """Graceful rundown: stop admitting (submits reject with reason
        ``draining``, /healthz answers 503) and wait for the queue and
        every slot to empty.  Returns True once nothing is in flight;
        raises TimeoutError if work remains after ``timeout``."""
        self._draining = True
        deadline = time.monotonic() + float(timeout)
        while time.monotonic() < deadline:
            if self._error is not None or not self._started:
                return True  # aborted/stopped: nothing left in flight
            with self._lock:
                empty = not self._queue \
                    and all(s is None for s in self._slots) \
                    and self._admitting is None
            if empty:
                return True
            time.sleep(0.01)
        raise TimeoutError(f"engine did not drain within {timeout}s: "
                           f"{self.stats()}")

    def stop(self, drain=False, drain_timeout=600):
        """Stop the scheduler.  ``drain=True`` first finishes all in-flight
        work (no request ever left hanging); without it, in-flight and
        queued requests FAIL FAST — their handles raise a clear
        :class:`EngineStoppedError` from ``result()``/``stream()`` instead
        of blocking until the caller's timeout."""
        if not self._started:
            return
        if drain:
            self.drain(timeout=drain_timeout)
        self._stop_evt.set()
        with self._cv:
            self._cv.notify_all()
        # generous join: a first-call prefill may sit in a minutes-long XLA
        # compile.  NEVER touch slots/pages while the thread could still be
        # alive — that would double-free pages it is about to retire.
        self._thread.join(timeout=600)
        if self._thread.is_alive():
            raise RuntimeError(
                "serving scheduler thread did not stop within 600s "
                "(stuck in a compile or device call); engine state left "
                "untouched — retry stop() once the call returns")
        for i, s in enumerate(self._slots):
            if s is not None:
                self._bm.free(s.alloc)
                self._release_tenant(s.req)
                self._slots[i] = None
                self._fail_stopped(s.handle)
        self._reset_host_buffers()
        with self._lock:
            while self._queue:
                self._fail_stopped(self._queue.popleft().handle)
        self._draining = False
        if self._modes is not None:
            for m, tr in self._modes:
                m.training = tr
            self._modes = None
        if self._watchdog is not None:
            self._watchdog.stop()
        if self._status_provider is not None or self._health_provider is not None:
            # unregister OUR providers only (a newer engine may own the key
            # by now); also frees this engine for GC — the global registry
            # must not pin model params/pools past stop()
            from ..observability import telemetry as _telemetry

            _telemetry.remove_providers_if_owner(
                self._provider_key, self._status_provider,
                self._health_provider)
            self._status_provider = None
            self._health_provider = None
        self._started = False

    def _fail_stopped(self, handle):
        """A request in flight at (non-drain) stop(): fail its handle
        loudly rather than leaving result() to block until timeout."""
        if handle.cancelled:
            self._finish(handle, "cancelled")
            return
        handle._error = EngineStoppedError(
            f"request {handle.request_id} was still in flight when the "
            "engine stopped; use stop(drain=True) to finish in-flight work")
        self._finish(handle, "stopped")

    def _start_observability(self):
        """Opt-in forensics: flight recorder from PADDLE_FLIGHT_DIR, the
        /metrics|/healthz|/statusz endpoint from PADDLE_TELEMETRY_PORT (or
        the ``telemetry_port`` ctor arg; 0 = ephemeral), the wedged-
        scheduler watchdog from PADDLE_SERVING_WATCHDOG_S (or
        ``watchdog_s``).  All default to off: an engine with none of them
        set behaves exactly as before."""
        from ..observability import flight_recorder as _flight
        from ..observability import telemetry as _telemetry
        from ..observability import watchdog as _watchdog

        _flight.maybe_enable_from_env()
        try:
            port = self._telemetry_port
            if port is None:
                env = os.environ.get("PADDLE_TELEMETRY_PORT")
                port = int(env) if env else None
            if port is not None:
                _telemetry.serve(port)
                # registration is KEYED by replica id ("serving/<replica>")
                # so a second engine in the process gets its own /statusz
                # section and /healthz component instead of clobbering the
                # first's, and unregister-on-stop stays per replica
                self._status_provider = self._statusz
                _telemetry.add_status_provider(self._provider_key,
                                               self._status_provider)
                self._health_provider = self.health_state
                _telemetry.add_health_provider(self._provider_key,
                                               self._health_provider,
                                               gating=self._health_gating)
        except Exception as e:
            # opt-in observability must never take down serving startup
            # (EADDRINUSE on a shared port, malformed env value, ...)
            import logging

            logging.getLogger("paddle_tpu.observability").error(
                "telemetry endpoint not started (%r); serving continues "
                "without /metrics|/statusz", e)
        wd = self._watchdog_s
        if wd is None:
            env = os.environ.get("PADDLE_SERVING_WATCHDOG_S")
            wd = float(env) if env else None
        if not wd or wd <= 0:  # 0 is the natural 'disabled' spelling
            wd = None
        if wd is not None and self._watchdog is None:
            self._watchdog = _watchdog.ServingWatchdog(self, deadline_s=wd)
        if self._watchdog is not None:
            self._watchdog.start()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # ------------------------------------------------------------------ api
    def submit(self, prompt_ids, max_new_tokens=32, temperature=0.0,
               eos_token_id=None, deadline_s=None, sampling=None,
               adapter=None, grammar=None, mode="generate", pooling="mean",
               tier=None, _fsm_state=None, _autostart=True):
        """Queue one request; returns a :class:`RequestHandle` immediately.
        ``deadline_s`` is a wall-clock budget from now — a sequence still
        queued or decoding past it is retired with status ``expired``.

        Multi-tenant parameters (:class:`MultiTenantEngine` only — the
        base engine rejects non-defaults loudly): ``adapter`` names a
        registered LoRA adapter serving this row; ``grammar`` is a
        :class:`~.multitenant.grammar.CompiledGrammar` constraining the
        row's output (``_fsm_state`` resumes it mid-document — the
        cluster failover path); ``mode`` picks generate | embed | score
        (embed/score ride the scheduler and prefill programs but retire
        without decode slots or pages); ``pooling`` (mean | last) shapes
        the embed vector.

        ``tier`` names a QoS tier (``ServingEngine(qos=...)`` engines
        only; ``None`` = the config's default tier) — it selects the
        request's queue, admission weight, SLO accounting and preemption
        rank (README "QoS tiers & autoscaling").

        ``_autostart=False`` (the cluster's leg path) never starts a
        stopped engine: the submit is rejected instead, atomically with
        the enqueue, so a leg racing ``stop()`` cannot resurrect the
        replica or enqueue past the stop-time handle sweep."""
        # chaos site: an armed fn here drives deterministic overload (the
        # bench's traffic-spike arm submits a burst from inside the Nth
        # submit call) — disarmed it is one flag check
        _faults.maybe("serving.traffic_spike")
        if self._qos is not None:
            tier = self._qos.resolve(tier)
        elif tier is not None:
            raise ValueError(
                "tier= needs a QoS-enabled engine (ServingEngine(qos=...))")
        prompt = self._normalize_prompt(prompt_ids)
        if not prompt:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        eos_token_id = self._validate_tenant(adapter, grammar, mode, pooling,
                                             eos_token_id)
        sampling = sampling if sampling is not None \
            else SamplingParams(temperature=temperature)
        if mode != "generate":
            max_new_tokens = 1          # no decode slot is ever occupied
        total = len(prompt) + int(max_new_tokens)
        handle = RequestHandle(next(self._rid_counter), len(prompt))
        handle.mode = mode
        handle.adapter = adapter
        handle.tier = tier
        if grammar is not None:
            handle._fsm_state = _fsm_state if _fsm_state is not None \
                else grammar.start
        if mode != "generate":
            # embed/score: the prompt runs through the prefill programs
            # against the scratch page — no pages, no decode positions
            if len(prompt) > self.max_model_len:
                self._m_requests.inc(status="rejected")
                raise RequestRejectedError(
                    f"{mode} prompt {len(prompt)} exceeds max_model_len "
                    f"{self.max_model_len}", reason="unservable")
        elif total > self.max_model_len \
                or self._bm.pages_for(total) > self._bm.num_pages:
            self._m_requests.inc(status="rejected")
            raise RequestRejectedError(
                f"prompt {len(prompt)} + max_new_tokens {max_new_tokens} "
                f"needs {self._bm.pages_for(total)} pages / "
                f"{total} positions; engine caps are "
                f"{self._bm.num_pages} pages / {self.max_model_len} positions",
                reason="unservable")
        if _autostart:
            self.start()  # before enqueue: a failed engine rejects loudly
        with _tracing.span("serving.submit", trace_id=handle.trace_id,
                           request_id=handle.request_id,
                           prompt_len=len(prompt)):
            with self._cv:
                # stop() sets _stop_evt before its queue sweep (which holds
                # this lock): a leg either rejects here, or its enqueue
                # precedes the sweep and the sweep fails its handle
                if not _autostart and (not self._started or self._error
                                       is not None
                                       or self._stop_evt.is_set()):
                    raise EngineStoppedError(
                        f"replica {self.replica} is not running")
                if self._draining:
                    self._shed("draining",
                               "engine is draining; not admitting new work",
                               tier=tier)
                if self._qos is not None:
                    self._check_qos_admission(tier)
                if self._max_queue is not None \
                        and len(self._queue) >= self._max_queue:
                    self._shed("queue_full",
                               f"admission queue full ({self._max_queue})",
                               tier=tier)
                if deadline_s is not None:
                    self._check_deadline_meetable(float(deadline_s),
                                                  tier=tier)
                self._preflight_hbm(handle, prompt, total, mode)
                deadline = time.time() + deadline_s \
                    if deadline_s is not None else None
                self._queue.append(Request(prompt, int(max_new_tokens),
                                           sampling, eos_token_id, deadline,
                                           handle, adapter=adapter,
                                           grammar=grammar, mode=mode,
                                           pooling=pooling, tier=tier))
                self._m_requests.inc(status="submitted")
                self._m_queue_depth.set(len(self._queue))
                self._cv.notify_all()
        return handle

    def _validate_tenant(self, adapter, grammar, mode, pooling,
                         eos_token_id):
        """Submit-time validation of the multi-tenant parameters; the
        base engine serves exactly one tenant in one mode, so anything
        non-default is rejected here (MultiTenantEngine overrides).
        Returns the effective ``eos_token_id``."""
        if adapter is not None or grammar is not None \
                or mode != "generate" or pooling != "mean":
            raise ValueError(
                "adapter=/grammar=/mode=/pooling= need a multi-tenant "
                "engine (paddle_tpu.serving.multitenant.MultiTenantEngine)")
        return eos_token_id

    def _shed(self, reason, message, tier=None):
        """Reject at admission with a distinct, machine-readable reason
        (load shedding under pressure beats timing out after queueing).
        The ``tier=`` label is only attached on QoS engines so that
        label-less ``.get(reason=...)`` lookups keep working elsewhere."""
        if tier is not None:
            self._m_shed.inc(reason=reason, tier=tier)
        else:
            self._m_shed.inc(reason=reason)
        self._m_requests.inc(status="rejected")
        raise RequestRejectedError(message, reason=reason)

    def _check_qos_admission(self, tier):
        """SLO-aware admission for QoS engines (called under the cv lock):
        shed whole tiers by the brownout ladder — a tier is shed once the
        protected tier's error-budget burn rate crosses that tier's
        ``shed_burn_rate`` — and enforce per-tier queue caps.  This
        replaces pressure signalling via one global ``queue_full`` gate
        with attribution: during a brownout only the tiers whose
        threshold tripped are rejected."""
        bo = self._brownout()
        if tier in bo["shed"]:
            self._shed(
                "brownout",
                f"tier {tier!r} shed at brownout level {bo['level']} "
                f"({bo['state']}): protected-tier burn rate "
                f"{bo['burn_rate']:.2f}", tier=tier)
        pol = self._qos.tier(tier)
        if pol.max_queue is not None \
                and self._queue.depth(tier) >= pol.max_queue:
            self._shed("queue_full",
                       f"tier {tier!r} queue full ({pol.max_queue})",
                       tier=tier)

    def _preflight_hbm(self, handle, prompt, total, mode):
        """OOM forensics' prevention half (observability/memory.py):
        when ``PADDLE_HBM_BUDGET_BYTES`` is set, project this request's
        worst-case page need against what the budget leaves after the
        fixed allocations (params + buffers + the full page pools are
        already resident; what grows with admission is the COMMITTED
        page count across admitted-but-unfinished requests).  Shedding
        here with ``reason="hbm_budget"`` never changes what admitted
        requests compute — pages either fit or the request never runs —
        so greedy outputs stay byte-identical to an unbudgeted engine."""
        if mode != "generate":
            return                      # no pages are ever committed
        budget = _obs_memory.hbm_budget_bytes()
        if budget is None:
            return
        need = self._bm.pages_for(total)
        headroom = int(budget) - self._fixed_bytes
        page_budget = headroom // self._bytes_per_page if headroom > 0 else 0
        # pools cap the committed total too: never promise pages past P
        page_budget = min(page_budget, self._num_pages)
        with self._commit_lock:
            if self._committed_pages + need > page_budget:
                self._shed(
                    "hbm_budget",
                    f"request needs {need} pages "
                    f"({need * self._bytes_per_page} B) but "
                    f"{self._committed_pages}/{page_budget} budgeted pages "
                    f"are committed (PADDLE_HBM_BUDGET_BYTES={budget}, "
                    f"fixed {self._fixed_bytes} B)")
            self._committed_pages += need
            handle._hbm_pages = need

    def _release_hbm(self, handle):
        """Idempotent un-commit of a handle's pre-flight page reservation
        (every terminal path funnels through ``_finish``)."""
        n = getattr(handle, "_hbm_pages", 0)
        if n:
            handle._hbm_pages = 0
            with self._commit_lock:
                self._committed_pages -= n

    def _check_deadline_meetable(self, deadline_s, tier=None):
        """Deadline-aware admission (called under the cv lock): shed NOW if
        the scheduler has been stalled longer than the whole deadline
        budget, or if the queue-position estimate (queue depth over slots
        times the completed-request duration EMA) already exceeds it —
        rejecting in microseconds beats returning 'expired' after the
        deadline burned queue and pages.

        QoS engines estimate per tier: the duration EMA is the submitting
        tier's own completed-request EMA (one global EMA lets slow
        batch-tier requests inflate the estimate and falsely shed fast
        realtime traffic), and the queue-ahead count only counts requests
        at the same or higher priority — lower tiers behind us in the
        weighted queue (and preemptible under pressure) don't delay us."""
        stamp = self._progress_t
        if stamp is not None and not self._compiling:
            stall = time.monotonic() - stamp
            if stall > max(self._degraded_stall_s, deadline_s):
                self._shed("deadline_unmeetable",
                           f"scheduler stalled for {stall:.2f}s, longer "
                           f"than the {deadline_s:.2f}s deadline",
                           tier=tier)
        if self._qos is not None and tier is not None:
            ema = self._tier_ema.get(tier, self._ema_request_s)
            ahead = self._queue.depth_at_or_above(
                self._qos.tier(tier).priority)
        else:
            ema = self._ema_request_s
            ahead = len(self._queue)
        if ema is not None and ahead:
            est = (ahead / max(self.num_slots, 1) + 1.0) * ema
            if est > deadline_s:
                self._shed(
                    "deadline_unmeetable",
                    f"estimated completion in {est:.2f}s (queue-ahead "
                    f"{ahead}, typical request {ema:.2f}s"
                    + (f" for tier {tier!r}" if tier is not None else "")
                    + f") exceeds the {deadline_s:.2f}s deadline",
                    tier=tier)

    def generate(self, prompt_ids, max_new_tokens=32, timeout=None, **kw):
        """Blocking convenience: submit + wait; returns generated ids."""
        return self.submit(prompt_ids, max_new_tokens, **kw).result(timeout)

    def stream(self, prompt_ids, max_new_tokens=32, **kw):
        """Token-at-a-time iterator (see :meth:`RequestHandle.stream`)."""
        return self.submit(prompt_ids, max_new_tokens, **kw).stream()

    # ------------------------------------------------------------ internals
    @staticmethod
    def _normalize_prompt(prompt_ids):
        arr = prompt_ids
        if hasattr(arr, "numpy"):
            arr = arr.numpy()
        arr = np.asarray(arr)
        if arr.ndim == 2 and arr.shape[0] == 1:
            arr = arr[0]
        if arr.ndim != 1:
            raise ValueError(f"prompt must be 1-D (or [1, S]), "
                             f"got shape {arr.shape}")
        return [int(t) for t in arr]

    def _next_key(self):
        return jax.random.fold_in(self._base_key, next(self._key_counter))

    def _program(self, key, build, family=None):
        store = self._store()
        ent = store.get(key)
        if ent is None:
            t0 = time.perf_counter()
            ent = store[key] = build()
            # every store mint lands a ledger row (provenance + build
            # wall); the dispatch site's compile window adds the stall
            _programs.ledger().record_mint(
                key, family=family or str(key[0]), replica=self.replica,
                device=self._device_label(), store=store,
                owner=self._model, build_s=time.perf_counter() - t0)
        return ent

    def _device_label(self):
        if self._mp > 1:
            return f"mesh[{self._mp}]:{_MP_AXIS}"
        if self._device is not None:
            return str(self._device)
        try:
            return str(jax.devices()[0])
        except Exception:
            return None

    def _guard_key(self):
        """Program-store key component for the numeric-guard variant.
        Empty when the guard is off so the unguarded keys — and therefore
        the cached programs and their trace counters — stay byte-for-byte
        what they were before the guard existed."""
        return ("nguard",) if self._numeric_guard else ()

    def _mp_key(self):
        """Program-store key component for the tensor-parallel variant.
        Pool shapes stay GLOBAL under GSPMD, so without this an mp engine
        sharing the model with an unsharded one would collide with its
        cached single-device programs.  Empty at mp=1 — pre-mesh keys
        (and trace counters) stay byte-for-byte identical."""
        return ("mp", self._mp) if self._mp > 1 else ()

    def _store(self):
        from ..text.models._decode import program_store

        return program_store(self._model)

    # program-store key builders — shared by the mint sites, the dispatch
    # sites' compile windows (ledger attribution), and warmup() replay
    def _step_store_key(self):
        return ("serve_step", self.num_slots, self.table_width,
                self._pools[0].shape, str(self._pools[0].dtype),
                self._top) + self._guard_key() + self._mp_key()

    def _verify_store_key(self, k_pad):
        return ("verify", k_pad, self.num_slots, self.table_width,
                self._pools[0].shape, str(self._pools[0].dtype),
                self._top) + self._guard_key() + self._mp_key()

    def _prefill_store_key(self, s_pad):
        return ("serve_prefill", s_pad, self.table_width,
                self._pools[0].shape, str(self._pools[0].dtype),
                self._top) + self._guard_key() + self._mp_key()

    def _prefill_chunk_store_key(self, c_pad):
        return ("serve_prefill_chunk", c_pad, self.table_width,
                self._pools[0].shape, str(self._pools[0].dtype),
                self._top) + self._guard_key() + self._mp_key()

    def _step_program(self):
        key = self._step_store_key()
        n = len(self._pools)  # pools are DONATED; count is adapter-defined

        def build():
            traces = [0]
            adapter, sampler = self._adapter, self._sampler
            guard, gsampler = self._numeric_guard, self._guard_sampler
            low = _numerics.low_dtype()

            @functools.partial(jax.jit,
                               donate_argnums=tuple(range(3, 3 + n)))
            def step(params, bufs, last, *rest):
                traces[0] += 1  # python side effect: runs at TRACE time only
                if guard:
                    # trailing [B] f32 inject vector (zeros disarmed, NaN
                    # in one lane when numerics.nan_inject trips) keeps
                    # the program shape independent of fault arming
                    pools, (table, lens, temps, rkey, inj) = \
                        rest[:n], rest[n:]
                    out = adapter.step(params, bufs, last, *pools, table,
                                       lens)
                    logits = out[0] + inj[:, None]
                    tok, bad = gsampler(logits, temps, rkey)
                    stats = _numerics.stats_row(logits, low)[None]
                    return (tok, bad, stats) + tuple(out[1:])
                pools, (table, lens, temps, rkey) = rest[:n], rest[n:]
                out = adapter.step(params, bufs, last, *pools, table, lens)
                return (sampler(out[0], temps, rkey),) + tuple(out[1:])

            return step, traces

        return self._program(key, build, family=self._decode_family())

    def _verify_program(self):
        """The compiled multi-token verification step (speculative
        decoding): the ``("verify", k_pad, …)`` bucket family in the
        program store — one trace per (k, batch-shape, sampler) tuple,
        exactly like the plain decode step."""
        k_pad = self._spec_k
        key = self._verify_store_key(k_pad)
        n = len(self._pools)

        def build():
            traces = [0]
            adapter, verifier = self._adapter, self._verifier
            guard = self._numeric_guard
            low = _numerics.low_dtype()

            @functools.partial(jax.jit,
                               donate_argnums=tuple(range(3, 3 + n)))
            def verify(params, bufs, ids, *rest):
                traces[0] += 1
                if guard:
                    pools, (table, lens, dlen, temps, rkey, inj) = \
                        rest[:n], rest[n:]
                    out = adapter.verify(params, bufs, ids, *pools, table,
                                         lens)
                    logits = out[0] + inj[:, None, None]
                    targets, accept = verifier(logits, ids[:, 1:], dlen,
                                               temps, rkey)
                    bad = ~jnp.all(jnp.isfinite(logits), axis=(-2, -1))
                    stats = _numerics.stats_row(logits, low)[None]
                    return (targets, accept, bad, stats) + tuple(out[1:])
                pools, (table, lens, dlen, temps, rkey) = rest[:n], rest[n:]
                out = adapter.verify(params, bufs, ids, *pools, table, lens)
                targets, accept = verifier(out[0], ids[:, 1:], dlen, temps,
                                           rkey)
                return (targets, accept) + tuple(out[1:])

            return verify, traces

        return self._program(key, build, family=self._verify_family())

    def _prefill_bucket(self, S0):
        """Padded prefill width for a prompt of ``S0`` tokens: multiples of
        page_size up to ``_PREFILL_POW2_PAGES`` pages, then the next
        power-of-two page count (clamped to the table width) — long-prompt
        traffic mints O(log max_len) compiled prefill programs instead of
        one per page-size increment.  Correctness is untouched: the pad
        region is causally invisible to the logits gather at ``lens-1``,
        and its junk K/V lands in pages a later real write overwrites
        before per-slot ``seq_lens`` masking ever exposes them."""
        ps = self.page_size
        pages = max(1, -(-int(S0) // ps))
        if pages > _PREFILL_POW2_PAGES:
            pages = 1 << (pages - 1).bit_length()
        return min(pages, self.table_width) * ps

    def _prefill_program(self, s_pad):
        key = self._prefill_store_key(s_pad)
        n = len(self._pools)

        def build():
            traces = [0]
            adapter, sampler = self._adapter, self._sampler
            guard, gsampler = self._numeric_guard, self._guard_sampler
            low = _numerics.low_dtype()

            @functools.partial(jax.jit,
                               donate_argnums=tuple(range(3, 3 + n)))
            def prefill(params, bufs, ids, *rest):
                traces[0] += 1
                if guard:
                    pools, (table, lens, temps, rkey, inj) = \
                        rest[:n], rest[n:]
                    out = adapter.prefill(params, bufs, ids, *pools, table,
                                          lens)
                    logits = out[0] + inj[:, None]
                    tok, bad = gsampler(logits, temps, rkey)
                    stats = _numerics.stats_row(logits, low)[None]
                    return (tok, bad, stats) + tuple(out[1:])
                pools, (table, lens, temps, rkey) = rest[:n], rest[n:]
                out = adapter.prefill(params, bufs, ids, *pools, table, lens)
                return (sampler(out[0], temps, rkey),) + tuple(out[1:])

            return prefill, traces

        return self._program(key, build, family=self._prefill_family(s_pad))

    def _prefill_chunk_program(self, c_pad):
        """The compiled chunked-prefill step: the ``("serve_prefill_chunk",
        C, …)`` family — every chunk of every long prompt reuses ONE trace
        per (chunk width, pool shape, sampler) tuple (trace-count plateau
        asserted in tests).  ``nvalid`` rides as a 4th positional so the
        adapter's ``_split_extra`` tail (LoRA ids/pools) composes
        unchanged; pools are donated from position 4."""
        key = self._prefill_chunk_store_key(c_pad)
        n = len(self._pools)

        def build():
            traces = [0]
            adapter, sampler = self._adapter, self._sampler
            guard, gsampler = self._numeric_guard, self._guard_sampler
            low = _numerics.low_dtype()

            @functools.partial(jax.jit,
                               donate_argnums=tuple(range(4, 4 + n)))
            def chunk(params, bufs, ids, nvalid, *rest):
                traces[0] += 1
                if guard:
                    pools, (table, lens, temps, rkey, inj) = \
                        rest[:n], rest[n:]
                    out = adapter.prefill_chunk(params, bufs, ids, nvalid,
                                                *pools, table, lens)
                    logits = out[0] + inj[:, None]
                    tok, bad = gsampler(logits, temps, rkey)
                    stats = _numerics.stats_row(logits, low)[None]
                    return (tok, bad, stats) + tuple(out[1:])
                pools, (table, lens, temps, rkey) = rest[:n], rest[n:]
                out = adapter.prefill_chunk(params, bufs, ids, nvalid,
                                            *pools, table, lens)
                return (sampler(out[0], temps, rkey),) + tuple(out[1:])

            return chunk, traces

        return self._program(key, build,
                             family=self._prefill_chunk_family(c_pad))

    @property
    def step_traces(self):
        """Trace count of this engine's decode-step program (the continuous
        batching invariant: 1 for the engine's lifetime)."""
        try:
            return self._step_program()[1][0]
        except Exception:
            return 0

    # ---------------------------------------------------------- loop thread
    def _loop(self):
        while not self._stop_evt.is_set():
            try:
                # heartbeat FIRST, fault hook second: a wedge injected here
                # leaves the stamp stale exactly like a real stuck iteration
                self._progress_t = time.monotonic()
                _faults.maybe("serving.scheduler_wedge")
                _faults.maybe(self._site_wedge)  # replica-scoped chaos site
                if _faults.armed(self._site_replica_preempt):
                    # injected replica loss (autoscaler reap / cluster
                    # reroute drill): when the site trips, this replica
                    # dies FATALLY — the raised message deliberately
                    # avoids every transient pattern (including the word
                    # in the site name) so classify_failure routes it to
                    # abort, not self-restart
                    before = _faults.trip_count(self._site_replica_preempt)
                    _faults.maybe(self._site_replica_preempt)
                    if _faults.trip_count(self._site_replica_preempt) \
                            > before:
                        raise RuntimeError(
                            f"replica {self.replica} lost: host reclaimed "
                            "by the cluster scheduler (injected replica "
                            "loss)")
                self._admit()
                # chunked prefill rides the SAME scheduler iteration as the
                # decode dispatch: one budget's worth of chunk work, then
                # the batch decode over the lanes that finished ingesting
                self._advance_prefills()
                self._update_gauges()
                if not any(s is not None and s.prefilled is None
                           for s in self._slots):
                    if any(s is not None for s in self._slots):
                        continue        # chunked prefills still advancing
                    with self._cv:
                        if not self._queue and not self._stop_evt.is_set():
                            self._cv.wait(timeout=0.02)
                    continue
                self._step_once()
            except BaseException as e:
                # OOM forensics FIRST, while the allocation state that
                # produced the failure is still live: one flight dump
                # carrying the ledger owner table and per-program peak
                # bytes (observability/memory.py), then normal recovery
                if _obs_memory.is_oom_error(e):
                    try:
                        _obs_memory.oom_dump(e, replica=self.replica)
                    except Exception:
                        pass
                # the budget is a burst limit, not a lifetime one: a full
                # cooldown of healthy operation since the last restart
                # heals it (3 recovered blips spread over weeks must not
                # arm a kill switch for the 4th)
                if self._engine_restarts and self._last_restart_t is not None \
                        and time.monotonic() - self._last_restart_t \
                        > self._restart_cooldown_s:
                    self._engine_restarts = 0
                if classify_failure(e) == "transient" \
                        and self._engine_restarts < self._max_engine_restarts:
                    try:
                        self._recover(e)
                        continue
                    except BaseException as e2:  # recovery itself died
                        e = e2
                # fatal (or restart budget burned): surface to every
                # waiter, don't hang
                self._error = e
                self._abort_all(e)
                return

    def _recover(self, exc):
        """Transient scheduler failure (classified by
        :func:`paddle_tpu.resilience.retry.classify_failure`): rebuild
        device state and transparently re-queue every in-flight request
        instead of failing its handle.  Tokens already emitted stay
        emitted — each request is re-admitted as prompt + tokens-so-far
        with the remaining budget, so a greedy request's final ids are the
        ones an uninterrupted run would have produced."""
        self._engine_restarts += 1
        self._last_restart_t = time.monotonic()
        self._m_engine_restarts.inc()
        _logger.error(
            "serving engine auto-restart %d/%d after transient failure %r; "
            "re-queueing in-flight requests", self._engine_restarts,
            self._max_engine_restarts, exc)
        inflight = []
        for i, s in enumerate(self._slots):
            if s is not None:
                self._slots[i] = None
                self._release_tenant(s.req)
                inflight.append((s.req, s.produced))
        pending, self._admitting = self._admitting, None
        if pending is not None:
            self._release_tenant(pending)
        if pending is not None and \
                all(req.handle is not pending.handle for req, _ in inflight):
            inflight.append((pending, 0))
        # fresh device state: the page pools were donated into the crashed
        # dispatch; re-admission prefills rewrite every sequence's K/V
        # (a quantized engine rebuilds int8 + scale pools the same way —
        # the adapter owns the layout).  The host spill tier resets with
        # it: spilled bytes would still be valid (K/V is deterministic in
        # tokens + weights) but the rebuilt radix index starts empty, and
        # a coherent cold cache beats a warm one that needs cross-checks.
        if self._spill is not None:
            self._spill.clear()
        self._bm = self._new_block_manager()
        self._pools = tuple(self._adapter.init_pools(self._num_pages + 1))
        if self._device is not None:
            self._pools = jax.device_put(self._pools, self._device)
        elif self._mesh is not None:
            # mp restart: the rebuilt pools re-commit to the mesh with the
            # same KV-head sharding, so re-admission dispatches land on
            # the cached SPMD programs (byte-identical ids, no retrace)
            self._pools = self._shard_pools(self._pools)
        self._set_pool_gauges()
        self._reset_host_buffers()
        with self._lock:
            for req, produced in reversed(inflight):
                h = req.handle
                if h.done:
                    continue
                if h.cancelled:
                    self._finish(h, "cancelled")
                    continue
                remaining = req.max_new_tokens - produced
                if remaining <= 0:  # had finished, crash beat the retire
                    self._finish(h, "completed")
                    continue
                prompt = list(req.prompt) + \
                    ([int(t) for t in h.token_ids[-produced:]]
                     if produced else [])
                h.status = "queued"
                # dataclasses.replace keeps the multi-tenant fields
                # (adapter / grammar / mode) riding across the restart;
                # the LEASE is dropped — re-admission re-acquires against
                # the rebuilt adapter pools.  The grammar state needs no
                # replay: it lives on the HANDLE, already advanced through
                # every emitted token.
                self._queue.appendleft(dataclasses.replace(
                    req, prompt=prompt, max_new_tokens=remaining,
                    lease=None))
                self._m_requeued.inc()
            self._m_queue_depth.set(len(self._queue))

    # --------------------------------------------------- QoS preemption
    def _queue_pop(self, req):
        """Pop the already-peeked head ``req`` (called under the lock).
        QoS engines pop by identity — preemption may have appendleft'd
        victims into lower-priority tiers between the peek and this pop,
        and a positional pop must never swallow a victim."""
        if self._qos is not None:
            self._queue.pop_exact(req)
        else:
            self._queue.popleft()

    def _count_preemption(self, req, reason):
        """serving.preemptions: label-less on non-tiered requests (the
        deadline-expiry path predates QoS; its exact-match ``.get()``
        lookups must keep resolving), ``{tier=,reason=}`` on QoS ones."""
        if req.tier is not None:
            self._m_preempt.inc(tier=req.tier, reason=reason)
        else:
            self._m_preempt.inc()

    def _preempt_victims(self, req):
        """Decode slots ``req`` may evict, cheapest first: strictly
        lower-priority preemptible tiers, ordered lowest priority then
        least produced (minimum re-prefill work on resume).  Slots that
        already hit EOS / budget are skipped — they retire and free their
        resources on the very next step without losing anything."""
        pri = self._qos.tier(req.tier).priority
        out = []
        for i, s in enumerate(self._slots):
            if s is None or s.req.tier is None:
                continue
            pol = self._qos.tier(s.req.tier)
            if not pol.preemptible or pol.priority >= pri:
                continue
            if (s.eos is not None and s.last == s.eos) \
                    or s.produced >= s.max_new:
                continue
            out.append((pol.priority, s.produced, i))
        out.sort()
        return [i for _, _, i in out]

    def _preempt_for_slot(self, req):
        """All slots busy: evict one lower-tier victim so ``req`` admits
        this iteration instead of waiting out a full decode.  Returns the
        freed slot index, or None (non-QoS engine / nothing evictable)."""
        if self._qos is None or req.tier is None:
            return None
        victims = self._preempt_victims(req)
        if not victims:
            return None
        i = victims[0]
        self._preempt_slot(i)
        return i

    def _preempt_for_pages(self, req):
        """Page pool exhausted: evict lower-tier victims until ``req``'s
        allocation fits.  Guarded against thrash — if evicting EVERY
        eligible victim still could not cover the need, nothing is
        evicted and the request parks (blocked), exactly as before."""
        if self._qos is None or req.tier is None:
            return None
        victims = self._preempt_victims(req)
        if not victims:
            return None
        need = self._bm.pages_for(len(req.prompt) + req.max_new_tokens)
        free = self._bm.num_pages - self._bm.used_pages
        gain = sum(len(self._slots[i].alloc.pages) for i in victims)
        if free + gain < need:
            return None
        for i in victims:
            self._preempt_slot(i)
            alloc = self._bm.allocate(
                req.prompt, len(req.prompt) + req.max_new_tokens)
            if alloc is not None:
                return alloc
        return None

    def _preempt_slot(self, i):
        """Evict slot ``i`` for QoS (called under the lock): free its
        pages, clear its lane, and re-queue it at the FRONT of its tier as
        prompt + tokens-so-far with the remaining budget — the _recover
        requeue machinery scheduled on purpose, so a preempted greedy
        request's final ids are byte-identical to an uninterrupted run.
        Tokens already emitted stay emitted."""
        s = self._slots[i]
        h = s.handle
        produced = s.produced
        self._bm.free(s.alloc)
        self._release_tenant(s.req)
        self._slots[i] = None
        self._clear_slot_row(i, s)
        if h.cancelled:
            self._finish(h, "cancelled")
            return
        remaining = s.req.max_new_tokens - produced
        if remaining <= 0:      # had finished; eviction beat the retire
            self._finish(h, "completed")
            return
        prompt = list(s.req.prompt) + \
            ([int(t) for t in h.token_ids[-produced:]] if produced else [])
        h.status = "queued"
        h.preemptions += 1
        self._queue.appendleft(dataclasses.replace(
            s.req, prompt=prompt, max_new_tokens=remaining, lease=None))
        self._m_requeued.inc()
        self._count_preemption(s.req, "qos")
        self._last_preempt_t = time.monotonic()
        self._bo_cache = (0.0, None)    # ladder rung changed: drop cache

    def _brownout(self):
        """Current brownout rung (cached ~50ms — burn rates move at
        request cadence, admission runs per submit)."""
        from . import qos as _qos_mod

        now = time.monotonic()
        cached_t, cached = self._bo_cache
        if cached is not None and now - cached_t < 0.05:
            return cached
        preempting = self._last_preempt_t is not None \
            and now - self._last_preempt_t < 1.0
        bo = _qos_mod.brownout(self._qos, self.qos_burn_rate(),
                               preempting=preempting)
        self._bo_cache = (now, bo)
        return bo

    def qos_burn_rate(self):
        """The protected (highest-priority) tier's error-budget burn rate
        — the scalar driving the brownout ladder and the autoscaler; 0.0
        until that tier has completed requests in its window (or on
        non-QoS engines)."""
        if self._qos is None:
            return 0.0
        acct = self._tier_slo.get(self._qos.protected.name)
        if acct is None:
            return 0.0
        cur = acct.current()
        if not cur or cur.get("burn_rate") is None:
            return 0.0
        return float(cur["burn_rate"])

    def begin_drain(self):
        """Non-blocking drain request (autoscaler scale-down): stop
        admitting — submits shed with reason ``draining`` — while
        in-flight work runs to completion.  Poll :attr:`quiescent` to
        learn when the replica can be retired."""
        self._draining = True

    @property
    def quiescent(self):
        """True once nothing is queued or in flight (drain complete)."""
        if self._error is not None or not self._started:
            return True
        with self._lock:
            return not self._queue \
                and all(s is None for s in self._slots) \
                and self._admitting is None

    def _abort_all(self, exc):
        pending, self._admitting = self._admitting, None
        if pending is not None:
            self._release_tenant(pending)
        if pending is not None and not pending.handle.done:
            pending.handle._error = exc
            self._finish(pending.handle, "error")
        for i, s in enumerate(self._slots):
            if s is not None:
                self._bm.free(s.alloc)
                self._release_tenant(s.req)
                self._slots[i] = None
                s.handle._error = exc
                self._finish(s.handle, "error")
        self._reset_host_buffers()
        with self._lock:
            while self._queue:
                req = self._queue.popleft()
                req.handle._error = exc
                self._finish(req.handle, "error")

    def _admit(self):
        while True:
            with self._lock:
                req = None
                while self._queue:
                    cand = self._queue[0]
                    if cand.handle.cancelled:
                        self._queue.popleft()
                        self._finish(cand.handle, "cancelled")
                        continue
                    if cand.deadline is not None \
                            and time.time() > cand.deadline:
                        self._queue.popleft()
                        self._finish(cand.handle, "expired")
                        continue
                    req = cand
                    break
                if req is None:
                    return
                if req.mode != "generate":
                    # embed/score: no decode slot, no pages — runs one
                    # prefill-family dispatch against the scratch page and
                    # retires immediately (multi-tenant engine only; the
                    # base engine's submit validation never queues these)
                    if not self._acquire_tenant(req):
                        return          # adapter slots pinned: stay queued
                    self._queue_pop(req)
                    self._m_queue_depth.set(len(self._queue))
                    self._admitting = req
                    alloc = free_slot = None
                else:
                    free_slot = next((i for i, s in enumerate(self._slots)
                                      if s is None), None)
                    if free_slot is None:
                        # QoS: a full batch must not gate high-tier work —
                        # evict the cheapest strictly-lower-tier slot and
                        # take its lane (no-op on non-QoS engines)
                        free_slot = self._preempt_for_slot(req)
                    if free_slot is None:
                        return
                    alloc = self._bm.allocate(
                        req.prompt, len(req.prompt) + req.max_new_tokens)
                    if alloc is None:
                        alloc = self._preempt_for_pages(req)
                    if alloc is None:
                        # FIFO admission: park until a retirement frees
                        # pages
                        self._m_blocked.inc()
                        return
                    if not self._acquire_tenant(req):
                        # adapter pool pinned solid: the adapter analog of
                        # page exhaustion — stay queued, release the pages
                        self._bm.free(alloc)
                        self._m_blocked.inc()
                        return
                    self._queue_pop(req)
                    self._m_queue_depth.set(len(self._queue))
                    # between dequeue and slot assignment the request lives
                    # in _admitting so a crash mid-prefill can still
                    # requeue it
                    self._admitting = req
            if req.mode != "generate":
                self._run_passthrough(req)
            elif self._chunk_tokens \
                    and len(req.prompt) > self._chunk_tokens:
                self._admit_chunked(req, alloc, free_slot)
            else:
                self._prefill(req, alloc, free_slot)

    def _acquire_tenant(self, req):
        """Pin the request's tenant resources (LoRA adapter slot) for its
        lifetime; False parks the request in the queue.  Base engine: no
        tenants, always True (MultiTenantEngine overrides)."""
        return True

    def _release_tenant(self, req):
        """Counterpart of :meth:`_acquire_tenant` at retirement."""

    def _run_passthrough(self, req):
        """Execute a non-generate (embed/score) request.  Unreachable in
        the base engine — submit validation rejects those modes."""
        raise RuntimeError(
            f"mode={req.mode!r} request reached the base engine scheduler")

    def _prefill(self, req, alloc, slot_idx):
        if req.handle.admitted_at is None:   # TTFT decomposition: queue_s
            req.handle.admitted_at = time.time()
        S0 = len(req.prompt)
        # hierarchical KV cache: leading pages the radix index matched
        # (or the spill tier resurrected) already hold byte-valid K/V —
        # dispatch only the divergent tail, clamped so at least the last
        # prompt position is computed (its logits seed the first token)
        if alloc.cached_pages:
            cached = min(alloc.cached_pages * self.page_size, S0 - 1)
            if cached > 0:
                return self._prefill_cached(req, alloc, slot_idx, cached)
        s_pad = self._prefill_bucket(S0)
        ids = np.zeros((1, s_pad), np.int64)
        ids[0, :S0] = req.prompt
        table_row = np.asarray(alloc.pages, np.int32)
        table = np.full((1, self.table_width), self._scratch, np.int32)
        table[0, :len(table_row)] = table_row
        lens = np.asarray([S0], np.int32)
        temps = np.asarray([req.sampling.temperature], np.float32)
        prog, traces = self._prefill_program(s_pad)
        n0 = traces[0]
        rkey = self._next_key()
        extra = self._prefill_extra(req)
        guard = self._numeric_guard
        tail = (self._numeric_inject(1),) if guard else ()
        fam = self._prefill_family(s_pad)
        if _perf.needs_cost(fam):
            # capture arg shapes ONCE per family; the cost_analysis
            # re-lower+compile itself runs lazily, off this thread
            _perf.register_cost_thunk(fam, _perf.jit_cost_thunk(
                prog, (self._params, self._bufs, ids, *self._pools,
                       table, lens, temps, rkey, *extra, *tail)))
        # first dispatch of this program = minutes-long XLA compile: the
        # ledger compile window flags self._compiling for the watchdog/
        # health paths, holds programs.compile_in_progress up, and bills
        # the stall to this request's TTFT decomposition
        win = _programs.ledger().compile_window(
            self._prefill_store_key(s_pad), family=fam, replica=self.replica,
            device=self._device_label(), store=self._store(),
            owner=self._model, handles=(req.handle,), engine=self,
            cold=n0 == 0)
        win.attach(prog, (self._params, self._bufs, ids, *self._pools,
                          table, lens, temps, rkey, *extra, *tail))
        t0 = time.perf_counter()
        bad = nstats = None
        try:
            with _tracing.span("serving.prefill",
                               trace_id=req.handle.trace_id,
                               request_id=req.handle.request_id,
                               slot=slot_idx, prompt_len=S0):
                if guard:
                    tok, bad, nstats, *pools = prog(
                        self._params, self._bufs, ids, *self._pools,
                        table, lens, temps, rkey, *extra, *tail)
                else:
                    tok, *pools = prog(self._params, self._bufs, ids,
                                       *self._pools, table, lens, temps,
                                       rkey, *extra)
                self._pools = tuple(pools)
                tok = int(np.asarray(tok)[0])
        finally:
            win.close(traced=traces[0] > n0)
            self._progress_t = time.monotonic()
        if traces[0] > n0:
            self._m_prefill_traces.inc(traces[0] - n0)
        elif traces[0]:
            # warm dispatch: attribute its device time to the program
            # family (a trace+compile wall is not device time — skipped)
            _perf.record(fam, time.perf_counter() - t0)
        self._m_prefill_seconds.observe(time.perf_counter() - t0)
        if guard:
            _numerics.submit(f"serving/{self.replica}", ("logits",), nstats,
                             step=self._iteration)
            if bool(np.asarray(bad)[0]):
                # non-finite first-token logits: fail THIS request before
                # it ever occupies a decode lane; nothing else is touched
                h = req.handle
                h._error = NumericFault(
                    "non-finite logits at prefill", site="logits",
                    stream=f"serving/{self.replica}", step=self._iteration)
                self._m_numeric_faults.inc()
                self._bm.free(alloc)
                self._release_tenant(req)
                self._admitting = None
                self._finish(h, "error")
                return
        slot = _Slot(req, alloc, table_row)
        slot.idx = slot_idx
        slot.last = tok
        slot.produced = 1
        req.handle.status = "running"
        self._slots[slot_idx] = slot
        self._admitting = None
        # persistent host-buffer row for the decode dispatch (rebuilt here
        # and on retire only, never per step)
        i = slot_idx
        self._h_table[i, :] = self._scratch
        self._h_table[i, :len(table_row)] = table_row
        self._h_lens[i] = slot.length
        self._h_temps[i] = slot.temp
        self._h_last[i, 0] = tok
        self._on_admitted(slot, slot_idx)
        if slot.temp > 0:
            self._n_temp += 1
        if self._drafter is not None:
            # draft context = prompt + every emitted token (re-admission
            # after a restart passes prompt+tokens-so-far as the prompt,
            # so the rebuilt index sees the same stream)
            self._drafter.register(i, req.prompt)
            self._drafter.extend(i, [tok])
        self._emit_token(slot, tok)
        self._retire_if_done(slot_idx)

    def _prefill_cached(self, req, alloc, slot_idx, cached):
        """Partial-prefix prefill: the first ``cached`` prompt tokens are
        covered by radix-matched / spill-resurrected pages whose K/V is
        already byte-valid, so ONE chunk-variant dispatch runs just the
        divergent tail at positions ``cached..S0-1`` (the chunk cache
        machinery reused at a nonzero offset — a scheduler change, not a
        program change) and its sampled token seeds decode exactly like a
        monolithic prefill.  Greedy output stays byte-identical: K/V at a
        position is a pure function of the token prefix and the weights,
        so reading the cached run is the same bytes recompute would have
        written.  Attributed to its own ``prefill/<b>@cached<p>`` perf
        family so the roofline table separates tail-only dispatches from
        full prefills."""
        S0 = len(req.prompt)
        tail = S0 - cached
        C = self._prefill_bucket(tail)
        ids = np.zeros((1, C), np.int64)
        ids[0, :tail] = req.prompt[cached:]
        table_row = np.asarray(alloc.pages, np.int32)
        table = np.full((1, self.table_width), self._scratch, np.int32)
        table[0, :len(table_row)] = table_row
        lens = np.asarray([cached], np.int32)
        nvalid = np.asarray([tail], np.int32)
        temps = np.asarray([req.sampling.temperature], np.float32)
        prog, traces = self._prefill_chunk_program(C)
        n0 = traces[0]
        rkey = self._next_key()
        extra = self._prefill_extra(req)
        guard = self._numeric_guard
        gtail = (self._numeric_inject(1),) if guard else ()
        fam = self._prefill_cached_family(C, alloc.cached_pages)
        if _perf.needs_cost(fam):
            _perf.register_cost_thunk(fam, _perf.jit_cost_thunk(
                prog, (self._params, self._bufs, ids, nvalid, *self._pools,
                       table, lens, temps, rkey, *extra, *gtail)))
        win = _programs.ledger().compile_window(
            self._prefill_chunk_store_key(C), family=fam,
            replica=self.replica, device=self._device_label(),
            store=self._store(), owner=self._model,
            handles=(req.handle,), engine=self, cold=n0 == 0)
        win.attach(prog, (self._params, self._bufs, ids, nvalid,
                          *self._pools, table, lens, temps, rkey,
                          *extra, *gtail))
        t0 = time.perf_counter()
        bad = nstats = None
        try:
            with _tracing.span("serving.prefill_cached",
                               trace_id=req.handle.trace_id,
                               request_id=req.handle.request_id,
                               slot=slot_idx, prompt_len=S0,
                               cached_tokens=cached):
                if guard:
                    tok, bad, nstats, *pools = prog(
                        self._params, self._bufs, ids, nvalid,
                        *self._pools, table, lens, temps, rkey,
                        *extra, *gtail)
                else:
                    tok, *pools = prog(self._params, self._bufs, ids,
                                       nvalid, *self._pools, table, lens,
                                       temps, rkey, *extra)
                self._pools = tuple(pools)
                tok = int(np.asarray(tok)[0])
        finally:
            win.close(traced=traces[0] > n0)
            self._progress_t = time.monotonic()
        if traces[0] > n0:
            self._m_prefill_traces.inc(traces[0] - n0)
        elif traces[0]:
            _perf.record(fam, time.perf_counter() - t0)
        self._m_prefill_seconds.observe(time.perf_counter() - t0)
        if guard:
            _numerics.submit(f"serving/{self.replica}", ("logits",), nstats,
                             step=self._iteration)
            if bool(np.asarray(bad)[0]):
                h = req.handle
                h._error = NumericFault(
                    "non-finite logits at prefill", site="logits",
                    stream=f"serving/{self.replica}", step=self._iteration)
                self._m_numeric_faults.inc()
                self._bm.free(alloc)
                self._release_tenant(req)
                self._admitting = None
                self._finish(h, "error")
                return
        slot = _Slot(req, alloc, table_row)
        slot.idx = slot_idx
        slot.last = tok
        slot.produced = 1
        req.handle.status = "running"
        self._slots[slot_idx] = slot
        self._admitting = None
        i = slot_idx
        self._h_table[i, :] = self._scratch
        self._h_table[i, :len(table_row)] = table_row
        self._h_lens[i] = slot.length
        self._h_temps[i] = slot.temp
        self._h_last[i, 0] = tok
        self._on_admitted(slot, slot_idx)
        if slot.temp > 0:
            self._n_temp += 1
        if self._drafter is not None:
            self._drafter.register(i, req.prompt)
            self._drafter.extend(i, [tok])
        self._emit_token(slot, tok)
        self._retire_if_done(slot_idx)

    # ------------------------------------------------- chunked prefill
    def _admit_chunked(self, req, alloc, slot_idx):
        """Admit a long prompt WITHOUT running its prefill: the slot goes
        live immediately with ``prefilled=0`` and ingests chunk-by-chunk
        via :meth:`_advance_prefills`, interleaved with decode — the
        decode batch never waits out a monolithic long-prompt dispatch.
        The lane's persistent host row stays inert (scratch table, length
        0) until the final chunk seeds decode."""
        table_row = np.asarray(alloc.pages, np.int32)
        if req.handle.admitted_at is None:   # TTFT decomposition: queue_s
            req.handle.admitted_at = time.time()
        slot = _Slot(req, alloc, table_row)
        slot.idx = slot_idx
        # hierarchical KV cache: ingestion starts PAST the cached shared
        # run (chunked prefill already admits at arbitrary offsets — the
        # radix hit just moves the starting offset); clamped so the final
        # chunk computes at least the last prompt position, whose logits
        # seed decode
        slot.prefilled = min(alloc.cached_pages * self.page_size,
                             max(len(req.prompt) - 1, 0))
        req.handle.status = "running"
        self._slots[slot_idx] = slot
        self._admitting = None
        # _n_temp counts LIVE slots with temperature: incremented at
        # admission (not at go-live) so the retire paths' _clear_slot_row
        # decrement stays balanced whether or not ingestion completed
        if slot.temp > 0:
            self._n_temp += 1

    def _advance_prefills(self):
        """One scheduler iteration's chunked-prefill work: up to
        ``prefill_chunk_tokens`` prompt tokens across the mid-prefill
        slots, round-robin so concurrent long prompts share the budget
        fairly.  Cancelled/expired slots retire here — they must not wait
        for a decode lane they never reached."""
        if not self._chunk_tokens:
            return
        prefilling = [i for i, s in enumerate(self._slots)
                      if s is not None and s.prefilled is not None]
        if not prefilling:
            return
        start = self._prefill_rr
        order = sorted(prefilling,
                       key=lambda i: (i - start) % self.num_slots)
        budget = self._chunk_tokens
        for i in order:
            if budget <= 0:
                return
            s = self._slots[i]
            if s is None or s.prefilled is None:
                continue
            h = s.handle
            if h.cancelled or (s.deadline is not None
                               and time.time() > s.deadline):
                status = "cancelled" if h.cancelled else "expired"
                if status == "expired":
                    self._count_preemption(s.req, "deadline")
                self._bm.free(s.alloc)
                self._release_tenant(s.req)
                self._slots[i] = None
                self._clear_slot_row(i, s)
                self._finish(h, status)
                continue
            budget -= self._prefill_chunk_step(i, s)
            self._prefill_rr = (i + 1) % self.num_slots

    def _prefill_chunk_step(self, i, slot):
        """Dispatch ONE chunk of slot ``i``'s prompt: tokens
        ``prefilled .. prefilled+C-1`` (right-padded on the last chunk)
        through the chunk cache variant at positions ``prefilled..``.
        Pad-lane junk K/V lands past the valid length (or drops OOB) —
        invisible to seq_lens masking, overwritten by the first decode
        write — so the padded dispatch is byte-equivalent to an exact one.
        The FINAL chunk's sampled token seeds decode and the lane goes
        live.  Returns the number of real prompt tokens ingested (the
        budget unit)."""
        req = slot.req
        C = self._chunk_tokens
        S0 = len(req.prompt)
        c0 = slot.prefilled
        nval = min(C, S0 - c0)
        final = c0 + nval >= S0
        ids = np.zeros((1, C), np.int64)
        ids[0, :nval] = req.prompt[c0:c0 + nval]
        table = np.full((1, self.table_width), self._scratch, np.int32)
        table[0, :len(slot.table_row)] = slot.table_row
        lens = np.asarray([c0], np.int32)
        nvalid = np.asarray([nval], np.int32)
        temps = np.asarray([slot.temp], np.float32)
        prog, traces = self._prefill_chunk_program(C)
        n0 = traces[0]
        rkey = self._next_key()
        extra = self._prefill_extra(req)
        guard = self._numeric_guard
        tail = (self._numeric_inject(1),) if guard else ()
        fam = self._prefill_chunk_family(C)
        if _perf.needs_cost(fam):
            _perf.register_cost_thunk(fam, _perf.jit_cost_thunk(
                prog, (self._params, self._bufs, ids, nvalid, *self._pools,
                       table, lens, temps, rkey, *extra, *tail)))
        win = _programs.ledger().compile_window(
            self._prefill_chunk_store_key(C), family=fam,
            replica=self.replica, device=self._device_label(),
            store=self._store(), owner=self._model,
            handles=(req.handle,), engine=self, cold=n0 == 0)
        win.attach(prog, (self._params, self._bufs, ids, nvalid,
                          *self._pools, table, lens, temps, rkey,
                          *extra, *tail))
        t0 = time.perf_counter()
        bad = nstats = None
        try:
            with _tracing.span("serving.prefill_chunk",
                               trace_id=req.handle.trace_id,
                               request_id=req.handle.request_id,
                               slot=i, chunk_start=c0, chunk_tokens=nval):
                if guard:
                    tok, bad, nstats, *pools = prog(
                        self._params, self._bufs, ids, nvalid,
                        *self._pools, table, lens, temps, rkey,
                        *extra, *tail)
                else:
                    tok, *pools = prog(self._params, self._bufs, ids,
                                       nvalid, *self._pools, table, lens,
                                       temps, rkey, *extra)
                self._pools = tuple(pools)
                tok = int(np.asarray(tok)[0])
        finally:
            win.close(traced=traces[0] > n0)
            self._progress_t = time.monotonic()
        if traces[0] > n0:
            self._m_prefill_chunk_traces.inc(traces[0] - n0)
        elif traces[0]:
            _perf.record(fam, time.perf_counter() - t0)
        self._m_prefill_chunk_seconds.observe(time.perf_counter() - t0)
        if guard:
            _numerics.submit(f"serving/{self.replica}", ("logits",), nstats,
                             step=self._iteration)
            if bool(np.asarray(bad)[0]):
                # non-finite chunk logits: fail exactly this request (the
                # decode-lane helper does the full retire dance; the lane
                # backfills at the next admit)
                self._fail_numeric(i)
                return nval
        slot.prefilled = c0 + nval
        if not final:
            return nval
        # last chunk: its sampled token is the monolithic prefill's first
        # token — the lane goes live for the decode dispatch
        slot.prefilled = None
        slot.last = tok
        slot.produced = 1
        self._h_table[i, :] = self._scratch
        self._h_table[i, :len(slot.table_row)] = slot.table_row
        self._h_lens[i] = slot.length
        self._h_temps[i] = slot.temp
        self._h_last[i, 0] = tok
        self._on_admitted(slot, i)
        if self._drafter is not None:
            self._drafter.register(i, req.prompt)
            self._drafter.extend(i, [tok])
        self._emit_token(slot, tok)
        self._retire_if_done(i)
        return nval

    def _step_key(self):
        """PRNG key for a decode dispatch.  A batch with no temperature
        rows never consumes randomness (the batched sampler/verifier
        returns argmax for ``temps <= 0`` rows), so the hot greedy path
        skips the per-step ``fold_in`` device dispatch and reuses the base
        key — one less host->device round trip per step."""
        return self._next_key() if self._n_temp else self._base_key

    def _step_once(self):
        # chaos site: an injected fn raising a TransientError here drives
        # the auto-restart + requeue path through the real scheduler
        # (covers BOTH the plain decode step and the speculative verify
        # step — a crash between verifies must requeue with exactly the
        # accepted-token state)
        _faults.maybe("serving.step_crash")
        _faults.maybe(self._site_step_crash)  # replica-scoped chaos site
        # mid-prefill chunked slots stay OUT of the decode batch: their
        # host rows are inert (scratch table, length 0) so the dispatch
        # computes a junk lane nobody reads
        active = [i for i, s in enumerate(self._slots)
                  if s is not None and s.prefilled is None]
        if self._spec_k:
            return self._verify_once(active)
        return self._plain_step(active)

    # ----------------------------------------------- multi-tenant hooks
    # Extension points MultiTenantEngine fills in; the base engine's
    # returns keep every dispatch signature and program family unchanged.
    def _prefill_family(self, s_pad):
        return f"prefill/{s_pad}{self._fam_suffix}{self._mp_suffix}"

    def _prefill_chunk_family(self, c):
        return f"prefill_chunk/{c}{self._fam_suffix}{self._mp_suffix}"

    def _prefill_cached_family(self, c, cached_pages):
        """Partial-prefix prefill attribution: the dispatch runs the
        chunk program at width ``c`` but only because ``cached_pages``
        leading pages were served from the hierarchical cache — a
        different roofline (tail-only compute) than a full prefill, so
        perf.is_cached_prefill_family can key hints on it."""
        return (f"prefill/{c}@cached{cached_pages}"
                f"{self._fam_suffix}{self._mp_suffix}")

    def _decode_family(self):
        return f"decode{self._flash_tag}{self._fam_suffix}{self._mp_suffix}"

    def _verify_family(self):
        return f"verify/k{self._spec_k}{self._fam_suffix}{self._mp_suffix}"

    def _prefill_extra(self, req):
        """Host arrays appended to the prefill dispatch (adapter ids,
        grammar mask, adapter pools)."""
        return ()

    def _step_extra(self):
        """Host arrays appended to the decode dispatch."""
        return ()

    def _verify_extra(self, active):
        """Host arrays appended to the verify dispatch (reads the draft
        buffers _h_ids/_h_dlen the caller just filled)."""
        return ()

    def _filter_draft(self, i, draft):
        """Trim a slot's n-gram draft before verification (a constrained
        row truncates at the first grammar-illegal token)."""
        return draft

    def _on_admitted(self, slot, i):
        """A request landed in decode lane ``i`` (persistent host rows
        already rebuilt)."""

    def _budget_status(self, slot):
        """Terminal status when ``max_new_tokens`` runs out.  The base
        engine's budget exhaustion IS completion; a grammar-constrained
        row cut off mid-document reports ``truncated`` instead
        (MultiTenantEngine)."""
        return "completed"

    # ------------------------------------------------ NaN-safe serving
    def _numeric_inject(self, B=None):
        """Trailing ``[B] f32`` inject vector for guarded dispatches: all
        zeros disarmed (the shape-stable no-op — the program adds it to
        the logits), NaN in lane :func:`~.numerics.nan_inject_row` when
        the ``numerics.nan_inject`` fault tripped since the last call."""
        if B is None:
            B = self.num_slots
        inj = np.zeros((B,), np.float32)
        v = _numerics.consume_nan_inject()
        if not np.isfinite(v):
            inj[_numerics.nan_inject_row() % B] = v
        return inj

    def _fail_numeric(self, i):
        """Retire decode lane ``i`` with a numeric fault: exactly this
        request errors (``status="error"``, ``handle._error`` a
        :class:`NumericFault`), its pages free and the lane backfills at
        the next admit — the batch's other rows are untouched."""
        slot = self._slots[i]
        h = slot.handle
        h._error = NumericFault(
            f"non-finite logits in decode lane {i}", site="logits",
            stream=f"serving/{self.replica}", step=self._iteration)
        self._m_numeric_faults.inc()
        self._bm.free(slot.alloc)
        self._release_tenant(slot.req)
        self._slots[i] = None
        self._clear_slot_row(i, slot)
        self._finish(h, "error")

    def _quant_drift_tick(self):
        """Sampled quantization-drift gauge (quant engines): one
        Int8Linear per tick, dequantize its stored payload and measure
        the requantize-on-fresh-absmax roundtrip error — drift above the
        rounding floor means the frozen ``w_scale`` no longer matches
        the weights it quantized."""
        from ..quantization import Int8Linear

        layers = [m for m in self._model.sublayers()
                  if isinstance(m, Int8Linear)]
        if not layers:
            return
        m = layers[self._drift_idx % len(layers)]
        self._drift_idx += 1
        q = np.asarray(m.weight_int8._value, np.float32)
        w = q * m.w_scale
        amax = float(np.abs(w).max())
        if amax <= 0.0:
            self._m_quant_drift.set(0.0)
            return
        s2 = amax / m._qmax
        q2 = np.clip(np.rint(w / s2), -m._qmax, m._qmax)
        drift = float(np.mean(np.abs(q2 * s2 - w))) / amax
        self._m_quant_drift.set(drift)

    def _plain_step(self, active):
        prog, traces = self._step_program()
        n0 = traces[0]
        rkey = self._step_key()
        extra = self._step_extra()
        guard = self._numeric_guard
        tail = (self._numeric_inject(),) if guard else ()
        fam = self._decode_family()
        if _perf.needs_cost(fam):
            _perf.register_cost_thunk(fam, _perf.jit_cost_thunk(
                prog, (self._params, self._bufs, self._h_last, *self._pools,
                       self._h_table, self._h_lens, self._h_temps, rkey,
                       *extra, *tail)))
        if _tracing._ACTIVE:
            # one span per batched iteration, LINKING every active
            # request's trace id (a decode step serves many traces at once
            # — the OTLP links model, not one parent)
            cm = _tracing.span(
                "serving.decode_step", iteration=self._iteration,
                batch=len(active),
                links=[self._slots[i].handle.trace_id for i in active])
        else:  # hot path: one flag read, no span/link-list construction
            cm = _tracing.NOOP
        # first decode dispatch = XLA compile; every active request waits
        # out the whole stall, so the window bills each of their TTFTs
        win = _programs.ledger().compile_window(
            self._step_store_key(), family=fam, replica=self.replica,
            device=self._device_label(), store=self._store(),
            owner=self._model,
            handles=[self._slots[i].handle for i in active],
            engine=self, cold=n0 == 0)
        if n0 == 0:
            win.attach(prog, (self._params, self._bufs, self._h_last,
                              *self._pools, self._h_table, self._h_lens,
                              self._h_temps, rkey, *extra, *tail))
        t0 = time.perf_counter()
        bad = nstats = None
        try:
            with cm:
                if guard:
                    tok, bad, nstats, *pools = prog(
                        self._params, self._bufs, self._h_last,
                        *self._pools, self._h_table, self._h_lens,
                        self._h_temps, rkey, *extra, *tail)
                else:
                    tok, *pools = prog(self._params, self._bufs,
                                       self._h_last, *self._pools,
                                       self._h_table, self._h_lens,
                                       self._h_temps, rkey, *extra)
                self._pools = tuple(pools)
                tok = np.asarray(tok)
        finally:
            win.close(traced=traces[0] > n0)
            self._progress_t = time.monotonic()
        if traces[0] > n0:
            self._m_step_traces.inc(traces[0] - n0)
        else:
            _perf.record(fam, time.perf_counter() - t0)
        self._m_step_seconds.observe(time.perf_counter() - t0)
        self._iteration += 1
        if guard:
            _numerics.submit(f"serving/{self.replica}", ("logits",), nstats,
                             step=self._iteration)
            bad = np.asarray(bad)
        for i in active:
            if guard and bad[i]:
                # this lane's logits went non-finite: fail exactly this
                # request; finite lanes below emit byte-identical tokens
                self._fail_numeric(i)
                continue
            s = self._slots[i]
            s.length += 1
            s.produced += 1
            s.last = int(tok[i])
            self._h_lens[i] = s.length
            self._h_last[i, 0] = s.last
            self._emit_token(s, s.last)
            if not self._retire_if_done(i) and self._drafter is not None:
                # a speculative engine can route no-draft iterations through
                # this path: the drafter's context must keep growing or it
                # would never find a matching suffix again
                self._drafter.extend(i, [s.last])

    def _verify_once(self, active):
        """One speculative iteration: draft up to k tokens per slot from
        the n-gram index, verify all of them (plus the pending last token)
        in ONE compiled multi-token dispatch, then consume the longest
        accepted prefix per slot + the bonus/resample token — 1..k+1
        tokens per slot per step, with retire/deadline/EOS checks applied
        per emitted token exactly like the single-token path."""
        K = self._spec_k
        drafts = {}
        for i in active:
            s = self._slots[i]
            self._h_ids[i, 0] = s.last
            self._h_ids[i, 1:] = 0
            # never draft past the request budget or the position cap: the
            # bonus token always lands, so at most remaining-1 drafts fit
            cap = min(K, s.max_new - s.produced - 1,
                      self.max_model_len - s.length - 1)
            d = self._drafter.propose(i, cap) if cap > 0 else []
            d = self._filter_draft(i, d)
            if d:
                self._h_ids[i, 1:1 + len(d)] = d
            self._h_dlen[i] = len(d)
            drafts[i] = d
        if not any(drafts.values()):
            # nothing drafted anywhere this iteration: the (k+1)-wide
            # verify dispatch would pay (k+1)x attention/FFN to emit one
            # token per slot — the plain step is the same result cheaper
            return self._plain_step(active)
        prog, traces = self._verify_program()
        n0 = traces[0]
        rkey = self._step_key()
        extra = self._verify_extra(active)
        guard = self._numeric_guard
        tail = (self._numeric_inject(),) if guard else ()
        fam = self._verify_family()
        if _perf.needs_cost(fam):
            _perf.register_cost_thunk(fam, _perf.jit_cost_thunk(
                prog, (self._params, self._bufs, self._h_ids, *self._pools,
                       self._h_table, self._h_lens, self._h_dlen,
                       self._h_temps, rkey, *extra, *tail)))
        if _tracing._ACTIVE:
            cm = _tracing.span(
                "serving.verify_step", iteration=self._iteration,
                batch=len(active), k=K,
                drafted=int(sum(len(drafts[i]) for i in active)),
                links=[self._slots[i].handle.trace_id for i in active])
        else:
            cm = _tracing.NOOP
        win = _programs.ledger().compile_window(
            self._verify_store_key(K), family=fam, replica=self.replica,
            device=self._device_label(), store=self._store(),
            owner=self._model,
            handles=[self._slots[i].handle for i in active],
            engine=self, cold=n0 == 0)
        if n0 == 0:
            win.attach(prog, (self._params, self._bufs, self._h_ids,
                              *self._pools, self._h_table, self._h_lens,
                              self._h_dlen, self._h_temps, rkey,
                              *extra, *tail))
        t0 = time.perf_counter()
        bad = nstats = None
        try:
            with cm:
                if guard:
                    targets, accept, bad, nstats, *pools = prog(
                        self._params, self._bufs, self._h_ids, *self._pools,
                        self._h_table, self._h_lens, self._h_dlen,
                        self._h_temps, rkey, *extra, *tail)
                else:
                    targets, accept, *pools = prog(
                        self._params, self._bufs, self._h_ids, *self._pools,
                        self._h_table, self._h_lens, self._h_dlen,
                        self._h_temps, rkey, *extra)
                self._pools = tuple(pools)
                targets = np.asarray(targets)
                accept = np.asarray(accept)
        finally:
            win.close(traced=traces[0] > n0)
            self._progress_t = time.monotonic()
        if traces[0] > n0:
            self._m_verify_traces.inc(traces[0] - n0)
        else:
            _perf.record(fam, time.perf_counter() - t0)
        self._m_step_seconds.observe(time.perf_counter() - t0)
        self._iteration += 1
        if guard:
            _numerics.submit(f"serving/{self.replica}", ("logits",), nstats,
                             step=self._iteration)
            bad = np.asarray(bad)
        proposed = accepted = 0
        for i in active:
            if guard and bad[i]:
                self._fail_numeric(i)
                continue
            s = self._slots[i]
            d = drafts[i]
            a = 0
            while a < len(d) and accept[i, a]:
                a += 1
            proposed += len(d)
            emitted = [int(t) for t in d[:a]] + [int(targets[i, a])]
            # pool state: positions length..length+a now hold the old
            # `last` + the a accepted drafts; rejected tail K/V sits past
            # the new length, where seq_lens masking hides it until the
            # next chunk write overwrites it (rollback = lens stays put)
            done = False
            emitted_n = 0
            for tok in emitted:
                s.length += 1
                s.produced += 1
                s.last = tok
                self._h_lens[i] = s.length
                self._h_last[i, 0] = tok
                self._emit_token(s, tok)
                emitted_n += 1
                if self._retire_if_done(i):
                    done = True
                    break
            # accepted = drafts that became OUTPUT tokens: early retirement
            # (EOS mid-draft, deadline, budget) discards the rest, and the
            # acceptance-rate gauge must not credit discarded tokens
            accepted += min(emitted_n, a)
            if not done:
                self._drafter.extend(i, emitted)
        if proposed:
            self._m_spec_proposed.inc(proposed)
            self._spec_proposed_total += proposed
        if accepted:
            self._m_spec_accepted.inc(accepted)
            self._spec_accepted_total += accepted
        if self._spec_proposed_total:
            self._m_accept_rate.set(
                self._spec_accepted_total / self._spec_proposed_total)

    def _emit_token(self, slot, tok):
        h = slot.handle
        now = time.time()
        # QoS engines label the latency histograms per tier (the bench's
        # per-tier p95s); non-tiered requests keep the label-less children
        # so existing exact-match lookups stay resolvable
        tier = slot.req.tier
        if h.first_token_at is None:
            h.first_token_at = now
            h.first_token_iteration = self._iteration
            if tier is not None:
                self._m_ttft.observe(now - h.submitted_at, tier=tier)
            else:
                self._m_ttft.observe(now - h.submitted_at)
            if h.compile_s > 0.0:
                # compile-paying first token: parallel family (not a label
                # on serving.ttft_seconds — existing per-replica children
                # and their bucket alignment stay byte-identical) so p95
                # TTFT dashboards can subtract cold starts
                self._m_ttft_cold.observe(now - h.submitted_at)
        elif slot.last_token_t is not None:
            if tier is not None:
                self._m_itl.observe(now - slot.last_token_t, tier=tier)
            else:
                self._m_itl.observe(now - slot.last_token_t)
        slot.last_token_t = now
        h.token_ids.append(tok)
        h.token_times.append(now)
        h._events.put(("token", tok))
        self._m_tokens.inc()

    def _retire_if_done(self, i):
        slot = self._slots[i]
        h = slot.handle
        status = None
        if h.cancelled:
            status = "cancelled"
        elif slot.eos is not None and slot.last == slot.eos:
            status = "completed"
        elif slot.produced >= slot.max_new:
            status = self._budget_status(slot)
        elif slot.deadline is not None and time.time() > slot.deadline:
            status = "expired"
            self._count_preemption(slot.req, "deadline")
        if status is None:
            return False
        self._bm.free(slot.alloc)
        self._release_tenant(slot.req)
        self._slots[i] = None
        self._clear_slot_row(i, slot)
        self._finish(h, status)
        return True

    def _clear_slot_row(self, i, slot):
        """Reset slot ``i``'s persistent host-buffer row (and drafter
        state) after retirement — the row points at scratch again so the
        next dispatch treats the lane as inactive."""
        self._h_table[i, :] = self._scratch
        self._h_lens[i] = 0
        self._h_temps[i] = 0.0
        self._h_last[i, 0] = 0
        if self._spec_k:
            self._h_ids[i, :] = 0
            self._h_dlen[i] = 0
        if slot.temp > 0:
            self._n_temp -= 1
        if self._drafter is not None:
            self._drafter.release(i)

    def _reset_host_buffers(self):
        """Full reset (engine restart / stop): every lane inactive."""
        self._h_table[:] = self._scratch
        self._h_lens[:] = 0
        self._h_temps[:] = 0.0
        self._h_last[:] = 0
        if self._spec_k:
            self._h_ids[:] = 0
            self._h_dlen[:] = 0
        self._n_temp = 0
        if self._drafter is not None:
            self._drafter.reset()

    def _finish(self, handle, status):
        self._release_hbm(handle)
        handle.status = status
        handle.finished_at = time.time()
        handle.finished_iteration = self._iteration
        if status == "completed":
            # completed-request duration EMA feeds deadline-aware shedding
            dur = handle.finished_at - handle.submitted_at
            self._ema_request_s = dur if self._ema_request_s is None \
                else 0.8 * self._ema_request_s + 0.2 * dur
            tier = getattr(handle, "tier", None)
            if tier is not None:
                # per-tier EMA: a slow batch request must not inflate the
                # realtime deadline estimate (see _check_deadline_meetable)
                prev = self._tier_ema.get(tier)
                self._tier_ema[tier] = dur if prev is None \
                    else 0.8 * prev + 0.2 * dur
        if self._slo is not None and status in ("completed", "expired") \
                and handle.mode == "generate":
            # expired = the deadline preempted it: an SLO miss by
            # definition, whatever its timeline says.  cancelled/stopped/
            # error requests are excluded — they measure the caller or the
            # engine, not the latency promise.
            self._slo.observe(handle, met_override=False
                              if status == "expired" else None)
        if self._tier_slo and status in ("completed", "expired") \
                and handle.mode == "generate":
            acct = self._tier_slo.get(getattr(handle, "tier", None))
            if acct is not None:
                acct.observe(handle, met_override=False
                             if status == "expired" else None)
        self._m_requests.inc(status=status)
        handle._events.put(("done", status))
        handle._done.set()

    def _update_gauges(self):
        # throttled: gauges are dashboards, not control flow — refreshing
        # six of them before EVERY decode dispatch was measurable host
        # overhead on the sub-ms step path (queue_depth is also refreshed
        # eagerly at submit/admit, where it actually changes)
        now = time.monotonic()
        if now - self._gauges_t < 0.05:
            return
        self._gauges_t = now
        n = sum(1 for s in self._slots if s is not None)
        self._m_queue_depth.set(len(self._queue))
        self._m_active.set(n)
        self._m_occupancy.set(n / self.num_slots)
        self._m_page_util.set(self._bm.utilization())
        self._m_pages_used.set(self._bm.used_pages)
        self._m_health.set(_HEALTH_CODE.get(self.health, 1))
        if self._qos is not None:
            for tname, depth in self._queue.depths().items():
                self._m_tier_depth.set(depth, tier=tname)
            active = dict.fromkeys(self._qos.names, 0)
            for s in self._slots:
                if s is not None and s.req.tier in active:
                    active[s.req.tier] += 1
            for tname, cnt in active.items():
                self._m_tier_active.set(cnt, tier=tname)
        if self.weight_dtype == "int8" and now - self._drift_t > 5.0:
            # quant drift is a slow dashboard (host-side weight walk):
            # one sampled layer every few seconds, never per step
            self._drift_t = now
            self._quant_drift_tick()
        if self._numeric_guard and now - self._npoll_t > 0.5:
            # resolve THIS replica's pending numerics table (one small
            # device sync) so the numerics.* gauges and /statusz stay
            # fresh; never raising — per-row failure is the guard's job,
            # an abort-level checker must not kill the scheduler thread
            self._npoll_t = now
            _numerics.poll(f"serving/{self.replica}", raise_on_fault=False)

    # --------------------------------------------------------------- health
    def health_state(self):
        """The health state machine surfaced on /healthz and /statusz:

        - ``healthy`` — scheduler progressing, queue under pressure limits;
        - ``degraded`` — serving, but queue pressure, a stalled scheduler,
          or a recent auto-restart says trouble (reasons list which);
        - ``draining`` — graceful rundown, no new admissions (503);
        - ``stopped`` / ``error`` — not serving.
        """
        if self._error is not None:
            return {"state": "error", "reasons": [repr(self._error)]}
        if self._draining:
            return {"state": "draining", "reasons": ["drain requested"]}
        if not self._started:
            return {"state": "stopped", "reasons": []}
        reasons = []
        qd = len(self._queue)
        if self._max_queue and qd >= max(1, int(0.8 * self._max_queue)):
            reasons.append(f"queue_pressure:{qd}/{self._max_queue}")
        stamp = self._progress_t
        busy = qd or any(s is not None for s in self._slots)
        if busy and stamp is not None and not self._compiling:
            age = time.monotonic() - stamp
            if age > self._degraded_stall_s:
                reasons.append(f"scheduler_stalled:{age:.2f}s")
        if self._last_restart_t is not None and \
                time.monotonic() - self._last_restart_t \
                < self._restart_cooldown_s:
            reasons.append(f"recent_restart:{self._engine_restarts}")
        if self._qos is not None:
            bo = self._brownout()
            if bo["level"]:
                # a brownout is degraded-but-serving: high tiers are fine
                # BY CONSTRUCTION of the shed, but operators must see it
                reasons.append(f"brownout:L{bo['level']}:{bo['state']}")
        return {"state": "degraded" if reasons else "healthy",
                "reasons": reasons}

    @property
    def health(self):
        return self.health_state()["state"]

    # -------------------------------------------------------------- insight
    @property
    def block_manager(self):
        return self._bm

    @property
    def slo_accountant(self):
        """The replica's SLO accountant (None unless ``slo=`` was set)."""
        return self._slo

    @property
    def acceptance_rate(self):
        """Lifetime speculative acceptance (None before any proposal)."""
        if not self._spec_proposed_total:
            return None
        return self._spec_accepted_total / self._spec_proposed_total

    def stats(self):
        st = {
            "replica": self.replica,
            "iteration": self._iteration,
            "queue_depth": len(self._queue),
            "active_slots": sum(1 for s in self._slots if s is not None),
            "num_slots": self.num_slots,
            "pages_in_use": self._bm.used_pages,
            "num_pages": self._bm.num_pages,
            "page_utilization": self._bm.utilization(),
            "step_traces": self.step_traces,
            # quantized-serving surface: what the pools are made of and
            # what a page/token costs in HBM (scale pools included)
            "kv_dtype": self.kv_dtype,
            "weight_dtype": self.weight_dtype,
            "pool_dtype": self._pool_dtype,
            # per-shard under mp (the per-chip capacity unit)
            "bytes_per_page": self._bytes_per_page,
            "kv_bytes_per_token": self._bytes_per_page / self.page_size,
            "mp": self._mp,
            "numeric_guard": self._numeric_guard,
            "prefill_chunk_tokens": self._chunk_tokens,
            "prefilling_slots": sum(
                1 for s in self._slots
                if s is not None and s.prefilled is not None),
        }
        if self._spec_k:
            st["speculative"] = {
                "k": self._spec_k,
                "proposed": self._spec_proposed_total,
                "accepted": self._spec_accepted_total,
                "acceptance_rate": self.acceptance_rate,
            }
        if self._prefix_sharing:
            # hierarchical-cache surface: hit/saved-token accounting (hit
            # TOKENS, not counts — the satellite fix) plus, in radix
            # mode, the resident-prefix summary the cluster's
            # deepest-match placement consumes via ReplicaPool.stats()
            bm_stats = self._bm.stats()
            st["prefix_cache"] = bm_stats.get("prefix_cache")
            summ = self.prefix_index_summary()
            if summ is not None:
                st["prefix_index"] = summ
        return st

    def _statusz(self):
        """/statusz provider: stats + the live slot table (diagnostic
        snapshot — reads race the scheduler thread benignly)."""
        st = self.stats()
        st["kv_cache"] = self._bm.stats()   # pool dtype + bytes/page live
        # memory observability: this replica's ledger owner rows (cheap —
        # no live-array walk; signal-path rule: no engine lock is held),
        # the pool tuple's actual per-dtype residency, and the admission
        # pre-flight state
        st["memory"] = {
            "owners": _obs_memory.ledger().owner_rows(replica=self.replica),
            "pool_bytes_by_dtype": self.pool_bytes_by_dtype(),
            # per-chip residency under mp (global // mp — the head dim
            # splits exactly; == pool_bytes_by_dtype at mp=1)
            "pool_shard_bytes_by_dtype": {
                dt: b // self._mp
                for dt, b in self.pool_bytes_by_dtype().items()},
            "fixed_bytes": self._fixed_bytes,
            "committed_pages": self._committed_pages,
            "hbm_budget_bytes": _obs_memory.hbm_budget_bytes(),
        }
        st["started"] = self._started
        st["error"] = repr(self._error) if self._error is not None else None
        st["health"] = self.health_state()
        st["engine_restarts"] = self._engine_restarts
        st["draining"] = self._draining
        st["typical_request_s"] = self._ema_request_s
        if self._slo is not None:
            st["slo"] = self._slo.summary()
        if self._qos is not None:
            # per-tier queue table + ladder rung: makes a brownout's shed
            # decisions attributable from the status page alone
            active = dict.fromkeys(self._qos.names, 0)
            for s in self._slots:
                if s is not None and s.req.tier in active:
                    active[s.req.tier] += 1
            st["qos"] = {
                "config": self._qos.to_dict(),
                "brownout": self._brownout(),
                "queue_by_tier": self._queue.depths(),
                "active_by_tier": active,
                "typical_request_s_by_tier": dict(self._tier_ema),
                "slo_by_tier": {name: acct.summary()
                                for name, acct in self._tier_slo.items()},
            }
        if self._progress_t is not None:
            st["last_progress_age_s"] = time.monotonic() - self._progress_t
        slots = []
        for i, s in enumerate(self._slots):
            if s is None:
                slots.append(None)
                continue
            slots.append({"slot": i, "request_id": s.handle.request_id,
                          "trace_id": s.handle.trace_id,
                          "status": s.handle.status, "length": s.length,
                          "produced": s.produced, "max_new": s.max_new,
                          "pages": len(s.table_row),
                          "prefilled": s.prefilled})
        st["slots"] = slots
        return st
