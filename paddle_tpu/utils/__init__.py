"""paddle.utils (reference: python/paddle/utils/) — misc helpers."""

from . import download  # noqa: F401
from .summary_writer import SummaryWriter  # noqa: F401
# custom-op plugin surface (reference: paddle.utils.cpp_extension / PD_BUILD_OP)
from ..framework.custom_op import register_op, load_op_library  # noqa: F401


def try_import(module_name, err_msg=None):
    import importlib

    try:
        return importlib.import_module(module_name)
    except ImportError:
        raise ImportError(err_msg or f"{module_name} is not installed")


def run_check():
    """paddle.utils.run_check: verify the device stack end-to-end."""
    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    x = jnp.ones((8, 8))
    y = jax.jit(lambda a: a @ a)(x)
    ok = float(y[0, 0]) == 8.0
    print(f"PaddleTPU works on {dev.platform}:{dev.id} "
          f"({'OK' if ok else 'FAILED'}), {jax.device_count()} device(s) visible")
    return ok


def require_version(min_version, max_version=None):
    return True


def deprecated(update_to="", since="", reason="", level=0):
    def wrapper(fn):
        return fn

    return wrapper
