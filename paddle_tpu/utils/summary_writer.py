"""Scalar summary writer (VisualDL / TensorBoard-analog, SURVEY.md §5.5).

Writes JSONL scalar events (always) and mirrors to TensorBoard via
jax.profiler-compatible layout when tensorboardX is available (it is not in
this image, so JSONL is the format of record; it is trivially plottable).
"""

from __future__ import annotations

import json
import os
import time


class SummaryWriter:
    def __init__(self, logdir="./log"):
        self.logdir = logdir
        os.makedirs(logdir, exist_ok=True)
        self._f = open(os.path.join(logdir, "scalars.jsonl"), "a")

    def add_scalar(self, tag, value, step=None, walltime=None):
        self._f.write(json.dumps({
            "tag": tag, "value": float(value), "step": step,
            "time": walltime or time.time(),
        }) + "\n")

    def add_scalars(self, main_tag, tag_scalar_dict, step=None):
        for k, v in tag_scalar_dict.items():
            self.add_scalar(f"{main_tag}/{k}", v, step)

    def add_text(self, tag, text, step=None):
        self._f.write(json.dumps({"tag": tag, "text": str(text), "step": step,
                                  "time": time.time()}) + "\n")

    def flush(self):
        self._f.flush()

    def close(self):
        try:
            self._f.flush()
            self._f.close()
        except ValueError:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
