"""Scalar summary writer (VisualDL / TensorBoard-analog, SURVEY.md §5.5).

Writes JSONL scalar events (trivially plottable, the greppable record) AND
real TensorBoard event files via the dependency-free TFRecord/proto encoder
in :mod:`._tfevents` — point actual TensorBoard at ``logdir``.
"""

from __future__ import annotations

import json
import os
import time


class SummaryWriter:
    def __init__(self, logdir="./log"):
        self.logdir = logdir
        os.makedirs(logdir, exist_ok=True)
        self._f = open(os.path.join(logdir, "scalars.jsonl"), "a")
        from ._tfevents import TFEventWriter

        self._tb = TFEventWriter(logdir)

    def add_scalar(self, tag, value, step=None, walltime=None):
        self._f.write(json.dumps({
            "tag": tag, "value": float(value), "step": step,
            "time": walltime or time.time(),
        }) + "\n")
        self._tb.add_scalar(tag, value, step, walltime)

    def add_scalars(self, main_tag, tag_scalar_dict, step=None):
        for k, v in tag_scalar_dict.items():
            self.add_scalar(f"{main_tag}/{k}", v, step)

    def add_text(self, tag, text, step=None):
        self._f.write(json.dumps({"tag": tag, "text": str(text), "step": step,
                                  "time": time.time()}) + "\n")

    def flush(self):
        self._f.flush()
        self._tb.flush()

    def close(self):
        try:
            self._f.flush()
            self._f.close()
        except ValueError:
            pass
        self._tb.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
