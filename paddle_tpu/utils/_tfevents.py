"""Minimal TensorBoard event-file writer — no TF/tensorboard dependency.

Hand-encodes the two protos scalar logging needs (Event{wall_time, step,
summary} and Summary{value{tag, simple_value}}) and frames them in the
TFRecord format (length + masked crc32c of length, payload, masked crc32c
of payload).  Real TensorBoard reads the result.  Reference analog: the
event writer underneath VisualDL/tensorboardX.
"""

from __future__ import annotations

import os
import socket
import struct
import time

# ----------------------------------------------------------------- crc32c
_CRC_TABLE = []


def _build_table():
    poly = 0x82F63B78
    for n in range(256):
        c = n
        for _ in range(8):
            c = (c >> 1) ^ poly if c & 1 else c >> 1
        _CRC_TABLE.append(c)


_build_table()


def _crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return ((((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF)


# ------------------------------------------------------------ protobuf bits
def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _field_bytes(num: int, payload: bytes) -> bytes:
    return _varint((num << 3) | 2) + _varint(len(payload)) + payload


def _field_double(num: int, v: float) -> bytes:
    return _varint((num << 3) | 1) + struct.pack("<d", v)


def _field_float(num: int, v: float) -> bytes:
    return _varint((num << 3) | 5) + struct.pack("<f", v)


def _field_varint(num: int, v: int) -> bytes:
    return _varint(num << 3) + _varint(v & 0xFFFFFFFFFFFFFFFF)


def _scalar_event(tag: str, value: float, step: int, wall: float) -> bytes:
    val = (_field_bytes(1, tag.encode("utf-8"))       # Summary.Value.tag
           + _field_float(2, float(value)))           # .simple_value
    summary = _field_bytes(1, val)                    # Summary.value (rep.)
    return (_field_double(1, wall)                    # Event.wall_time
            + _field_varint(2, int(step or 0))        # Event.step
            + _field_bytes(5, summary))               # Event.summary


def _version_event(wall: float) -> bytes:
    return (_field_double(1, wall)
            + _field_bytes(3, b"brain.Event:2"))      # Event.file_version


class TFEventWriter:
    """Appends TFRecord-framed Event protos to one tfevents file."""

    _SEQ = [0]  # per-process uniquifier: two writers in the same second
    # must not interleave records into one file (CRC framing would break)

    def __init__(self, logdir):
        os.makedirs(logdir, exist_ok=True)
        TFEventWriter._SEQ[0] += 1
        fname = (f"events.out.tfevents.{int(time.time())}."
                 f"{socket.gethostname()}.{os.getpid()}"
                 f".{TFEventWriter._SEQ[0]}")
        self._f = open(os.path.join(logdir, fname), "ab")
        self._write(_version_event(time.time()))

    def _write(self, payload: bytes):
        header = struct.pack("<Q", len(payload))
        self._f.write(header + struct.pack("<I", _masked_crc(header))
                      + payload + struct.pack("<I", _masked_crc(payload)))

    def add_scalar(self, tag, value, step=None, walltime=None):
        self._write(_scalar_event(tag, value, step,
                                  walltime or time.time()))

    def flush(self):
        self._f.flush()

    def close(self):
        try:
            self._f.flush()
            self._f.close()
        except Exception:
            pass
