"""paddle.utils.download (reference: python/paddle/utils/download.py).

This image has no network egress; get_weights_path_from_url resolves only
already-cached files and raises a clear error otherwise.
"""

from __future__ import annotations

import os

WEIGHTS_HOME = os.path.expanduser("~/.cache/paddle_tpu/weights")


def get_weights_path_from_url(url, md5sum=None):
    fname = os.path.basename(url)
    path = os.path.join(WEIGHTS_HOME, fname)
    if os.path.exists(path):
        return path
    raise RuntimeError(
        f"pretrained weights {fname} not cached at {WEIGHTS_HOME} and this "
        "environment has no network access; place the file there manually")


def get_path_from_url(url, root_dir, md5sum=None, check_exist=True):
    fname = os.path.basename(url)
    path = os.path.join(root_dir, fname)
    if os.path.exists(path):
        return path
    raise RuntimeError(f"{fname} not present under {root_dir}; no network access")
