"""FP8 training surface (SURVEY.md §2.2 incubate row: "fp8 (3.0 era)").

TPU-native design: the reference's fp8 support (transformer-engine-style
cublasLt fp8 GEMMs) maps onto jax's native float8 dtypes.  The recipe here
is the standard delayed-scaling one:

- activations/weights quantize to ``float8_e4m3fn`` (wider mantissa),
  gradients to ``float8_e5m2`` (wider exponent),
- each quantized tensor carries a per-tensor scale derived from an amax
  history (max of recent abs-max, so one outlier step doesn't thrash the
  scale),
- matmuls run on the quantized values and dequantize by the product of
  scales.

Portability note: the quantization error is ALWAYS modeled (values really
round-trip through fp8), while the matmul itself upcasts the quantized
values to bf16 — on TPU generations without native fp8 MXU paths this is
exactly what XLA would do anyway, and on CPU test meshes it keeps the op
lowerable.  Numerics are therefore the fp8 numerics everywhere.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

E4M3_MAX = 448.0
E5M2_MAX = 57344.0

_FP8 = {"e4m3": (jnp.float8_e4m3fn, E4M3_MAX),
        "e5m2": (jnp.float8_e5m2, E5M2_MAX)}


def compute_scale(amax, fmt="e4m3", margin=0.0):
    """scale s.t. x/scale fills the fp8 range: scale = amax / fmt_max."""
    _, fmax = _FP8[fmt]
    amax = jnp.maximum(amax, 1e-12)
    return amax * (2.0 ** margin) / fmax


def quantize(x, scale, fmt="e4m3"):
    dt, fmax = _FP8[fmt]
    y = jnp.clip(x.astype(jnp.float32) / scale, -fmax, fmax)
    return y.astype(dt)


def dequantize(q, scale, dtype=jnp.float32):
    return (q.astype(jnp.float32) * scale).astype(dtype)


def fp8_quantize_roundtrip(x, fmt="e4m3"):
    """Per-tensor dynamic scaling: quantize and return (q, scale)."""
    amax = jnp.max(jnp.abs(x)).astype(jnp.float32)
    scale = compute_scale(amax, fmt)
    return quantize(x, scale, fmt), scale


def _fp8_matmul(x, w, x_scale, w_scale):
    """Matmul over fp8-quantized operands; dequantized f32 out.

    Upcasts the QUANTIZED values to bf16 for the MXU (see module note) —
    the fp8 rounding has already happened, so numerics match an fp8 GEMM.
    """
    acc = jax.lax.dot_general(
        x.astype(jnp.bfloat16), w.astype(jnp.bfloat16),
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return acc * (x_scale * w_scale)


@jax.custom_vjp
def _fp8_mm(x, w):
    qx, sx = fp8_quantize_roundtrip(x, "e4m3")
    qw, sw = fp8_quantize_roundtrip(w, "e4m3")
    return _fp8_matmul(qx, qw, sx, sw).astype(x.dtype)


def _fp8_mm_fwd(x, w):
    qx, sx = fp8_quantize_roundtrip(x, "e4m3")
    qw, sw = fp8_quantize_roundtrip(w, "e4m3")
    y = _fp8_matmul(qx, qw, sx, sw).astype(x.dtype)
    # residuals are the QUANTIZED operands: bwd recompute uses fp8 values,
    # and the saved activation memory is 1/4 of f32 (the fp8 point)
    return y, (qx, sx, qw, sw)


def _fp8_mm_bwd(res, g):
    qx, sx, qw, sw = res
    qg, sg = fp8_quantize_roundtrip(g, "e5m2")
    # dx = g @ w.T ; dw = x.T @ g — both with the e5m2-quantized grad
    gf = qg.astype(jnp.bfloat16)
    dx = jax.lax.dot_general(
        gf, qw.astype(jnp.bfloat16).T,
        (((gf.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) * (sg * sw)
    x2 = qx.astype(jnp.bfloat16).reshape(-1, qx.shape[-1])
    g2 = gf.reshape(-1, gf.shape[-1])
    dw = jax.lax.dot_general(
        x2.T, g2, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) * (sx * sg)
    return dx.astype(g.dtype), dw.astype(g.dtype)


_fp8_mm.defvjp(_fp8_mm_fwd, _fp8_mm_bwd)


def fp8_linear(x, w, b=None):
    """y = x @ w (+ b) with e4m3 fwd operands and e5m2 grads (fp8 recipe).
    The bias adds in full precision outside the custom VJP."""
    y = _fp8_mm(x, w)
    return y if b is None else y + b


class FP8Linear:
    """nn.Linear drop-in computing its matmul in fp8 (delayed amax scaling
    lives inside the traced step via the dynamic per-call amax — no host
    state, so it works under TrainStep/jit unchanged)."""

    def __new__(cls, in_features, out_features, bias_attr=None, name=None):
        from ..nn.layer import Layer
        from ..nn import Linear

        class _FP8Linear(Linear):
            def forward(self, x):
                from ..tensor.dispatch import apply as _apply

                if self.bias is not None:
                    return _apply(fp8_linear, x, self.weight, self.bias,
                                  op_name="fp8_linear")
                return _apply(lambda xx, ww: fp8_linear(xx, ww, None),
                              x, self.weight, op_name="fp8_linear")

        return _FP8Linear(in_features, out_features, bias_attr=bias_attr)
