"""reference namespace parity: paddle.incubate.distributed.models.moe."""

from ....distributed.fleet.meta_parallel.moe import MoELayer, top2_gating  # noqa: F401
