"""ASP — automatic structured (n:m) sparsity (SURVEY.md §2.2 incubate row).

Reference workflow (paddle.incubate.asp): ``prune_model(model)`` computes
n:m magnitude masks for prunable weights and zeroes them; ``decorate(opt)``
makes the optimizer re-apply the masks after every ``step()`` so pruned
positions stay zero through training.  That exact workflow is kept.

TPU note: the reference's payoff is cusparseLt 2:4 GEMMs; XLA:TPU has no
structured-sparse MXU path, so here ASP delivers the MODEL (a network whose
weights are verifiably n:m sparse, exportable to hardware that exploits
it), not a TPU speedup — masked matmuls run dense.  Masks group along the
weight's reduction (input) dimension, matching the n:m-along-K convention.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

_EXCLUDED: set[int] = set()  # id(Layer) excluded from pruning


def _reduction_groups(shape, m):
    """How this weight groups along its REDUCTION axis, or None.

    Linear weights are [K, out] (paddle layout): K is axis 0.  Conv weights
    are [Co, Ci, kh, kw]: the reduction dim is the flattened Ci*kh*kw TAIL —
    axis 0 is the OUTPUT channel, and grouping along it would not be
    n:m-along-K (ADVICE r4: the old code always grouped axis 0, breaking the
    documented sparse-hardware export convention for convs)."""
    if len(shape) < 2:
        return None
    if len(shape) == 2:
        return ("axis0", shape[0]) if shape[0] % m == 0 else None
    k = int(np.prod(shape[1:]))
    return ("tail", k) if k % m == 0 else None


def calculate_mask(w, n=2, m=4):
    """n:m mask over groups of ``m`` along the reduction axis (axis 0 for
    [in, out] linear weights; flattened Ci*kh*kw tail for conv)."""
    arr = jnp.asarray(w if not hasattr(w, "_value") else w._value)
    grouping = _reduction_groups(arr.shape, m)
    if grouping is None:
        return None
    kind, k = grouping
    if kind == "axis0":
        flat = jnp.moveaxis(arr, 0, -1)  # [out, K]
    else:
        flat = arr.reshape(arr.shape[0], k)  # [Co, Ci*kh*kw]
    lead = flat.shape[:-1]
    grp = flat.reshape(*lead, k // m, m)
    # rank positions by |w| within each group; keep the top n
    order = jnp.argsort(jnp.abs(grp), axis=-1)  # ascending
    ranks = jnp.argsort(order, axis=-1)
    mask = (ranks >= m - n).astype(arr.dtype).reshape(*lead, k)
    if kind == "axis0":
        return jnp.moveaxis(mask, -1, 0)
    return mask.reshape(arr.shape)


def check_sparsity(w, n=2, m=4):
    """True iff every m-group along the reduction axis has <= n nonzeros."""
    # paddle Tensors expose .numpy(); raw jax arrays ALSO have a private
    # ``_value`` (their numpy view), so dispatch on the method, not on it
    arr = np.asarray(w.numpy() if hasattr(w, "numpy") and hasattr(w, "_value")
                     else w)
    grouping = _reduction_groups(arr.shape, m)
    if grouping is None:
        return False
    kind, k = grouping
    flat = np.moveaxis(arr, 0, -1) if kind == "axis0" \
        else arr.reshape(arr.shape[0], k)
    g = flat.reshape(*flat.shape[:-1], k // m, m)
    return bool(((g != 0).sum(-1) <= n).all())


def set_excluded_layers(model, layer_names):
    """Exclude sublayers (by name as in named_sublayers) from pruning."""
    named = dict(model.named_sublayers())
    for name in layer_names:
        if name not in named:
            raise KeyError(f"no sublayer named {name!r}")
        _EXCLUDED.add(id(named[name]))


def reset_excluded_layers(model=None):
    _EXCLUDED.clear()


def _prunable_params(model):
    from ..nn.layer import Layer

    seen = set()
    for _, sub in model.named_sublayers(include_self=True):
        if id(sub) in _EXCLUDED:
            continue
        for pname, p in sub._parameters.items():
            if p is None or id(p) in seen:
                continue
            seen.add(id(p))
            # weights only (2D+, K divisible by the group); never biases
            if pname == "weight" and p._value.ndim >= 2:
                yield p


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Compute n:m masks, zero the pruned weights, remember the masks.

    Returns {param_name: mask} for inspection (reference returns the same
    shape of mapping).
    """
    if mask_algo not in ("mask_1d", "mask_2d_greedy", "mask_2d_best"):
        raise ValueError(f"unknown mask_algo {mask_algo!r}")
    out = {}
    name_of = {id(p): k for k, p in model.named_parameters()}
    for p in _prunable_params(model):
        mask = calculate_mask(p._value, n, m)
        if mask is None:
            continue
        p._value = p._value * mask
        if getattr(p, "_master", None) is not None:
            p._master = p._master * mask.astype(p._master.dtype)
        if with_mask:
            # the mask lives ON the parameter: a global id()-keyed registry
            # can hand a STALE mask to an unrelated new param when ids are
            # reused after GC (observed as flaky corruption in the suite)
            p._asp_mask = mask
        out[name_of.get(id(p), f"param_{id(p)}")] = mask
    return out


def decorate(optimizer):
    """Wrap ``optimizer.step`` to re-apply the ASP masks after each update
    (reference asp.decorate semantics), keeping pruned weights at zero.

    Works with the eager backward()/step() loop.  For the fused TrainStep
    path, prune after training or apply masks inside the model's forward.
    """
    if getattr(optimizer, "_asp_decorated", False):
        return optimizer
    inner = optimizer.step

    def step():
        r = inner()
        from ..framework.state import no_grad_ctx

        with no_grad_ctx():
            for p in optimizer._parameter_list:
                mask = getattr(p, "_asp_mask", None)
                if mask is not None:
                    p._value = p._value * mask
                    if getattr(p, "_master", None) is not None:
                        p._master = p._master * mask.astype(p._master.dtype)
        return r

    optimizer.step = step
    optimizer._asp_decorated = True
    return optimizer
