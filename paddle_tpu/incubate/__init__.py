"""paddle.incubate (reference: python/paddle/incubate/) — the slice the
TPU rebuild keeps: fused transformer front-ends (SURVEY.md §2.2 incubate
row: "fused attention/ffn become Pallas kernels") and softmax_mask_fuse.
"""

from . import nn  # noqa: F401
from . import distributed  # noqa: F401
from . import optimizer  # noqa: F401
from . import asp  # noqa: F401
from . import fp8  # noqa: F401
from .nn import functional  # noqa: F401
from .optimizer import ExponentialMovingAverage, LookAhead, ModelAverage  # noqa: F401


def softmax_mask_fuse(x, mask, name=None):
    from ..tensor.dispatch import apply as _apply
    import jax

    return _apply(lambda v, m: jax.nn.softmax(v + m, axis=-1), x, mask,
                  op_name="softmax_mask_fuse")


def softmax_mask_fuse_upper_triangle(x):
    from ..tensor.dispatch import apply as _apply
    import jax
    import jax.numpy as jnp

    def fn(v):
        T = v.shape[-1]
        mask = jnp.triu(jnp.full((T, T), -1e9, v.dtype), k=1)
        return jax.nn.softmax(v + mask, axis=-1)

    return _apply(fn, x, op_name="softmax_mask_fuse_upper_triangle")
