from . import functional  # noqa: F401
from .layer import FusedMultiHeadAttention, FusedFeedForward, FusedLinear  # noqa: F401
