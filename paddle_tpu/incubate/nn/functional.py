"""incubate fused-op front-ends (reference:
python/paddle/incubate/nn/functional/ — fused_multi_head_attention,
fused_feedforward: single CUDA kernels fusing matmul+bias+residual+norm).

TPU-native: the "fusion" is XLA's job; these compose the same math so one
compiled region emerges.  The attention core routes through
nn.functional.scaled_dot_product_attention, which picks the Pallas flash
kernel when profitable (paddle_tpu.ops.flash_attention).
"""

from __future__ import annotations

import jax.numpy as jnp

from ...nn import functional as F
from ...tensor.tensor import Tensor


def fused_multi_head_attention(
        x, qkv_weight, linear_weight, pre_layer_norm=False, pre_ln_scale=None,
        pre_ln_bias=None, ln_scale=None, ln_bias=None, pre_ln_epsilon=1e-5,
        qkv_bias=None, linear_bias=None, cache_kv=None, attn_mask=None,
        dropout_rate=0.5, attn_dropout_rate=0.5, ln_epsilon=1e-5, training=True,
        mode="upscale_in_train", ring_id=-1, add_residual=True, num_heads=None,
        name=None):
    """qkv_weight: [3, n_heads, head_dim, embed_dim] (reference layout)."""
    residual = x
    if pre_layer_norm and pre_ln_scale is not None:
        x = F.layer_norm(x, x.shape[-1:], weight=pre_ln_scale, bias=pre_ln_bias,
                         epsilon=pre_ln_epsilon)
    three, n_heads, head_dim, embed = qkv_weight.shape
    w = qkv_weight.reshape([3 * n_heads * head_dim, embed]).T
    qkv = F.linear(x, w, qkv_bias.reshape([-1]) if qkv_bias is not None else None)
    B, T = x.shape[0], x.shape[1]
    qkv = qkv.reshape([B, T, 3, n_heads, head_dim]).transpose([2, 0, 1, 3, 4])
    q, k, v = qkv[0], qkv[1], qkv[2]
    out = F.scaled_dot_product_attention(
        q, k, v, attn_mask=attn_mask, dropout_p=attn_dropout_rate if training else 0.0,
        training=training)
    out = out.reshape([B, T, n_heads * head_dim])
    out = F.linear(out, linear_weight, linear_bias)
    if training and dropout_rate:
        out = F.dropout(out, p=dropout_rate, training=True)
    if add_residual:
        out = residual + out
    if not pre_layer_norm and ln_scale is not None:
        out = F.layer_norm(out, out.shape[-1:], weight=ln_scale, bias=ln_bias,
                           epsilon=ln_epsilon)
    return out


def fused_feedforward(
        x, linear1_weight, linear2_weight, linear1_bias=None, linear2_bias=None,
        ln1_scale=None, ln1_bias=None, ln2_scale=None, ln2_bias=None,
        dropout1_rate=0.5, dropout2_rate=0.5, activation="relu",
        ln1_epsilon=1e-5, ln2_epsilon=1e-5, pre_layer_norm=False, training=True,
        mode="upscale_in_train", ring_id=-1, add_residual=True, name=None):
    residual = x
    if pre_layer_norm and ln1_scale is not None:
        x = F.layer_norm(x, x.shape[-1:], weight=ln1_scale, bias=ln1_bias,
                         epsilon=ln1_epsilon)
    h = F.linear(x, linear1_weight, linear1_bias)
    h = getattr(F, activation)(h)
    if training and dropout1_rate:
        h = F.dropout(h, p=dropout1_rate, training=True)
    h = F.linear(h, linear2_weight, linear2_bias)
    if training and dropout2_rate:
        h = F.dropout(h, p=dropout2_rate, training=True)
    out = residual + h if add_residual else h
    if not pre_layer_norm and ln2_scale is not None:
        out = F.layer_norm(out, out.shape[-1:], weight=ln2_scale, bias=ln2_bias,
                           epsilon=ln2_epsilon)
    return out


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    w = weight.T if transpose_weight else weight
    return F.linear(x, w, bias)


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      name=None):
    return F.dropout(x, p=p, training=training, mode=mode) + y


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, name=None):
    from ...tensor.dispatch import apply as _apply

    def fn(v, w, *b):
        var = jnp.mean(jnp.square(v.astype(jnp.float32)), axis=-1, keepdims=True)
        out = (v.astype(jnp.float32) * jnp.reciprocal(jnp.sqrt(var + epsilon)))
        out = out.astype(v.dtype) * w
        if b:
            out = out + b[0]
        return out

    args = (x, norm_weight) if norm_bias is None else (x, norm_weight, norm_bias)
    return _apply(fn, *args, op_name="rms_norm")
