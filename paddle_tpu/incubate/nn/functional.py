"""incubate fused-op front-ends (reference:
python/paddle/incubate/nn/functional/ — fused_multi_head_attention,
fused_feedforward: single CUDA kernels fusing matmul+bias+residual+norm).

TPU-native: the "fusion" is XLA's job; these compose the same math so one
compiled region emerges.  The attention core routes through
nn.functional.scaled_dot_product_attention, which picks the Pallas flash
kernel when profitable (paddle_tpu.ops.flash_attention).
"""

from __future__ import annotations

import jax.numpy as jnp

from ...nn import functional as F
from ...tensor.tensor import Tensor


def fused_multi_head_attention(
        x, qkv_weight, linear_weight, pre_layer_norm=False, pre_ln_scale=None,
        pre_ln_bias=None, ln_scale=None, ln_bias=None, pre_ln_epsilon=1e-5,
        qkv_bias=None, linear_bias=None, cache_kv=None, attn_mask=None,
        dropout_rate=0.5, attn_dropout_rate=0.5, ln_epsilon=1e-5, training=True,
        mode="upscale_in_train", ring_id=-1, add_residual=True, num_heads=None,
        name=None):
    """qkv_weight: [3, n_heads, head_dim, embed_dim] (reference layout)."""
    residual = x
    if pre_layer_norm and pre_ln_scale is not None:
        x = F.layer_norm(x, x.shape[-1:], weight=pre_ln_scale, bias=pre_ln_bias,
                         epsilon=pre_ln_epsilon)
    three, n_heads, head_dim, embed = qkv_weight.shape
    w = qkv_weight.reshape([3 * n_heads * head_dim, embed]).T
    qkv = F.linear(x, w, qkv_bias.reshape([-1]) if qkv_bias is not None else None)
    B, T = x.shape[0], x.shape[1]
    qkv = qkv.reshape([B, T, 3, n_heads, head_dim]).transpose([2, 0, 1, 3, 4])
    q, k, v = qkv[0], qkv[1], qkv[2]
    out = F.scaled_dot_product_attention(
        q, k, v, attn_mask=attn_mask, dropout_p=attn_dropout_rate if training else 0.0,
        training=training)
    out = out.reshape([B, T, n_heads * head_dim])
    out = F.linear(out, linear_weight, linear_bias)
    if training and dropout_rate:
        out = F.dropout(out, p=dropout_rate, training=True)
    if add_residual:
        out = residual + out
    if not pre_layer_norm and ln_scale is not None:
        out = F.layer_norm(out, out.shape[-1:], weight=ln_scale, bias=ln_bias,
                           epsilon=ln_epsilon)
    return out


def fused_feedforward(
        x, linear1_weight, linear2_weight, linear1_bias=None, linear2_bias=None,
        ln1_scale=None, ln1_bias=None, ln2_scale=None, ln2_bias=None,
        dropout1_rate=0.5, dropout2_rate=0.5, activation="relu",
        ln1_epsilon=1e-5, ln2_epsilon=1e-5, pre_layer_norm=False, training=True,
        mode="upscale_in_train", ring_id=-1, add_residual=True, name=None):
    residual = x
    if pre_layer_norm and ln1_scale is not None:
        x = F.layer_norm(x, x.shape[-1:], weight=ln1_scale, bias=ln1_bias,
                         epsilon=ln1_epsilon)
    h = F.linear(x, linear1_weight, linear1_bias)
    h = getattr(F, activation)(h)
    if training and dropout1_rate:
        h = F.dropout(h, p=dropout1_rate, training=True)
    h = F.linear(h, linear2_weight, linear2_bias)
    if training and dropout2_rate:
        h = F.dropout(h, p=dropout2_rate, training=True)
    out = residual + h if add_residual else h
    if not pre_layer_norm and ln2_scale is not None:
        out = F.layer_norm(out, out.shape[-1:], weight=ln2_scale, bias=ln2_bias,
                           epsilon=ln2_epsilon)
    return out


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    w = weight.T if transpose_weight else weight
    return F.linear(x, w, bias)


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      name=None):
    return F.dropout(x, p=p, training=training, mode=mode) + y


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, name=None):
    from ...tensor.dispatch import apply as _apply

    def fn(v, w, *b):
        var = jnp.mean(jnp.square(v.astype(jnp.float32)), axis=-1, keepdims=True)
        out = (v.astype(jnp.float32) * jnp.reciprocal(jnp.sqrt(var + epsilon)))
        out = out.astype(v.dtype) * w
        if b:
            out = out + b[0]
        return out

    args = (x, norm_weight) if norm_bias is None else (x, norm_weight, norm_bias)
    return _apply(fn, *args, op_name="rms_norm")


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False,
                      name=None):
    """One fused matmul+bias (reference: incubate fused op over cublasLt;
    XLA fuses the bias add into the GEMM epilogue on TPU)."""
    from ...tensor.dispatch import apply
    import jax.numpy as jnp

    def fn(xv, yv, *b):
        a = jnp.swapaxes(xv, -1, -2) if transpose_x else xv
        w = jnp.swapaxes(yv, -1, -2) if transpose_y else yv
        out = a @ w
        return out + b[0] if b else out

    args = (x, y) if bias is None else (x, y, bias)
    return apply(fn, *args, op_name="fused_matmul_bias")


def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5,
                     residual=None, bias=None, name=None):
    """LayerNorm with optional pre-norm residual+bias fusion (reference:
    fused_layer_norm / fused_bias_residual_layernorm)."""
    from ...tensor.dispatch import apply
    import jax
    import jax.numpy as jnp

    def fn(xv, g, b, *extra):
        h = xv
        i = 0
        if residual is not None:
            h = h + extra[i]
            i += 1
        if bias is not None:
            h = h + extra[i]
        mu = jnp.mean(h, axis=-1, keepdims=True)
        var = jnp.var(h, axis=-1, keepdims=True)
        return ((h - mu) * jax.lax.rsqrt(var + epsilon)) * g + b

    args = [x, norm_weight, norm_bias]
    if residual is not None:
        args.append(residual)
    if bias is not None:
        args.append(bias)
    return apply(fn, *args, op_name="fused_layer_norm")


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True,
                                    name=None):
    """RoPE applied to q (and k) in one traced op (reference:
    incubate.nn.functional.fused_rotary_position_embedding)."""
    from ...tensor.dispatch import apply
    import jax.numpy as jnp

    def rope_one(t, sinv, cosv):
        # t: [B, S, H, D]
        if use_neox_rotary_style:
            half = t.shape[-1] // 2
            t1, t2 = t[..., :half], t[..., half:]
            rotated = jnp.concatenate([-t2, t1], axis=-1)
        else:
            t1 = t[..., ::2]
            t2 = t[..., 1::2]
            rotated = jnp.stack([-t2, t1], axis=-1).reshape(t.shape)
        return t * cosv + rotated * sinv

    def build_sin_cos(t):
        B, S, H, D = t.shape
        if position_ids is not None:
            pos = jnp.asarray(position_ids._value if hasattr(
                position_ids, "_value") else position_ids).astype(jnp.float32)
            if pos.ndim == 1:
                pos = pos[None, :]
        else:
            pos = jnp.arange(S, dtype=jnp.float32)[None, :]  # [1 or B, S]
        inv = 1.0 / (10000.0 ** (jnp.arange(0, D, 2, dtype=jnp.float32) / D))
        ang = pos[..., None] * inv[None, None, :]            # [B?, S, D/2]
        if use_neox_rotary_style:
            # rotate-half pairs (i, i + D/2): frequencies tile as two halves
            sinv = jnp.concatenate([jnp.sin(ang), jnp.sin(ang)], axis=-1)
            cosv = jnp.concatenate([jnp.cos(ang), jnp.cos(ang)], axis=-1)
        else:
            # interleaved pairs (2i, 2i+1): each frequency repeats adjacently
            sinv = jnp.repeat(jnp.sin(ang), 2, axis=-1)
            cosv = jnp.repeat(jnp.cos(ang), 2, axis=-1)
        return sinv[:, :, None, :], cosv[:, :, None, :]

    def fn(qv, *rest):
        i = 0
        kv = vv = None
        if k is not None:
            kv = rest[i]
            i += 1
        if v is not None:
            vv = rest[i]
            i += 1
        if sin is not None:
            sinv, cosv = rest[i], rest[i + 1]
            if sinv.ndim == 2:
                sinv = sinv[None, :, None, :]
                cosv = cosv[None, :, None, :]
            if position_ids is not None:
                # gather the table rows at each token's position (the
                # KV-cache decode case: positions are not 0..S-1)
                pos = jnp.asarray(position_ids._value if hasattr(
                    position_ids, "_value") else position_ids)
                if pos.ndim == 1:
                    pos = pos[None, :]
                sinv = jnp.broadcast_to(
                    sinv, (pos.shape[0],) + sinv.shape[1:])[
                        jnp.arange(pos.shape[0])[:, None], pos]
                cosv = jnp.broadcast_to(
                    cosv, (pos.shape[0],) + cosv.shape[1:])[
                        jnp.arange(pos.shape[0])[:, None], pos]
        else:
            sinv, cosv = build_sin_cos(qv)
        # the reference rotates EVERY provided tensor, v included
        outs = [rope_one(t, sinv, cosv)
                for t in (qv, kv, vv) if t is not None]
        return tuple(outs) if len(outs) > 1 else outs[0]

    args = [q]
    n_provided = 1
    if k is not None:
        args.append(k)
        n_provided += 1
    if v is not None:
        args.append(v)
        n_provided += 1
    if sin is not None:
        args.extend([sin, cos])
    out = apply(fn, *args, op_name="fused_rope",
                n_outs=1 if n_provided == 1 else None)
    if n_provided == 1:
        out = (out,)
    out = list(out)
    # reference returns a (q, k, v) triple with None placeholders
    result = [None, None, None]
    j = 0
    for slot, t in enumerate((q, k, v)):
        if t is not None:
            result[slot] = out[j]
            j += 1
    if k is None and v is None:
        return result[0]
    return tuple(result)


def swiglu(x, y=None, name=None):
    """silu(x) * y; with y=None, x splits in half (reference: incubate
    swiglu used by Llama-family FFNs)."""
    from ...tensor.dispatch import apply
    import jax
    import jax.numpy as jnp

    def fn(xv, *ys):
        if ys:
            return jax.nn.silu(xv) * ys[0]
        a, b = jnp.split(xv, 2, axis=-1)
        return jax.nn.silu(a) * b

    args = (x,) if y is None else (x, y)
    return apply(fn, *args, op_name="swiglu")
