"""incubate fused layers (reference: python/paddle/incubate/nn/layer/
fused_transformer.py)."""

from __future__ import annotations

import math

from ...nn.layer import Layer, LayerList
from ...nn import initializer as I
from ...tensor.tensor import Parameter
from . import functional as FF


class FusedMultiHeadAttention(Layer):
    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None, normalize_before=False,
                 need_weights=False, qkv_weight_attr=None, qkv_bias_attr=None,
                 linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None, ln_scale_attr=None,
                 ln_bias_attr=None, epsilon=1e-5, nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        self._epsilon = epsilon
        self.qkv_weight = self.create_parameter(
            [3, num_heads, self.head_dim, embed_dim], attr=qkv_weight_attr,
            default_initializer=I.XavierUniform())
        self.qkv_bias = self.create_parameter(
            [3, num_heads, self.head_dim], attr=qkv_bias_attr, is_bias=True)
        self.linear_weight = self.create_parameter(
            [embed_dim, embed_dim], attr=linear_weight_attr,
            default_initializer=I.XavierUniform())
        self.linear_bias = self.create_parameter([embed_dim], attr=linear_bias_attr,
                                                 is_bias=True)
        self.pre_ln_scale = self.create_parameter(
            [embed_dim], attr=pre_ln_scale_attr, default_initializer=I.Constant(1.0))
        self.pre_ln_bias = self.create_parameter([embed_dim], attr=pre_ln_bias_attr,
                                                 is_bias=True)
        self.ln_scale = self.create_parameter(
            [embed_dim], attr=ln_scale_attr, default_initializer=I.Constant(1.0))
        self.ln_bias = self.create_parameter([embed_dim], attr=ln_bias_attr,
                                             is_bias=True)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        return FF.fused_multi_head_attention(
            query, self.qkv_weight, self.linear_weight,
            pre_layer_norm=self.normalize_before,
            pre_ln_scale=self.pre_ln_scale, pre_ln_bias=self.pre_ln_bias,
            ln_scale=self.ln_scale, ln_bias=self.ln_bias,
            pre_ln_epsilon=self._epsilon, qkv_bias=self.qkv_bias,
            linear_bias=self.linear_bias, attn_mask=attn_mask,
            dropout_rate=self.dropout_rate,
            attn_dropout_rate=self.attn_dropout_rate,
            ln_epsilon=self._epsilon, training=self.training,
            num_heads=self.num_heads)


class FusedFeedForward(Layer):
    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1, epsilon=1e-5,
                 activation="relu", act_dropout_rate=None, normalize_before=False,
                 linear1_weight_attr=None, linear1_bias_attr=None,
                 linear2_weight_attr=None, linear2_bias_attr=None,
                 ln1_scale_attr=None, ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.activation = activation
        self.dropout_rate = dropout_rate
        self.act_dropout_rate = act_dropout_rate if act_dropout_rate is not None \
            else dropout_rate
        self._epsilon = epsilon
        bound = 1.0 / math.sqrt(d_model)
        self.linear1_weight = self.create_parameter(
            [d_model, dim_feedforward], attr=linear1_weight_attr,
            default_initializer=I.Uniform(-bound, bound))
        self.linear1_bias = self.create_parameter([dim_feedforward],
                                                  attr=linear1_bias_attr, is_bias=True)
        self.linear2_weight = self.create_parameter(
            [dim_feedforward, d_model], attr=linear2_weight_attr,
            default_initializer=I.Uniform(-bound, bound))
        self.linear2_bias = self.create_parameter([d_model], attr=linear2_bias_attr,
                                                  is_bias=True)
        self.ln1_scale = self.create_parameter([d_model], attr=ln1_scale_attr,
                                               default_initializer=I.Constant(1.0))
        self.ln1_bias = self.create_parameter([d_model], attr=ln1_bias_attr,
                                              is_bias=True)
        self.ln2_scale = self.create_parameter([d_model], attr=ln2_scale_attr,
                                               default_initializer=I.Constant(1.0))
        self.ln2_bias = self.create_parameter([d_model], attr=ln2_bias_attr,
                                              is_bias=True)

    def forward(self, src, cache=None):
        return FF.fused_feedforward(
            src, self.linear1_weight, self.linear2_weight,
            linear1_bias=self.linear1_bias, linear2_bias=self.linear2_bias,
            ln1_scale=self.ln1_scale, ln1_bias=self.ln1_bias,
            ln2_scale=self.ln2_scale, ln2_bias=self.ln2_bias,
            dropout1_rate=self.act_dropout_rate, dropout2_rate=self.dropout_rate,
            activation=self.activation, ln1_epsilon=self._epsilon,
            ln2_epsilon=self._epsilon, pre_layer_norm=self.normalize_before,
            training=self.training)


class FusedLinear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None, bias_attr=None,
                 transpose_weight=False, name=None):
        super().__init__()
        self.transpose_weight = transpose_weight
        shape = [out_features, in_features] if transpose_weight else \
            [in_features, out_features]
        self.weight = self.create_parameter(shape, attr=weight_attr)
        self.bias = self.create_parameter([out_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return FF.fused_linear(x, self.weight, self.bias, self.transpose_weight)


class FusedTransformerEncoderLayer(Layer):
    """reference: paddle.incubate.nn.FusedTransformerEncoderLayer — one
    encoder block over the fused attention/ffn front-ends (the fusion
    itself is XLA's; this class keeps the reference's constructor and
    state_dict shape)."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead,
            dropout_rate=dropout_rate,
            attn_dropout_rate=(attn_dropout_rate if attn_dropout_rate
                               is not None else dropout_rate),
            normalize_before=normalize_before,
            qkv_weight_attr=weight_attr, qkv_bias_attr=bias_attr,
            linear_weight_attr=weight_attr, linear_bias_attr=bias_attr)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation,
            act_dropout_rate=(act_dropout_rate if act_dropout_rate
                              is not None else dropout_rate),
            normalize_before=normalize_before,
            linear1_weight_attr=weight_attr, linear1_bias_attr=bias_attr,
            linear2_weight_attr=weight_attr, linear2_bias_attr=bias_attr)

    def forward(self, src, src_mask=None, cache=None):
        if cache is not None:
            raise NotImplementedError(
                "FusedTransformerEncoderLayer cache (incremental decoding) "
                "is not supported; use nn.TransformerEncoderLayer's cache "
                "path")
        out = self.fused_attn(src, attn_mask=src_mask)
        return self.ffn(out)


class FusedMultiTransformer(Layer):
    """reference: paddle.incubate.nn.FusedMultiTransformer — the serving
    decoder stack (pre-LN self-attention + FFN per layer) with static
    KV caches written at ``time_step`` for incremental decoding.

    TPU-native: caches are fixed-shape [B, max_len, H, D] buffers updated
    with dynamic_update_slice (one compiled decode step serves every
    position), and the whole stack is one traced program — the reference's
    single-CUDA-kernel fusion is XLA's fusion here.
    """

    def __init__(self, embed_dim, num_heads, dim_feedforward, dropout_rate=0.0,
                 activation="gelu", normalize_before=True, num_layers=1,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.dropout_rate = dropout_rate
        self.activation = activation
        self.num_layers = num_layers
        self.normalize_before = normalize_before
        self.layers = LayerList([
            _FusedMTBlock(embed_dim, num_heads, dim_feedforward,
                          dropout_rate, activation, normalize_before)
            for _ in range(num_layers)])

    def gen_cache(self, batch_size, max_length, dtype=None, impl="dense",
                  page_size=16):
        """Per-layer KV cache buffers.

        dtype defaults to the MODEL's compute dtype (r4 weak #8: f32-only
        caches doubled serving HBM for bf16 models — bf16 caches halve the
        KV footprint and the attention math still runs its softmax in f32).

        impl="paged": block-paged pools [B, PP, page, H, D] instead of the
        dense [B, max_length] rectangle — decode attention runs the Pallas
        scalar-prefetch paged kernel and serving HBM is bounded by pages
        (ceil(max_length/page_size) per sequence), the property the
        reference's paged engine exists for.
        """
        import jax.numpy as jnp

        from ...tensor.tensor import Tensor

        if dtype is None:
            dtype = self.layers[0].qkv.weight._value.dtype
        if impl == "paged":
            pp = -(-max_length // page_size)
            shape = (batch_size, pp, page_size, self.num_heads, self.head_dim)
            return [("paged", Tensor(jnp.zeros(shape, dtype)),
                     Tensor(jnp.zeros(shape, dtype)))
                    for _ in range(self.num_layers)]
        if impl != "dense":
            raise ValueError(f"impl must be 'dense' or 'paged', got {impl!r}")
        shape = (batch_size, max_length, self.num_heads, self.head_dim)
        return [(Tensor(jnp.zeros(shape, dtype)),
                 Tensor(jnp.zeros(shape, dtype)))
                for _ in range(self.num_layers)]

    def forward(self, src, attn_mask=None, caches=None, time_step=None):
        new_caches = []
        out = src
        for i, blk in enumerate(self.layers):
            cache = caches[i] if caches is not None else None
            out, new_cache = blk(out, attn_mask, cache, time_step)
            new_caches.append(new_cache)
        if caches is not None:
            return out, new_caches
        return out


class _FusedMTBlock(Layer):
    def __init__(self, embed_dim, num_heads, dim_feedforward, dropout_rate,
                 activation, normalize_before=True):
        super().__init__()
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        from ...nn import LayerNorm

        self.ln1 = LayerNorm(embed_dim)
        self.qkv = FusedLinear(embed_dim, 3 * embed_dim)
        self.out_proj = FusedLinear(embed_dim, embed_dim)
        self.ln2 = LayerNorm(embed_dim)
        self.fc1 = FusedLinear(embed_dim, dim_feedforward)
        self.fc2 = FusedLinear(dim_feedforward, embed_dim)
        self.dropout_rate = dropout_rate
        self.activation = activation

    def forward(self, src, attn_mask, cache, time_step):
        from ...nn import functional as F
        from ...tensor.dispatch import apply
        import jax
        import jax.numpy as jnp

        # pre-LN: h = attn(ln1(src)); src += h  (reference serving default)
        # post-LN: src = ln1(src + attn(src))   (r4 weak #8: was refused)
        h = self.ln1(src) if self.normalize_before else src
        B, T = h.shape[0], h.shape[1]
        qkv = self.qkv(h).reshape([B, T, 3, self.num_heads, self.head_dim])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        new_cache = None
        if cache is not None and len(cache) == 3 and cache[0] == "paged":
            # PAGED serving cache (gen_cache(impl="paged")): prefill attends
            # densely and writes pages; decode steps run the Pallas paged
            # kernel (see ops/paged_attention).  Prefill must start at
            # time_step 0; continuation chunks need the dense cache.
            from ...ops.paged_attention import (paged_decode_attend,
                                                paged_prefill_write,
                                                paged_token_write)

            if time_step is None:
                raise ValueError("caches need time_step (decode position)")
            if attn_mask is not None:
                raise NotImplementedError(
                    "paged FusedMultiTransformer caches do not take an "
                    "attn_mask; per-sequence lengths belong in seq_lens "
                    "(PagedKVCache)")
            _, kp, vp = cache
            if T > 1:
                ts_val = getattr(time_step, "_value", time_step)
                try:
                    if int(ts_val) != 0:
                        raise ValueError(
                            "paged prefill must start at time_step 0; use "
                            "the dense cache for continuation chunks")
                except TypeError:
                    pass  # traced: the caller's contract
                att = F.scaled_dot_product_attention(
                    q, k, v, is_causal=True, training=False)
                kp = apply(paged_prefill_write, kp, k, op_name="paged_write")
                vp = apply(paged_prefill_write, vp, v, op_name="paged_write")
            else:
                def wr(pgs, tok, t_):
                    return paged_token_write(pgs, tok[:, 0],
                                             t_.astype(jnp.int32).reshape(()))

                kp = apply(wr, kp, k, time_step, op_name="paged_write")
                vp = apply(wr, vp, v, time_step, op_name="paged_write")
                att = apply(
                    lambda qq, kps, vps, t_:
                        paged_decode_attend(
                            qq[:, 0], kps, vps,
                            t_.astype(jnp.int32).reshape(()))[:, None],
                    q, kp, vp, time_step, op_name="paged_attention")
            o = self.out_proj(att.reshape([B, T, -1]))
            if self.dropout_rate and self.training:
                o = F.dropout(o, p=self.dropout_rate, training=True)
            src = src + o
            if not self.normalize_before:
                src = self.ln1(src)
            h2 = self.fc1(self.ln2(src) if self.normalize_before else src)
            h2 = self.fc2(getattr(F, self.activation)(h2))
            if self.dropout_rate and self.training:
                h2 = F.dropout(h2, p=self.dropout_rate, training=True)
            out = src + h2
            if not self.normalize_before:
                out = self.ln2(out)
            return out, ("paged", kp, vp)
        if cache is not None:
            ck, cv = cache
            if time_step is None:
                raise ValueError("caches need time_step (decode position)")
            ts_val = getattr(time_step, "_value", time_step)
            if not hasattr(ts_val, "aval") or not hasattr(
                    ts_val.aval, "weak_type") or hasattr(ts_val, "item"):
                try:  # eager: catch silent overwrite past the cache end
                    if int(ts_val) + T > ck.shape[1]:
                        raise ValueError(
                            f"decode position {int(ts_val)}+{T} exceeds "
                            f"cache max_length {ck.shape[1]}")
                except TypeError:
                    pass  # traced value: bounds are the caller's contract

            def upd(buf, new):
                def fn(b_, n_, t_):
                    t_ = t_.astype(jnp.int32).reshape(())
                    zero = jnp.zeros((), jnp.int32)
                    return jax.lax.dynamic_update_slice(
                        b_, n_.astype(b_.dtype), (zero, t_, zero, zero))

                return apply(fn, buf, new, time_step, op_name="cache_update")

            ck = upd(ck, k)
            cv = upd(cv, v)
            new_cache = (ck, cv)
            # attend over the cache prefix [0, time_step + T)
            k_all, v_all = ck, cv
            L = k_all.shape[1]

            def masked_attn(qq, kk, vv, ts, *mask):
                # [B, T, H, D] x [B, L, H, D]; causal WITHIN the new-token
                # window too (prefill with T>1 must not see its own future)
                s = jnp.einsum("bthd,blhd->bhtl", qq, kk) \
                    / jnp.sqrt(jnp.float32(qq.shape[-1]))
                pos = jnp.arange(L)[None, None, None, :]
                tq = jnp.arange(T)[None, None, :, None]
                limit = ts.astype(jnp.int32) + 1 + tq
                s = jnp.where(pos < limit, s, -1e30)
                if mask:
                    s = s + mask[0].astype(jnp.float32)
                p = jax.nn.softmax(s.astype(jnp.float32), -1).astype(qq.dtype)
                return jnp.einsum("bhtl,blhd->bthd", p, vv)

            attn_args = (q, k_all, v_all, time_step) \
                if attn_mask is None else (q, k_all, v_all, time_step,
                                           attn_mask)
            o = apply(masked_attn, *attn_args,
                      op_name="fused_mt_cached_attn")
        else:
            o = F.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask,
                                               is_causal=attn_mask is None,
                                               training=self.training)
        o = self.out_proj(o.reshape([B, T, -1]))
        if self.dropout_rate and self.training:
            o = F.dropout(o, p=self.dropout_rate, training=True)
        src = src + o
        if not self.normalize_before:
            src = self.ln1(src)
        h2 = self.fc1(self.ln2(src) if self.normalize_before else src)
        h2 = self.fc2(getattr(F, self.activation)(h2))
        if self.dropout_rate and self.training:
            h2 = F.dropout(h2, p=self.dropout_rate, training=True)
        out = src + h2
        if not self.normalize_before:
            out = self.ln2(out)
        return out, new_cache

