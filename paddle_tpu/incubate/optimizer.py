"""paddle.incubate.optimizer — optimizer wrappers (reference:
python/paddle/incubate/optimizer/: LookAhead, ModelAverage) plus
ExponentialMovingAverage (reference: paddle.static.ExponentialMovingAverage,
re-homed here for the dygraph-first rebuild).

TPU-first: every wrapper keeps its auxiliary weights as a jax pytree and
exposes the same pure ``functional_init/functional_update`` contract the
fused :class:`~paddle_tpu.jit.train_step.TrainStep` compiles — the slow/EMA
updates are traced ops (``jnp.where`` on a carried counter), not host-side
Python, so wrapping an optimizer does not break the one-XLA-program step.
"""

from __future__ import annotations

import contextlib

import jax
import jax.numpy as jnp

from ..tensor.tensor import Tensor


def _tree_val(t):
    return t._value if isinstance(t, Tensor) else t


class LookAhead:
    """Lookahead (k steps forward, 1 step back): fast weights follow the
    inner optimizer; every ``k`` steps the slow weights move ``alpha`` of the
    way toward the fast weights and the fast weights reset to them.

    Wraps any paddle_tpu optimizer; usable eagerly (``step()``) and inside
    TrainStep (functional path, the sync is a traced ``jnp.where``).
    """

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        if not 0.0 <= alpha <= 1.0:
            raise ValueError(f"alpha must be in [0,1], got {alpha}")
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._slow = {}  # id(param) -> slow array (eager path)
        self._eager_count = 0

    # delegate everything the trainer/model code reads off an optimizer
    def __getattr__(self, name):
        return getattr(self.inner_optimizer, name)

    # ------------------------------------------------------------ eager
    def step(self):
        if self._eager_count == 0:
            # slow weights start at theta_0 (same seeding as functional_init)
            for p in self.inner_optimizer._parameter_list:
                pv = p._master if getattr(p, "_master", None) is not None else p._value
                self._slow[id(p)] = pv
        self.inner_optimizer.step()
        self._eager_count += 1
        if self._eager_count % self.k == 0:
            for p in self.inner_optimizer._parameter_list:
                pv = p._master if getattr(p, "_master", None) is not None else p._value
                slow = self._slow[id(p)]
                new_slow = slow + self.alpha * (pv - slow)
                self._slow[id(p)] = new_slow
                if getattr(p, "_master", None) is not None:
                    p._master = new_slow
                    p._value = new_slow.astype(p._value.dtype)
                else:
                    p._value = new_slow.astype(pv.dtype)

    def clear_grad(self):
        self.inner_optimizer.clear_grad()

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        loss.backward()
        self.step()
        return None, None

    # ------------------------------------------------- functional (jit)
    def functional_init(self, param_tree):
        return {
            "inner": self.inner_optimizer.functional_init(param_tree),
            # copy: slow weights live in the (donated) opt-state tree, so they
            # must not alias the (also donated) param buffers
            "slow": jax.tree_util.tree_map(
                lambda p: jnp.array(_tree_val(p), copy=True), param_tree),
            "count": jnp.zeros((), jnp.int32),
        }

    def resolve_leaf_meta(self, param_tree):
        return self.inner_optimizer.resolve_leaf_meta(param_tree)

    def functional_update(self, param_tree, grad_tree, state_tree, lr, leaf_meta=None):
        new_p, new_inner = self.inner_optimizer.functional_update(
            param_tree, grad_tree, state_tree["inner"], lr, leaf_meta=leaf_meta)
        count = state_tree["count"] + 1
        sync = (count % self.k) == 0
        new_slow = jax.tree_util.tree_map(
            lambda s, p: jnp.where(sync, s + self.alpha * (p.astype(s.dtype) - s), s),
            state_tree["slow"], new_p)
        new_p = jax.tree_util.tree_map(
            lambda s, p: jnp.where(sync, s.astype(p.dtype), p), new_slow, new_p)
        return new_p, {"inner": new_inner, "slow": new_slow, "count": count}

    def sync_functional_state(self, named_diff, state_tree, step_count):
        """TrainStep.sync() hook: route the {'inner','slow','count'} layout
        back into the wrapped optimizer and the eager slow-weight store."""
        inner = state_tree["inner"]
        slow = state_tree["slow"]
        for k, t in named_diff:
            self.inner_optimizer._states[id(t)] = inner[k]
            self._slow[id(t)] = slow[k]
        self.inner_optimizer._step_count = step_count
        self._eager_count = int(state_tree["count"])

    def state_dict(self):
        sd = self.inner_optimizer.state_dict()
        # slow weights serialized by parameter ORDER (ids don't survive a
        # process restart)
        plist = self.inner_optimizer._parameter_list
        sd["lookahead"] = {"alpha": self.alpha, "k": self.k,
                           "count": self._eager_count,
                           "slow": [self._slow.get(id(p)) for p in plist]}
        return sd

    def set_state_dict(self, sd):
        la = sd.get("lookahead")
        inner_sd = {k: v for k, v in sd.items() if k != "lookahead"}
        self.inner_optimizer.set_state_dict(inner_sd)
        if la:
            self._eager_count = la.get("count", 0)
            slow = la.get("slow")
            if slow is not None:
                for p, s in zip(self.inner_optimizer._parameter_list, slow):
                    if s is not None:
                        self._slow[id(p)] = jnp.asarray(
                            s._value if isinstance(s, Tensor) else s)


class _AveragerBase:
    """Shared shadow-weight machinery: a name->array shadow tree over a
    Layer's (or param list's) trainable parameters, an ``apply()`` context
    that swaps the shadow in (optionally restoring on exit), and a single
    jitted donated update so tracking costs one XLA call per step."""

    def __init__(self, params_or_model):
        if hasattr(params_or_model, "named_parameters"):
            named = [(k, p) for k, p in params_or_model.named_parameters()
                     if not p.stop_gradient]
        else:
            named = [(f"param_{i}", p) for i, p in enumerate(params_or_model)
                     if not getattr(p, "stop_gradient", False)]
        self._params = named
        # zero-init: both averagers accumulate from zero (EMA debiases, the
        # mean divides by t); no model-sized copy is materialized
        self._shadow = {k: jnp.zeros_like(self._pval(p)) for k, p in named}
        self._backup = None
        self._jit_update = None

    @staticmethod
    def _pval(p):
        return p._master if getattr(p, "_master", None) is not None else p._value

    def _current_tree(self):
        return {k: self._pval(p) for k, p in self._params}

    def _swap_in(self, tree):
        self._backup = {k: (p._value, getattr(p, "_master", None))
                        for k, p in self._params}
        for k, p in self._params:
            v = tree[k]
            if getattr(p, "_master", None) is not None:
                p._master = v
                p._value = v.astype(p._value.dtype)
            else:
                p._value = v.astype(p._value.dtype)

    def restore(self, executor=None):
        if self._backup is None:
            return
        for k, p in self._params:
            v, m = self._backup[k]
            p._value = v
            if m is not None:
                p._master = m
        self._backup = None

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        self._swap_in(self._averaged_tree())
        try:
            yield
        finally:
            if need_restore:
                self.restore()

    def state_dict(self):
        return {"shadow": dict(self._shadow)}

    def set_state_dict(self, sd):
        self._shadow.update(sd.get("shadow", {}))


class ExponentialMovingAverage(_AveragerBase):
    """EMA of model weights: ``shadow = decay * shadow + (1-decay) * param``,
    with the standard zero-debias correction ``shadow / (1 - decay^t)``
    applied at :meth:`apply` time.  Call :meth:`update` once per train step
    (after ``opt.step()`` or a ``TrainStep`` call — it reads the live
    parameter arrays either way).
    """

    def __init__(self, params_or_model, decay=0.999, thres_steps=None, name=None):
        super().__init__(params_or_model)
        self.decay = float(decay)
        self._t = 0

    def update(self):
        self._t += 1
        if self._jit_update is None:
            decay = self.decay

            @jax.jit
            def upd(shadow, cur):  # donation skipped: tiny trees, keeps it simple
                return jax.tree_util.tree_map(
                    lambda s, c: decay * s + (1.0 - decay) * c.astype(s.dtype),
                    shadow, cur)

            self._jit_update = upd
        self._shadow = self._jit_update(self._shadow, self._current_tree())

    def _averaged_tree(self):
        if self._t == 0:  # no update yet: apply() is the identity (reference
            return self._current_tree()  # EMA seeds from the live weights)
        debias = 1.0 - self.decay ** self._t
        return {k: v / debias for k, v in self._shadow.items()}

    def state_dict(self):
        return {"shadow": dict(self._shadow), "t": self._t, "decay": self.decay}

    def set_state_dict(self, sd):
        self._shadow.update(sd.get("shadow", {}))
        self._t = sd.get("t", self._t)
        if "decay" in sd and sd["decay"] != self.decay:
            self.decay = sd["decay"]
            self._jit_update = None  # old closure captured the old decay


class ModelAverage(_AveragerBase):
    """Running (cumulative) average of parameters, the reference
    incubate.ModelAverage simplified to the TPU-friendly exact mean: at
    ``apply()`` the evaluated weights are ``sum_t(param_t) / t``.  The
    window arguments are accepted for API parity; the exact mean over the
    tracked steps is what evaluation uses.
    """

    def __init__(self, average_window_rate=0.15, parameters=None,
                 min_average_window=10000, max_average_window=10000, name=None,
                 model=None):
        target = model if model is not None else (parameters or [])
        super().__init__(target)  # base zero-inits the shadow
        self._t = 0

    def update(self):
        self._t += 1
        if self._jit_update is None:
            @jax.jit
            def upd(shadow, cur):
                return jax.tree_util.tree_map(
                    lambda s, c: s + c.astype(s.dtype), shadow, cur)

            self._jit_update = upd
        self._shadow = self._jit_update(self._shadow, self._current_tree())

    def _averaged_tree(self):
        if self._t == 0:
            return self._current_tree()
        return {k: v / self._t for k, v in self._shadow.items()}

    def state_dict(self):
        return {"shadow": dict(self._shadow), "t": self._t}

    def set_state_dict(self, sd):
        self._shadow.update(sd.get("shadow", {}))
        self._t = sd.get("t", self._t)
