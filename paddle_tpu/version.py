"""paddle.version (reference: generated python/paddle/version/__init__.py)."""

from . import __version__ as _v

full_version = _v
major, minor, patch = (_v.split(".") + ["0", "0"])[:3]
rc = 0
commit = "tpu-native"
cuda_version = "False"
cudnn_version = "False"
tpu = True


def show():
    print(f"paddle_tpu {full_version} (commit {commit}); "
          "backend: jax/XLA on TPU")


def cuda():
    return False


def cudnn():
    return False
