"""paddle_tpu.text — NLP model zoo + tokenization.

Reference analog: PaddleNLP's model zoo (ernie-3.0 / bert / gpt) which the
baseline configs name but which lives outside the core Paddle repo
(SURVEY.md §2.3).  The rebuild carries an in-repo equivalent: BERT/ERNIE
encoders (baseline config #2, fine-tune via to_static/TrainStep) and a GPT
decoder LM whose blocks are TP-sharded through fleet's parallel layers and
homogeneous for the SPMD pipeline engine (config #5).
"""

from . import models  # noqa: F401
from .models import (  # noqa: F401
    BertModel, BertForSequenceClassification, BertForPretraining,
    ErnieModel, ErnieForSequenceClassification,
    GPTModel, GPTForCausalLM,
)
from .tokenizer import SimpleTokenizer, BertTokenizer  # noqa: F401

from .viterbi import ViterbiDecoder, viterbi_decode  # noqa: F401
from .datasets import (  # noqa: F401
    Imdb, Imikolov, Movielens, UCIHousing, WMT14, WMT16,
)
