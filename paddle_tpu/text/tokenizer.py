"""Tokenizers (reference analog: PaddleNLP BertTokenizer — WordPiece over a
BasicTokenizer).  No network egress in this environment, so vocabularies are
built from corpora (`BertTokenizer.from_corpus`) or loaded from a local
vocab file, never downloaded.
"""

from __future__ import annotations

import collections
import re
import unicodedata


class SimpleTokenizer:
    """Whitespace/punctuation word-level tokenizer with a built vocab."""

    PAD, UNK, CLS, SEP, MASK = "[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"

    def __init__(self, vocab=None, lower=True):
        self.lower = lower
        specials = [self.PAD, self.UNK, self.CLS, self.SEP, self.MASK]
        if vocab is None:
            vocab = []
        ordered = specials + [w for w in vocab if w not in specials]
        self.vocab = {w: i for i, w in enumerate(ordered)}
        self.inv_vocab = {i: w for w, i in self.vocab.items()}

    @classmethod
    def from_corpus(cls, texts, max_vocab=30000, lower=True):
        counter = collections.Counter()
        for t in texts:
            counter.update(cls._basic_tokens(t, lower))
        words = [w for w, _ in counter.most_common(max_vocab)]
        return cls(words, lower)

    @staticmethod
    def _basic_tokens(text, lower=True):
        if lower:
            text = text.lower()
        text = unicodedata.normalize("NFKC", text)
        return re.findall(r"\w+|[^\w\s]", text)

    @property
    def vocab_size(self):
        return len(self.vocab)

    @property
    def pad_token_id(self):
        return self.vocab[self.PAD]

    @property
    def unk_token_id(self):
        return self.vocab[self.UNK]

    @property
    def cls_token_id(self):
        return self.vocab[self.CLS]

    @property
    def sep_token_id(self):
        return self.vocab[self.SEP]

    @property
    def mask_token_id(self):
        return self.vocab[self.MASK]

    def tokenize(self, text):
        return [t if t in self.vocab else self.UNK
                for t in self._basic_tokens(text, self.lower)]

    def convert_tokens_to_ids(self, tokens):
        unk = self.unk_token_id
        return [self.vocab.get(t, unk) for t in tokens]

    def convert_ids_to_tokens(self, ids):
        return [self.inv_vocab.get(int(i), self.UNK) for i in ids]

    def __call__(self, text, text_pair=None, max_length=128, padding="max_length",
                 truncation=True, return_token_type_ids=True):
        ids = self.convert_tokens_to_ids(self.tokenize(text))
        pair = self.convert_tokens_to_ids(self.tokenize(text_pair)) if text_pair else []
        cls_, sep = self.cls_token_id, self.sep_token_id
        input_ids = [cls_] + ids + [sep] + (pair + [sep] if pair else [])
        token_type = [0] * (len(ids) + 2) + [1] * (len(pair) + 1 if pair else 0)
        if truncation:
            input_ids = input_ids[:max_length]
            token_type = token_type[:max_length]
        attn = [1] * len(input_ids)
        if padding == "max_length":
            pad = max_length - len(input_ids)
            input_ids += [self.pad_token_id] * pad
            token_type += [0] * pad
            attn += [0] * pad
        return {"input_ids": input_ids, "token_type_ids": token_type,
                "attention_mask": attn}


class BertTokenizer(SimpleTokenizer):
    """WordPiece on top of the basic tokenizer (reference BertTokenizer).

    Build with ``from_corpus`` (learns greedy-longest-match wordpieces from
    word frequency) or with an explicit vocab list/file.
    """

    def __init__(self, vocab=None, lower=True, wordpiece=True,
                 max_input_chars_per_word=100):
        super().__init__(vocab, lower)
        self.wordpiece = wordpiece
        self.max_chars = max_input_chars_per_word

    @classmethod
    def from_vocab_file(cls, path, lower=True):
        with open(path) as f:
            vocab = [line.rstrip("\n") for line in f]
        return cls(vocab, lower)

    @classmethod
    def from_corpus(cls, texts, max_vocab=30000, lower=True, min_freq=2):
        counter = collections.Counter()
        for t in texts:
            counter.update(cls._basic_tokens(t, lower))
        # whole words + suffix pieces (##x) by frequency
        pieces = collections.Counter()
        for w, c in counter.items():
            pieces[w] += c
            for i in range(1, len(w)):
                pieces[w[:i]] += c
                pieces["##" + w[i:]] += c
        words = [w for w, c in pieces.most_common(max_vocab) if c >= min_freq]
        return cls(words, lower)

    def _wordpiece(self, word):
        if len(word) > self.max_chars:
            return [self.UNK]
        out, start = [], 0
        while start < len(word):
            end = len(word)
            cur = None
            while start < end:
                sub = word[start:end]
                if start > 0:
                    sub = "##" + sub
                if sub in self.vocab:
                    cur = sub
                    break
                end -= 1
            if cur is None:
                return [self.UNK]
            out.append(cur)
            start = end
        return out

    def tokenize(self, text):
        out = []
        for w in self._basic_tokens(text, self.lower):
            if not self.wordpiece or w in self.vocab:
                out.append(w if w in self.vocab else self.UNK)
            else:
                out.extend(self._wordpiece(w))
        return out
