"""paddle.text datasets (reference: python/paddle/text/datasets/ —
UCIHousing, Imdb, Imikolov, Movielens, WMT14, WMT16).

No network egress: every dataset takes ``data_file`` pointing at the
standard local archive/directory the reference would have downloaded.
Formats match the reference's extracted layouts; tests use synthetic
fixtures in the same shapes.
"""

from __future__ import annotations

import gzip
import os
import re
import tarfile

import numpy as np

from ..io import Dataset


class UCIHousing(Dataset):
    """Boston-housing regression table: 14 whitespace columns, features
    normalized (x - mean) / (max - min) over the FULL table — the
    reference's feature_range normalization — then split 80/20."""

    TRAIN_RATIO = 0.8

    def __init__(self, data_file=None, mode="train", download=False):
        if data_file is None:
            raise RuntimeError("no network egress; pass data_file "
                               "(housing.data)")
        rows = []
        opener = gzip.open if str(data_file).endswith(".gz") else open
        with opener(data_file, "rt") as f:
            for line in f:
                vals = line.split()
                if len(vals) == 14:
                    rows.append([float(v) for v in vals])
        data = np.asarray(rows, np.float32)
        n_train = int(len(data) * self.TRAIN_RATIO)
        feats, target = data[:, :-1], data[:, -1:]
        mx, mn, avg = feats.max(0), feats.min(0), feats.mean(0)
        feats = (feats - avg) / np.maximum(mx - mn, 1e-6)
        if mode == "train":
            self.x, self.y = feats[:n_train], target[:n_train]
        else:
            self.x, self.y = feats[n_train:], target[n_train:]

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


_WORD_RE = re.compile(r"[A-Za-z0-9']+")


class Imdb(Dataset):
    """IMDB sentiment (aclImdb tar layout: <mode>/{pos,neg}/*.txt inside the
    archive).  Builds the frequency-cutoff word dict from the train split
    (reference semantics); samples are (int64 ids, int64 label 0/1)."""

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 download=False):
        if data_file is None:
            raise RuntimeError("no network egress; pass data_file "
                               "(aclImdb tar/tar.gz or extracted dir)")
        self.mode = mode
        # vocab needs train; samples need `mode` — one archive pass total
        need = {"train", mode}
        docs = {s: [] for s in need}
        for split, label, text in self._iter_docs(data_file, need):
            docs[split].append((text, label))
        freq = {}
        for text, _ in docs["train"]:
            for w in _WORD_RE.findall(text.lower()):
                freq[w] = freq.get(w, 0) + 1
        vocab = sorted(w for w, c in freq.items() if c >= cutoff)
        self.word_idx = {w: i for i, w in enumerate(vocab)}
        self.word_idx["<unk>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        self.docs = [
            (np.asarray([self.word_idx.get(w, unk)
                         for w in _WORD_RE.findall(text.lower())], np.int64),
             np.int64(label))
            for text, label in docs[mode]]

    @staticmethod
    def _iter_docs(data_file, splits):
        """Yield (split, label, text) in ONE pass over the dir/archive."""
        labels = {"neg": 0, "pos": 1}
        path = str(data_file)
        if os.path.isdir(path):
            root = path if os.path.basename(path) == "aclImdb" else \
                os.path.join(path, "aclImdb")
            for split in sorted(splits):
                for sub, label in labels.items():
                    d = os.path.join(root, split, sub)
                    for name in sorted(os.listdir(d)) if os.path.isdir(d) else []:
                        with open(os.path.join(d, name), errors="ignore") as f:
                            yield split, label, f.read()
        else:
            pat = re.compile(r"aclImdb/(train|test)/(pos|neg)/.*\.txt$")
            with tarfile.open(path) as tf:
                for member in tf:
                    m = pat.search(member.name)
                    if m and m.group(1) in splits:
                        yield (m.group(1), labels[m.group(2)],
                               tf.extractfile(member).read().decode(
                                   "utf-8", "ignore"))

    def __len__(self):
        return len(self.docs)

    def __getitem__(self, i):
        return self.docs[i]


class Imikolov(Dataset):
    """PTB language-model n-grams (reference: imikolov dataset over the
    simple-examples ptb.{train,valid}.txt files).

    data_type='NGRAM' yields window_size-grams; 'SEQ' yields (input, target)
    shifted sequences per line.
    """

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50, download=False):
        if data_file is None:
            raise RuntimeError("no network egress; pass data_file "
                               "(simple-examples dir or ptb txt files' dir)")
        names = {"train": "ptb.train.txt", "valid": "ptb.valid.txt",
                 "test": "ptb.test.txt"}
        root = str(data_file)
        cand = [os.path.join(root, names[mode]),
                os.path.join(root, "simple-examples", "data", names[mode])]
        path = next((c for c in cand if os.path.exists(c)), None)
        if path is None:
            raise RuntimeError(f"no {names[mode]} under {root!r}")
        train_path = os.path.join(os.path.dirname(path), names["train"])
        freq = {}
        with open(train_path) as f:
            for line in f:
                for w in line.split():
                    freq[w] = freq.get(w, 0) + 1
        vocab = sorted(w for w, c in freq.items() if c >= min_word_freq)
        self.word_idx = {w: i for i, w in enumerate(vocab)}
        for tok in ("<s>", "<e>", "<unk>"):
            self.word_idx.setdefault(tok, len(self.word_idx))
        unk = self.word_idx["<unk>"]
        self.samples = []
        with open(path) as f:
            for line in f:
                ids = ([self.word_idx["<s>"]]
                       + [self.word_idx.get(w, unk) for w in line.split()]
                       + [self.word_idx["<e>"]])
                if data_type.upper() == "NGRAM":
                    if window_size <= 0:
                        raise ValueError("NGRAM needs window_size > 0")
                    # reference layout: window_size tokens TOTAL, the last
                    # one being the target
                    for i in range(window_size, len(ids) + 1):
                        self.samples.append(
                            np.asarray(ids[i - window_size:i], np.int64))
                else:  # SEQ
                    if len(ids) > 1:
                        self.samples.append(
                            (np.asarray(ids[:-1], np.int64),
                             np.asarray(ids[1:], np.int64)))

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, i):
        return self.samples[i]


class Movielens(Dataset):
    """MovieLens-1M ratings (ml-1m layout: users.dat, movies.dat,
    ratings.dat with '::' separators).  Samples follow the reference shape:
    (user_id, gender, age, occupation, movie_id, title_ids, genre_ids,
    rating)."""

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=False):
        if data_file is None:
            raise RuntimeError("no network egress; pass data_file "
                               "(ml-1m directory or tar)")
        root = str(data_file)
        if os.path.isdir(os.path.join(root, "ml-1m")):
            root = os.path.join(root, "ml-1m")

        def read(name):
            with open(os.path.join(root, name), errors="ignore") as f:
                return [ln.rstrip("\n").split("::") for ln in f if ln.strip()]

        users = {u[0]: u for u in read("users.dat")}
        movies = {}
        titles, genres = {}, {}
        for mid, title, genre in read("movies.dat"):
            words = _WORD_RE.findall(title.lower())
            for w in words:
                titles.setdefault(w, len(titles))
            gs = genre.split("|")
            for g in gs:
                genres.setdefault(g, len(genres))
            movies[mid] = (words, gs)
        rng = np.random.RandomState(rand_seed)
        self.samples = []
        for uid, mid, rating, _ts in read("ratings.dat"):
            if uid not in users or mid not in movies:
                continue
            is_test = rng.rand() < test_ratio
            if (mode == "test") != is_test:
                continue
            _, gender, age, occupation, _zip = users[uid]
            words, gs = movies[mid]
            self.samples.append((
                np.int64(uid), np.int64(0 if gender == "M" else 1),
                np.int64(age), np.int64(occupation), np.int64(mid),
                np.asarray([titles[w] for w in words], np.int64),
                np.asarray([genres[g] for g in gs], np.int64),
                np.float32(rating)))

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, i):
        return self.samples[i]


class _WMTBase(Dataset):
    """Parallel-corpus reader: <prefix>.<src_lang> / <prefix>.<trg_lang>
    line-aligned text files, dictionary truncated to dict_size by train-side
    frequency.  Samples are (src_ids, trg_ids[:-1], trg_ids[1:]) with
    <s>/<e>/<unk> ids 0/1/2 (reference convention)."""

    SRC_LANG = "en"
    TRG_LANG = "de"
    FILES = {"train": "train", "dev": "dev", "test": "test"}

    def __init__(self, data_file=None, mode="train", src_dict_size=-1,
                 trg_dict_size=-1, lang=None, download=False):
        if data_file is None:
            raise RuntimeError("no network egress; pass data_file "
                               "(extracted corpus directory)")
        src_lang, trg_lang = self.SRC_LANG, self.TRG_LANG
        if lang is not None:
            # reference: lang names the SOURCE side; the other becomes target
            if lang not in (self.SRC_LANG, self.TRG_LANG):
                raise ValueError(f"lang must be {self.SRC_LANG!r} or "
                                 f"{self.TRG_LANG!r}, got {lang!r}")
            if lang == self.TRG_LANG:
                src_lang, trg_lang = self.TRG_LANG, self.SRC_LANG
        root = str(data_file)
        prefix = os.path.join(root, self.FILES[mode])
        train_prefix = os.path.join(root, self.FILES["train"])
        self.src_dict = self._dict(f"{train_prefix}.{src_lang}",
                                   src_dict_size)
        self.trg_dict = self._dict(f"{train_prefix}.{trg_lang}",
                                   trg_dict_size)
        with open(f"{prefix}.{src_lang}") as f:
            src_lines = [ln.split() for ln in f]
        with open(f"{prefix}.{trg_lang}") as f:
            trg_lines = [ln.split() for ln in f]
        self.samples = []
        for s, t in zip(src_lines, trg_lines):
            sid = [self.src_dict.get(w, 2) for w in s]
            tid = [0] + [self.trg_dict.get(w, 2) for w in t] + [1]
            self.samples.append((np.asarray(sid, np.int64),
                                 np.asarray(tid[:-1], np.int64),
                                 np.asarray(tid[1:], np.int64)))

    @staticmethod
    def _dict(path, size):
        freq = {}
        with open(path) as f:
            for line in f:
                for w in line.split():
                    freq[w] = freq.get(w, 0) + 1
        ordered = sorted(freq, key=lambda w: (-freq[w], w))
        if size and size > 0:
            ordered = ordered[:max(size - 3, 0)]
        return {w: i + 3 for i, w in enumerate(ordered)}  # 0/1/2 reserved

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, i):
        return self.samples[i]


class WMT14(_WMTBase):
    SRC_LANG, TRG_LANG = "en", "fr"


class WMT16(_WMTBase):
    SRC_LANG, TRG_LANG = "en", "de"
