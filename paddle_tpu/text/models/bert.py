"""BERT / ERNIE encoders (reference analog: PaddleNLP
paddlenlp/transformers/bert/modeling.py and ernie/modeling.py — the
ERNIE-3.0-base fine-tune is baseline config #2, SURVEY.md §2.3).

TPU-first: the whole encoder is trace-friendly (static shapes, no Python
control flow on values), so a fine-tune step through TrainStep/to_static is
one fused XLA program.  ERNIE-3.0-base is architecturally a BERT encoder
(relative task heads aside), so ErnieModel shares the implementation with
its own defaults.
"""

from __future__ import annotations

import jax.numpy as jnp

from ...nn import functional as F
from ...nn.layer import Layer
from ...nn.layers.common import Dropout, Embedding, Linear
from ...nn.layers.norm import LayerNorm
from ...nn.layers.transformer import TransformerEncoder, TransformerEncoderLayer
from ...tensor.dispatch import apply as _apply
from ...tensor.tensor import Tensor


class BertEmbeddings(Layer):
    def __init__(self, vocab_size, hidden_size, hidden_dropout_prob,
                 max_position_embeddings, type_vocab_size, pad_token_id=0):
        super().__init__()
        self.word_embeddings = Embedding(vocab_size, hidden_size,
                                         padding_idx=pad_token_id)
        self.position_embeddings = Embedding(max_position_embeddings, hidden_size)
        self.token_type_embeddings = Embedding(type_vocab_size, hidden_size)
        self.layer_norm = LayerNorm(hidden_size, 1e-12)
        self.dropout = Dropout(hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        if position_ids is None:
            seq = input_ids.shape[1]
            position_ids = Tensor(jnp.arange(seq, dtype=jnp.int64)[None, :])
        if token_type_ids is None:
            position_vals = input_ids._value
            token_type_ids = Tensor(jnp.zeros_like(position_vals))
        emb = (self.word_embeddings(input_ids)
               + self.position_embeddings(position_ids)
               + self.token_type_embeddings(token_type_ids))
        return self.dropout(self.layer_norm(emb))


class BertPooler(Layer):
    def __init__(self, hidden_size):
        super().__init__()
        self.dense = Linear(hidden_size, hidden_size)

    def forward(self, hidden_states):
        return F.tanh(self.dense(hidden_states[:, 0]))


class BertModel(Layer):
    """reference: BertModel(vocab_size, hidden_size=768, ...) returning
    (sequence_output, pooled_output)."""

    def __init__(self, vocab_size=30522, hidden_size=768, num_hidden_layers=12,
                 num_attention_heads=12, intermediate_size=3072, hidden_act="gelu",
                 hidden_dropout_prob=0.1, attention_probs_dropout_prob=0.1,
                 max_position_embeddings=512, type_vocab_size=2,
                 initializer_range=0.02, pad_token_id=0, pool_act="tanh"):
        super().__init__()
        self.pad_token_id = pad_token_id
        self.embeddings = BertEmbeddings(vocab_size, hidden_size,
                                         hidden_dropout_prob,
                                         max_position_embeddings, type_vocab_size,
                                         pad_token_id)
        enc_layer = TransformerEncoderLayer(
            hidden_size, num_attention_heads, intermediate_size,
            dropout=hidden_dropout_prob, activation=hidden_act,
            attn_dropout=attention_probs_dropout_prob, act_dropout=0.0,
            normalize_before=False, layer_norm_eps=1e-12)
        self.encoder = TransformerEncoder(enc_layer, num_hidden_layers)
        self.pooler = BertPooler(hidden_size)

    def _attn_mask(self, input_ids, attention_mask):
        if attention_mask is None:
            attention_mask = _apply(
                lambda ids: (ids != self.pad_token_id).astype(jnp.float32),
                input_ids, op_name="pad_mask")
        # [B, S] -> additive [B, 1, 1, S]
        return _apply(
            lambda m: ((1.0 - m.astype(jnp.float32)) * -1e4)[:, None, None, :],
            attention_mask, op_name="extend_mask")

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        mask = self._attn_mask(input_ids, attention_mask)
        emb = self.embeddings(input_ids, token_type_ids, position_ids)
        seq_out = self.encoder(emb, mask)
        return seq_out, self.pooler(seq_out)


class ErnieModel(BertModel):
    """ERNIE-3.0-base shape defaults (BERT-base-compatible encoder)."""

    def __init__(self, vocab_size=40000, hidden_size=768, num_hidden_layers=12,
                 num_attention_heads=12, intermediate_size=3072, hidden_act="gelu",
                 hidden_dropout_prob=0.1, attention_probs_dropout_prob=0.1,
                 max_position_embeddings=2048, type_vocab_size=4,
                 initializer_range=0.02, pad_token_id=0, **kw):
        super().__init__(vocab_size, hidden_size, num_hidden_layers,
                         num_attention_heads, intermediate_size, hidden_act,
                         hidden_dropout_prob, attention_probs_dropout_prob,
                         max_position_embeddings, type_vocab_size,
                         initializer_range, pad_token_id)


class BertForSequenceClassification(Layer):
    def __init__(self, bert=None, num_classes=2, dropout=None, **bert_kwargs):
        super().__init__()
        self.bert = bert if bert is not None else BertModel(**bert_kwargs)
        hidden = self.bert.pooler.dense.weight.shape[0]
        self.dropout = Dropout(dropout if dropout is not None else 0.1)
        self.classifier = Linear(hidden, num_classes)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        _, pooled = self.bert(input_ids, token_type_ids, position_ids,
                              attention_mask)
        return self.classifier(self.dropout(pooled))


class ErnieForSequenceClassification(BertForSequenceClassification):
    def __init__(self, ernie=None, num_classes=2, dropout=None, **kw):
        super().__init__(bert=ernie if ernie is not None else ErnieModel(**kw),
                         num_classes=num_classes, dropout=dropout)


class BertLMPredictionHead(Layer):
    def __init__(self, hidden_size, vocab_size, activation="gelu",
                 embedding_weights=None):
        super().__init__()
        self.transform = Linear(hidden_size, hidden_size)
        self.activation = getattr(F, activation)
        self.layer_norm = LayerNorm(hidden_size, 1e-12)
        from ...nn import initializer as I

        if embedding_weights is None:
            self.decoder_weight = self.create_parameter(
                [vocab_size, hidden_size], default_initializer=I.XavierNormal())
        else:
            # tied to the embedding table: must NOT register as a second
            # parameter (double registration would double-apply optimizer
            # updates eagerly and break bind() under TrainStep) — keep a
            # plain reference, read at forward time like GPT's tied head
            object.__setattr__(self, "_tied_weight", embedding_weights)
        self.decoder_bias = self.create_parameter([vocab_size], is_bias=True)

    @property
    def _weight(self):
        tied = self.__dict__.get("_tied_weight")
        return tied if tied is not None else self.decoder_weight

    def forward(self, hidden_states):
        h = self.layer_norm(self.activation(self.transform(hidden_states)))
        return _apply(lambda hv, w, b: hv @ w.T + b, h, self._weight,
                      self.decoder_bias, op_name="matmul")


class BertForPretraining(Layer):
    """MLM + NSP heads (reference BertForPretraining)."""

    def __init__(self, bert=None, **bert_kwargs):
        super().__init__()
        self.bert = bert if bert is not None else BertModel(**bert_kwargs)
        hidden = self.bert.pooler.dense.weight.shape[0]
        vocab = self.bert.embeddings.word_embeddings.weight.shape[0]
        self.cls = BertLMPredictionHead(
            hidden, vocab, embedding_weights=self.bert.embeddings.word_embeddings.weight)
        self.nsp = Linear(hidden, 2)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        seq, pooled = self.bert(input_ids, token_type_ids, position_ids,
                                attention_mask)
        return self.cls(seq), self.nsp(pooled)


class BertPretrainingCriterion(Layer):
    def __init__(self, vocab_size):
        super().__init__()
        self.vocab_size = vocab_size

    def forward(self, prediction_scores, seq_relationship_score, masked_lm_labels,
                next_sentence_labels, masked_lm_scale=1.0):
        mlm = F.cross_entropy(prediction_scores.reshape([-1, self.vocab_size]),
                              masked_lm_labels.reshape([-1]), ignore_index=-100,
                              reduction="mean")
        nsp = F.cross_entropy(seq_relationship_score,
                              next_sentence_labels.reshape([-1]), reduction="mean")
        return mlm + nsp
