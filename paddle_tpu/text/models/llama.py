"""Llama family (reference analog: PaddleNLP paddlenlp/transformers/llama —
the modern decoder architecture: RMSNorm pre-norm, rotary position
embeddings, grouped-query attention, SwiGLU MLP, no biases).

TPU-first notes:
- RoPE uses the HF half-split rotate convention so weights interchange
  with the torch/transformers reference bit-for-bit (cross-validated in
  tests/test_text.py).
- GQA K/V heads are repeated to the query head count BEFORE sdpa, so the
  Pallas flash kernel serves the attention (the repeat is a broadcast XLA
  folds into the kernel's K/V loads).
- Projections route through the same column/row-parallel helpers as GPT:
  under a live 'mp' mesh axis the weights shard and the partitioner
  inserts the Megatron collectives.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...nn import functional as F
from ...nn.layer import Layer
from ...nn.layers.norm import RMSNorm
from ...tensor.dispatch import apply as _apply
from ...tensor.tensor import Tensor
from .gpt import _col_linear, _row_linear, _vocab_embedding

__all__ = ["LlamaModel", "LlamaForCausalLM", "LlamaConfig"]


class LlamaConfig(dict):
    """Config bag (attribute + dict access, PaddleNLP-style)."""

    def __init__(self, **kw):
        defaults = dict(vocab_size=32000, hidden_size=4096,
                        intermediate_size=11008, num_hidden_layers=32,
                        num_attention_heads=32, num_key_value_heads=None,
                        max_position_embeddings=4096, rms_norm_eps=1e-6,
                        rope_theta=10000.0, tie_word_embeddings=False)
        defaults.update(kw)
        if defaults["num_key_value_heads"] is None:
            defaults["num_key_value_heads"] = defaults["num_attention_heads"]
        super().__init__(**defaults)
        self.__dict__ = self


def _rope_cos_sin(positions, head_dim, theta):
    """[S] or [B, S] int positions -> cos/sin [..., S, head_dim] in the HF
    half-split layout (freqs duplicated across the two halves)."""
    inv = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, jnp.float32) / head_dim))
    ang = positions.astype(jnp.float32)[..., None] * inv        # [..., S, d/2]
    ang = jnp.concatenate([ang, ang], axis=-1)                  # [..., S, d]
    return jnp.cos(ang), jnp.sin(ang)


def _rotate_half(x):
    half = x.shape[-1] // 2
    return jnp.concatenate([-x[..., half:], x[..., :half]], axis=-1)


def _apply_rope(q, k, cos, sin):
    """q/k [B, S, h, d]; cos/sin [S, d] or [B, S, d] broadcast over heads."""
    if cos.ndim == 2:
        cos, sin = cos[None], sin[None]
    c = cos[:, :, None, :]
    s = sin[:, :, None, :]
    return q * c + _rotate_half(q) * s, k * c + _rotate_half(k) * s


def _reference_init(layer):
    """HF _init_weights: every >=2D weight N(0, 0.02), preserving any TP
    sharding already laid on the parameter."""
    import jax.random as _jr

    from ...framework import random as _rng

    key = _rng.next_key()
    for _, p in layer.named_parameters():
        if p._value.ndim >= 2:
            key, sub = _jr.split(key)
            new = (0.02 * _jr.normal(sub, p._value.shape, jnp.float32)
                   ).astype(p._value.dtype)
            sh = p._value.sharding
            if hasattr(sh, "spec"):
                new = jax.device_put(new, sh)
            p._value = new


class LlamaMLP(Layer):
    """SwiGLU: down(silu(gate(x)) * up(x)) — two column-parallel inputs,
    one row-parallel output (Megatron layout)."""

    def __init__(self, hidden_size, intermediate_size):
        super().__init__()
        # llama uses no biases (bias=False reaches the TP classes too)
        self.gate_proj = _col_linear(hidden_size, intermediate_size, bias=False)
        self.up_proj = _col_linear(hidden_size, intermediate_size, bias=False)
        self.down_proj = _row_linear(intermediate_size, hidden_size, bias=False)

    def forward(self, x):
        return self.down_proj(F.silu(self.gate_proj(x)) * self.up_proj(x))


class LlamaAttention(Layer):
    def __init__(self, config):
        super().__init__()
        h = config.hidden_size
        self.num_heads = config.num_attention_heads
        self.num_kv_heads = config.num_key_value_heads
        self.head_dim = h // self.num_heads
        self.rope_theta = config.rope_theta
        self.q_proj = _col_linear(h, self.num_heads * self.head_dim,
                                  bias=False)
        self.k_proj = _col_linear(h, self.num_kv_heads * self.head_dim,
                                  bias=False)
        self.v_proj = _col_linear(h, self.num_kv_heads * self.head_dim,
                                  bias=False)
        self.o_proj = _row_linear(self.num_heads * self.head_dim, h,
                                  bias=False)

    def forward(self, x, rope, attn_bias=None, cache=None):
        B, S = x.shape[0], x.shape[1]
        hd = self.head_dim
        q = self.q_proj(x)
        k = self.k_proj(x)
        v = self.v_proj(x)
        # local head counts from actual widths (TP shards carry h/mp heads)
        hq = q.shape[-1] // hd
        hkv = k.shape[-1] // hd
        rep = hq // hkv

        def attend(qv, kv, vv, cos, sin):
            qh = qv.reshape(B, S, hq, hd)
            kh = kv.reshape(B, S, hkv, hd)
            vh = vv.reshape(B, S, hkv, hd)
            qh, kh = _apply_rope(qh, kh, cos, sin)
            return qh, kh, vh

        qh, kh, vh = _apply(attend, q, k, v, rope[0], rope[1],
                            op_name="llama_rope", n_outs=3)
        if cache is not None and len(cache) == 4 and cache[0] == "paged":
            # PAGED cache: per-layer [B, PP, ps, hkv, hd] pools, keys stored
            # pre-rotated like the dense path; GQA attends grouped against
            # the pools (no repeated-KV materialization in HBM) via
            # ops.paged_attention's length-bounded flash-decode kernel —
            # each page streams once for all g query heads of its KV head,
            # and the sweep is clamped per row by the prefetched seq_lens.
            from ...ops.paged_attention import (paged_decode_attend,
                                                paged_prefill_write,
                                                paged_token_write)

            _, kp, vp, pos = cache
            if attn_bias is not None:
                raise NotImplementedError(
                    "paged cache + attention_mask: per-sequence padding "
                    "masks belong in seq_lens (PagedKVCache) — the uniform "
                    "generate() paged path does not take a mask")
            if S > 1:  # prefill: dense causal attention + page write
                kf, vf = kh, vh
                if rep > 1:
                    kf = _apply(lambda t: jnp.repeat(t, rep, axis=2), kh,
                                op_name="gqa_repeat")
                    vf = _apply(lambda t: jnp.repeat(t, rep, axis=2), vh,
                                op_name="gqa_repeat")
                att = F.scaled_dot_product_attention(qh, kf, vf,
                                                     is_causal=True,
                                                     training=False)
                kp = _apply(paged_prefill_write, kp, kh, op_name="paged_write")
                vp = _apply(paged_prefill_write, vp, vh, op_name="paged_write")
            else:
                kp = _apply(lambda pgs, kk, p: paged_token_write(pgs, kk[:, 0], p),
                            kp, kh, pos, op_name="paged_write")
                vp = _apply(lambda pgs, vv, p: paged_token_write(pgs, vv[:, 0], p),
                            vp, vh, pos, op_name="paged_write")
                att = _apply(
                    lambda qq, kps, vps, p:
                        paged_decode_attend(qq[:, 0], kps, vps, p)[:, None],
                    qh, kp, vp, pos, op_name="paged_attention")
            att = att.reshape([B, S, hq * hd])
            return self.o_proj(att), ("paged", kp, vp, pos)
        if cache is not None:
            # STATIC cache decode (GPT pattern): fixed [B, T, hkv, hd]
            # buffers updated in place at ``pos``; keys stored PRE-ROTATED
            k_buf, v_buf, pos = cache

            def write(buf, new, p):
                # rope math runs in f32; store in the buffer's dtype
                return jax.lax.dynamic_update_slice_in_dim(
                    buf, new.astype(buf.dtype), p, 1)

            k_buf = _apply(write, k_buf, kh, pos, op_name="cache_write")
            v_buf = _apply(write, v_buf, vh, pos, op_name="cache_write")
            T = k_buf.shape[1]

            def expand_and_mask(kb, vb, p, *bias):
                kk, vv2 = kb, vb
                if rep > 1:
                    kk = jnp.repeat(kk, rep, axis=2)
                    vv2 = jnp.repeat(vv2, rep, axis=2)
                i = jnp.arange(S, dtype=jnp.int32)[:, None]
                j = jnp.arange(T, dtype=jnp.int32)[None, :]
                m = jnp.where(j <= p + i, jnp.float32(0.0),
                              jnp.float32(-1e30))[None, None]
                if bias:  # key-side padding bias [B,1,1,T] joins the mask
                    b = bias[0]
                    if b.shape[-1] != T:
                        raise ValueError(
                            f"cache-mode attention_mask must cover all "
                            f"{T} cache slots, got {b.shape[-1]}")
                    m = m + b
                return kk, vv2, m

            mask_args = (k_buf, v_buf, pos) + (
                (attn_bias,) if attn_bias is not None else ())
            kf, vf, mask = _apply(expand_and_mask, *mask_args,
                                  op_name="cache_expand", n_outs=3)
            att = F.scaled_dot_product_attention(qh, kf, vf, attn_mask=mask,
                                                 dropout_p=0.0,
                                                 training=False)
            att = att.reshape([B, S, hq * hd])
            return self.o_proj(att), (k_buf, v_buf, pos)
        if rep > 1:  # GQA: broadcast kv heads up to the q head count
            kh = _apply(lambda t: jnp.repeat(t, rep, axis=2), kh,
                        op_name="gqa_repeat")
            vh = _apply(lambda t: jnp.repeat(t, rep, axis=2), vh,
                        op_name="gqa_repeat")
        if attn_bias is not None:
            att = F.scaled_dot_product_attention(qh, kh, vh,
                                                 attn_mask=attn_bias,
                                                 training=self.training)
        else:
            att = F.scaled_dot_product_attention(qh, kh, vh, is_causal=True,
                                                 training=self.training)
        att = att.reshape([B, S, hq * hd])
        return self.o_proj(att)


class LlamaDecoderLayer(Layer):
    def __init__(self, config):
        super().__init__()
        self.input_layernorm = RMSNorm(config.hidden_size,
                                       epsilon=config.rms_norm_eps)
        self.self_attn = LlamaAttention(config)
        self.post_attention_layernorm = RMSNorm(config.hidden_size,
                                                epsilon=config.rms_norm_eps)
        self.mlp = LlamaMLP(config.hidden_size, config.intermediate_size)

    def forward(self, x, rope, attn_bias=None, cache=None):
        if cache is not None:
            att, new_cache = self.self_attn(self.input_layernorm(x), rope,
                                            attn_bias, cache)
            x = x + att
            x = x + self.mlp(self.post_attention_layernorm(x))
            return x, new_cache
        x = x + self.self_attn(self.input_layernorm(x), rope, attn_bias)
        x = x + self.mlp(self.post_attention_layernorm(x))
        return x


class LlamaModel(Layer):
    def __init__(self, config=None, **kw):
        super().__init__()
        self.config = config if isinstance(config, LlamaConfig) \
            else LlamaConfig(**(config or {}), **kw)
        cfg = self.config
        self.embed_tokens = _vocab_embedding(cfg.vocab_size, cfg.hidden_size)
        self.layers = [LlamaDecoderLayer(cfg)
                       for _ in range(cfg.num_hidden_layers)]
        for i, l in enumerate(self.layers):
            self.add_sublayer(f"layers.{i}", l)
        self.norm = RMSNorm(cfg.hidden_size, epsilon=cfg.rms_norm_eps)
        # reference init — the Embedding default N(0,1) would start CE ~8x
        # above ln(V)
        _reference_init(self)

    def forward(self, input_ids, position_ids=None, attention_mask=None,
                cache=None):
        x = self.embed_tokens(input_ids)
        S = x.shape[1]
        if position_ids is None:
            position_ids = Tensor(jnp.arange(S, dtype=jnp.int32))
        hd = self.config.hidden_size // self.config.num_attention_heads
        theta = self.config.rope_theta
        # rope tables + padding bias built ONCE and shared by all layers
        cos, sin = _apply(
            lambda pos: _rope_cos_sin(pos, hd, theta), position_ids,
            op_name="rope_tables", n_outs=2)
        bias = None
        if attention_mask is not None:
            if cache is not None:
                # cache mode: the mask covers KEY SLOTS [B, T_cache]; the
                # causal part comes from the cache position mask
                def build_kbias(am):
                    return jnp.where(am.astype(jnp.bool_), 0.0,
                                     -1e30).astype(jnp.float32)[:, None,
                                                                None, :]

                bias = _apply(build_kbias, attention_mask,
                              op_name="llama_key_pad")
            else:
                def build_bias(am):
                    # [B, S] padding mask -> additive causal+pad [B,1,S,S]
                    pad = jnp.where(am.astype(jnp.bool_), 0.0,
                                    -1e30)[:, None, None, :]
                    i = jnp.arange(S)[:, None]
                    j = jnp.arange(S)[None, :]
                    causal = jnp.where(j <= i, 0.0, -1e30)[None, None]
                    return (pad + causal).astype(jnp.float32)

                bias = _apply(build_bias, attention_mask,
                              op_name="llama_mask")
        if cache is not None:
            new_caches = []
            for layer, c in zip(self.layers, cache):
                x, nc = layer(x, (cos, sin), bias, c)
                new_caches.append(nc)
            return self.norm(x), new_caches
        for layer in self.layers:
            x = layer(x, (cos, sin), bias)
        return self.norm(x)


class LlamaForCausalLM(Layer):
    def __init__(self, config=None, **kw):
        super().__init__()
        self.llama = LlamaModel(config, **kw)
        cfg = self.llama.config
        self.tie = cfg.tie_word_embeddings
        if not self.tie:
            self.lm_head = _col_linear(cfg.hidden_size, cfg.vocab_size,
                                       bias=False)
            _reference_init(self.lm_head)

    def forward(self, input_ids, position_ids=None, attention_mask=None,
                labels=None):
        hidden = self.llama(input_ids, position_ids, attention_mask)
        if self.tie:
            w = self.llama.embed_tokens.weight  # [vocab, hidden]
            logits = _apply(lambda h, wv: h @ wv.T, hidden, w,
                            op_name="matmul")
        else:
            logits = self.lm_head(hidden)
        if labels is not None:
            return F.cross_entropy(
                logits[:, :-1].reshape([-1, logits.shape[-1]]),
                labels[:, 1:].reshape([-1]), reduction="mean")
        return logits

    def generate(self, input_ids, max_new_tokens=32, temperature=1.0,
                 top_k=0, top_p=1.0, seed=None, use_cache=True,
                 decode_strategy="sampling", num_beams=4, length_penalty=0.0,
                 eos_token_id=None, cache_impl="dense", page_size=16,
                 max_len=None):
        """Autoregressive decode.

        ``use_cache=True`` (default): jitted two-phase decode — compiled
        prefill writes the prompt K/V into fixed [B, T, hkv, hd] buffers
        (keys stored pre-rotated), then ONE compiled single-token step
        (donated cache, static shapes) runs per new token.  Greedy output
        is identical to the eager loop.  ``use_cache=False``: eager
        full-prefix loop (debug/reference path).

        ``cache_impl="paged"``: block-paged KV pools + the Pallas
        paged-attention kernel; GQA attends grouped against the pools, so
        the kv cache stays at hkv heads in HBM (see GPT.generate)."""
        if decode_strategy == "beam_search":
            from ._decode import beam_search

            return beam_search(self, input_ids, max_new_tokens,
                               num_beams=num_beams,
                               length_penalty=length_penalty,
                               eos_token_id=eos_token_id)
        if not use_cache:
            return self._generate_eager(input_ids, max_new_tokens,
                                        temperature, top_k, top_p, seed)
        if max_new_tokens <= 0:
            return input_ids
        import numpy as np

        ids0 = np.asarray(input_ids.numpy()).astype("int64")
        B, S0 = ids0.shape
        # max_len pre-sizes the cache independently of max_new_tokens (see
        # GPT.generate)
        T = max(S0 + max_new_tokens, max_len or 0)
        cfg = self.llama.config
        if T > cfg.max_position_embeddings:
            raise ValueError(
                f"generate: prompt {S0} + max_new_tokens {max_new_tokens} "
                f"(cache {T}) exceeds max_position_embeddings "
                f"{cfg.max_position_embeddings}")
        L = cfg.num_hidden_layers
        hkv = cfg.num_key_value_heads
        hd = cfg.hidden_size // cfg.num_attention_heads

        from ...framework import random as _rng
        from ...framework.state import no_grad_ctx
        from ._decode import jitted_decode

        dt0 = self.llama.embed_tokens.weight._value.dtype
        if cache_impl == "paged":
            from ._decode import decode_loop, paged_pool_shape

            pool = paged_pool_shape(B, T, hkv, hd, page_size)

            def fwd_paged(params, bufs, ids, cache, pos):
                kps, vps = cache
                with no_grad_ctx(), _rng.rng_scope(jax.random.key(0)), \
                        self.bind(params, bufs):
                    S = ids.shape[1]
                    pos_ids = Tensor(pos + jnp.arange(S, dtype=jnp.int32))
                    lc = [("paged", Tensor(kps[i]), Tensor(vps[i]),
                           Tensor(pos)) for i in range(L)]
                    hidden, new_cache = self.llama(Tensor(ids),
                                                   position_ids=pos_ids,
                                                   cache=lc)
                    h = hidden._value[:, -1].astype(jnp.float32)
                    if self.tie:
                        w = self.llama.embed_tokens.weight._value
                        logits = h @ w.T.astype(jnp.float32)
                    else:
                        logits = h @ self.lm_head.weight._value.astype(jnp.float32)
                    kps = jnp.stack([c[1]._value for c in new_cache])
                    vps = jnp.stack([c[2]._value for c in new_cache])
                return logits, (kps, vps)

            def init_cache():
                kp = jnp.zeros((L,) + pool, dt0)
                return kp, jnp.zeros_like(kp)

            return decode_loop(self, fwd_paged, ids0, max_new_tokens,
                               init_cache, temperature=temperature,
                               top_k=top_k, top_p=top_p, seed=seed,
                               program_key=("paged", B, S0, T, page_size,
                                            temperature, top_k, top_p,
                                            bool(self.training)))
        if cache_impl != "dense":
            raise ValueError(f"cache_impl must be 'dense' or 'paged', "
                             f"got {cache_impl!r}")

        def fwd(params, bufs, ids, ks, vs, pos):
            with no_grad_ctx(), _rng.rng_scope(jax.random.key(0)), \
                    self.bind(params, bufs):
                S = ids.shape[1]
                pos_ids = Tensor(pos + jnp.arange(S, dtype=jnp.int32))
                cache = [(Tensor(ks[i]), Tensor(vs[i]), Tensor(pos))
                         for i in range(L)]
                hidden, new_cache = self.llama(Tensor(ids),
                                               position_ids=pos_ids,
                                               cache=cache)
                h = hidden._value[:, -1].astype(jnp.float32)
                if self.tie:
                    w = self.llama.embed_tokens.weight._value
                    logits = h @ w.T.astype(jnp.float32)
                else:
                    logits = h @ self.lm_head.weight._value.astype(jnp.float32)
                ks = jnp.stack([c[0]._value for c in new_cache])
                vs = jnp.stack([c[1]._value for c in new_cache])
            return logits, ks, vs

        dt = self.llama.embed_tokens.weight._value.dtype
        return jitted_decode(self, fwd, ids0, max_new_tokens,
                             (L, B, T, hkv, hd), dt,
                             temperature=temperature, top_k=top_k,
                             top_p=top_p, seed=seed)

    def _generate_eager(self, input_ids, max_new_tokens=32, temperature=1.0,
                        top_k=0, top_p=1.0, seed=None):
        """Greedy/sampled decode, eager full-prefix loop (reference path)."""
        import numpy as np

        ids = np.asarray(input_ids.numpy()).astype("int64")
        rs = np.random.RandomState(seed if seed is not None else 0)
        was = [(m, m.training) for m in self.sublayers(include_self=True)]
        self.eval()
        try:
            for _ in range(max_new_tokens):
                logits = self(Tensor(jnp.asarray(ids))).numpy()[:, -1]
                if temperature == 0.0:
                    nxt = logits.argmax(-1)
                else:
                    logits = logits / max(temperature, 1e-6)
                    if top_k:
                        top_k = min(int(top_k), logits.shape[-1])
                        kth = np.sort(logits, -1)[:, -top_k][:, None]
                        logits = np.where(logits < kth, -np.inf, logits)
                    p = np.exp(logits - logits.max(-1, keepdims=True))
                    p = p / p.sum(-1, keepdims=True)
                    if top_p < 1.0:  # nucleus: keep the smallest top set
                        srt = np.argsort(-p, axis=-1)
                        ps = np.take_along_axis(p, srt, -1)
                        keep = np.cumsum(ps, -1) - ps < top_p
                        ps = np.where(keep, ps, 0.0)
                        ps = ps / ps.sum(-1, keepdims=True)
                        pick = np.stack([rs.choice(ps.shape[-1], p=ps[b])
                                         for b in range(ps.shape[0])])
                        nxt = np.take_along_axis(srt, pick[:, None], -1)[:, 0]
                    else:
                        nxt = np.stack([rs.choice(p.shape[-1], p=p[b])
                                        for b in range(p.shape[0])])
                ids = np.concatenate([ids, nxt[:, None]], axis=1)
        finally:
            for m, t in was:
                m.training = t
        return Tensor(jnp.asarray(ids))
