"""Shared jitted KV-cache decode loop (used by GPT and Llama heads).

The per-model piece is ONE closure: ``fwd(params, bufs, ids, ks, vs, pos)
-> (last-token logits f32, new ks, new vs)`` over stacked [L, B, T, h, d]
cache buffers.  This module owns everything else — sampling (greedy /
temperature / top-k / top-p as traced ops), the compiled prefill, the
single compiled decode step with DONATED cache buffers, and the
train-mode save/restore discipline — so decode fixes land in one place.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ...tensor.tensor import Tensor


def program_store(model):
    """The per-model compiled-program cache.

    decode_loop keys it by its program_key tuples; the serving engine
    (paddle_tpu.serving) keys it by (kind, batch-shape, sampler) tuples so
    a second engine over the same model reuses the compiled prefill/step
    pair instead of re-tracing.  Stored via object.__setattr__ so Layer's
    attribute bookkeeping never sees it."""
    store = model.__dict__.get("_decode_programs")
    if store is None:
        store = {}
        object.__setattr__(model, "_decode_programs", store)
    return store


def apply_top_k_top_p(l, top_k, top_p):
    """Static top-k / top-p (nucleus) filtering on [N, V] logits.

    top_k/top_p are trace-time constants (part of every compiled program's
    key); filtered entries become -inf.  Shared by the generate() samplers,
    the serving engine's batched sampler, and the speculative-decoding
    verifier (serving/speculative.py), so the three paths can never drift
    on what distribution "temperature + top_k/top_p" means."""
    if top_k:
        kk = min(int(top_k), l.shape[-1])
        kth = jax.lax.top_k(l, kk)[0][:, -1][:, None]
        l = jnp.where(l < kth, -jnp.inf, l)
    if top_p < 1.0:  # nucleus: smallest prefix of sorted probs >= top_p
        srt = jnp.sort(l, axis=-1)[:, ::-1]
        p = jax.nn.softmax(srt, axis=-1)
        keep_n = (jnp.cumsum(p, axis=-1) - p < top_p).sum(-1)
        kth = jnp.take_along_axis(srt, (keep_n - 1)[:, None], axis=-1)
        l = jnp.where(l < kth, -jnp.inf, l)
    return l


def make_sampler(temperature, top_k, top_p):
    def sample(logits, key):
        if temperature == 0.0:
            return jnp.argmax(logits, axis=-1)
        l = logits / jnp.float32(max(temperature, 1e-6))
        l = apply_top_k_top_p(l, top_k, top_p)
        return jax.random.categorical(key, l, axis=-1)

    return sample


def make_batched_sampler(top_k=0, top_p=1.0):
    """Per-slot sampler for the serving engine: ONE traced program covers
    greedy and temperature rows (``temps[b] <= 0`` selects argmax), so a
    batch mixing greedy and sampled requests shares a single compiled
    decode step.  top_k/top_p stay static — they are part of the engine's
    program key, matching make_sampler's trace-time specialization."""

    def sample(logits, temps, key):
        greedy = jnp.argmax(logits, axis=-1)
        l = logits / jnp.maximum(temps, jnp.float32(1e-6))[:, None]
        l = apply_top_k_top_p(l, top_k, top_p)
        samp = jax.random.categorical(key, l, axis=-1)
        return jnp.where(temps <= jnp.float32(0.0), greedy, samp)

    return sample


def make_guarded_batched_sampler(top_k=0, top_p=1.0):
    """NaN-safe twin of :func:`make_batched_sampler` for the serving
    engine's numeric-guard program variant: returns ``(tokens, bad)``
    where ``bad [B] bool`` flags rows whose logits contain ANY non-finite
    value.  The token math is untouched — the flag is a pure extra
    reduction over the same logits, so every finite row's greedy/sampled
    token is byte-identical to the unguarded sampler's — which is what
    lets the engine fail exactly the poisoned requests while the rest of
    the batch streams on."""
    inner = make_batched_sampler(top_k, top_p)

    def sample(logits, temps, key):
        bad = ~jnp.all(jnp.isfinite(logits), axis=-1)
        return inner(logits, temps, key), bad

    return sample


def make_masked_batched_sampler(top_k=0, top_p=1.0):
    """Constrained-decoding twin of :func:`make_batched_sampler`: the
    multi-tenant engine's per-row token-FSM masks (``allowed [B, V]``
    bool, computed host-side each step — serving/multitenant/grammar.py)
    are applied BEFORE greedy/temperature sampling, so a schema-
    constrained row can only ever emit grammar-legal tokens while
    unconstrained rows (all-True mask) sample bit-identically to the
    unmasked path (``where`` with an all-True predicate is the identity).
    Disallowed entries get a large negative constant rather than -inf so
    a temperature row's softmax stays NaN-free by construction."""
    inner = make_batched_sampler(top_k, top_p)

    def sample(logits, allowed, temps, key):
        return inner(jnp.where(allowed, logits, jnp.float32(-1e30)),
                     temps, key)

    return sample


def decode_loop(model, fwd, ids0, max_new_tokens, init_cache,
                temperature=1.0, top_k=0, top_p=1.0, seed=None,
                program_key=None):
    """Generic prefill + per-token decode over an arbitrary cache PYTREE.

    fwd(params, bufs, ids, cache, pos) -> (last-token logits f32, cache).
    The cache (dense [L,B,T,h,d] buffers, paged pools, anything jax) is
    DONATED into each compiled step, so decode state updates in-place in
    HBM.  Returns the full id matrix.

    program_key: when the caller can name everything its fwd closure is
    specialized on (cache impl, shapes, sampling params — see generate()),
    the compiled prefill/step pair is CACHED on the model and reused by
    later calls.  Without it every generate() call re-traced and
    re-compiled both programs, which dominated short decodes (~30s compile
    vs ms/token through a tunneled chip).
    """
    import numpy as np

    S0 = ids0.shape[1]
    # snapshot under the model's bind lock: a serving replica tracing on
    # its scheduler thread holds bind() on this model, and an unlocked
    # read here would capture its tracers instead of the real arrays
    with model.bind_lock():
        params = {k: p._value for k, p in model.named_parameters()}
        bufs = {k: b._value for k, b in model.named_buffers()}
    modes = [(m, m.training) for m in model.sublayers(include_self=True)]
    model.eval()

    progs = None
    store = None
    if program_key is not None:
        store = program_store(model)
        progs = store.get(program_key)
    warm = progs is not None  # cached pair: no trace/compile in this call
    if progs is None:
        sample = make_sampler(temperature, top_k, top_p)

        @jax.jit
        def prefill(params, bufs, ids, cache, key):
            logits, cache = fwd(params, bufs, ids, cache, jnp.int32(0))
            return sample(logits, key), cache

        @functools.partial(jax.jit, donate_argnums=(3,))
        def step(params, bufs, last, cache, pos, key):
            logits, cache = fwd(params, bufs, last, cache, pos)
            return sample(logits, key), cache

        progs = (prefill, step)
        if store is not None:
            store[program_key] = progs
    prefill, step = progs

    from time import perf_counter

    from ...observability import perf as _perf
    from ...observability import programs as _programs
    from ...observability import tracing as _tracing

    if store is not None:
        # every store mint lands a ledger row; warm hits record provenance
        # only (no stall), so /statusz accounts 100% of live store keys
        _programs.ledger().record_mint(
            program_key, family="generate.decode", kind="generate",
            store=store, owner=model, replica="-", warm=warm)
    try:
        cache = init_cache()
        base = jax.random.key(seed if seed is not None else 0)
        key0 = jax.random.fold_in(base, 0)
        t_loop = perf_counter()
        nxt, cache = prefill(params, bufs, jnp.asarray(ids0), cache, key0)
        if not warm and store is not None:
            # the prefill dispatch above paid this key's trace+compile
            # (the step program compiles asynchronously under the same
            # episode); attribute the wall to the ambient trace id
            _programs.ledger().record_compile(
                program_key, perf_counter() - t_loop,
                family="generate.decode", kind="generate", store=store,
                owner=model, replica="-",
                trace_id=_tracing.current_trace_id())
        if store is not None and _perf.needs_cost("generate.decode"):
            # per-token roofline attribution for the generate() path: one
            # representative step program's cost (shapes captured here,
            # the re-lower+compile runs lazily off this path)
            _perf.register_cost_thunk("generate.decode", _perf.jit_cost_thunk(
                step, (params, bufs, nxt[:, None].astype(jnp.int64), cache,
                       np.int32(S0), key0)))
        # tokens stay ON DEVICE across the loop: async dispatch queues every
        # step without a host round-trip (through a tunneled TPU, a per-token
        # np.asarray sync made RTT — not step time — the decode bottleneck),
        # and ONE transfer at the end collects the whole id matrix.
        # Per-step host work is hoisted off the dispatch path too: greedy
        # decode never consumes randomness, so it reuses one key instead of
        # paying a fold_in dispatch per token, and the position scalar is a
        # host numpy int32 (same aval, no per-step device-array creation).
        greedy = temperature == 0.0
        out = [nxt[:, None]]
        for t in range(1, max_new_tokens):
            nxt, cache = step(params, bufs, nxt[:, None].astype(jnp.int64),
                              cache, np.int32(S0 + t - 1),
                              key0 if greedy else jax.random.fold_in(base, t))
            out.append(nxt[:, None])
        new = np.asarray(jnp.concatenate(out, axis=1))
        if warm:
            # whole pipelined loop (prefill + steps + the one sync),
            # attributed per emitted token; cold calls are trace+compile
            # walls, not device time, and are skipped
            _perf.record("generate.decode", perf_counter() - t_loop,
                         calls=max_new_tokens)
    finally:
        for m, tr in modes:
            m.training = tr
    return Tensor(jnp.asarray(np.concatenate([ids0, new], axis=1)))


def jitted_decode(model, fwd, ids0, max_new_tokens, cache_shape, cache_dtype,
                  temperature=1.0, top_k=0, top_p=1.0, seed=None,
                  program_key=None):
    """Dense-cache decode (the original API): zero-initialized K/V buffers
    [L, B, T, h, d]; fwd takes (params, bufs, ids, ks, vs, pos)."""

    def fwd_cache(params, bufs, ids, cache, pos):
        ks, vs = cache
        logits, ks, vs = fwd(params, bufs, ids, ks, vs, pos)
        return logits, (ks, vs)

    def init_cache():
        ks = jnp.zeros(tuple(cache_shape), cache_dtype)
        return ks, jnp.zeros_like(ks)

    return decode_loop(model, fwd_cache, ids0, max_new_tokens, init_cache,
                       temperature=temperature, top_k=top_k, top_p=top_p,
                       seed=seed, program_key=program_key)


def paged_pool_shape(batch, max_len, num_kv_heads, head_dim, page_size=16):
    """[B, PP, ps, h, d] pool shape covering max_len tokens."""
    pp = -(-max_len // page_size)
    return (batch, pp, page_size, num_kv_heads, head_dim)


def beam_search(model, input_ids, max_new_tokens, num_beams=4,
                length_penalty=0.0, eos_token_id=None):
    """Reference-style beam search (PaddleNLP generate
    decode_strategy='beam_search'): maintain num_beams hypotheses per batch
    item, expand by log-prob, keep the global top beams, penalize each
    hypothesis by ITS OWN finished length at the end.  Beam bookkeeping is
    host logic; scoring runs through ONE compiled static-shape forward
    (prefixes right-padded to S0+max_new_tokens, last-position logits
    gathered by traced index), so all steps share a single trace and only
    [N, V] logits leave the device.

    model: a causal LM Layer (called as model(ids) -> [N, S, V] logits).
    Returns a Tensor [B, S0 + max_new_tokens] (best beam per item).
    """
    import numpy as np

    ids0 = np.asarray(input_ids.numpy()).astype("int64")
    if max_new_tokens <= 0:
        return input_ids
    B, S0 = ids0.shape
    modes = [(m, m.training) for m in model.sublayers(include_self=True)]
    model.eval()

    # Static-shape scoring (ADVICE r3): every pass feeds [N, S_max] ids
    # right-padded to the final length, and gathers the logits of the
    # current last position with a traced index.  Causality makes padding
    # after position pos-1 invisible to it, so one compiled program serves
    # every step — no per-length retrace, no O(S^2) growth in traced work.
    from ... import jit as _jit

    S_max = S0 + max_new_tokens

    @_jit.to_static
    def _score(ids, pos):
        out = model(ids)                       # [N, S_max, V]
        from ...tensor.manipulation import index_select

        return index_select(out, pos - 1, axis=1)[:, 0]  # [N, V]

    _fallback = [False]  # model does host logic / can't trace -> eager path
    # ONLY trace-incompatibility flips to the eager path (r4 weak #5: a bare
    # `except Exception` turned shape bugs in user models into a silent 100x
    # slower decode).  Real model errors propagate; the fallback itself is
    # announced with a warning.
    _TRACE_ERRS = (jax.errors.ConcretizationTypeError,
                   jax.errors.TracerArrayConversionError,
                   jax.errors.TracerBoolConversionError,
                   jax.errors.TracerIntegerConversionError,
                   jax.errors.UnexpectedTracerError,
                   NotImplementedError)

    def last_logits(arr, cur_len):
        if not _fallback[0]:
            try:
                n = arr.shape[0]
                padded = np.zeros((n, S_max), np.int64)
                padded[:, :cur_len] = arr
                pos = Tensor(jnp.asarray([cur_len], jnp.int64))
                out = _score(Tensor(jnp.asarray(padded)), pos)
                # only [N, V] crosses to host, not [N, S, V]
                return np.asarray(out._value).astype(np.float64)
            except _TRACE_ERRS as e:
                import warnings

                warnings.warn(
                    "beam_search: model is not jax-traceable "
                    f"({type(e).__name__}); falling back to the EAGER "
                    "per-step decode path, which is much slower",
                    RuntimeWarning, stacklevel=2)
                _fallback[0] = True
        out = model(Tensor(jnp.asarray(arr[:, :cur_len])))
        return np.asarray(out._value[:, -1]).astype(np.float64)

    def log_softmax(l):
        m = l.max(-1, keepdims=True)
        return l - (np.log(np.exp(l - m).sum(-1, keepdims=True)) + m)

    try:
        # first expansion: top num_beams continuations of each prompt
        logp = log_softmax(last_logits(ids0, S0))
        V = logp.shape[-1]
        top = np.argsort(-logp, axis=-1)[:, :num_beams]        # [B, beams]
        scores = np.take_along_axis(logp, top, -1)             # [B, beams]
        seqs = np.concatenate(
            [np.repeat(ids0[:, None], num_beams, 1), top[..., None]], -1)
        done = np.zeros((B, num_beams), bool)
        fin_len = np.full((B, num_beams), max_new_tokens, np.int64)
        # finished-hypothesis POOL per item: a completed beam is recorded
        # the moment it hits EOS, so later eviction from the active set
        # cannot lose it (reference BeamHypotheses semantics)
        pool = [[] for _ in range(B)]  # (penalized score, seq list)

        def penalize(sc, ln):
            return sc / (max(ln, 1) ** length_penalty) if length_penalty \
                else sc

        def record(b, k, t):
            pool[b].append((penalize(scores[b, k], t), seqs[b, k].copy()))

        if eos_token_id is not None:
            done |= top == eos_token_id
            fin_len = np.where(done, 1, fin_len)
            for b, k in zip(*np.nonzero(done)):
                record(b, k, 1)

        for t in range(1, max_new_tokens):
            if done.all():
                break
            logp = log_softmax(last_logits(seqs.reshape(B * num_beams, -1),
                                           seqs.shape[-1]))
            logp = logp.reshape(B, num_beams, V)
            if eos_token_id is not None:
                # finished beams only extend with EOS at no cost
                frozen = np.full((V,), -np.inf)
                frozen[eos_token_id] = 0.0
                logp = np.where(done[..., None], frozen, logp)
            cand = scores[..., None] + logp                    # [B, beams, V]
            pick = np.argsort(-cand.reshape(B, num_beams * V),
                              axis=-1)[:, :num_beams]
            beam_idx, tok = pick // V, pick % V
            scores = np.take_along_axis(cand.reshape(B, num_beams * V),
                                        pick, -1)
            seqs = np.concatenate(
                [np.take_along_axis(seqs, beam_idx[..., None], 1),
                 tok[..., None]], -1)
            done = np.take_along_axis(done, beam_idx, 1)
            fin_len = np.take_along_axis(fin_len, beam_idx, 1)
            if eos_token_id is not None:
                just = (~done) & (tok == eos_token_id)
                fin_len = np.where(just, t + 1, fin_len)
                done |= just
                for b, k in zip(*np.nonzero(just)):
                    record(b, k, t + 1)
    finally:
        for m, tr in modes:
            m.training = tr

    # best hypothesis = max over the finished pool and the live beams
    out_rows = []
    gen_total = seqs.shape[1] - S0
    for b in range(B):
        cands = list(pool[b])
        for k in range(num_beams):
            if not done[b, k]:  # live beam: penalize by full current length
                cands.append((penalize(scores[b, k], gen_total),
                              seqs[b, k]))
        best_seq = max(cands, key=lambda x: x[0])[1]
        if len(best_seq) < seqs.shape[1]:  # pool snapshot from an early step
            padv = eos_token_id if eos_token_id is not None else 0
            best_seq = np.concatenate(
                [best_seq, np.full(seqs.shape[1] - len(best_seq), padv,
                                   best_seq.dtype)])
        out_rows.append(best_seq)
    out = np.stack(out_rows)
    if out.shape[1] < S0 + max_new_tokens:  # early-EOS: pad with EOS
        pad = np.full((B, S0 + max_new_tokens - out.shape[1]),
                      eos_token_id if eos_token_id is not None else 0,
                      out.dtype)
        out = np.concatenate([out, pad], 1)
    return Tensor(jnp.asarray(out))
