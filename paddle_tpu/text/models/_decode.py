"""Shared jitted KV-cache decode loop (used by GPT and Llama heads).

The per-model piece is ONE closure: ``fwd(params, bufs, ids, ks, vs, pos)
-> (last-token logits f32, new ks, new vs)`` over stacked [L, B, T, h, d]
cache buffers.  This module owns everything else — sampling (greedy /
temperature / top-k / top-p as traced ops), the compiled prefill, the
single compiled decode step with DONATED cache buffers, and the
train-mode save/restore discipline — so decode fixes land in one place.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ...tensor.tensor import Tensor


def make_sampler(temperature, top_k, top_p):
    def sample(logits, key):
        if temperature == 0.0:
            return jnp.argmax(logits, axis=-1)
        l = logits / jnp.float32(max(temperature, 1e-6))
        if top_k:
            kk = min(int(top_k), l.shape[-1])
            kth = jax.lax.top_k(l, kk)[0][:, -1][:, None]
            l = jnp.where(l < kth, -jnp.inf, l)
        if top_p < 1.0:  # nucleus: smallest prefix of sorted probs >= top_p
            srt = jnp.sort(l, axis=-1)[:, ::-1]
            p = jax.nn.softmax(srt, axis=-1)
            keep_n = (jnp.cumsum(p, axis=-1) - p < top_p).sum(-1)
            kth = jnp.take_along_axis(srt, (keep_n - 1)[:, None], axis=-1)
            l = jnp.where(l < kth, -jnp.inf, l)
        return jax.random.categorical(key, l, axis=-1)

    return sample


def jitted_decode(model, fwd, ids0, max_new_tokens, cache_shape, cache_dtype,
                  temperature=1.0, top_k=0, top_p=1.0, seed=None):
    """Run prefill + per-token decode; returns the full id matrix.

    model: Layer (eval'd recursively for the duration).
    fwd: closure as in the module docstring.
    ids0: np.int64 [B, S0] prompt.
    cache_shape: [L, B, T, h, d] for the zero-initialized K/V buffers.
    """
    import numpy as np

    S0 = ids0.shape[1]
    params = {k: p._value for k, p in model.named_parameters()}
    bufs = {k: b._value for k, b in model.named_buffers()}
    modes = [(m, m.training) for m in model.sublayers(include_self=True)]
    model.eval()
    sample = make_sampler(temperature, top_k, top_p)

    @jax.jit
    def prefill(params, bufs, ids, ks, vs, key):
        logits, ks, vs = fwd(params, bufs, ids, ks, vs, jnp.int32(0))
        return sample(logits, key), ks, vs

    @functools.partial(jax.jit, donate_argnums=(3, 4))
    def step(params, bufs, last, ks, vs, pos, key):
        logits, ks, vs = fwd(params, bufs, last, ks, vs, pos)
        return sample(logits, key), ks, vs

    try:
        ks = jnp.zeros(tuple(cache_shape), cache_dtype)
        vs = jnp.zeros_like(ks)
        base = jax.random.key(seed if seed is not None else 0)
        nxt, ks, vs = prefill(params, bufs, jnp.asarray(ids0), ks, vs,
                              jax.random.fold_in(base, 0))
        out = [np.asarray(nxt)[:, None]]
        for t in range(1, max_new_tokens):
            nxt, ks, vs = step(params, bufs,
                               jnp.asarray(nxt)[:, None].astype(jnp.int64),
                               ks, vs, jnp.int32(S0 + t - 1),
                               jax.random.fold_in(base, t))
            out.append(np.asarray(nxt)[:, None])
    finally:
        for m, tr in modes:
            m.training = tr
    new = np.concatenate(out, axis=1)
    return Tensor(jnp.asarray(np.concatenate([ids0, new], axis=1)))
