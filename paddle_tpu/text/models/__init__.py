from .bert import (  # noqa: F401
    BertModel, BertForSequenceClassification, BertForPretraining,
    BertPretrainingCriterion, ErnieModel, ErnieForSequenceClassification,
)
from .gpt import (  # noqa: F401
    GPTModel, GPTForCausalLM, GPTForCausalLMPipe, GPTDecoderLayer,
    stack_block_params, block_fn_for, pipeline_forward,
)
from .llama import (  # noqa: F401
    LlamaConfig, LlamaForCausalLM, LlamaModel,
)
