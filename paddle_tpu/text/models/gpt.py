"""GPT decoder LM (reference analog: PaddleNLP gpt/modeling.py — baseline
config #5 trains GPT-3-style models under dp+mp+pp hybrid parallelism,
SURVEY.md §2.3/§3.4).

TPU-first structure:
- TP: when fleet's hybrid mesh has mp>1, projections build as
  Column/RowParallelLinear and the vocab embedding as
  VocabParallelEmbedding — distribution is sharding annotations, the
  module code is identical either way.
- PP: every decoder block is structurally identical, so the stacked block
  parameters feed the SPMD pipeline engine
  (``stack_block_params`` + ``pipeline_forward`` →
  fleet.meta_parallel.spmd_pipeline) for dp x mp x pp training in ONE
  compiled program.
- Long context: attention routes through
  nn.functional.scaled_dot_product_attention (flash/ring kernels pluggable
  via paddle_tpu.ops).
"""

from __future__ import annotations


import jax.numpy as jnp

from ...nn import functional as F
from ...nn.layer import Layer, LayerList
from ...nn.layers.common import Dropout, Embedding, Linear
from ...nn.layers.norm import LayerNorm
from ...tensor.dispatch import apply as _apply
from ...tensor.tensor import Tensor


def _mp_degree():
    from ...distributed.topology import get_hybrid_communicate_group

    hcg = get_hybrid_communicate_group()
    if hcg is not None and "mp" in hcg.mesh.axis_names:
        return hcg.mesh.shape["mp"]
    return 1


def _col_linear(d_in, d_out, bias=True):
    if _mp_degree() > 1:
        from ...distributed.fleet.meta_parallel import ColumnParallelLinear

        return ColumnParallelLinear(d_in, d_out, gather_output=False,
                                    has_bias=bias)
    return Linear(d_in, d_out, bias_attr=None if bias else False)


def _row_linear(d_in, d_out, bias=True):
    if _mp_degree() > 1:
        from ...distributed.fleet.meta_parallel import RowParallelLinear

        return RowParallelLinear(d_in, d_out, input_is_parallel=True,
                                 has_bias=bias)
    return Linear(d_in, d_out, bias_attr=None if bias else False)


def _vocab_embedding(vocab, hidden):
    if _mp_degree() > 1:
        from ...distributed.fleet.meta_parallel import VocabParallelEmbedding

        return VocabParallelEmbedding(vocab, hidden)
    return Embedding(vocab, hidden)


class GPTDecoderLayer(Layer):
    """Pre-LN causal block: ln1 -> attn -> +res -> ln2 -> mlp -> +res."""

    def __init__(self, hidden_size, num_heads, intermediate_size, dropout=0.0,
                 attn_dropout=0.0, act="gelu"):
        super().__init__()
        self.hidden_size = hidden_size
        self.num_heads = num_heads
        self.head_dim = hidden_size // num_heads
        self.ln1 = LayerNorm(hidden_size, 1e-5)
        self.qkv = _col_linear(hidden_size, 3 * hidden_size)
        self.out_proj = _row_linear(hidden_size, hidden_size)
        self.ln2 = LayerNorm(hidden_size, 1e-5)
        self.ffn1 = _col_linear(hidden_size, intermediate_size)
        self.ffn2 = _row_linear(intermediate_size, hidden_size)
        self.dropout = Dropout(dropout)
        self.attn_dropout = attn_dropout
        self.act = getattr(F, act)

    def _lin(self, name, x, lora):
        """One decoder Linear call with an optional per-row LoRA bypass.

        ``lora`` is this layer's multi-tenant adapter slice (or None): a
        dict mapping target name -> flat tuple of per-row gathered
        ``(A [B, d_in, r], B [B, r, d_out])`` pairs, one pair per rank
        bucket (serving.multitenant; ops.lora).  The base projection may
        be an Int8Linear (weight_dtype="int8") — the bypass rides on its
        output either way, which is exactly how int8 base + full-precision
        LoRA compose."""
        y = getattr(self, name)(x)
        if lora is not None and name in lora:
            from ...ops.lora import apply_lora

            y = _apply(apply_lora, x, y, *lora[name], op_name="lora")
        return y

    def forward(self, x, cache=None, lora=None):
        residual = x
        h = self.ln1(x)
        qkv = self._lin("qkv", h, lora)
        B, S = h.shape[0], h.shape[1]
        # head count derived from the actual projection width: under manual
        # tensor parallelism the local shard carries num_heads/mp heads.
        # qkv output layout is HEAD-MAJOR [heads, 3, head_dim] so a contiguous
        # column split over 'mp' hands each rank whole (q,k,v) heads.
        heads_here = qkv.shape[-1] // (3 * self.head_dim)
        qkv = qkv.reshape([B, S, heads_here, 3, self.head_dim])
        q, k, v = qkv[:, :, :, 0], qkv[:, :, :, 1], qkv[:, :, :, 2]
        if cache is not None and len(cache) == 7 \
                and cache[0] in ("served_q", "served_chunk_q"):
            # QUANTIZED paged serving (paddle_tpu.serving.quant): the same
            # global-pool/page-table/per-slot-lens contract as the "served"
            # and "served_chunk" variants below, but the pools hold int8
            # payloads with parallel per-(slot, head) scale pools — quant
            # is fused into every pool write and dequant into the paged
            # attention consumers (ops.paged_attention int8 section), so a
            # full-precision cache copy never materializes in HBM.
            from ...ops.paged_attention import (
                paged_attention_quantized, paged_chunk_attend_quant,
                paged_table_chunk_write_quant, paged_table_prefill_write_quant,
                paged_table_token_write_quant)

            tag, kp, vp, ks, vs, table, lens = cache
            if tag == "served_chunk_q":
                # speculative verify chunk: C tokens per slot, one
                # quantizing scatter each for K and V, then every position
                # attends with its own valid length
                kp, ks = _apply(paged_table_chunk_write_quant, kp, ks, k,
                                table, lens, n_outs=None,
                                op_name="paged_write")
                vp, vs = _apply(paged_table_chunk_write_quant, vp, vs, v,
                                table, lens, n_outs=None,
                                op_name="paged_write")
                attn = _apply(paged_chunk_attend_quant, q, kp, vp, ks, vs,
                              table, lens, op_name="paged_attention")
            elif S > 1:
                # admit-time prefill: dense causal attention over the
                # full-precision prompt activations (only the CACHE is
                # quantized), quantizing page writes
                attn = F.scaled_dot_product_attention(
                    q, k, v, is_causal=True, dropout_p=0.0, training=False)
                kp, ks = _apply(paged_table_prefill_write_quant, kp, ks, k,
                                table, n_outs=None, op_name="paged_write")
                vp, vs = _apply(paged_table_prefill_write_quant, vp, vs, v,
                                table, n_outs=None, op_name="paged_write")
            else:
                kp, ks = _apply(
                    lambda pool, sp, kk, tb, ln:
                        paged_table_token_write_quant(pool, sp, kk[:, 0],
                                                      tb, ln),
                    kp, ks, k, table, lens, n_outs=None,
                    op_name="paged_write")
                vp, vs = _apply(
                    lambda pool, sp, vv, tb, ln:
                        paged_table_token_write_quant(pool, sp, vv[:, 0],
                                                      tb, ln),
                    vp, vs, v, table, lens, n_outs=None,
                    op_name="paged_write")
                attn = _apply(
                    lambda qq, kpl, vpl, ksc, vsc, tb, ln:
                        paged_attention_quantized(
                            qq[:, 0], kpl, vpl, ksc, vsc, tb,
                            ln.astype(jnp.int32) + 1)[:, None],
                    q, kp, vp, ks, vs, table, lens,
                    op_name="paged_attention")
            attn = attn.reshape([B, S, heads_here * self.head_dim])
            x = residual + self.dropout(self._lin("out_proj", attn, lora))
            residual = x
            h = self.ln2(x)
            h = self._lin("ffn2", self.act(self._lin("ffn1", h, lora)), lora)
            x = residual + self.dropout(h)
            return x, (tag, kp, vp, ks, vs, table, lens)
        if cache is not None and len(cache) == 5 and cache[0] == "served_chunk":
            # SPECULATIVE VERIFY chunk (paddle_tpu.serving.speculative): the
            # S tokens of each row are the slot's last sampled token plus
            # S-1 draft tokens at per-slot positions lens[b]..lens[b]+S-1.
            # All S K/V land in the global pools through the page table in
            # one chunk write, then every position attends against the
            # pools with its OWN valid length — no dense in-chunk fallback;
            # causality within the chunk comes from the per-position lens
            # (ops.paged_attention.paged_chunk_attend).
            from ...ops.paged_attention import (paged_chunk_attend,
                                                paged_table_chunk_write)

            _, kp, vp, table, lens = cache
            kp = _apply(paged_table_chunk_write, kp, k, table, lens,
                        op_name="paged_write")
            vp = _apply(paged_table_chunk_write, vp, v, table, lens,
                        op_name="paged_write")
            attn = _apply(paged_chunk_attend, q, kp, vp, table, lens,
                          op_name="paged_attention")
            attn = attn.reshape([B, S, heads_here * self.head_dim])
            x = residual + self.dropout(self._lin("out_proj", attn, lora))
            residual = x
            h = self.ln2(x)
            h = self._lin("ffn2", self.act(self._lin("ffn1", h, lora)), lora)
            x = residual + self.dropout(h)
            return x, ("served_chunk", kp, vp, table, lens)
        if cache is not None and len(cache) == 5 and cache[0] == "served":
            # SERVED cache (continuous-batching engine, paddle_tpu.serving):
            # ONE global page pool [P, ps, h, d] shared by every slot
            # through an explicit per-slot page table [B, NP], and per-slot
            # lengths [B] — each slot decodes at its OWN position, which is
            # what iteration-level batching needs (the "paged" branch below
            # locks the whole batch to a single scalar ``pos``).
            from ...ops.paged_attention import (paged_attention,
                                                paged_table_prefill_write,
                                                paged_table_token_write)

            _, kp, vp, table, lens = cache
            if S > 1:
                # admit-time prefill: dense causal attention over the
                # (right-padded) prompt; positions past a row's true length
                # write junk into pages that per-slot seq_lens masking (or
                # the engine's scratch page) keeps invisible
                attn = F.scaled_dot_product_attention(
                    q, k, v, is_causal=True, dropout_p=0.0, training=False)
                kp = _apply(paged_table_prefill_write, kp, k, table,
                            op_name="paged_write")
                vp = _apply(paged_table_prefill_write, vp, v, table,
                            op_name="paged_write")
            else:
                kp = _apply(
                    lambda pgs, kk, tb, ln:
                        paged_table_token_write(pgs, kk[:, 0], tb, ln),
                    kp, k, table, lens, op_name="paged_write")
                vp = _apply(
                    lambda pgs, vv, tb, ln:
                        paged_table_token_write(pgs, vv[:, 0], tb, ln),
                    vp, v, table, lens, op_name="paged_write")
                attn = _apply(
                    lambda qq, kps, vps, tb, ln:
                        paged_attention(qq[:, 0], kps, vps, tb,
                                        ln.astype(jnp.int32) + 1)[:, None],
                    q, kp, vp, table, lens, op_name="paged_attention")
            attn = attn.reshape([B, S, heads_here * self.head_dim])
            x = residual + self.dropout(self._lin("out_proj", attn, lora))
            residual = x
            h = self.ln2(x)
            h = self._lin("ffn2", self.act(self._lin("ffn1", h, lora)), lora)
            x = residual + self.dropout(h)
            return x, ("served", kp, vp, table, lens)
        if cache is not None and len(cache) == 4 and cache[0] == "paged":
            # PAGED cache (serving decode): per-layer page pools
            # [B, PP, ps, h, d] — HBM bound by pages allocated, not a dense
            # [B, max_len] rectangle.  Prefill attends densely (flash/sdpa
            # over the prompt) and writes the prompt's K/V into pages;
            # each decode step writes one token and runs the length-bounded
            # Pallas flash-decode kernel (ops/paged_attention): the page
            # sweep is clamped per row by the scalar-prefetched seq_lens,
            # so dead table slots past a row's length are never DMA'd.
            from ...ops.paged_attention import (paged_decode_attend,
                                                paged_prefill_write,
                                                paged_token_write)

            _, kp, vp, pos = cache
            if S > 1:  # prefill
                attn = F.scaled_dot_product_attention(
                    q, k, v, is_causal=True, dropout_p=0.0, training=False)
                kp = _apply(paged_prefill_write, kp, k, op_name="paged_write")
                vp = _apply(paged_prefill_write, vp, v, op_name="paged_write")
            else:
                kp = _apply(lambda pgs, kk, p: paged_token_write(pgs, kk[:, 0], p),
                            kp, k, pos, op_name="paged_write")
                vp = _apply(lambda pgs, vv, p: paged_token_write(pgs, vv[:, 0], p),
                            vp, v, pos, op_name="paged_write")
                attn = _apply(
                    lambda qq, kps, vps, p:
                        paged_decode_attend(qq[:, 0], kps, vps, p)[:, None],
                    q, kp, vp, pos, op_name="paged_attention")
            attn = attn.reshape([B, S, heads_here * self.head_dim])
            x = residual + self.dropout(self._lin("out_proj", attn, lora))
            residual = x
            h = self.ln2(x)
            h = self._lin("ffn2", self.act(self._lin("ffn1", h, lora)), lora)
            x = residual + self.dropout(h)
            return x, ("paged", kp, vp, pos)
        if cache is not None and len(cache) == 3:
            # STATIC cache (jitted decode): fixed [B, T, h, d] buffers written
            # in place at ``pos`` — shapes never change, so every decode step
            # reuses one compiled program (donated cache, no concat growth)
            import jax as _jax

            k_buf, v_buf, pos = cache

            def write(buf, new, p):
                return _jax.lax.dynamic_update_slice_in_dim(buf, new, p, 1)

            k_buf = _apply(write, k_buf, k, pos, op_name="cache_write")
            v_buf = _apply(write, v_buf, v, pos, op_name="cache_write")
            T = k_buf.shape[1]

            def build_mask(p):
                i = jnp.arange(S, dtype=jnp.int32)[:, None]
                j = jnp.arange(T, dtype=jnp.int32)[None, :]
                return jnp.where(j <= p + i, jnp.float32(0.0),
                                 jnp.float32(-1e30))[None, None]

            mask = _apply(build_mask, pos, op_name="cache_mask")
            attn = F.scaled_dot_product_attention(
                q, k_buf, v_buf, attn_mask=mask, dropout_p=0.0,
                training=False)
            attn = attn.reshape([B, S, heads_here * self.head_dim])
            x = residual + self.dropout(self._lin("out_proj", attn, lora))
            residual = x
            h = self.ln2(x)
            h = self._lin("ffn2", self.act(self._lin("ffn1", h, lora)), lora)
            x = residual + self.dropout(h)
            return x, (k_buf, v_buf, pos)
        if cache is not None:
            from ...tensor import manipulation as M

            k = M.concat([cache[0], k], axis=1)
            v = M.concat([cache[1], v], axis=1)
            cache = (k, v)
        attn = F.scaled_dot_product_attention(
            q, k, v, is_causal=cache is None, dropout_p=self.attn_dropout,
            training=self.training)
        attn = attn.reshape([B, S, heads_here * self.head_dim])
        x = residual + self.dropout(self._lin("out_proj", attn, lora))
        residual = x
        h = self.ln2(x)
        h = self._lin("ffn2", self.act(self._lin("ffn1", h, lora)), lora)
        x = residual + self.dropout(h)
        return x if cache is None else (x, cache)


class GPTModel(Layer):
    def __init__(self, vocab_size=50304, hidden_size=768, num_hidden_layers=12,
                 num_attention_heads=12, intermediate_size=None,
                 hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
                 max_position_embeddings=1024, type_vocab_size=1,
                 initializer_range=0.02, pad_token_id=0, hidden_act="gelu"):
        super().__init__()
        intermediate_size = intermediate_size or 4 * hidden_size
        self.hidden_size = hidden_size
        self.word_embeddings = _vocab_embedding(vocab_size, hidden_size)
        self.position_embeddings = Embedding(max_position_embeddings, hidden_size)
        self.drop = Dropout(hidden_dropout_prob)
        self.layers = LayerList([
            GPTDecoderLayer(hidden_size, num_attention_heads, intermediate_size,
                            hidden_dropout_prob, attention_probs_dropout_prob,
                            hidden_act)
            for _ in range(num_hidden_layers)
        ])
        self.final_ln = LayerNorm(hidden_size, 1e-5)

    def embed(self, input_ids, position_ids=None):
        if position_ids is None:
            S = input_ids.shape[1]
            position_ids = Tensor(jnp.arange(S, dtype=jnp.int64)[None, :])
        return self.drop(self.word_embeddings(input_ids)
                         + self.position_embeddings(position_ids))

    def forward(self, input_ids, position_ids=None, attention_mask=None,
                use_cache=False, cache=None, lora=None):
        # ``lora``: per-layer multi-tenant adapter slices (see
        # GPTDecoderLayer._lin / paddle_tpu.serving.multitenant) — a list
        # of per-layer dicts, or None for the base model
        x = self.embed(input_ids, position_ids)
        new_cache = []
        for i, layer in enumerate(self.layers):
            li = lora[i] if lora is not None else None
            if cache is not None:
                x, c = layer(x, cache[i], lora=li)
                new_cache.append(c)
            else:
                x = layer(x, lora=li)
        x = self.final_ln(x)
        return (x, new_cache) if cache is not None else x


class GPTForCausalLM(Layer):
    """LM head tied to the vocab embedding (reference GPTForCausalLM /
    GPTLMHeadModel)."""

    def __init__(self, gpt=None, **kwargs):
        super().__init__()
        self.gpt = gpt if gpt is not None else GPTModel(**kwargs)

    def forward(self, input_ids, position_ids=None, attention_mask=None,
                labels=None):
        hidden = self.gpt(input_ids, position_ids, attention_mask)
        w = self.gpt.word_embeddings.weight  # [vocab, hidden]
        logits = _apply(lambda h, wv: h @ wv.T, hidden, w, op_name="matmul")
        if labels is not None:
            loss = F.cross_entropy(
                logits[:, :-1].reshape([-1, logits.shape[-1]]),
                labels[:, 1:].reshape([-1]), reduction="mean")
            return loss
        return logits

    # ------------------------------------------------------------ generation
    def generate(self, input_ids, max_new_tokens=32, temperature=1.0, top_k=0,
                 top_p=1.0, seed=None, use_cache=True,
                 decode_strategy="sampling", num_beams=4, length_penalty=0.0,
                 eos_token_id=None, cache_impl="dense", page_size=16,
                 max_len=None):
        """Autoregressive generation.

        ``use_cache=True`` (default): jitted two-phase decode via the shared
        decode loop (``_decode.jitted_decode``) — one compiled prefill
        writes the prompt's K/V into fixed [B, T, h, d] buffers, then ONE
        compiled single-token step (donated cache, static shapes) runs per
        new token.  Greedy (temperature=0) output is identical to the eager
        loop; sampling supports temperature/top-k/top-p via jax PRNG.
        ``use_cache=False``: the eager full-prefix loop (reference parity /
        debug path).

        ``cache_impl="paged"``: block-paged KV cache — per-layer page pools
        instead of dense [B, T] rectangles, decode attention through the
        length-bounded Pallas flash-decode kernel (ops/paged_attention):
        each row's page sweep stops at its own last valid page.  Same
        tokens as the dense path (tests/test_paged_attention.py); KV HBM is
        bounded by pages allocated (ceil(T/page_size) per sequence), the
        serving property the reference's paged engine exists for."""
        if decode_strategy == "beam_search":
            from ._decode import beam_search

            return beam_search(self, input_ids, max_new_tokens,
                               num_beams=num_beams,
                               length_penalty=length_penalty,
                               eos_token_id=eos_token_id)
        if not use_cache:
            return self._generate_eager(input_ids, max_new_tokens, temperature,
                                        top_k, top_p, seed)
        if max_new_tokens <= 0:
            return input_ids
        import jax
        import numpy as np

        from ...framework import random as _rng
        from ...framework.state import no_grad_ctx
        from ._decode import jitted_decode

        ids0 = np.asarray(input_ids.numpy()).astype("int64")
        B, S0 = ids0.shape
        # max_len pre-sizes the KV cache/page pool independently of this
        # call's max_new_tokens (serving: one compiled step serves requests
        # of any length up to it; bench: pins compiled shapes across runs)
        T = max(S0 + max_new_tokens, max_len or 0)
        max_pos = self.gpt.position_embeddings.weight.shape[0]
        if T > max_pos:
            raise ValueError(
                f"generate: prompt {S0} + max_new_tokens {max_new_tokens} "
                f"(cache {T}) exceeds max_position_embeddings {max_pos}")
        gpt = self.gpt
        L = len(gpt.layers)
        blk = gpt.layers[0]
        h_heads = blk.qkv.weight.shape[-1] // (3 * blk.head_dim)
        dt = gpt.word_embeddings.weight._value.dtype

        if cache_impl == "paged":
            from ._decode import decode_loop, paged_pool_shape

            pool = paged_pool_shape(B, T, h_heads, blk.head_dim, page_size)

            def fwd_paged(params, bufs, ids, cache, pos):
                kps, vps = cache
                with no_grad_ctx(), _rng.rng_scope(jax.random.key(0)), \
                        self.bind(params, bufs):
                    S = ids.shape[1]
                    pos_ids = pos + jnp.arange(S, dtype=jnp.int32)[None, :]
                    lc = [("paged", Tensor(kps[i]), Tensor(vps[i]),
                           Tensor(pos)) for i in range(L)]
                    x, new_cache = gpt(Tensor(ids),
                                       position_ids=Tensor(pos_ids), cache=lc)
                    w = gpt.word_embeddings.weight._value
                    logits = (x._value[:, -1].astype(jnp.float32)
                              @ w.T.astype(jnp.float32))
                    kps = jnp.stack([c[1]._value for c in new_cache])
                    vps = jnp.stack([c[2]._value for c in new_cache])
                return logits, (kps, vps)

            def init_cache():
                kp = jnp.zeros((L,) + pool, dt)
                return kp, jnp.zeros_like(kp)

            return decode_loop(self, fwd_paged, ids0, max_new_tokens,
                               init_cache, temperature=temperature,
                               top_k=top_k, top_p=top_p, seed=seed,
                               program_key=("paged", B, S0, T, page_size,
                                            temperature, top_k, top_p,
                                            bool(self.training)))
        if cache_impl != "dense":
            raise ValueError(f"cache_impl must be 'dense' or 'paged', "
                             f"got {cache_impl!r}")

        def fwd(params, bufs, ids, ks, vs, pos):
            with no_grad_ctx(), _rng.rng_scope(jax.random.key(0)), \
                    self.bind(params, bufs):
                S = ids.shape[1]
                pos_ids = pos + jnp.arange(S, dtype=jnp.int32)[None, :]
                cache = [(Tensor(ks[i]), Tensor(vs[i]), Tensor(pos))
                         for i in range(L)]
                x, new_cache = gpt(Tensor(ids), position_ids=Tensor(pos_ids),
                                   cache=cache)
                w = gpt.word_embeddings.weight._value
                logits = (x._value[:, -1].astype(jnp.float32)
                          @ w.T.astype(jnp.float32))
                ks = jnp.stack([c[0]._value for c in new_cache])
                vs = jnp.stack([c[1]._value for c in new_cache])
            return logits, ks, vs

        return jitted_decode(self, fwd, ids0, max_new_tokens,
                             (L, B, T, h_heads, blk.head_dim), dt,
                             temperature=temperature, top_k=top_k,
                             top_p=top_p, seed=seed,
                             program_key=("dense", B, S0, T, temperature,
                                          top_k, top_p, bool(self.training)))

    def _generate_eager(self, input_ids, max_new_tokens=32, temperature=1.0,
                        top_k=0, top_p=1.0, seed=None):
        """Greedy/top-k sampling loop (eager; each step reuses the jit cache
        for its shape)."""
        import numpy as np

        ids = input_ids.numpy()
        max_pos = self.gpt.position_embeddings.weight.shape[0]
        if ids.shape[1] + max_new_tokens > max_pos:
            raise ValueError(
                f"generate: prompt {ids.shape[1]} + max_new_tokens {max_new_tokens} "
                f"exceeds max_position_embeddings {max_pos}")
        rng = np.random.RandomState(seed)
        for _ in range(max_new_tokens):
            logits = self.forward(Tensor(jnp.asarray(ids)))
            step = np.asarray(logits.numpy()[:, -1])
            if temperature != 1.0:
                step = step / max(temperature, 1e-6)
            if top_k:
                kk = min(int(top_k), step.shape[-1])
                kth = np.sort(step, axis=-1)[:, -kk][:, None]
                step = np.where(step < kth, -np.inf, step)
            if temperature == 0.0:
                nxt = step.argmax(-1)
            else:
                p = np.exp(step - step.max(-1, keepdims=True))
                p /= p.sum(-1, keepdims=True)
                if top_p < 1.0:  # nucleus: smallest prefix >= top_p
                    srt = np.argsort(-p, axis=-1)
                    ps = np.take_along_axis(p, srt, -1)
                    keep = np.cumsum(ps, -1) - ps < top_p
                    ps = np.where(keep, ps, 0.0)
                    ps = ps / ps.sum(-1, keepdims=True)
                    pick = np.stack([rng.choice(ps.shape[-1], p=ps[i])
                                     for i in range(ps.shape[0])])
                    nxt = np.take_along_axis(srt, pick[:, None], -1)[:, 0]
                else:
                    nxt = np.array([rng.choice(p.shape[-1], p=p[i])
                                    for i in range(p.shape[0])])
            ids = np.concatenate([ids, nxt[:, None]], axis=1)
        return Tensor(jnp.asarray(ids))


# ---------------------------------------------------------------- pipeline
# TP placement of each block parameter inside the manual pipeline region:
# which dim of the RAW weight is sharded over 'mp' (None = replicated).
_TP_DIM = {
    "qkv.weight": 1, "qkv.bias": 0,
    "ffn1.weight": 1, "ffn1.bias": 0,
    "out_proj.weight": 0, "ffn2.weight": 0,
}


def mp_param_specs(axis="model"):
    """Suffix -> ``PartitionSpec`` map for Megatron-style tensor
    parallelism of a decoder block's parameters over one mesh axis —
    the serving-side reading of :data:`_TP_DIM` (qkv/ffn1
    column-parallel, out_proj/ffn2 row-parallel).  The qkv projection is
    HEAD-MAJOR (``[heads, 3, head_dim]`` flattened), so a contiguous
    column split hands each shard whole (q, k, v) head triples — the
    layout the per-shard paged KV pools line up with.

    Keys are dotted-name suffixes (match with ``name.endswith``), so one
    map covers every layer of ``named_parameters()``.  ``weight_int8``
    buffers (quantization.Int8Linear payloads) shard exactly like the
    full-precision weights they replace; anything unmatched (embeddings,
    LayerNorms, the row-parallel biases) is replicated.
    """
    from jax.sharding import PartitionSpec as P

    specs = {}
    for name, dim in _TP_DIM.items():
        ndim = 2 if name.endswith(".weight") else 1
        entries = [None] * ndim
        entries[dim] = axis
        specs["." + name] = P(*entries)
        if name.endswith(".weight"):
            specs["." + name + "_int8"] = P(*entries)
    return specs


def stack_block_params(model: GPTModel, pp: int, order="stage"):
    """Stack the (structurally identical) decoder blocks' parameters into
    [pp, layers_per_stage, ...] pytrees for the SPMD pipeline engine.
    ``order='stage'`` places layer j at [j // per, j % per] (contiguous
    chunks per rank — the gpipe schedule); ``order='lap'`` places layer j
    at [j % pp, j // pp] (round-robin virtual stages — what the circular /
    interleaved schedule executes lap-major).
    Returns (stacked, specs): specs shard the stage dim over 'pp' and the
    TP dim (per _TP_DIM) over 'mp' when the model was built tensor-parallel."""
    from jax.sharding import PartitionSpec as P

    n = len(model.layers)
    if n % pp:
        raise ValueError(f"{n} layers not divisible by pp={pp}")
    per = n // pp
    names = [k for k, _ in model.layers[0].named_parameters()]
    mp = _mp_degree()
    stacked, specs = {}, {}
    for name in names:
        leaves = []
        for layer in model.layers:
            p = dict(layer.named_parameters())[name]
            leaves.append(p._value)
        arr = jnp.stack(leaves)  # [n_layers, ...]
        if order == "lap":
            stacked[name] = arr.reshape((per, pp) + arr.shape[1:]).swapaxes(0, 1)
        else:
            stacked[name] = arr.reshape((pp, per) + arr.shape[1:])
        entries = ["pp", None] + [None] * (arr.ndim - 1)
        tp_dim = _TP_DIM.get(name)
        if mp > 1 and tp_dim is not None:
            entries[2 + tp_dim] = "mp"
        specs[name] = P(*entries)
    return stacked, specs


def block_fn_for(model: GPTModel):
    """(stage_params, x) -> x for spmd_pipeline: runs layers_per_stage blocks
    sequentially, binding each slice into block 0's module structure."""
    block = model.layers[0]

    def block_fn(stage_params, x):
        per = next(iter(stage_params.values())).shape[0]
        h = x
        for i in range(per):
            sl = {k: v[i] for k, v in stage_params.items()}
            with block.bind(sl, {}):
                h = block(Tensor(h))._value if not isinstance(h, Tensor) else \
                    block(h)
        return h._value if isinstance(h, Tensor) else h

    return block_fn


def single_block_fn_for(model: GPTModel):
    """(one-layer params, x) -> x — the per-VIRTUAL-stage body the circular
    (interleaved) schedule calls once per lap."""
    block = model.layers[0]

    def block_fn(stage_params, x):
        with block.bind(stage_params, {}):
            return block(Tensor(x))._value

    return block_fn


class GPTForCausalLMPipe(Layer):
    """GPTForCausalLM with the decoder stack run through the SPMD pipeline
    engine (reference analog: PaddleNLP's GPTForCausalLMPipe built on
    PipelineLayer).  Embedding + head stay partitioner-sharded; blocks run
    manual pp (x mp x dp)."""

    def __init__(self, lm: "GPTForCausalLM" = None, mesh=None, n_micro=1,
                 batch_axis=None, schedule=None, **kwargs):
        super().__init__()
        self.lm = lm if lm is not None else GPTForCausalLM(**kwargs)
        if mesh is None:
            from ...distributed.topology import get_hybrid_communicate_group

            hcg = get_hybrid_communicate_group()
            mesh = hcg.mesh if hcg is not None else None
        if mesh is None:
            raise ValueError("GPTForCausalLMPipe needs a mesh (fleet.init first)")
        if schedule is None:
            # reference contract: with strategy.pipeline ENABLED,
            # pipeline_configs['schedule_mode'] selects the schedule
            # ('F-then-B'/'1F1B'/'Interleave'); otherwise gpipe
            schedule = "gpipe"
            try:
                from ...distributed import fleet as _fleet

                st = _fleet.get_strategy()
                if st is not None and getattr(st, "pipeline", False):
                    mode = str(st.pipeline_configs.get(
                        "schedule_mode", "1F1B")).strip().lower()
                    table = {"1f1b": "1f1b", "interleave": "interleaved",
                             "interleaved": "interleaved",
                             "f-then-b": "gpipe", "gpipe": "gpipe"}
                    if mode not in table:
                        import warnings

                        warnings.warn(
                            f"unknown pipeline schedule_mode {mode!r}; "
                            "falling back to gpipe (F-then-B)")
                    schedule = table.get(mode, "gpipe")
            except ImportError:  # fleet not importable: single-process use
                pass
        self._mesh = mesh
        self._n_micro = n_micro
        self._batch_axis = batch_axis
        self._schedule = schedule

    def forward(self, input_ids, labels=None):
        hidden = pipeline_forward(self.lm.gpt, input_ids, self._mesh,
                                  self._n_micro, axis="pp",
                                  batch_axis=self._batch_axis,
                                  schedule=self._schedule)
        w = self.lm.gpt.word_embeddings.weight
        mp = dict(zip(self._mesh.axis_names, self._mesh.devices.shape)).get("mp", 1)
        if labels is not None and mp > 1:
            # vocab-sharded head + CE: the [B, S, V] logits tensor never
            # materializes per rank (c_softmax_with_cross_entropy analog)
            from ...distributed.fleet.meta_parallel.mp_layers import (
                sharded_vocab_head_loss)

            return sharded_vocab_head_loss(hidden, w, labels, self._mesh,
                                           batch_axis=self._batch_axis)
        logits = _apply(lambda h, wv: h @ wv.T, hidden, w, op_name="matmul")
        if labels is not None:
            return F.cross_entropy(
                logits[:, :-1].reshape([-1, logits.shape[-1]]),
                labels[:, 1:].reshape([-1]), reduction="mean")
        return logits


def pipeline_forward(model: GPTModel, input_ids, mesh, n_micro, axis="pp",
                     batch_axis=None, schedule="gpipe"):
    """Full GPT forward with the decoder stack pipelined over ``axis``:
    embed (all ranks, partitioner-sharded) -> spmd_pipeline(blocks, manual
    pp x mp x dp) -> final_ln.  input_ids: [B, S]; B divides into n_micro
    micro-batches.  ``schedule='interleaved'`` runs the circular virtual-
    stage schedule (layer j on rank j % pp), shrinking the fill/drain bubble
    by ~layers_per_stage."""
    from ...distributed.fleet.meta_parallel import spmd_pipeline

    pp = mesh.shape[axis]
    order = "lap" if schedule == "interleaved" else "stage"
    stacked, specs = stack_block_params(model, pp, order=order)
    x = model.embed(input_ids)
    B = x.shape[0]
    micro = B // n_micro
    xm = x._value.reshape((n_micro, micro) + tuple(x.shape[1:]))
    fn = single_block_fn_for(model) if schedule == "interleaved" \
        else block_fn_for(model)
    out = spmd_pipeline(fn, stacked, xm, mesh, axis=axis,
                        batch_axis=batch_axis, param_specs=specs,
                        schedule=schedule)
    out = out.reshape((B,) + tuple(x.shape[1:]))
    return model.final_ln(Tensor(out))
