"""ViterbiDecoder (reference: python/paddle/text/viterbi_decode.py).

TPU-native: the forward max-sum recursion is a ``lax.scan`` over time
(static shapes, one compiled program) collecting argmax backpointers; the
backtrace is a second scan in reverse.  ``with_start_stop_tag`` follows the
reference convention: the LAST tag index is the start tag and the
SECOND-TO-LAST is the stop tag (their transition rows/columns bracket the
sequence).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..nn.layer import Layer
from ..tensor.dispatch import apply


def viterbi_decode(potentials, transitions, lengths,
                   include_bos_eos_tag=True, name=None):
    """Best tag path per sequence.

    Args:
        potentials: [B, T, N] unary emission scores.
        transitions: [N, N] transition scores (from-tag, to-tag).
        lengths: [B] int valid timesteps per sequence.
        include_bos_eos_tag: treat tag N-1 as BOS and N-2 as EOS
            (reference ``with_start_stop_tag``).

    Returns:
        (scores [B], paths [B, T] int64) — positions beyond a sequence's
        length hold 0.
    """

    def fn(pot, trans, lens):
        B, T, N = pot.shape
        start_idx, stop_idx = N - 1, N - 2
        alpha = pot[:, 0]
        if include_bos_eos_tag:
            alpha = alpha + trans[start_idx][None, :]

        def step(carry, xs):
            alpha, t = carry
            emit = xs  # [B, N]
            # [B, from, to]
            scores = alpha[:, :, None] + trans[None, :, :]
            best_from = jnp.argmax(scores, axis=1)            # [B, N]
            best_score = jnp.max(scores, axis=1) + emit
            live = (t < lens)[:, None]
            alpha = jnp.where(live, best_score, alpha)
            ptr = jnp.where(live, best_from,
                            jnp.arange(N)[None, :])           # identity hold
            return (alpha, t + 1), ptr

        (alpha, _), ptrs = lax.scan(
            step, (alpha, jnp.ones((), jnp.int32)),
            jnp.moveaxis(pot[:, 1:], 0, 1) if T > 1 else
            jnp.zeros((0, B, N), pot.dtype))
        if include_bos_eos_tag:
            alpha = alpha + trans[:, stop_idx][None, :]
        scores = jnp.max(alpha, axis=-1)
        last_tag = jnp.argmax(alpha, axis=-1)                 # [B]

        # backtrace: walk ptrs from each sequence's end
        def back(carry, xs):
            tag, t = carry
            ptr = xs                                           # [B, N]
            prev = jnp.take_along_axis(ptr, tag[:, None], 1)[:, 0]
            # only move while t < len (ptr rows past the end hold identity)
            tag_prev = prev
            return (tag_prev, t - 1), tag

        (first_tag, _), rev_tags = lax.scan(
            back, (last_tag, jnp.full((), T - 1, jnp.int32)), ptrs,
            reverse=True)
        # rev_tags[t] is the tag at position t+1; prepend position 0's tag
        path = jnp.concatenate([first_tag[:, None],
                                jnp.moveaxis(rev_tags, 0, 1)], axis=1) \
            if T > 1 else first_tag[:, None]
        mask = jnp.arange(T)[None, :] < lens[:, None]
        return scores, jnp.where(mask, path, 0).astype(jnp.int64)

    return apply(fn, potentials, transitions, lengths, n_outs=None,
                 op_name="viterbi_decode")


class ViterbiDecoder(Layer):
    """reference: paddle.text.ViterbiDecoder — holds the transition matrix
    option and decodes (potentials, lengths) batches."""

    def __init__(self, transitions=None, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths, transitions=None):
        trans = transitions if transitions is not None else self.transitions
        if trans is None:
            raise ValueError("ViterbiDecoder needs a transitions matrix")
        return viterbi_decode(potentials, trans, lengths,
                              self.include_bos_eos_tag)
