"""paddle.optimizer namespace (reference: python/paddle/optimizer/)."""

from . import lr  # noqa: F401
from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa: F401
from .optimizer import (  # noqa: F401
    ASGD, LBFGS, SGD, Adadelta, Adagrad, Adam, Adamax, AdamW, Lamb, Lars,
    Momentum, NAdam, Optimizer, RAdam, RMSProp, Rprop,
)
