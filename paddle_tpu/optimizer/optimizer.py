"""Optimizers (reference: python/paddle/optimizer/).

Architecture: each optimizer defines a PURE update rule
``_rule(p, g, state, lr, hyper) -> (new_p, new_state)`` over jax arrays.
Eager ``.step()`` folds the rule over parameters (reading ``.grad`` set by
the tape, honoring grad clip + weight decay ordering like the reference:
clip first, then decoupled/coupled decay, then the rule).  The SAME rule
powers the compiled training path (hapi.Model / jit trainers / pjit
distribution), so optimizer math exists exactly once.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.state import no_grad_ctx
from ..tensor.tensor import Parameter, Tensor
from .lr import LRScheduler


class Optimizer:
    _hyper_defaults: dict = {}

    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, **hyper):
        self._lr = learning_rate
        self._groups = self._build_groups(parameters, weight_decay, hyper)
        self._grad_clip = grad_clip
        self._states: dict[int, dict] = {}
        self._step_count = 0

    # ------------------------------------------------------------- groups
    def _build_groups(self, parameters, weight_decay, hyper):
        base = dict(self._hyper_defaults)
        base.update(hyper)
        wd = weight_decay
        if wd is None:
            wd = 0.0
        if hasattr(wd, "coeff"):  # L2Decay / L1Decay object
            wd = wd.coeff
        groups = []
        if parameters is None:
            parameters = []
        plist = list(parameters)
        if plist and isinstance(plist[0], dict):
            for g in plist:
                gh = dict(base)
                gwd = g.get("weight_decay", wd)
                if hasattr(gwd, "coeff"):
                    gwd = gwd.coeff
                groups.append({
                    "params": list(g["params"]),
                    "weight_decay": gwd,
                    "lr_scale": g.get("learning_rate", 1.0),
                    "hyper": gh,
                })
        else:
            groups.append({"params": plist, "weight_decay": wd, "lr_scale": 1.0, "hyper": base})
        return groups

    @property
    def _parameter_list(self):
        return [p for g in self._groups for p in g["params"]]

    # ----------------------------------------------------------------- lr
    def get_lr(self):
        if isinstance(self._lr, LRScheduler):
            return float(self._lr())
        return float(self._lr)

    def set_lr(self, value):
        if isinstance(self._lr, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._lr = value

    def set_lr_scheduler(self, scheduler):
        self._lr = scheduler

    # --------------------------------------------------------------- step
    @jax.named_scope("optimizer_step")
    def step(self):
        with no_grad_ctx():
            lr = self.get_lr()
            for group in self._groups:
                pg = [(p, p.grad) for p in group["params"]
                      if p.grad is not None and not getattr(p, "stop_gradient", False)]
                if not pg:
                    continue
                if self._grad_clip is not None:
                    pg = self._grad_clip(pg)
                for p, g in pg:
                    # master-weight path: O2/amp keeps an f32 copy, the rule
                    # runs in f32, the bf16/f16 working copy is re-derived
                    master = getattr(p, "_master", None)
                    pv = master if master is not None else p._value
                    state = self._states.get(id(p))
                    if state is None:
                        state = self.init_state(pv)
                        self._states[id(p)] = state
                    gv = g._value.astype(pv.dtype) if isinstance(g, Tensor) else g
                    wd = self._param_weight_decay(p, group)
                    if getattr(p, "regularizer", None) is not None:
                        gv = gv + p.regularizer(pv)
                        wd = 0.0
                    new_p, new_state = self._rule(
                        pv, gv, state, lr * group["lr_scale"],
                        group["hyper"], wd)
                    if master is not None:
                        p._master = new_p
                        p._value = new_p.astype(p._value.dtype)
                    else:
                        p._value = new_p
                    self._states[id(p)] = new_state
            self._step_count += 1

    def _param_weight_decay(self, p, group):
        return group["weight_decay"]

    @staticmethod
    def _rule(p, g, state, lr, hyper, wd):
        raise NotImplementedError

    def init_state(self, p_value):
        return {}

    # ------------------------------------------------------------- utils
    def clear_grad(self, set_to_zero=True):
        for p in self._parameter_list:
            p.clear_grad()

    clear_gradients = clear_grad

    def state_dict(self):
        flat = {}
        for i, p in enumerate(self._parameter_list):
            st = self._states.get(id(p))
            if st:
                flat[str(i)] = {k: Tensor(v) if isinstance(v, jax.Array) else v
                                for k, v in st.items()}
        out = {"states": flat, "step": self._step_count}
        if isinstance(self._lr, LRScheduler):
            out["LR_Scheduler"] = self._lr.state_dict()
        return out

    def set_state_dict(self, sd):
        params = self._parameter_list
        for k, st in sd.get("states", {}).items():
            p = params[int(k)]
            self._states[id(p)] = {
                kk: (vv._value if isinstance(vv, Tensor) else vv) for kk, vv in st.items()
            }
        self._step_count = sd.get("step", 0)
        if "LR_Scheduler" in sd and isinstance(self._lr, LRScheduler):
            self._lr.set_state_dict(sd["LR_Scheduler"])

    # ------------------------------------------- functional API (jit path)
    def functional_init(self, param_tree):
        """Per-leaf optimizer state pytree for the compiled trainer."""
        return jax.tree_util.tree_map(lambda p: self.init_state(p), param_tree)

    def resolve_leaf_meta(self, param_tree):
        """Per-leaf (lr_scale, hyper, weight_decay) resolved OUTSIDE any jit.

        Leaves are matched to param groups by identity of the underlying jax
        array (Parameter._value), the only association that survives arbitrary
        tree ordering; order-based matching is the fallback.  Tree leaves may
        be Parameters or their raw arrays.
        """
        leaves, _ = jax.tree_util.tree_flatten(
            param_tree, is_leaf=lambda x: isinstance(x, (Parameter, Tensor)))
        by_val = {}
        for g in self._groups:
            for p in g["params"]:
                by_val[id(p)] = (p, g)
                by_val[id(p._value)] = (p, g)
        plist = self._parameter_list
        meta = []
        for i, leaf in enumerate(leaves):
            hit = by_val.get(id(leaf))
            if hit is None and i < len(plist):
                p = plist[i]
                hit = (p, next(g for g in self._groups if any(q is p for q in g["params"])))
            if hit is None:
                meta.append((1.0, self._groups[0]["hyper"], self._groups[0]["weight_decay"]))
            else:
                p, g = hit
                meta.append((g["lr_scale"], g["hyper"], self._param_weight_decay(p, g)))
        return meta

    def functional_update(self, param_tree, grad_tree, state_tree, lr, leaf_meta=None):
        """Pure pytree update — usable under jit/pjit/shard_map.
        Grad clip (global-norm class) is applied tree-wide first.
        ``leaf_meta`` (from :meth:`resolve_leaf_meta`, computed outside jit)
        carries per-leaf group settings; without it every leaf gets group-0."""
        if self._grad_clip is not None and hasattr(self._grad_clip, "tree_clip"):
            grad_tree = self._grad_clip.tree_clip(grad_tree)

        leaves_p, treedef = jax.tree_util.tree_flatten(param_tree)
        leaves_g = treedef.flatten_up_to(grad_tree)
        leaves_s = treedef.flatten_up_to(state_tree)

        if leaf_meta is None:
            if len(self._groups) > 1 and len(leaves_p) == len(self._parameter_list):
                leaf_meta = self.resolve_leaf_meta(param_tree)
            else:
                if len(self._groups) > 1:
                    import warnings

                    warnings.warn(
                        f"functional_update: param tree has {len(leaves_p)} leaves but "
                        f"the optimizer tracks {len(self._parameter_list)} params across "
                        f"{len(self._groups)} groups; applying group-0 settings to every "
                        "leaf (pass leaf_meta=resolve_leaf_meta(...) to fix)")
                g0 = self._groups[0]
                leaf_meta = [(g0["lr_scale"], g0["hyper"], g0["weight_decay"])] * len(leaves_p)

        new_p, new_s = [], []
        for p, g, s, (lr_scale, hyper, wd) in zip(leaves_p, leaves_g, leaves_s, leaf_meta):
            np_, ns_ = self._rule(p, g.astype(p.dtype), s, lr * lr_scale, hyper, wd)
            new_p.append(np_)
            new_s.append(ns_)
        return treedef.unflatten(new_p), treedef.unflatten(new_s)

    def _apply_optimize(self, loss, startup_program=None, params_grads=None):
        self.step()

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        loss.backward()
        self.step()
        return None, None


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)

    @staticmethod
    def _rule(p, g, state, lr, hyper, wd):
        if wd:
            g = g + wd * p
        return p - lr * g, state


class Momentum(Optimizer):
    _hyper_defaults = {"momentum": 0.9, "use_nesterov": False}

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name,
                         momentum=momentum, use_nesterov=use_nesterov)

    def init_state(self, p):
        return {"velocity": jnp.zeros_like(p)}

    @staticmethod
    def _rule(p, g, state, lr, hyper, wd):
        if wd:
            g = g + wd * p
        v = hyper["momentum"] * state["velocity"] + g
        if hyper["use_nesterov"]:
            new_p = p - lr * (g + hyper["momentum"] * v)
        else:
            new_p = p - lr * v
        return new_p, {"velocity": v}


class Adam(Optimizer):
    _hyper_defaults = {"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8, "lazy_mode": False,
                       "amsgrad": False}

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, name=None, amsgrad=False, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name,
                         beta1=beta1, beta2=beta2, epsilon=epsilon, lazy_mode=lazy_mode,
                         amsgrad=amsgrad)

    def init_state(self, p):
        s = {"m": jnp.zeros_like(p), "v": jnp.zeros_like(p),
             "t": jnp.zeros([], jnp.float32)}
        return s

    @staticmethod
    def _rule(p, g, state, lr, hyper, wd):
        if wd:  # reference Adam applies coupled L2 (weight_decay as regularizer)
            g = g + wd * p
        b1, b2, eps = hyper["beta1"], hyper["beta2"], hyper["epsilon"]
        t = state["t"] + 1
        m = b1 * state["m"] + (1 - b1) * g
        v = b2 * state["v"] + (1 - b2) * jnp.square(g)
        mhat = m / (1 - b1 ** t)
        vhat = v / (1 - b2 ** t)
        if hyper.get("amsgrad"):
            vmax = jnp.maximum(state.get("vmax", jnp.zeros_like(v)), vhat)
            new_p = p - lr * mhat / (jnp.sqrt(vmax) + eps)
            return new_p.astype(p.dtype), {"m": m, "v": v, "t": t, "vmax": vmax}
        new_p = p - lr * mhat / (jnp.sqrt(vhat) + eps)
        return new_p.astype(p.dtype), {"m": m, "v": v, "t": t}


class AdamW(Adam):
    """Decoupled weight decay (reference: python/paddle/optimizer/adamw.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=0.01, lr_ratio=None, apply_decay_param_fun=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False, name=None, **kw):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, lazy_mode, multi_precision, name)
        self._apply_decay_param_fun = apply_decay_param_fun

    @staticmethod
    def _rule(p, g, state, lr, hyper, wd):
        b1, b2, eps = hyper["beta1"], hyper["beta2"], hyper["epsilon"]
        t = state["t"] + 1
        m = b1 * state["m"] + (1 - b1) * g
        v = b2 * state["v"] + (1 - b2) * jnp.square(g)
        mhat = m / (1 - b1 ** t)
        vhat = v / (1 - b2 ** t)
        new_p = p * (1 - lr * wd) - lr * mhat / (jnp.sqrt(vhat) + eps)
        return new_p.astype(p.dtype), {"m": m, "v": v, "t": t}

    def _param_weight_decay(self, p, group):
        if self._apply_decay_param_fun is not None and not self._apply_decay_param_fun(p.name or ""):
            return 0.0
        return group["weight_decay"]


class Adamax(Optimizer):
    _hyper_defaults = {"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8}

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name,
                         beta1=beta1, beta2=beta2, epsilon=epsilon)

    def init_state(self, p):
        return {"m": jnp.zeros_like(p), "u": jnp.zeros_like(p), "t": jnp.zeros([], jnp.float32)}

    @staticmethod
    def _rule(p, g, state, lr, hyper, wd):
        if wd:
            g = g + wd * p
        b1, b2, eps = hyper["beta1"], hyper["beta2"], hyper["epsilon"]
        t = state["t"] + 1
        m = b1 * state["m"] + (1 - b1) * g
        u = jnp.maximum(b2 * state["u"], jnp.abs(g))
        new_p = p - lr / (1 - b1 ** t) * m / (u + eps)
        return new_p.astype(p.dtype), {"m": m, "u": u, "t": t}


class Adagrad(Optimizer):
    _hyper_defaults = {"epsilon": 1e-6, "initial_accumulator_value": 0.0}

    def __init__(self, learning_rate, epsilon=1e-6, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, initial_accumulator_value=0.0):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name,
                         epsilon=epsilon, initial_accumulator_value=initial_accumulator_value)

    def init_state(self, p):
        return {"moment": jnp.full_like(p, self._groups[0]["hyper"]["initial_accumulator_value"])}

    @staticmethod
    def _rule(p, g, state, lr, hyper, wd):
        if wd:
            g = g + wd * p
        mom = state["moment"] + jnp.square(g)
        new_p = p - lr * g / (jnp.sqrt(mom) + hyper["epsilon"])
        return new_p.astype(p.dtype), {"moment": mom}


class RMSProp(Optimizer):
    _hyper_defaults = {"rho": 0.95, "epsilon": 1e-6, "momentum": 0.0, "centered": False}

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0, centered=False,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name,
                         rho=rho, epsilon=epsilon, momentum=momentum, centered=centered)

    def init_state(self, p):
        return {"mean_square": jnp.zeros_like(p), "mean_grad": jnp.zeros_like(p),
                "momentum": jnp.zeros_like(p)}

    @staticmethod
    def _rule(p, g, state, lr, hyper, wd):
        if wd:
            g = g + wd * p
        rho, eps = hyper["rho"], hyper["epsilon"]
        ms = rho * state["mean_square"] + (1 - rho) * jnp.square(g)
        if hyper["centered"]:
            mg = rho * state["mean_grad"] + (1 - rho) * g
            denom = jnp.sqrt(ms - jnp.square(mg) + eps)
        else:
            mg = state["mean_grad"]
            denom = jnp.sqrt(ms + eps)
        mom = hyper["momentum"] * state["momentum"] + lr * g / denom
        return (p - mom).astype(p.dtype), {"mean_square": ms, "mean_grad": mg, "momentum": mom}


class Adadelta(Optimizer):
    _hyper_defaults = {"rho": 0.95, "epsilon": 1e-6}

    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name,
                         rho=rho, epsilon=epsilon)

    def init_state(self, p):
        return {"avg_sq_grad": jnp.zeros_like(p), "avg_sq_update": jnp.zeros_like(p)}

    @staticmethod
    def _rule(p, g, state, lr, hyper, wd):
        if wd:
            g = g + wd * p
        rho, eps = hyper["rho"], hyper["epsilon"]
        asg = rho * state["avg_sq_grad"] + (1 - rho) * jnp.square(g)
        update = jnp.sqrt(state["avg_sq_update"] + eps) / jnp.sqrt(asg + eps) * g
        asu = rho * state["avg_sq_update"] + (1 - rho) * jnp.square(update)
        return (p - lr * update).astype(p.dtype), {"avg_sq_grad": asg, "avg_sq_update": asu}


class Lamb(Optimizer):
    _hyper_defaults = {"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-6, "lamb_weight_decay": 0.01}

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name,
                         beta1=beta1, beta2=beta2, epsilon=epsilon,
                         lamb_weight_decay=lamb_weight_decay)
        self._exclude_fn = exclude_from_weight_decay_fn

    def init_state(self, p):
        return {"m": jnp.zeros_like(p), "v": jnp.zeros_like(p), "t": jnp.zeros([], jnp.float32)}

    @staticmethod
    def _rule(p, g, state, lr, hyper, wd):
        b1, b2, eps = hyper["beta1"], hyper["beta2"], hyper["epsilon"]
        lwd = hyper["lamb_weight_decay"]
        t = state["t"] + 1
        m = b1 * state["m"] + (1 - b1) * g
        v = b2 * state["v"] + (1 - b2) * jnp.square(g)
        mhat = m / (1 - b1 ** t)
        vhat = v / (1 - b2 ** t)
        r = mhat / (jnp.sqrt(vhat) + eps) + lwd * p
        w_norm = jnp.linalg.norm(p.reshape(-1))
        r_norm = jnp.linalg.norm(r.reshape(-1))
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return (p - lr * trust * r).astype(p.dtype), {"m": m, "v": v, "t": t}


class Rprop(Optimizer):
    _hyper_defaults = {"etas": (0.5, 1.2), "sizes": (1e-6, 50.0)}

    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50), parameters=None,
                 etas=(0.5, 1.2), grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name,
                         etas=etas, sizes=learning_rate_range)

    def init_state(self, p):
        return {"prev": jnp.zeros_like(p), "step_size": jnp.full_like(p, self.get_lr())}

    @staticmethod
    def _rule(p, g, state, lr, hyper, wd):
        em, ep = hyper["etas"]
        smin, smax = hyper["sizes"]
        sign = jnp.sign(g * state["prev"])
        factor = jnp.where(sign > 0, ep, jnp.where(sign < 0, em, 1.0))
        step = jnp.clip(state["step_size"] * factor, smin, smax)
        g_eff = jnp.where(sign < 0, 0.0, g)
        new_p = p - jnp.sign(g_eff) * step
        return new_p.astype(p.dtype), {"prev": g_eff, "step_size": step}


class NAdam(Optimizer):
    """Nesterov-momentum Adam (reference: paddle.optimizer.NAdam)."""

    _hyper_defaults = {"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8,
                       "momentum_decay": 0.004}

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, momentum_decay=0.004, parameters=None,
                 weight_decay=None, grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, beta1=beta1, beta2=beta2, epsilon=epsilon,
                         momentum_decay=momentum_decay)

    def init_state(self, p):
        return {"m": jnp.zeros_like(p), "v": jnp.zeros_like(p),
                "t": jnp.zeros([], jnp.float32),
                "mu_prod": jnp.ones([], jnp.float32)}

    @staticmethod
    def _rule(p, g, state, lr, hyper, wd):
        if wd:
            g = g + wd * p
        b1, b2, eps = hyper["beta1"], hyper["beta2"], hyper["epsilon"]
        psi = hyper["momentum_decay"]
        t = state["t"] + 1
        mu_t = b1 * (1 - 0.5 * 0.96 ** (t * psi))
        mu_next = b1 * (1 - 0.5 * 0.96 ** ((t + 1) * psi))
        mu_prod = state["mu_prod"] * mu_t
        m = b1 * state["m"] + (1 - b1) * g
        v = b2 * state["v"] + (1 - b2) * jnp.square(g)
        mhat = mu_next * m / (1 - mu_prod * mu_next) \
            + (1 - mu_t) * g / (1 - mu_prod)
        vhat = v / (1 - b2 ** t)
        new_p = p - lr * mhat / (jnp.sqrt(vhat) + eps)
        return new_p.astype(p.dtype), {"m": m, "v": v, "t": t,
                                       "mu_prod": mu_prod}


class RAdam(Optimizer):
    """Rectified Adam (reference: paddle.optimizer.RAdam): falls back to
    un-adapted SGD-with-momentum while the variance estimate is unrectifiable."""

    _hyper_defaults = {"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8}

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, beta1=beta1, beta2=beta2, epsilon=epsilon)

    def init_state(self, p):
        return {"m": jnp.zeros_like(p), "v": jnp.zeros_like(p),
                "t": jnp.zeros([], jnp.float32)}

    @staticmethod
    def _rule(p, g, state, lr, hyper, wd):
        if wd:
            g = g + wd * p
        b1, b2, eps = hyper["beta1"], hyper["beta2"], hyper["epsilon"]
        t = state["t"] + 1
        m = b1 * state["m"] + (1 - b1) * g
        v = b2 * state["v"] + (1 - b2) * jnp.square(g)
        mhat = m / (1 - b1 ** t)
        rho_inf = 2.0 / (1 - b2) - 1
        rho_t = rho_inf - 2.0 * t * (b2 ** t) / (1 - b2 ** t)
        r_num = (rho_t - 4) * (rho_t - 2) * rho_inf
        r_den = (rho_inf - 4) * (rho_inf - 2) * rho_t
        rect = jnp.sqrt(jnp.maximum(r_num / jnp.maximum(r_den, 1e-12), 0.0))
        vhat = jnp.sqrt(v / (1 - b2 ** t)) + eps
        adaptive = p - lr * rect * mhat / vhat
        plain = p - lr * mhat
        new_p = jnp.where(rho_t > 5.0, adaptive, plain)
        return new_p.astype(p.dtype), {"m": m, "v": v, "t": t}


class ASGD(Optimizer):
    """Averaged SGD (reference: paddle.optimizer.ASGD): steps with the mean
    of the last ``batch_num`` gradients (a circular buffer per param, as the
    reference keeps) and maintains the online average of iterates."""

    _hyper_defaults = {"batch_num": 1}

    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=None, grad_clip=None, name=None, **kw):
        if batch_num < 1:
            raise ValueError("batch_num must be >= 1")
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, batch_num=batch_num)
        self._batch_num = int(batch_num)

    def init_state(self, p):
        s = {"avg": p, "t": jnp.zeros([], jnp.float32)}
        if self._batch_num > 1:
            s["grad_buf"] = jnp.zeros((self._batch_num,) + tuple(p.shape),
                                      p.dtype)
            s["grad_sum"] = jnp.zeros_like(p)
        return s

    @staticmethod
    def _rule(p, g, state, lr, hyper, wd):
        if wd:
            g = g + wd * p
        t = state["t"] + 1
        n = int(hyper["batch_num"])
        new_state = {"t": t}
        if n > 1:
            slot = (t.astype(jnp.int32) - 1) % n
            old = state["grad_buf"][slot]
            grad_sum = state["grad_sum"] - old + g
            new_state["grad_buf"] = state["grad_buf"].at[slot].set(g)
            new_state["grad_sum"] = grad_sum
            g_eff = grad_sum / jnp.minimum(t, float(n))
        else:
            g_eff = g
        new_p = p - lr * g_eff
        new_state["avg"] = state["avg"] + (new_p - state["avg"]) / t
        return new_p.astype(p.dtype), new_state


class Lars(Optimizer):
    """Layer-wise adaptive rate scaling (reference: fleet's lars meta
    optimizer / paddle LarsMomentum): trust ratio ||w||/(||g|| + wd*||w||)
    scales the local LR per parameter."""

    _hyper_defaults = {"momentum": 0.9, "lars_coeff": 0.001,
                       "lars_weight_decay": 0.0005, "epsilon": 1e-9}

    def __init__(self, learning_rate=0.001, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, epsilon=1e-9, parameters=None,
                 grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, None, grad_clip, name,
                         momentum=momentum, lars_coeff=lars_coeff,
                         lars_weight_decay=lars_weight_decay, epsilon=epsilon)

    def init_state(self, p):
        return {"velocity": jnp.zeros_like(p)}

    @staticmethod
    def _rule(p, g, state, lr, hyper, wd):
        mu, coeff = hyper["momentum"], hyper["lars_coeff"]
        lwd, eps = hyper["lars_weight_decay"], hyper["epsilon"]
        w_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
        g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
        local_lr = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            coeff * w_norm / (g_norm + lwd * w_norm + eps), 1.0)
        vel = mu * state["velocity"] + local_lr * lr * (g + lwd * p)
        new_p = p - vel
        return new_p.astype(p.dtype), {"velocity": vel}


class LBFGS(Optimizer):
    """Limited-memory BFGS with closure-driven strong-Wolfe-free backtracking
    (reference: paddle.optimizer.LBFGS).  Unlike the pure-rule optimizers,
    ``step(closure)`` re-evaluates the loss (the reference contract), so it
    runs in the eager path only."""

    def __init__(self, learning_rate=1.0, max_iter=20, history_size=10,
                 tolerance_grad=1e-7, tolerance_change=1e-9, parameters=None,
                 line_search_fn=None, weight_decay=None, grad_clip=None,
                 name=None, **kw):
        # step() bypasses the per-param rule path, so silently accepting
        # these would run different dynamics than requested
        if weight_decay:
            raise ValueError("LBFGS does not support weight_decay; add an "
                             "L2 term to the closure's loss instead")
        if grad_clip is not None:
            raise ValueError("LBFGS does not support grad_clip")
        if line_search_fn not in (None, "backtracking"):
            raise ValueError(f"unsupported line_search_fn "
                             f"{line_search_fn!r} (only backtracking)")
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self.max_iter = max_iter
        self.history_size = history_size
        self.tol_grad = tolerance_grad
        self.tol_change = tolerance_change
        self._hist = []  # [(s, y, rho)] newest last

    def _flat_params(self):
        return jnp.concatenate([p._value.reshape(-1)
                                for p in self._parameter_list])

    def _set_flat(self, flat):
        ofs = 0
        for p in self._parameter_list:
            n = p._value.size
            p._value = flat[ofs:ofs + n].reshape(p._value.shape).astype(
                p._value.dtype)
            ofs += n

    def _flat_grad(self):
        gs = []
        for p in self._parameter_list:
            g = p.grad._value if p.grad is not None else jnp.zeros_like(p._value)
            gs.append(g.reshape(-1))
        return jnp.concatenate(gs)

    def step(self, closure=None):
        if closure is None:
            raise ValueError("LBFGS.step requires a closure returning the "
                             "loss (reference contract)")
        from ..framework.state import no_grad_ctx

        loss = closure()
        g = self._flat_grad()
        x = self._flat_params()
        for _ in range(self.max_iter):
            if float(jnp.max(jnp.abs(g))) <= self.tol_grad:
                break
            # two-loop recursion
            q = g
            alphas = []
            for s, y, rho in reversed(self._hist):
                a = rho * jnp.dot(s, q)
                alphas.append(a)
                q = q - a * y
            if self._hist:
                s, y, _ = self._hist[-1]
                gamma = jnp.dot(s, y) / jnp.maximum(jnp.dot(y, y), 1e-12)
                q = q * gamma
            for (s, y, rho), a in zip(self._hist, reversed(alphas)):
                b = rho * jnp.dot(y, q)
                q = q + s * (a - b)
            d = -q
            # backtracking line search on the closure
            t = float(self.get_lr())
            f0 = float(loss)
            gtd = float(jnp.dot(g, d))
            x_new = x
            for _ls in range(10):
                x_new = x + t * d
                with no_grad_ctx():
                    self._set_flat(x_new)
                self.clear_grad()
                loss = closure()
                if float(loss) <= f0 + 1e-4 * t * gtd:
                    break
                t *= 0.5
            g_new = self._flat_grad()
            s_vec = x_new - x
            y_vec = g_new - g
            ys = float(jnp.dot(s_vec, y_vec))
            if ys > 1e-10:
                self._hist.append((s_vec, y_vec, 1.0 / ys))
                if len(self._hist) > self.history_size:
                    self._hist.pop(0)
            if float(jnp.max(jnp.abs(s_vec))) < self.tol_change:
                x, g = x_new, g_new
                break
            x, g = x_new, g_new
        self._step_count += 1
        return loss
