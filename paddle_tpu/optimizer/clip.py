"""Gradient clipping (reference: python/paddle/nn/clip.py — ClipGradBy*).

Clip classes are callables over [(param, grad)] pairs (eager path) and
expose ``tree_clip`` for the compiled pytree path — same math both ways.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..tensor.tensor import Tensor


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = max
        self.min = -max if min is None else min

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if getattr(p, "need_clip", True):
                g = Tensor(jnp.clip(g._value, self.min, self.max))
            out.append((p, g))
        return out

    def tree_clip(self, grad_tree):
        return jax.tree_util.tree_map(lambda g: jnp.clip(g, self.min, self.max), grad_tree)


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def _clip_one(self, g):
        norm = jnp.linalg.norm(g.reshape(-1))
        scale = jnp.where(norm > self.clip_norm, self.clip_norm / norm, 1.0)
        return g * scale

    def __call__(self, params_grads):
        return [(p, Tensor(self._clip_one(g._value)) if getattr(p, "need_clip", True) else g)
                for p, g in params_grads]

    def tree_clip(self, grad_tree):
        return jax.tree_util.tree_map(self._clip_one, grad_tree)


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group", auto_skip_clip=False):
        self.clip_norm = clip_norm

    def __call__(self, params_grads):
        gs = [g._value for p, g in params_grads if getattr(p, "need_clip", True)]
        if not gs:
            return params_grads
        global_norm = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in gs))
        scale = jnp.minimum(self.clip_norm / (global_norm + 1e-6), 1.0)
        out = []
        for p, g in params_grads:
            if getattr(p, "need_clip", True):
                g = Tensor((g._value.astype(jnp.float32) * scale).astype(g.dtype))
            out.append((p, g))
        return out

    def tree_clip(self, grad_tree):
        leaves = jax.tree_util.tree_leaves(grad_tree)
        global_norm = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves))
        scale = jnp.minimum(self.clip_norm / (global_norm + 1e-6), 1.0)
        return jax.tree_util.tree_map(
            lambda g: (g.astype(jnp.float32) * scale).astype(g.dtype), grad_tree)
