"""Native C++ runtime sources (SURVEY.md §2.1: data-loader/transform kernels).

The .cc here is built lazily by paddle_tpu.io.native with g++; shipping it as
package data keeps the wheel pure-Python while still delivering native code
to installed users.
"""
