// paddle_tpu native runtime: threaded host-side input pipeline kernels.
//
// Reference analog: the C++ DataLoader worker path + image decode/augment
// ops the reference runs in its worker processes (python/paddle/io backed
// by fluid/operators data ops).  On TPU hosts the input pipeline competes
// with dispatch for the GIL, so the hot per-batch transforms (uint8 ->
// normalized float CHW, flips, crops, collation) run here: C++ threads,
// zero Python object traffic, one memcpy-free pass per image.
//
// Built by paddle_tpu.io.native via: g++ -O3 -march=native -shared -fPIC
// Exposed through ctypes (no pybind11 in this environment).

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

int hw_threads() {
  unsigned n = std::thread::hardware_concurrency();
  return n == 0 ? 4 : static_cast<int>(n);
}

// Run fn(i) for i in [0, n) across a transient thread pool.
template <typename F>
void parallel_for(int n, int max_threads, F fn) {
  int nt = std::min(n, std::max(1, max_threads));
  if (nt <= 1) {
    for (int i = 0; i < n; ++i) fn(i);
    return;
  }
  std::atomic<int> next(0);
  std::vector<std::thread> threads;
  threads.reserve(nt);
  for (int t = 0; t < nt; ++t) {
    threads.emplace_back([&]() {
      int i;
      while ((i = next.fetch_add(1)) < n) fn(i);
    });
  }
  for (auto& th : threads) th.join();
}

}  // namespace

extern "C" {

// Batch uint8 HWC -> float32 CHW with per-channel mean/std and optional
// horizontal flip, one thread per image.
//   src:  [n, h, w, c] uint8
//   dst:  [n, c, h, w] float32
//   mean/stdv: [c] (in 0..255 units)
//   flips: [n] (0/1), may be null
void pt_normalize_chw(const uint8_t* src, float* dst, int n, int h, int w,
                      int c, const float* mean, const float* stdv,
                      const uint8_t* flips, int num_threads) {
  std::vector<float> inv(c);
  for (int k = 0; k < c; ++k) inv[k] = 1.0f / stdv[k];
  const int64_t img_in = static_cast<int64_t>(h) * w * c;
  const int64_t plane = static_cast<int64_t>(h) * w;
  parallel_for(n, num_threads > 0 ? num_threads : hw_threads(), [&](int i) {
    const uint8_t* s = src + i * img_in;
    float* d = dst + i * plane * c;
    bool flip = flips != nullptr && flips[i] != 0;
    for (int y = 0; y < h; ++y) {
      for (int x = 0; x < w; ++x) {
        int xs = flip ? (w - 1 - x) : x;
        const uint8_t* px = s + (static_cast<int64_t>(y) * w + xs) * c;
        for (int k = 0; k < c; ++k) {
          d[k * plane + y * w + x] = (static_cast<float>(px[k]) - mean[k]) * inv[k];
        }
      }
    }
  });
}

// Batch random-crop (pre-computed offsets) from [n, H, W, c] uint8 into
// [n, oh, ow, c] uint8; one thread per image.
void pt_crop_batch(const uint8_t* src, uint8_t* dst, int n, int H, int W,
                   int c, int oh, int ow, const int32_t* ys,
                   const int32_t* xs, int num_threads) {
  const int64_t img_in = static_cast<int64_t>(H) * W * c;
  const int64_t img_out = static_cast<int64_t>(oh) * ow * c;
  const int64_t row_out = static_cast<int64_t>(ow) * c;
  parallel_for(n, num_threads > 0 ? num_threads : hw_threads(), [&](int i) {
    const uint8_t* s = src + i * img_in;
    uint8_t* d = dst + i * img_out;
    int y0 = ys[i], x0 = xs[i];
    for (int y = 0; y < oh; ++y) {
      const uint8_t* srow = s + (static_cast<int64_t>(y0 + y) * W + x0) * c;
      std::memcpy(d + y * row_out, srow, row_out);
    }
  });
}

// Collate a list of equally-sized float32 sample buffers into one batch
// buffer (threaded memcpy) — the DataLoader's default_collate hot path.
void pt_collate_f32(const float** samples, float* dst, int n,
                    int64_t sample_elems, int num_threads) {
  parallel_for(n, num_threads > 0 ? num_threads : hw_threads(), [&](int i) {
    std::memcpy(dst + i * sample_elems, samples[i],
                sizeof(float) * static_cast<size_t>(sample_elems));
  });
}

int pt_version() { return 1; }

}  // extern "C"
