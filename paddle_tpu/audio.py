"""paddle.audio — feature extraction (reference: python/paddle/audio/:
functional windows/mel utilities + features.Spectrogram/MelSpectrogram/
LogMelSpectrogram/MFCC layers).

TPU-native: everything reduces to the framed-matmul STFT in
``paddle_tpu.signal`` plus one mel filter-bank matmul — MXU-shaped ops a
jitted feature pipeline fuses with the model; no librosa-style host DSP.
"""

from __future__ import annotations

import math

import jax.numpy as jnp

from . import signal as _signal
from .io import Dataset as _Dataset
from .nn.layer import Layer
from .tensor.dispatch import apply as _apply
from .tensor.tensor import Tensor

__all__ = ["functional", "features", "backends", "datasets",
           "load", "save", "info"]


class functional:
    """paddle.audio.functional namespace."""

    @staticmethod
    def get_window(window, win_length, fftbins=True, dtype="float64"):
        known = ("hann", "hanning", "hamming", "blackman", "rect",
                 "rectangular", "boxcar", "ones")
        if window not in known:
            raise ValueError(f"unsupported window {window!r}")
        n = win_length
        if n == 1:  # scipy convention: a length-1 window is [1.0]
            from .framework import dtypes as _dt

            return Tensor(jnp.ones((1,), _dt.to_jax(dtype)))
        k = jnp.arange(n, dtype=jnp.float64)
        denom = n if fftbins else n - 1
        if window in ("hann", "hanning"):
            w = 0.5 - 0.5 * jnp.cos(2 * math.pi * k / denom)
        elif window == "hamming":
            w = 0.54 - 0.46 * jnp.cos(2 * math.pi * k / denom)
        elif window == "blackman":
            w = (0.42 - 0.5 * jnp.cos(2 * math.pi * k / denom)
                 + 0.08 * jnp.cos(4 * math.pi * k / denom))
        elif window in ("rect", "rectangular", "boxcar", "ones"):
            w = jnp.ones((n,), jnp.float64)
        else:
            raise ValueError(f"unsupported window {window!r}")
        from .framework import dtypes as _dt

        return Tensor(w.astype(_dt.to_jax(dtype)))

    @staticmethod
    def hz_to_mel(freq, htk=False):
        f = jnp.asarray(freq, jnp.float64)
        if htk:
            out = 2595.0 * jnp.log10(1.0 + f / 700.0)
            return float(out) if out.ndim == 0 else Tensor(out)
        # slaney scale
        mel = (f - 0.0) / (200.0 / 3)
        min_log_hz = 1000.0
        min_log_mel = min_log_hz / (200.0 / 3)
        logstep = math.log(6.4) / 27.0
        out = jnp.where(f >= min_log_hz,
                        min_log_mel + jnp.log(f / min_log_hz) / logstep, mel)
        return float(out) if out.ndim == 0 else Tensor(out)

    @staticmethod
    def mel_to_hz(mel, htk=False):
        m = jnp.asarray(mel, jnp.float64)
        if htk:
            out = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
        else:
            freqs = (200.0 / 3) * m
            min_log_hz = 1000.0
            min_log_mel = min_log_hz / (200.0 / 3)
            logstep = math.log(6.4) / 27.0
            out = jnp.where(m >= min_log_mel,
                            min_log_hz * jnp.exp(logstep * (m - min_log_mel)),
                            freqs)
        return float(out) if out.ndim == 0 else Tensor(out)

    @staticmethod
    def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                             htk=False, norm="slaney", dtype="float32"):
        """[n_mels, n_fft//2 + 1] triangular mel filter bank."""
        f_max = f_max if f_max is not None else sr / 2.0
        n_bins = n_fft // 2 + 1
        fft_freqs = jnp.linspace(0.0, sr / 2.0, n_bins, dtype=jnp.float64)
        mel_min = functional.hz_to_mel(f_min, htk)
        mel_max = functional.hz_to_mel(f_max, htk)
        mel_pts = jnp.linspace(float(mel_min), float(mel_max), n_mels + 2,
                               dtype=jnp.float64)
        hz_pts = functional.mel_to_hz(mel_pts, htk)
        hz_pts = hz_pts._value if isinstance(hz_pts, Tensor) else hz_pts
        lower = hz_pts[:-2][:, None]
        center = hz_pts[1:-1][:, None]
        upper = hz_pts[2:][:, None]
        up = (fft_freqs[None] - lower) / jnp.maximum(center - lower, 1e-10)
        down = (upper - fft_freqs[None]) / jnp.maximum(upper - center, 1e-10)
        fb = jnp.maximum(0.0, jnp.minimum(up, down))
        if norm == "slaney":
            enorm = 2.0 / (hz_pts[2:] - hz_pts[:-2])
            fb = fb * enorm[:, None]
        from .framework import dtypes as _dt

        return Tensor(fb.astype(_dt.to_jax(dtype)))

    @staticmethod
    def power_to_db(spect, ref_value=1.0, amin=1e-10, top_db=80.0):
        def fn(s):
            db = 10.0 * jnp.log10(jnp.maximum(s, amin))
            db = db - 10.0 * jnp.log10(jnp.maximum(ref_value, amin))
            if top_db is not None:
                db = jnp.maximum(db, db.max() - top_db)
            return db

        return _apply(fn, spect, op_name="power_to_db")

    @staticmethod
    def create_dct(n_mfcc, n_mels, norm="ortho", dtype="float32"):
        k = jnp.arange(n_mels, dtype=jnp.float64)
        n = jnp.arange(n_mfcc, dtype=jnp.float64)[:, None]
        dct = jnp.cos(math.pi / n_mels * (k + 0.5) * n)       # [n_mfcc, n_mels]
        if norm == "ortho":
            dct = dct * math.sqrt(2.0 / n_mels)
            dct = dct.at[0].multiply(1.0 / math.sqrt(2.0))
        from .framework import dtypes as _dt

        return Tensor(dct.T.astype(_dt.to_jax(dtype)))      # [n_mels, n_mfcc]


class _Spectrogram(Layer):
    def __init__(self, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 dtype="float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.window = functional.get_window(window, self.win_length,
                                            dtype=dtype)
        self.power = power
        self.center = center
        self.pad_mode = pad_mode

    def forward(self, x):
        spec = _signal.stft(x, self.n_fft, hop_length=self.hop_length,
                            win_length=self.win_length, window=self.window,
                            center=self.center, pad_mode=self.pad_mode)
        return _apply(lambda s: jnp.abs(s) ** self.power, spec,
                      op_name="spec_power")


class _MelSpectrogram(Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 dtype="float32"):
        super().__init__()
        self.spectrogram = _Spectrogram(n_fft, hop_length, win_length, window,
                                        power, center, pad_mode, dtype)
        self.fbank = functional.compute_fbank_matrix(
            sr, n_fft, n_mels, f_min, f_max, htk, norm, dtype)

    def forward(self, x):
        s = self.spectrogram(x)                      # [..., n_bins, T]
        return _apply(lambda sv, fb: jnp.einsum("mf,...ft->...mt", fb, sv),
                      s, self.fbank, op_name="mel_project")


class _LogMelSpectrogram(Layer):
    def __init__(self, sr=22050, ref_value=1.0, amin=1e-10, top_db=None,
                 **kw):
        super().__init__()
        self.mel = _MelSpectrogram(sr=sr, **kw)
        self.ref_value, self.amin, self.top_db = ref_value, amin, top_db

    def forward(self, x):
        return functional.power_to_db(self.mel(x), self.ref_value, self.amin,
                                      self.top_db)


class _MFCC(Layer):
    def __init__(self, sr=22050, n_mfcc=40, norm="ortho", dtype="float32",
                 **kw):
        super().__init__()
        kw.setdefault("n_mels", 64)
        self.log_mel = _LogMelSpectrogram(sr=sr, dtype=dtype, **kw)
        self.dct = functional.create_dct(n_mfcc, kw["n_mels"], norm, dtype)

    def forward(self, x):
        lm = self.log_mel(x)                         # [..., n_mels, T]
        return _apply(lambda v, d: jnp.einsum("mk,...mt->...kt", d, v),
                      lm, self.dct, op_name="mfcc_dct")


class features:
    """paddle.audio.features namespace."""

    Spectrogram = _Spectrogram
    MelSpectrogram = _MelSpectrogram
    LogMelSpectrogram = _LogMelSpectrogram
    MFCC = _MFCC


class backends:
    """paddle.audio.backends (reference: the soundfile-backed
    load/save/info trio).  Here the codec is the stdlib ``wave`` module —
    16/32-bit PCM WAV in and out, which is what the bundled datasets use —
    so audio IO works with zero extra dependencies."""

    class AudioInfo:
        def __init__(self, sample_rate, num_samples, num_channels,
                     bits_per_sample, encoding="PCM_S"):
            self.sample_rate = sample_rate
            self.num_samples = num_samples
            self.num_channels = num_channels
            self.bits_per_sample = bits_per_sample
            self.encoding = encoding

    @staticmethod
    def info(filepath):
        import wave

        with wave.open(str(filepath), "rb") as w:
            bits = w.getsampwidth() * 8
            return backends.AudioInfo(
                w.getframerate(), w.getnframes(), w.getnchannels(),
                bits, encoding="PCM_U" if bits == 8 else "PCM_S")

    @staticmethod
    def load(filepath, frame_offset=0, num_frames=-1, normalize=True,
             channels_first=True):
        """-> (Tensor [C, T] (or [T, C]), sample_rate); normalize=True
        scales PCM to [-1, 1] float32 (reference contract)."""
        import wave

        import numpy as _np

        with wave.open(str(filepath), "rb") as w:
            sr = w.getframerate()
            nch = w.getnchannels()
            width = w.getsampwidth()
            w.setpos(frame_offset)
            n = w.getnframes() - frame_offset if num_frames < 0 else num_frames
            raw = w.readframes(n)
        if width not in (1, 2, 4):
            raise ValueError(f"unsupported PCM sample width {width*8} bits "
                             "(supported: 8, 16, 32)")
        dt = {1: _np.uint8, 2: _np.int16, 4: _np.int32}[width]
        arr = _np.frombuffer(raw, dt).reshape(-1, nch)
        if width == 1:
            arr = arr.astype(_np.int16) - 128
        if normalize:
            arr = arr.astype(_np.float32) / float(2 ** (8 * width - 1))
        out = arr.T if channels_first else arr
        return Tensor(jnp.asarray(out)), sr

    @staticmethod
    def save(filepath, src, sample_rate, channels_first=True,
             encoding="PCM_S", bits_per_sample=16):
        import wave

        import numpy as _np

        if encoding != "PCM_S":
            raise NotImplementedError(
                f"the wave backend writes signed PCM only; got {encoding!r}")
        if bits_per_sample not in (16, 32):
            raise ValueError(f"unsupported bits_per_sample {bits_per_sample} "
                             "(supported: 16, 32)")
        arr = _np.asarray(src.numpy() if hasattr(src, "numpy") else src)
        if channels_first:
            arr = arr.T                                  # -> [T, C]
        width = bits_per_sample // 8
        tgt = {2: _np.int16, 4: _np.int32}[width]
        if arr.dtype.kind == "f":
            scale = float(2 ** (bits_per_sample - 1) - 1)
            arr = (_np.clip(arr, -1.0, 1.0) * scale).astype(tgt)
        elif arr.dtype == _np.int16 and width == 4:
            arr = arr.astype(_np.int32) << 16            # re-scale, not pad
        elif arr.dtype == _np.int32 and width == 2:
            arr = (arr >> 16).astype(_np.int16)
        elif arr.dtype == tgt:
            pass
        else:
            raise ValueError(
                f"integer input dtype {arr.dtype} cannot be written as "
                f"{bits_per_sample}-bit PCM without silent wrap; pass "
                "float [-1, 1] or a matching int dtype")
        with wave.open(str(filepath), "wb") as w:
            w.setnchannels(arr.shape[1] if arr.ndim > 1 else 1)
            w.setsampwidth(width)
            w.setframerate(int(sample_rate))
            w.writeframes(arr.tobytes())

    @staticmethod
    def list_available_backends():
        return ["wave"]

    @staticmethod
    def get_current_backend():
        return "wave"

    @staticmethod
    def set_backend(backend_name):
        if backend_name != "wave":
            raise NotImplementedError(
                f"only the stdlib 'wave' backend ships; got {backend_name!r}")


load = backends.load
save = backends.save
info = backends.info


class _AudioClassificationDataset(_Dataset):
    """Shared base for the wav-folder datasets: builds the (optional)
    feature extractor ONCE, mixes multi-channel down to mono, and serves
    (waveform-or-feature, label) — paddle.io.Dataset-compatible."""

    _FEATS = {"spectrogram": "Spectrogram", "melspectrogram": "MelSpectrogram",
              "logmelspectrogram": "LogMelSpectrogram", "mfcc": "MFCC"}

    def _init_features(self, feat_type, feat_kwargs):
        self.feat_type = feat_type
        if feat_type == "raw":
            self.feature = None
        elif feat_type in self._FEATS:
            self.feature = getattr(features, self._FEATS[feat_type])(
                **feat_kwargs)
        else:
            raise ValueError(f"unknown feat_type {feat_type!r} "
                             f"(raw or one of {sorted(self._FEATS)})")

    def __len__(self):
        return len(self.files)

    def __getitem__(self, idx):
        path, label = self.files[idx]
        wav, _sr = backends.load(path)
        x = wav[0] if wav.shape[0] == 1 else wav.mean(axis=0)
        if self.feature is None:
            return x, label
        return self.feature(x.unsqueeze(0))[0], label


class datasets:
    """paddle.audio.datasets (reference: TESS, ESC50) over local extracted
    archives — the no-egress convention of this repo's other datasets."""

    class TESS(_AudioClassificationDataset):
        """Toronto emotional speech set: WAV files named
        *_<emotion>.wav under per-actor folders."""

        EMOTIONS = ["angry", "disgust", "fear", "happy", "neutral", "ps",
                    "sad"]

        def __init__(self, mode="train", n_folds=5, split=1, data_file=None,
                     feat_type="raw", archive=None, **feat_kwargs):
            if data_file is None:
                raise RuntimeError("no network egress; pass data_file "
                                   "(extracted TESS root)")
            import os as _os

            wavs = []
            for root, _dirs, files in sorted(_os.walk(str(data_file))):
                for f in sorted(files):
                    if f.lower().endswith(".wav"):
                        emotion = f.rsplit("_", 1)[-1][:-4].lower()
                        if emotion in self.EMOTIONS:
                            wavs.append((_os.path.join(root, f),
                                         self.EMOTIONS.index(emotion)))
            # reference split: every n_folds-th file is the held-out fold
            self.files = [(p, y) for i, (p, y) in enumerate(wavs)
                          if (i % n_folds == split - 1) == (mode != "train")]
            self._init_features(feat_type, feat_kwargs)

    class ESC50(_AudioClassificationDataset):
        """ESC-50 environmental sounds: audio/ WAVs named
        <fold>-<src>-<take>-<target>.wav (fold 1..5 = the official CV
        split; ``split`` selects the held-out fold)."""

        def __init__(self, mode="train", split=1, data_file=None,
                     feat_type="raw", **feat_kwargs):
            if data_file is None:
                raise RuntimeError("no network egress; pass data_file "
                                   "(extracted ESC-50 root)")
            import os as _os

            root = str(data_file)
            audio_dir = _os.path.join(root, "audio")
            if not _os.path.isdir(audio_dir):
                audio_dir = root
            self.files = []
            for f in sorted(_os.listdir(audio_dir)):
                if not f.lower().endswith(".wav"):
                    continue
                parts = f[:-4].split("-")
                # skip non-conforming names (e.g. AppleDouble '._*' files)
                if len(parts) < 4 or not (parts[0].isdigit()
                                          and parts[-1].isdigit()):
                    continue
                fold, target = int(parts[0]), int(parts[-1])
                held_out = fold == split
                if (mode == "train") != held_out:
                    self.files.append((_os.path.join(audio_dir, f), target))
            self._init_features(feat_type, feat_kwargs)
