"""paddle.linalg as an importable module (reference: python/paddle/linalg.py
is likewise a re-export shim; `import paddle.linalg` must work, not just
attribute access)."""

from .tensor.linalg import *  # noqa: F401,F403
from .tensor.linalg import (  # noqa: F401
    cholesky, cholesky_solve, cond, corrcoef, cov, det, eig, eigh, eigvals,
    eigvalsh, householder_product, inv, lstsq, lu, matrix_norm, matrix_power,
    matrix_rank, multi_dot, norm, pca_lowrank, pinv, qr, slogdet, solve, svd,
    svdvals, triangular_solve, vector_norm,
)
