"""paddle_tpu.static — minimal static-graph compat layer.

Reference analog: python/paddle/static/ (Program, program_guard, Executor).
SURVEY.md §2.2 marks this "minimal compat layer only": in the TPU rebuild
there is no ProgramDesc — a "Program" records the python callables staged
under ``program_guard`` and ``Executor.run`` jit-compiles the recorded fetch
computation.  Static-first user code largely predates dygraph; the supported
path is: build with ``static.data`` placeholders, run with feed/fetch — the
whole fetch subgraph traces through jax.jit, giving one XLA module like the
reference's whole-Program executor.
"""

from __future__ import annotations

import contextlib

import jax
import jax.export  # noqa: F401  (binds jax.export — lazy attr since 0.4.34)
import jax.numpy as jnp

from ..framework import dtypes as _dt
from ..tensor.tensor import Tensor
from .input_spec import InputSpec  # noqa: F401

_STATIC_MODE = [False]


class Variable(Tensor):
    """Placeholder tensor in a static Program (reference: framework.Variable)."""

    def __init__(self, name, shape, dtype):
        concrete = [1 if (s is None or s < 0) else int(s) for s in shape]
        # stop_gradient=False so downstream ops record tape nodes — the tape
        # IS the "Program" that Executor.run replays with new feeds
        super().__init__(jnp.zeros(concrete, dtype=_dt.to_jax(dtype)),
                         stop_gradient=False, name=name)
        self.is_data = True
        self.declared_shape = tuple(shape)


class Program:
    """Records data placeholders created while it is the active program."""

    def __init__(self):
        self.data_vars: dict[str, Variable] = {}
        self.random_seed = None

    def global_block(self):
        return self

    def clone(self, for_test=False):
        return self

    def var(self, name):
        return self.data_vars[name]

    def list_vars(self):
        return list(self.data_vars.values())


_default_main = Program()
_default_startup = Program()
_prog_stack: list[tuple[Program, Program]] = []


def default_main_program():
    return _prog_stack[-1][0] if _prog_stack else _default_main


def default_startup_program():
    return _prog_stack[-1][1] if _prog_stack else _default_startup


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    _prog_stack.append((main_program, startup_program or Program()))
    try:
        yield
    finally:
        _prog_stack.pop()


def data(name, shape, dtype="float32", lod_level=0):
    """Declare a feed placeholder (reference: paddle.static.data)."""
    v = Variable(name, shape, dtype)
    default_main_program().data_vars[name] = v
    return v


class Executor:
    """Feed/fetch runner.  ``run`` rebinds the feeds into the placeholder
    variables and (re)evaluates the fetch tensors' defining computation by
    replaying the eager tape forward — adequate for the compat use cases
    (the real perf path is jit/to_static)."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, return_numpy=True):
        import numpy as np

        feed = feed or {}
        prog = program or default_main_program()
        loaded = getattr(prog, "_loaded", None)
        if loaded is not None:
            # a static.load'ed program: execute the deserialized StableHLO
            # module (weights baked at save time) on the named feeds.
            # Fetch mapping is POSITIONAL in the save-time order; where the
            # save recorded fetch names, a reordered fetch_list is caught
            # instead of silently returning mismapped values.
            exported, feed_names, fetch_names = loaded
            vals = [jnp.asarray(feed[n]) for n in feed_names]
            outs = exported.call(*vals)
            outs = outs if isinstance(outs, (tuple, list)) else (outs,)
            if fetch_list:
                if len(fetch_list) != len(outs):
                    raise ValueError(
                        f"loaded program returns {len(outs)} fetches "
                        "(positional, save-time order), fetch_list has "
                        f"{len(fetch_list)}")
                for i, (f, saved) in enumerate(zip(fetch_list,
                                                   fetch_names or [])):
                    got = getattr(f, "name", None)
                    if saved and got and got != saved:
                        raise ValueError(
                            f"loaded program fetch {i} was saved as "
                            f"{saved!r} but fetch_list has {got!r}: "
                            "fetches map positionally to the save-time "
                            "order")
            return [np.asarray(o) if return_numpy else Tensor(o)
                    for o in outs]
        for name, value in feed.items():
            var = prog.data_vars.get(name)
            if var is not None:
                var._value = jnp.asarray(value)
        results = []
        for f in fetch_list or []:
            t = _replay(f)
            results.append(np.asarray(t._value) if return_numpy else t)
        if fetch_list:
            prog._last_fetches = list(fetch_list)  # static.save's default
        return results


def _replay(t: Tensor):
    """Recompute ``t`` from the tape graph with current placeholder values.
    Iterative post-order walk — Programs can be deeper than Python's
    recursion limit (same reason autograd/tape.py walks iteratively)."""
    if t._grad_node is None:
        return t
    memo: dict[int, object] = {}

    def is_pending(x):
        return (isinstance(x, Tensor) and not getattr(x, "is_data", False)
                and x._grad_node is not None and id(x) not in memo)

    stack = [(t, False)]
    while stack:
        x, expanded = stack.pop()
        if not is_pending(x):
            continue
        n = x._grad_node
        if not expanded:
            stack.append((x, True))
            for a in n.inputs:
                if is_pending(a):
                    stack.append((a, False))
            continue
        args = [memo[id(a)] if (isinstance(a, Tensor) and id(a) in memo)
                else (a._value if isinstance(a, Tensor) else a)
                for a in n.inputs]
        out = n.fn(*args, **n.kwargs)
        outs = out if isinstance(out, (tuple, list)) else (out,)
        for ref, v in zip(n.outputs, outs):
            ot = ref()
            if ot is not None:
                memo[id(ot)] = v
    return Tensor(memo[id(t)])


class CompiledProgram:
    def __init__(self, program, build_strategy=None):
        self.program = program


class BuildStrategy:
    pass


class ExecutionStrategy:
    pass


def name_scope(prefix=None):
    return jax.named_scope(prefix or "scope")


# re-export the nn free functions users reach via paddle.static in old code
def save(program, model_path, protocol=4, fetch_vars=None):
    """Serialize the Program's feed->fetch computation (r4 missing #5: this
    used to raise).

    Reference static.save persists a Program's parameters; here the whole
    feed->fetch computation — tape-recorded ops with current parameter
    values baked in — exports to the SAME StableHLO artifact format as
    jit.save ({path}.stablehlo + {path}.spec.json), loadable by
    ``static.load`` into an Executor-runnable program.  The fetch targets
    are ``fetch_vars`` or the last ``Executor.run(fetch_list=...)``.
    """
    import json

    fetches = fetch_vars or getattr(program, "_last_fetches", None)
    if not fetches:
        raise ValueError(
            "static.save: no fetch targets — run Executor.run(..., "
            "fetch_list=[...]) once first, or pass fetch_vars=[...]")
    feed_names = list(program.data_vars)
    for n in feed_names:
        if getattr(program.data_vars[n], "_value", None) is None:
            raise ValueError(
                f"static.save: placeholder {n!r} was never fed; run the "
                "program once so every feed has a concrete shape")

    def fn(*feed_vals):
        saved = {n: program.data_vars[n]._value for n in feed_names}
        try:
            for n, v in zip(feed_names, feed_vals):
                program.data_vars[n]._value = v
            outs = [_replay(f) for f in fetches]
            return tuple(o._value for o in outs)
        finally:
            for n, v in saved.items():
                program.data_vars[n]._value = v

    structs = [jax.ShapeDtypeStruct(tuple(program.data_vars[n]._value.shape),
                                    program.data_vars[n]._value.dtype)
               for n in feed_names]
    exported = jax.export.export(jax.jit(fn))(*structs)
    with open(str(model_path) + ".stablehlo", "wb") as f:
        f.write(exported.serialize())
    meta = {"kind": "static_program", "feed_names": feed_names,
            "n_fetch": len(fetches),
            # fetch identities (names where the user set them) so run() on
            # the loaded program can catch a reordered fetch_list instead of
            # silently mismapping outputs
            "fetch_names": [getattr(f, "name", None) for f in fetches]}
    with open(str(model_path) + ".spec.json", "w") as f:
        json.dump(meta, f)


def load(program, model_path, executor=None, var_list=None):
    """Inverse of ``static.save``: attach the deserialized StableHLO module
    to ``program`` so ``Executor.run(program, feed, fetch_list)`` executes
    it (weights are the values baked at save time)."""
    import json

    with open(str(model_path) + ".stablehlo", "rb") as f:
        exported = jax.export.deserialize(bytearray(f.read()))
    with open(str(model_path) + ".spec.json") as f:
        meta = json.load(f)
    program._loaded = (exported, meta["feed_names"],
                       meta.get("fetch_names"))
    return program


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    """reference: paddle.static.save_inference_model(prefix, feeds, fetches,
    exe).  Here inference programs ARE jit.save artifacts: when
    ``fetch_vars`` is a Layer (or ``program`` carries one via
    ``Program.layer``), export it with the feed specs; pure
    Program-building workflows have no captured computation to export and
    get a descriptive error pointing at the traced path."""
    from .. import jit as _jit

    layer = None
    if hasattr(fetch_vars, "forward"):
        layer = fetch_vars
    elif program is not None and getattr(program, "layer", None) is not None:
        layer = program.layer
    if layer is None:
        raise NotImplementedError(
            "save_inference_model needs the model: pass the Layer as "
            "fetch_vars (or set program.layer). Op-by-op Program "
            "construction is not re-executed here — trace with "
            "@paddle.jit.to_static and save that (SURVEY.md §3.2: this "
            "runtime lowers whole traced models, not ProgramDescs).")
    input_spec = list(feed_vars) if feed_vars is not None else None
    return _jit.save(layer, path_prefix, input_spec=input_spec)


def load_inference_model(path_prefix, executor=None, **kwargs):
    """reference: paddle.static.load_inference_model -> (program,
    feed_names, fetch_names).  The returned 'program' is the loaded
    TranslatedLayer (callable); names follow the positional convention."""
    from .. import jit as _jit

    layer = _jit.load(path_prefix)
    spec = (getattr(layer, "_meta", None) or {}).get("input_spec", [])
    feed_names = [(s.get("name") or f"feed_{i}")
                  for i, s in enumerate(spec)]
    return layer, feed_names, ["fetch_0"]


class _GlobalScope:
    """Compat scope object (reference: paddle.static.global_scope) — state
    lives in Layers/Tensors here, so the scope only records variables users
    explicitly stash via ``var()``."""

    def __init__(self):
        self._vars = {}

    def var(self, name):
        from ..tensor.tensor import Tensor

        if name not in self._vars:
            self._vars[name] = Tensor(0.0)  # placeholder; set_value rebinds
        return self._vars[name]

    def find_var(self, name):
        return self._vars.get(name)


_SCOPE = _GlobalScope()


def global_scope():
    return _SCOPE


class scope_guard:
    """Compat context manager (reference: paddle.static.scope_guard)."""

    def __init__(self, scope):
        self._scope = scope

    def __enter__(self):
        global _SCOPE
        self._prev, _SCOPE = _SCOPE, self._scope
        return self._scope

    def __exit__(self, *exc):
        global _SCOPE
        _SCOPE = self._prev
        return False


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """reference: paddle.static.gradients — here autodiff is jax.grad over
    the traced function, exposed eagerly: returns d(sum(targets))/d(inputs)
    via the tape (targets must depend on inputs through recorded ops)."""
    from ..autograd import grad as _grad

    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    outs = _grad(targets, inputs, grad_outputs=target_gradients,
                 allow_unused=True)
    return list(outs)


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None):
    """reference: paddle.static.append_backward(loss) -> [(param, grad)].
    Eager translation: run backward() on the loss and report the resulting
    (param, param.grad) pairs."""
    loss.backward()
    params = parameter_list
    if params is None:
        from ..tensor.tensor import Parameter

        # collect every Parameter reachable from the tape
        seen, stack, params = set(), [loss._grad_node], []
        while stack:
            node = stack.pop()
            if node is None or id(node) in seen:
                continue
            seen.add(id(node))
            for a in getattr(node, "inputs", ()):  # recorded op inputs
                if isinstance(a, Parameter) and all(a is not q for q in params):
                    params.append(a)
                if getattr(a, "_grad_node", None) is not None:
                    stack.append(a._grad_node)
    return [(p, p.grad) for p in params if getattr(p, "grad", None) is not None]

from . import nn  # noqa: E402,F401 — control-flow ops (cond/while_loop/...)
