"""paddle.static.nn control-flow ops (reference:
python/paddle/static/nn/control_flow.py — cond, while_loop, case,
switch_case: the graph-mode control-flow ops dy2static lowers Python
if/while into).

TPU-native: these ARE `lax.cond` / `lax.while_loop` / `lax.switch` — the
compiled control flow XLA executes on-device.  They work eagerly AND inside
to_static/TrainStep traces, which is how data-dependent control flow is
expressed in this framework (jax traces Python by value, so a Python `if`
on a traced tensor cannot branch; use these instead — the same rule the
reference enforces in static graph mode).

Differentiability: Tensors the branch/body closures capture are discovered
(closure cells + referenced globals) and threaded as real inputs through the
dispatch layer, so gradients flow into them — the tape sees one node for the
whole control-flow op, mirroring the reference's ConditionalBlockGrad /
WhileGrad ops.
"""

from __future__ import annotations

import contextlib

import jax
import jax.numpy as jnp

from ..tensor.dispatch import apply as _apply
from ..tensor.tensor import Tensor


def _closure_tensors(*fns):
    """Tensors the callables can reach: closure cells and referenced globals,
    looking through Layers (their params/buffers), dicts, lists and tuples —
    everything found is threaded as a dispatch input so gradients flow."""
    from ..nn.layer import Layer

    seen, out = set(), []

    import functools as _ft

    def visit(v, depth=0):
        if isinstance(v, Tensor):
            if id(v) not in seen:
                seen.add(id(v))
                out.append(v)
        elif isinstance(v, Layer):
            if id(v) in seen:
                return
            seen.add(id(v))
            for p in v.parameters():
                visit(p)
            for b in v.buffers():
                visit(b)
        elif hasattr(v, "__self__"):  # bound method: fwd = layer.forward
            visit(v.__self__, depth)
        elif isinstance(v, _ft.partial):
            visit(v.func, depth)
            for a in v.args:
                visit(a, depth + 1)
            for a in v.keywords.values():
                visit(a, depth + 1)
        elif depth < 2 and isinstance(v, dict):
            for x in v.values():
                visit(x, depth + 1)
        elif depth < 2 and isinstance(v, (list, tuple)):
            for x in v:
                visit(x, depth + 1)

    for fn in fns:
        if fn is None:
            continue
        code = getattr(fn, "__code__", None)
        if code is None:
            continue
        if getattr(fn, "__closure__", None):
            for cell in fn.__closure__:
                try:
                    visit(cell.cell_contents)
                except ValueError:
                    pass
        for name in code.co_names:
            visit(getattr(fn, "__globals__", {}).get(name))
    return out


@contextlib.contextmanager
def _swapped(tensors, values):
    saved = [t._value for t in tensors]
    for t, v in zip(tensors, values):
        t._value = v
    try:
        yield
    finally:
        for t, v in zip(tensors, saved):
            t._value = v


def _unwrap_tree(tree):
    return jax.tree_util.tree_map(
        lambda v: v._value if isinstance(v, Tensor) else v, tree,
        is_leaf=lambda v: isinstance(v, Tensor))


def _is_traced(v):
    return isinstance(v._value if isinstance(v, Tensor) else v, jax.core.Tracer)


def _flatten_branch(out, tree_box):
    """Flatten a branch's (possibly nested) output for the dispatch layer;
    the treedef is recorded for reassembly outside."""
    leaves, tree = jax.tree_util.tree_flatten(
        out, is_leaf=lambda v: isinstance(v, Tensor))
    tree_box[0] = tree
    return tuple(v._value if isinstance(v, Tensor) else jnp.asarray(v)
                 for v in leaves)


def _reassemble(result, tree_box):
    leaves = list(result) if isinstance(result, tuple) else [result]
    return jax.tree_util.tree_unflatten(tree_box[0], leaves)


def cond(pred, true_fn=None, false_fn=None, name=None, return_names=None):
    """Run ``true_fn()`` or ``false_fn()`` by a boolean (reference:
    paddle.static.nn.cond).  Concrete predicate (eager): only the taken
    branch executes, directly on the tape — dygraph semantics.  Traced
    predicate: both branches compile inside ``lax.cond`` and XLA selects at
    run time (the untaken branch neither executes nor contributes vjp)."""
    captured = _closure_tensors(true_fn, false_fn)
    pred_t = pred if isinstance(pred, Tensor) else Tensor(jnp.asarray(pred))
    if not _is_traced(pred_t):
        taken = true_fn if bool(pred_t) else false_fn
        return taken() if taken is not None else None

    tree_box = [None]

    def fn(pv, *tvals):
        def t_branch():
            with _swapped(captured, tvals):
                return _flatten_branch(
                    true_fn() if true_fn is not None else None, tree_box)

        def f_branch():
            with _swapped(captured, tvals):
                return _flatten_branch(
                    false_fn() if false_fn is not None else None, tree_box)

        return jax.lax.cond(pv.reshape(()).astype(bool), t_branch, f_branch)

    out = _apply(fn, pred_t, *captured, op_name="cond", n_outs=None)
    return _reassemble(out, tree_box)


def while_loop(cond_fn, body_fn, loop_vars, is_test=False, name=None):
    """reference: paddle.static.nn.while_loop(cond, body, loop_vars).
    cond_fn/body_fn take and return the loop-var list.

    Eager: runs as a Python loop over Tensors — every iteration is on the
    tape, so backward() works (the reference's WhileGrad).  Inside a jit
    trace: lowers to ``lax.while_loop``, which XLA cannot
    reverse-differentiate — use a bounded loop (scan/fori pattern) when you
    need gradients through a compiled dynamic loop.
    """
    captured = _closure_tensors(cond_fn, body_fn)
    loop_vars = list(loop_vars)
    n_loop = len(loop_vars)

    traced = any(isinstance(v._value if isinstance(v, Tensor) else v,
                            jax.core.Tracer) for v in loop_vars + captured)
    if not traced:
        # eager: plain taped Python loop — fully differentiable
        vars_ = [v if isinstance(v, Tensor) else Tensor(jnp.asarray(v))
                 for v in loop_vars]
        while bool(cond_fn(*vars_)):
            out = body_fn(*vars_)
            vars_ = list(out) if isinstance(out, (list, tuple)) else [out]
        return vars_

    def fn(*all_vals):
        loop_init = all_vals[:n_loop]
        tvals = all_vals[n_loop:]

        def c(state):
            with _swapped(captured, tvals):
                r = cond_fn(*[Tensor(s) for s in state])
            rv = r._value if isinstance(r, Tensor) else jnp.asarray(r)
            return rv.reshape(()).astype(bool)

        def b(state):
            with _swapped(captured, tvals):
                out = body_fn(*[Tensor(s) for s in state])
            out = out if isinstance(out, (list, tuple)) else [out]
            return tuple(_unwrap_tree(list(out)))

        return jax.lax.while_loop(c, b, tuple(loop_init))

    out = _apply(fn, *loop_vars, *captured, op_name="while_loop", n_outs=None)
    return list(out) if isinstance(out, tuple) else [out]


def switch_case(branch_index, branch_fns, default=None, name=None):
    """reference: paddle.static.nn.switch_case."""
    if isinstance(branch_fns, dict):
        keys = sorted(branch_fns)
        fns = [branch_fns[k] for k in keys]
    elif branch_fns and isinstance(branch_fns[0], (tuple, list)):
        keys = [k for k, _ in branch_fns]
        fns = [f for _, f in branch_fns]
    else:
        fns = list(branch_fns)
        keys = list(range(len(fns)))
    # default=None means "last branch" — reuse its slot instead of tracing
    # that branch twice
    default_slot = len(fns) - 1 if default is None else len(fns)
    branch_list = list(fns) if default is None else list(fns) + [default]
    captured = _closure_tensors(*branch_list)
    idx_t = branch_index if isinstance(branch_index, Tensor) else \
        Tensor(jnp.asarray(branch_index))
    if not _is_traced(idx_t):
        i = int(idx_t)
        taken = dict(zip(keys, fns)).get(i, branch_list[default_slot])
        return taken()

    tree_box = [None]

    def fn(iv, *tvals):
        i = iv.reshape(()).astype(jnp.int32)
        slot = jnp.asarray(default_slot, jnp.int32)
        for s, k in enumerate(keys):
            slot = jnp.where(i == k, jnp.int32(s), slot)

        def make(f):
            def run():
                with _swapped(captured, tvals):
                    return _flatten_branch(f(), tree_box)
            return run

        return jax.lax.switch(slot, [make(f) for f in branch_list])

    out = _apply(fn, idx_t, *captured, op_name="switch_case", n_outs=None)
    return _reassemble(out, tree_box)


def case(pred_fn_pairs, default=None, name=None):
    """reference: paddle.static.nn.case — first true predicate wins."""
    preds = [p if isinstance(p, Tensor) else Tensor(jnp.asarray(p))
             for p, _ in pred_fn_pairs]
    fns = [f for _, f in pred_fn_pairs]
    default_slot = len(fns) - 1 if default is None else len(fns)
    branch_list = list(fns) if default is None else list(fns) + [default]
    captured = _closure_tensors(*branch_list)
    n_p = len(preds)

    if not any(_is_traced(p) for p in preds):
        for p, f in zip(preds, fns):
            if bool(p):
                return f()
        return branch_list[default_slot]()

    tree_box = [None]

    def fn(*all_vals):
        pvs = all_vals[:n_p]
        tvals = all_vals[n_p:]
        stacked = jnp.stack([p.reshape(()).astype(bool) for p in pvs])
        idx = jnp.where(jnp.any(stacked), jnp.argmax(stacked), default_slot)

        def make(f):
            def run():
                with _swapped(captured, tvals):
                    return _flatten_branch(f(), tree_box)
            return run

        return jax.lax.switch(idx.astype(jnp.int32),
                              [make(f) for f in branch_list])

    out = _apply(fn, *preds, *captured, op_name="case", n_outs=None)
    return _reassemble(out, tree_box)
