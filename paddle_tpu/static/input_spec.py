"""InputSpec (reference: python/paddle/static/input.py).

Describes an input signature for to_static tracing and jit.save: shape with
None/-1 wildcard dims, dtype, name.  In the TPU rebuild wildcards pin to the
concrete size at first trace (XLA requires static shapes); each distinct
concrete signature gets its own cached trace, same as the reference caching
one Program per InputSpec signature.
"""

from __future__ import annotations

import numpy as np


class InputSpec:
    def __init__(self, shape, dtype="float32", name=None, stop_gradient=False):
        from ..framework import dtypes as _dt

        self.shape = tuple(None if (s is None or (isinstance(s, int) and s < 0)) else int(s)
                           for s in shape)
        self.dtype = np.dtype(_dt.to_jax(dtype)).name if dtype is not None else "float32"
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, str(np.dtype(tensor.dtype)), name or tensor.name)

    @classmethod
    def from_numpy(cls, ndarray, name=None):
        return cls(ndarray.shape, str(ndarray.dtype), name)

    def batch(self, batch_size):
        return InputSpec((batch_size,) + tuple(self.shape), self.dtype, self.name)

    def unbatch(self):
        if not self.shape:
            raise ValueError("unbatch on a 0-d InputSpec")
        return InputSpec(tuple(self.shape[1:]), self.dtype, self.name)

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"

    def __eq__(self, other):
        return (isinstance(other, InputSpec) and self.shape == other.shape
                and self.dtype == other.dtype and self.name == other.name)

    def __hash__(self):
        return hash((self.shape, self.dtype, self.name))
