"""Baseline config #4: Fleet data-parallel ResNet across all visible chips
(allreduce handled by the XLA partitioner; run on CPU with a virtual mesh
via XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu).

    python examples/train_resnet_dp.py [--steps 20] [--batch-size 64]
"""

import argparse
import time

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
import paddle_tpu.distributed as dist
import paddle_tpu.distributed.fleet as fleet


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--depth", type=int, default=18)
    args = ap.parse_args()

    dist.init_parallel_env()
    fleet.init(is_collective=True)  # pure DP over every visible chip
    paddle.seed(0)
    net = paddle.vision.models.resnet18(num_classes=100) if args.depth == 18 \
        else paddle.vision.models.resnet50(num_classes=100)
    model = fleet.distributed_model(net)
    optim = fleet.distributed_optimizer(
        opt.Momentum(learning_rate=0.1, momentum=0.9,
                     parameters=net.parameters()))
    step = paddle.jit.TrainStep(net, optim, loss_fn=nn.CrossEntropyLoss())

    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(args.batch_size, 3, 64, 64).astype("float32"))
    y = paddle.to_tensor(rng.randint(0, 100, (args.batch_size,)).astype("int64"))
    model.shard_input(x)  # batch rides the dp axis
    model.shard_input(y)

    loss = step(x, y)
    float(loss)
    t0 = time.time()
    for i in range(args.steps):
        loss = step(x, y)
        if (i + 1) % 5 == 0:
            print(f"step {i + 1}: loss {float(loss):.4f}")
    dt = (time.time() - t0) / args.steps
    print(f"{args.batch_size / dt:.0f} imgs/sec over "
          f"{model.mesh.devices.size} devices")


if __name__ == "__main__":
    main()
