"""Numerics-debugging walkthrough (paddle_tpu.observability.numerics).

Runs on the CPU backend: the full ISSUE-13 loop, end to end —

1. eager checks: ``check_numerics`` + ``collect_operator_stats`` (the
   ``paddle.amp.debugging`` API) on a tiny model;
2. in-program probes: a fused ``TrainStep`` compiles a distinct probed
   variant whose extra output is a per-site stats table (layer
   activations, the loss, every grad leaf), resolved off the dispatch
   path by ``numerics.poll()``;
3. forensics: the ``numerics.nan_inject`` fault site poisons one step,
   the anomaly engine names the first offending layer in ONE
   flight-recorder dump and ``poll`` raises ``NumericFault``;
4. recovery: a ``RecoverySupervisor`` classifies the fault as
   ``"numeric"``, rolls back to the last VALID checkpoint and the rerun
   finishes with a clean loss.

    JAX_PLATFORMS=cpu python examples/numerics_debugging.py
"""

import json
import tempfile

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.observability import faults, flight_recorder, numerics
from paddle_tpu.resilience import AsyncCheckpointManager, RecoverySupervisor
from paddle_tpu.resilience.retry import NumericFault, RetryPolicy

TOTAL_STEPS = 6
rs = np.random.RandomState(0)
x = paddle.to_tensor(rs.randn(16, 8).astype("float32"))
y = paddle.to_tensor(rs.randint(0, 4, (16,)).astype("int64"))


def build():
    paddle.seed(7)
    m = nn.Sequential(nn.Linear(8, 32), nn.Tanh(), nn.Linear(32, 4))
    o = opt.Momentum(learning_rate=0.05, momentum=0.9,
                     parameters=m.parameters())
    return m, o


# ---------------------------------------------------------- 1. eager checks
print("== eager: check_numerics + collect_operator_stats ==")
m, _ = build()
stats = numerics.check_numerics(m(x), name="logits")
print(f"logits: absmax={stats['absmax']:.3f} rms={stats['rms']:.3f} "
      f"nonfinite={int(stats['nonfinite'])}")
with numerics.collect_operator_stats(model=m) as col:
    m(x)
print(col.report())

# ------------------------------------------------- 2. probed fused TrainStep
print("\n== in-program probes: one probed TrainStep variant ==")
flight_dir = tempfile.mkdtemp(prefix="paddle_numerics_flight_")
flight_recorder.get_flight_recorder().dir = flight_dir
numerics.enable_tensor_checker(level="dump")   # warn | dump | abort

m, o = build()
step = paddle.jit.TrainStep(m, o, loss_fn=nn.CrossEntropyLoss())
print(f"step 0: loss={float(step(x, y)):.4f}")
numerics.poll()                                # resolve OFF the dispatch path
table = numerics.latest(step._perf_tag)
print(f"probed sites ({len(table['sites'])}): {', '.join(table['sites'])}")

# -------------------------------------------- 3. nan_inject -> one dump
print("\n== forensics: numerics.nan_inject names the first bad layer ==")
faults.inject("numerics.nan_inject", times=1)  # next probed step is poisoned
float(step(x, y))
# the step's own throttled maybe_poll may have resolved the table already;
# the monitor keeps the episode either way
ep = (numerics.poll() or numerics.monitor().episodes())[0]
print(f"anomaly: kind={ep.kind} site={ep.site!r} stream={ep.stream}")
doc = json.load(open(ep.dump))
worst = [r for r in doc["extra"]["stats"] if r["nonfinite"] > 0][0]
print(f"flight dump -> {ep.dump}")
print(f"first offending tensor in the dump: {worst['tensor']!r}")

# ------------------------------------- 4. NumericFault -> checkpoint rollback
print("\n== recovery: supervisor rolls back past the poisoned step ==")
numerics.reset()
numerics.enable_tensor_checker(level="abort")  # poll() now raises
ckpt_dir = tempfile.mkdtemp(prefix="paddle_numerics_ckpt_")
mgr = AsyncCheckpointManager(ckpt_dir, max_to_keep=4)
faults.inject("numerics.nan_inject", at_trips={3})  # poison step 2, attempt 1
attempts = []


def train_fn(start, state):
    attempts.append(start)
    m, o = build()                              # fresh params per attempt;
    st = paddle.jit.TrainStep(m, o, loss_fn=nn.CrossEntropyLoss())
    loss = None
    for s in range(start, TOTAL_STEPS):
        loss = float(st(x, y))
        numerics.poll()                         # raises NumericFault on NaN
        mgr.save(s + 1, {"marker": paddle.to_tensor(np.float32(s + 1))},
                 block=True)
        print(f"  step {s}: loss={loss:.4f} (checkpointed)")
    return loss


sup = RecoverySupervisor(
    mgr, policy=RetryPolicy(base_delay=0.05, max_delay=0.1, seed=0),
    max_numeric_restarts=2,
    on_restart=lambda kind, exc, n: print(
        f"  !! {kind} failure ({exc}); rolling back to last valid checkpoint"))
final = sup.run(train_fn)
mgr.close()

assert np.isfinite(final), "rerun should be clean"
assert sup.restarts.get("numeric") == 1
assert len(attempts) == 2 and attempts[1] >= 1   # rolled back, not replayed
print(f"\nfinal loss {final:.4f} after {len(attempts)} attempts "
      f"(restart budget used: {sup.restarts})")
print("numerics observability round trip: probe -> dump -> rollback OK")
