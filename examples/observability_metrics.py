"""Observability walkthrough: profile a fused TrainStep run, print the
per-op summary table, and get per-epoch metrics (compiles / retraces /
MFU / dataloader stall) from ``Model.fit`` for free.

    PADDLE_METRICS_DIR=/tmp/obs python examples/observability_metrics.py
    # -> /tmp/obs/metrics.jsonl, metrics.prom, train_metrics.jsonl,
    #    plus a chrome trace (host events) and the XPlane device trace

Env knobs (README "Observability"): PADDLE_PROFILER_DIR,
PADDLE_METRICS_DIR, PADDLE_METRICS_FLUSH_SECS, PADDLE_TRAINSTEP_COST,
PADDLE_PEAK_FLOPS.
"""

import argparse
import os

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
import paddle_tpu.profiler as profiler


def profile_train_step(steps, batch):
    """Profiler around a TrainStep loop: scheduler-driven device trace +
    host op timers -> summary table + chrome-trace export."""
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(64, 256), nn.ReLU(), nn.Linear(256, 10))
    optim = opt.AdamW(learning_rate=1e-3, parameters=model.parameters())
    step = paddle.jit.TrainStep(model, optim, loss_fn=nn.CrossEntropyLoss())
    x = paddle.to_tensor(np.random.RandomState(0)
                         .randn(batch, 64).astype("float32"))
    y = paddle.to_tensor(np.random.RandomState(1)
                         .randint(0, 10, (batch,)).astype("int64"))

    trace_dir = os.environ.get("PADDLE_PROFILER_DIR", "/tmp/paddle_tpu_trace")
    p = profiler.Profiler(
        scheduler=profiler.make_scheduler(closed=1, ready=1,
                                          record=steps - 2, repeat=1),
        on_trace_ready=profiler.export_chrome_tracing(trace_dir))
    with p:
        for _ in range(steps):
            float(step(x, y))
            p.step(num_samples=batch)
    p.summary(sorted_by="total")          # per-op table to stdout
    print("step cost:", step.cost_analysis())  # XLA flops/bytes of the step

    loaded = profiler.load_profiler_result(trace_dir)
    print(f"reloaded {len(loaded.events)} host events from {loaded.path}")


def fit_with_metrics_logger(epochs, batch):
    """Model.fit users get the observability table via one callback."""
    from paddle_tpu.io import TensorDataset

    paddle.seed(1)
    net = nn.Sequential(nn.Linear(16, 64), nn.ReLU(), nn.Linear(64, 4))
    model = paddle.Model(net)
    model.prepare(optimizer=opt.Adam(learning_rate=1e-3,
                                     parameters=net.parameters()),
                  loss=nn.CrossEntropyLoss())
    ds = TensorDataset([np.random.RandomState(0)
                        .randn(256, 16).astype("float32"),
                        np.random.RandomState(1)
                        .randint(0, 4, (256,)).astype("int64")])
    model.fit(ds, batch_size=batch, epochs=epochs, verbose=0, shuffle=False,
              callbacks=[paddle.callbacks.MetricsLoggerCallback()])


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=6)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=32)
    args = ap.parse_args()

    profile_train_step(args.steps, args.batch_size)
    fit_with_metrics_logger(args.epochs, args.batch_size)

    from paddle_tpu.profiler import metrics

    d = metrics.flush()  # one explicit snapshot (flusher also runs if env set)
    if d:
        print(f"metrics snapshot in {d}/metrics.jsonl and {d}/metrics.prom")
    else:
        print("set PADDLE_METRICS_DIR to export metrics snapshots")


if __name__ == "__main__":
    main()
