"""Real-data path: ImageFolder -> transforms -> DataLoader(workers) ->
fused TrainStep, end to end.

Usage:
    python examples/train_imagefolder.py [DATA_DIR]

DATA_DIR is a standard class-per-subdir image tree (the layout
paddle.vision.datasets.ImageFolder / DatasetFolder reads).  Without a
DATA_DIR the script synthesizes a small 3-class tree of .npy images so the
pipeline is runnable anywhere (no network egress in this environment).

Demonstrates: DatasetFolder with a loader, Compose transforms (resize /
random-flip / normalize as host-side numpy), DataLoader with worker
prefetch, paddle.Model.fit driving the single-program train step, and
evaluation — SURVEY.md §2.3 config #1's shape on a local tree.
"""

import os
import sys
import tempfile

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.io import DataLoader
from paddle_tpu.vision import transforms as T
from paddle_tpu.vision.datasets import DatasetFolder
from paddle_tpu.vision.models import resnet18

IMG = 64


def synthesize_tree(root, n_per_class=24):
    """3 classes of colored-blob .npy images."""
    rs = np.random.RandomState(0)
    for cls in range(3):
        d = os.path.join(root, f"class_{cls}")
        os.makedirs(d, exist_ok=True)
        for i in range(n_per_class):
            img = rs.rand(IMG, IMG, 3).astype("float32") * 0.3
            img[..., cls] += 0.7  # class-colored channel
            np.save(os.path.join(d, f"{i}.npy"), (img * 255).astype("uint8"))
    return root


def main():
    if len(sys.argv) > 1:
        root = sys.argv[1]
    else:
        root = synthesize_tree(tempfile.mkdtemp(prefix="imagefolder_"))
        print(f"(no DATA_DIR given: synthesized 3-class tree at {root})")

    train_tf = T.Compose([
        T.Resize(IMG + 8),
        T.RandomCrop(IMG),
        T.RandomHorizontalFlip(),
        T.Transpose(),                       # HWC -> CHW
        T.Normalize(mean=[127.5] * 3, std=[127.5] * 3),
    ])
    ds = DatasetFolder(root, transform=train_tf)
    print(f"{len(ds)} images, {len(ds.classes)} classes: {ds.classes}")

    loader_train = DataLoader(ds, batch_size=16, shuffle=True, num_workers=2,
                              drop_last=True)

    paddle.seed(0)
    net = resnet18(num_classes=len(ds.classes))
    model = paddle.Model(net)
    model.prepare(
        optimizer=opt.Momentum(learning_rate=0.01, momentum=0.9,
                               parameters=net.parameters()),
        loss=nn.CrossEntropyLoss(),
        metrics=paddle.metric.Accuracy())
    model.fit(loader_train, epochs=3, verbose=1)
    # eval with deterministic transforms (and note: BatchNorm running stats
    # need a few epochs of warmup before eval-mode accuracy catches up)
    eval_tf = T.Compose([
        T.Resize(IMG), T.CenterCrop(IMG), T.Transpose(),
        T.Normalize(mean=[127.5] * 3, std=[127.5] * 3),
    ])
    eval_ds = DatasetFolder(root, transform=eval_tf)
    res = model.evaluate(DataLoader(eval_ds, batch_size=16), verbose=0)
    print("eval:", res)


if __name__ == "__main__":
    main()
