"""Baseline config #1: ResNet-50 single-device training (dygraph-equivalent
API, fused-compiled step).  Synthetic data unless an ImageFolder path is
given.

    python examples/train_resnet50.py [--batch-size 128] [--steps 50]
                                      [--amp O2] [--data /path/to/imagefolder]
"""

import argparse
import time

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--steps", type=int, default=50)
    ap.add_argument("--amp", default="O2", choices=["O0", "O1", "O2"])
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--data", default=None, help="ImageFolder root (optional)")
    args = ap.parse_args()

    paddle.seed(0)
    model = paddle.vision.models.resnet50(num_classes=1000)
    optim = opt.Momentum(learning_rate=args.lr, momentum=0.9,
                         parameters=model.parameters(), weight_decay=1e-4)
    step = paddle.jit.TrainStep(model, optim, loss_fn=nn.CrossEntropyLoss(),
                                amp_level=None if args.amp == "O0" else args.amp)

    if args.data:
        from paddle_tpu.io import DataLoader
        from paddle_tpu.vision import transforms as T
        from paddle_tpu.vision.datasets import ImageFolder

        tf = T.Compose([T.Resize(256), T.RandomCrop(224),
                        T.RandomHorizontalFlip(), T.ToTensor(),
                        T.Normalize([0.485, 0.456, 0.406], [0.229, 0.224, 0.225])])
        loader = DataLoader(ImageFolder(args.data, transform=tf),
                            batch_size=args.batch_size, shuffle=True,
                            num_workers=4, drop_last=True)

        def batches():
            while True:
                yield from loader
    else:
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(args.batch_size, 3, 224, 224).astype("float32"))
        y = paddle.to_tensor(rng.randint(0, 1000, (args.batch_size,)).astype("int64"))

        def batches():
            while True:
                yield x, y

    it = batches()
    loss = step(*next(it))  # compile
    float(loss)
    t0 = time.time()
    for i in range(args.steps):
        loss = step(*next(it))
        if (i + 1) % 10 == 0:
            print(f"step {i + 1}: loss {float(loss):.4f}")
    dt = (time.time() - t0) / args.steps
    print(f"{args.batch_size / dt:.0f} imgs/sec ({dt * 1e3:.1f} ms/step)")


if __name__ == "__main__":
    main()
