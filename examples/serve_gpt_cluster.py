"""Multi-replica GPT serving: a ServingCluster with prefix-affinity
routing and cross-replica failover (README "Cluster serving").

Demonstrates paddle_tpu.serving.cluster:

- two ServingEngine replicas behind the PrefixAffinityRouter: requests
  sharing a prompt prefix land on the SAME replica, so the BlockManager's
  refcounted prefix pages keep hitting under fan-out;
- mixed-prefix traffic — three prefix "templates" (think: three system
  prompts), several requests each, fanned out concurrently;
- a replica loss mid-decode: the survivor picks up the dead replica's
  in-flight requests as prompt + tokens-so-far, and greedy output stays
  byte-identical to an uninterrupted run;
- cluster.* + per-replica serving.* metrics in the PR-1 registry, and the
  cluster /statusz section when telemetry is armed.

Run (CPU works; one replica per device when devices are visible):

    JAX_PLATFORMS=cpu python examples/serve_gpt_cluster.py
"""

import time

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.profiler import metrics as prof_metrics
from paddle_tpu.serving import ServingCluster
from paddle_tpu.text.models import GPTForCausalLM


def main():
    paddle.seed(0)
    model = GPTForCausalLM(vocab_size=1024, hidden_size=128,
                           num_hidden_layers=4, num_attention_heads=4,
                           max_position_embeddings=256).eval()
    rs = np.random.RandomState(0)

    cluster = ServingCluster(model, replicas=2, num_slots=4, page_size=16,
                             max_model_len=256, prefix_sharing=True)
    with cluster:
        # --- mixed-prefix traffic: 3 shared templates x 3 requests ----
        templates = [rs.randint(1, 1024, (32,)).tolist() for _ in range(3)]
        prompts = [t + rs.randint(1, 1024, (6,)).tolist()
                   for t in templates for _ in range(3)]
        handles = [cluster.submit(p, max_new_tokens=24) for p in prompts]
        for h in handles:
            h.result(timeout=600)
        for g, t in enumerate(templates):
            served_by = {h.replica_history[0]
                         for h, p in zip(handles, prompts)
                         if p[:32] == t}
            print(f"template {g}: affine replica "
                  f"{cluster.router.affine_index(t)}, served by {served_by}")
        print(f"affinity hit rate: {cluster.affinity_hit_rate():.2f}")
        hits = prof_metrics.counter("serving.prefix_cache_hits")
        for e in cluster.engines:
            print(f"replica {e.replica}: prefix-cache hits "
                  f"{int(hits.get(replica=e.replica) or 0)}, "
                  f"pages free {e.block_manager.free_pages}"
                  f"/{e.block_manager.num_pages}")

        # --- replica loss mid-decode: requests fail over ---------------
        victim = cluster.engines[0]
        p = templates[0] + rs.randint(1, 1024, (4,)).tolist()
        # aim at replica 0's affine traffic; an uninterrupted reference
        ref = cluster.generate(p, max_new_tokens=32, timeout=600)
        h = cluster.submit(p, max_new_tokens=32)
        while len(h.token_ids) < 4:      # let it get some tokens in flight
            time.sleep(0.001)
        victim.stop()                    # kill the replica mid-decode
        toks = h.result(timeout=600)
        print(f"replica path {h.replica_history}: "
              f"{'byte-identical' if toks == ref else 'MISMATCH'} after "
              f"failover ({len(toks)} tokens)")
        print("cluster:", {k: v for k, v in cluster.stats().items()
                           if k in ("rerouted_requests", "affinity")})
        print("health:", cluster.health_state())


if __name__ == "__main__":
    main()
