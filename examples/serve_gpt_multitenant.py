"""Multi-tenant serving: paged multi-LoRA + grammar-constrained decoding
+ embedding requests on ONE engine (README "Multi-tenant serving").

A small GPT is overfit on a cyclic token stream, three LoRA "fine-tunes"
are registered into one rank-bucketed :class:`LoRAStore`, and a SINGLE
batch then serves:

- three requests on three DIFFERENT adapters (per-row paged adapter
  gather inside one compiled decode program — the trace counter proves
  no per-adapter retrace);
- one JSON-schema-constrained row (a token FSM masks the sampler every
  step, so the output parses under the schema by construction);
- one embedding request (rides the same scheduler and prefill programs,
  retires without touching a single KV page — asserted).

Each adapter row is then replayed on a dedicated single-tenant engine to
show the mixed batch is byte-identical per row.

Run (CPU works):

    JAX_PLATFORMS=cpu python examples/serve_gpt_multitenant.py
"""

import json

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.optimizer as opt
from paddle_tpu.serving.multitenant import (
    LoRAAdapter, LoRAStore, MultiTenantEngine, compile_json_schema,
)
from paddle_tpu.text.models import GPTForCausalLM

PAGE = 16
S0, MAX_NEW = 24, 48
VSIZE = 128

SCHEMA = {"type": "object",
          "properties": {"x": {"type": "integer"},
                         "ok": {"type": "boolean"}}}


def build_model(period=8, train_steps=150):
    paddle.seed(0)
    m = GPTForCausalLM(vocab_size=VSIZE, hidden_size=128,
                       num_hidden_layers=4, num_attention_heads=4,
                       max_position_embeddings=256)
    cyc = (np.arange(256 + 64) % period + 1).astype("int64")
    o = opt.AdamW(learning_rate=3e-3, parameters=m.parameters())
    step = paddle.jit.TrainStep(m, o, loss_fn=None)
    ids = paddle.to_tensor(np.stack([cyc[i:i + 64] for i in range(8)]))
    for _ in range(train_steps):
        step({"input_ids": ids, "labels": ids})
    return m.eval(), cyc, period


def build_vocab():
    """Token-id -> string map so the grammar is spellable: JSON machinery
    first, filler for the rest, EOS last."""
    chars = list("0123456789{}[]\",:-abcdefghijklmnopqrstuvwxyz. _")
    vocab = ["<pad>"] + chars + ["true", "false", "null"]
    vocab += [f"<u{i}>" for i in range(VSIZE - 1 - len(vocab))]
    return vocab + ["<eos>"]


def main():
    print("overfitting the demo model ...")
    model, cyc, period = build_model()
    prompts = [cyc[i % period:i % period + S0].tolist() for i in range(5)]
    vocab = build_vocab()
    grammar = compile_json_schema(SCHEMA, vocab, len(vocab) - 1)

    store = LoRAStore(model, capacity=8, ranks=(8,),
                      targets=("qkv", "out_proj"))
    names = ["tenant-a", "tenant-b", "tenant-c"]
    for i, name in enumerate(names):
        store.register(LoRAAdapter.random(model, name, rank=4,
                                          seed=7 + i, scale=0.3))
    print(f"registered adapters: {store.names} "
          f"(rank buckets {store.ranks}, capacity {store.capacity})")

    engine = MultiTenantEngine(model, lora_store=store, num_slots=4,
                               page_size=PAGE, max_model_len=S0 + MAX_NEW)
    with engine:
        engine.generate(prompts[0], max_new_tokens=4, timeout=600)  # compile
        print("\n-- ONE batch: 3 adapters + 1 schema row + 1 embed row --")
        tenant_handles = {n: engine.submit(p, max_new_tokens=MAX_NEW,
                                           adapter=n)
                          for n, p in zip(names, prompts)}
        schema_handle = engine.submit(prompts[3], max_new_tokens=MAX_NEW,
                                      grammar=grammar)
        embed_handle = engine.submit(prompts[4], mode="embed")
        tenant_out = {n: h.result(timeout=600)
                      for n, h in tenant_handles.items()}
        schema_out = schema_handle.result(timeout=600)
        embedding = embed_handle.result(timeout=600)
        assert engine.step_traces == 1, "multi-LoRA minted extra programs!"
        assert engine.block_manager.used_pages == 0  # all rows retired
        print(f"decode programs traced: {engine.step_traces} "
              f"(3 adapters, zero per-adapter retrace)")

        text = "".join(vocab[t] for t in schema_out
                       if t != grammar.eos_token_id)
        doc = json.loads(text)          # valid by construction
        print(f"schema-constrained row: {text}  -> parsed {doc}")
        print(f"embedding row: shape {np.asarray(embedding).shape}, "
              f"no KV pages allocated")
        for n in names:
            print(f"  {n}: {tenant_out[n][:10]} ...")

        print("\n-- per-row byte-identity vs dedicated engines --")
        for n in names:
            dedicated = MultiTenantEngine(model, lora_store=store,
                                          num_slots=4, page_size=PAGE,
                                          max_model_len=S0 + MAX_NEW)
            with dedicated:
                solo = dedicated.generate(prompts[names.index(n)],
                                          max_new_tokens=MAX_NEW,
                                          adapter=n, timeout=600)
            assert solo == tenant_out[n]
            print(f"  {n}: mixed batch == dedicated engine "
                  f"({len(solo)} tokens)")

        st = engine._statusz()
        print("\n/statusz tenants:",
              json.dumps(st["tenants"], indent=2, default=str))


if __name__ == "__main__":
    main()
