"""Continuous-batching GPT serving: concurrent submitters + streaming.

Demonstrates the paddle_tpu.serving engine (README "Serving"):

- several client threads submit mixed-length requests concurrently;
- one streams tokens as they decode (and cancels early);
- the engine interleaves everything in ONE fixed-shape decode batch,
  backfilling slots as short requests finish;
- the serving.* metrics land in the PR-1 registry (exported under
  PADDLE_METRICS_DIR when set).

Run (CPU works; a TPU runs the Pallas paged-attention kernel):

    JAX_PLATFORMS=cpu python examples/serve_gpt_continuous.py
"""

import threading
import time

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.profiler import metrics as prof_metrics
from paddle_tpu.serving import ContinuousBatchingPredictor, ServingEngine
from paddle_tpu.text.models import GPTForCausalLM


def main():
    paddle.seed(0)
    model = GPTForCausalLM(vocab_size=1024, hidden_size=128,
                           num_hidden_layers=4, num_attention_heads=4,
                           max_position_embeddings=256).eval()
    rs = np.random.RandomState(0)

    engine = ServingEngine(model, num_slots=4, page_size=16,
                           max_model_len=256, prefix_sharing=True)
    with engine:
        # --- concurrent submitters (mixed lengths: nobody waits for the
        # slowest sequence in the batch) -------------------------------
        results = {}

        def client(name, prompt_len, max_new, temperature):
            prompt = rs.randint(1, 1024, (prompt_len,)).tolist()
            t0 = time.time()
            toks = engine.generate(prompt, max_new_tokens=max_new,
                                   temperature=temperature, timeout=600)
            results[name] = (len(toks), round(time.time() - t0, 3))

        threads = [
            threading.Thread(target=client, args=(f"client{i}", 8 + 4 * i,
                                                  [12, 48, 24, 96][i],
                                                  0.0 if i % 2 else 0.8))
            for i in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for name in sorted(results):
            n, dt = results[name]
            print(f"{name}: {n} tokens in {dt}s")

        # --- streaming + early cancellation (frees the KV pages) ------
        prompt = rs.randint(1, 1024, (12,)).tolist()
        handle = engine.submit(prompt, max_new_tokens=64)
        got = []
        for tok in handle.stream():
            got.append(tok)
            if len(got) == 8:
                break  # closing the iterator cancels the request
        handle._done.wait(60)  # cancellation lands at the next iteration
        print(f"streamed {got[:8]} then cancelled; "
              f"pages free: {engine.block_manager.free_pages}"
              f"/{engine.block_manager.num_pages}")

        # --- metrics: the same registry the trainers/bench export ------
        reg = prof_metrics.get_registry()
        ttft = reg.get("serving.ttft_seconds").labels(replica="0")
        itl = reg.get("serving.inter_token_seconds").labels(replica="0")
        print(f"TTFT mean {ttft.mean * 1e3:.1f} ms | "
              f"inter-token p50 {itl.quantile(0.5) * 1e3:.2f} ms "
              f"p95 {itl.quantile(0.95) * 1e3:.2f} ms | "
              f"decode-step traces "
              f"{int(prof_metrics.counter('serving.step_traces').total())}")
        print(engine.stats())

    # --- the paddle.inference-shaped facade ---------------------------
    ids = np.zeros((3, 16), np.int64)
    for b, n in enumerate((16, 9, 12)):
        ids[b, :n] = rs.randint(1, 1024, (n,))
    with ContinuousBatchingPredictor(model, max_new_tokens=8, num_slots=4,
                                     page_size=16,
                                     max_model_len=256) as pred:
        pred.get_input_handle("input_ids").copy_from_cpu(ids)
        pred.run()
        out = pred.get_output_handle("output_0").copy_to_cpu()
    print("predictor facade output:", out.shape)


if __name__ == "__main__":
    main()
