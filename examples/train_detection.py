"""Baseline config #3: detection training (PP-YOLOE-style anchor-free head
or FasterRCNN) on synthetic boxes.

    python examples/train_detection.py [--arch yolo|ppyoloe|rcnn] [--steps 20]
"""

import argparse

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.optimizer as opt
from paddle_tpu.vision.models import faster_rcnn, ppyoloe, yolov3


def synth_batch(rng, b=2, size=160, max_boxes=8, classes=8):
    img = rng.randn(b, 3, size, size).astype("float32")
    gtb = np.zeros((b, max_boxes, 4), dtype="float32")
    gtl = np.full((b, max_boxes), -1, dtype="int64")
    for i in range(b):
        n = rng.randint(1, 4)
        for j in range(n):
            x1, y1 = rng.randint(0, size - 48, 2)
            w, h = rng.randint(24, 48, 2)
            gtb[i, j] = [x1, y1, x1 + w, y1 + h]
            gtl[i, j] = rng.randint(0, classes)
    return (paddle.to_tensor(img), paddle.to_tensor(gtb), paddle.to_tensor(gtl))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--arch", default="yolo", choices=["yolo", "ppyoloe", "rcnn"])
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--classes", type=int, default=8)
    args = ap.parse_args()

    paddle.seed(0)
    if args.arch == "yolo":
        model = yolov3(num_classes=args.classes, depth=18)
    elif args.arch == "ppyoloe":
        model = ppyoloe(num_classes=args.classes, size="s")
    else:
        model = faster_rcnn(num_classes=args.classes, depth=18,
                            num_proposals=64)
    optim = opt.Adam(learning_rate=2e-4, parameters=model.parameters())
    rng = np.random.RandomState(0)
    for i in range(args.steps):
        img, gtb, gtl = synth_batch(rng, classes=args.classes)
        losses = model(img, gtb, gtl)
        losses["loss"].backward()
        optim.step()
        optim.clear_grad()
        if (i + 1) % 5 == 0:
            print(f"step {i + 1}: " +
                  " ".join(f"{k}={float(v):.3f}" for k, v in losses.items()))
    model.eval()
    dets = model(synth_batch(rng, classes=args.classes)[0])
    n = int(dets[0]["valid"].numpy().sum())
    print(f"eval: {n} detections on image 0")


if __name__ == "__main__":
    main()
