"""Hierarchical KV cache: radix prefix index + host-DRAM spill tier
(README "Hierarchical KV cache").

Eight chat-style requests share one 24-token "system prompt" but diverge
afterwards — the workload where exact-key prefix matching scores zero and
the radix tree shines.  The same requests run through the engine three
ways:

- legacy:      ``prefix_sharing=True`` — exact-key block sharing; the
  divergent tails make every request a miss;
- radix:       ``prefix_cache="radix"`` — page-granular radix tree; every
  request after the first reuses the shared-prefix pages and prefill
  starts at ``shared_pages * page_size``;
- radix+spill: ``kv_spill=True`` with an undersized page pool — idle
  prefix pages LRU-evict to host DRAM (``PADDLE_KV_SPILL_BUDGET_BYTES``)
  and resurrect into free device slots on the next hit, no recompute.

Printed at the end: greedy byte-identity of all three arms (partial reuse
changes WHEN the first token arrives, never WHAT tokens come out), the
hit / saved-token accounting per arm, the spill tier's
spill / resurrect counters, and the memory ledger's ``kv.spilled``
host-tier row next to the device pools.

Run (CPU works; no training needed — byte-identity only needs greedy
determinism):

    JAX_PLATFORMS=cpu python examples/serve_gpt_prefix_cache.py
"""

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.observability import memory
from paddle_tpu.observability import perf as obs_perf
from paddle_tpu.serving import ServingEngine

from paddle_tpu.text.models import GPTForCausalLM

PAGE = 8
SHARED, TAIL, MAX_NEW = 24, 8, 16          # 3 shared pages + 1 tail page


def build_model():
    paddle.seed(0)
    m = GPTForCausalLM(vocab_size=128, hidden_size=64, num_hidden_layers=2,
                       num_attention_heads=2, max_position_embeddings=128)
    return m.eval()


def build_prompts(n=8):
    rng = np.random.default_rng(7)
    system = rng.integers(1, 127, size=SHARED).tolist()
    prompts = [system + rng.integers(1, 127, size=TAIL).tolist()
               for _ in range(n)]
    flush = rng.integers(1, 127, size=SHARED + TAIL).tolist()
    return prompts, flush


def run_engine(model, prompts, flush=None, **kw):
    engine = ServingEngine(model, num_slots=4, page_size=PAGE,
                           max_model_len=SHARED + TAIL + MAX_NEW, **kw)
    with engine:
        if flush is not None:
            # one at a time with a disjoint cache-flusher in the middle:
            # pages sit idle between requests, so the undersized pool
            # must evict the shared prefix into the spill tier — and the
            # second half of the prompts resurrects it from host DRAM
            outs = [engine.submit(p, max_new_tokens=MAX_NEW).result(
                timeout=600) for p in prompts[:4]]
            engine.submit(flush, max_new_tokens=MAX_NEW).result(timeout=600)
            outs += [engine.submit(p, max_new_tokens=MAX_NEW).result(
                timeout=600) for p in prompts[4:]]
        else:
            handles = [engine.submit(p, max_new_tokens=MAX_NEW)
                       for p in prompts]
            outs = [h.result(timeout=600) for h in handles]
        stats = engine.stats()
        # read the ledger while the engine (device pools + host spill
        # tier registrations) is still alive
        stats["memory_owners"] = memory.ledger().owner_rows(
            replica=engine.replica)
    return outs, stats


def show_prefix(tag, stats):
    pc = stats.get("prefix_cache") or {}
    print(f"  {tag:<12} hits {pc.get('hits', 0):>3}  "
          f"misses {pc.get('misses', 0):>3}  "
          f"evictions {pc.get('evictions', 0):>3}  "
          f"saved_tokens {pc.get('saved_tokens', 0):>4}")
    return pc


def main():
    model = build_model()
    prompts, flush = build_prompts()
    print(f"8 prompts: {SHARED}-token shared prefix "
          f"({SHARED // PAGE} pages) + {TAIL}-token unique tail\n")

    legacy, legacy_stats = run_engine(model, prompts, prefix_sharing=True)
    fams_legacy = {r["program"] for r in obs_perf.table().snapshot()}
    radix, radix_stats = run_engine(model, prompts, prefix_cache="radix")
    fams_radix = {r["program"] for r in obs_perf.table().snapshot()} \
        - fams_legacy
    # undersized pool: 8 pages hold exactly one in-flight request
    # (4 prompt pages + 2 generation pages) plus the idle shared pages
    # only until pressure evicts them into the spill tier
    spill, spill_stats = run_engine(model, prompts, flush=flush,
                                    prefix_cache="radix", kv_spill=True,
                                    num_pages=8)

    print("-- greedy byte-identity across arms --")
    same_radix = all(a == b for a, b in zip(legacy, radix))
    same_spill = all(a == b for a, b in zip(legacy, spill))
    print(f"  radix       == legacy: {same_radix}")
    print(f"  radix+spill == legacy: {same_spill}")
    if not (same_radix and same_spill):
        raise SystemExit("FAIL: prefix reuse changed generated tokens")

    print("\n-- prefix-cache accounting --")
    show_prefix("legacy", legacy_stats)
    pc_radix = show_prefix("radix", radix_stats)
    pc_spill = show_prefix("radix+spill", spill_stats)

    sp = (pc_spill.get("spill") or {})
    print("\n-- spill tier (radix+spill arm) --")
    print(f"  spills {sp.get('spills', 0)}  "
          f"resurrections {sp.get('resurrections', 0)}  "
          f"drops {sp.get('drops', 0)}  "
          f"resident entries {sp.get('entries', 0)}  "
          f"host bytes {sp.get('bytes', 0):,}")

    print("\n-- memory ledger (radix+spill arm) --")
    for row in spill_stats["memory_owners"]:
        print(f"  {row['owner']:<22} {row['bytes']:>12,} B  "
              f"device={row['device']}")

    # both arms HIT the same shared pages, but only radix turns the hits
    # into skipped compute: legacy returns cached_pages=0 (memory-only
    # sharing — prefill recomputes from token 0), radix prefill families
    # carry @cached<p> and dispatch only the un-cached tail
    print("\n-- prefill program families --")
    print(f"  legacy: {sorted(f for f in fams_legacy if 'prefill' in f)}")
    print(f"  radix:  {sorted(f for f in fams_radix if 'prefill' in f)}")
    saved = pc_radix.get("saved_tokens", 0)
    total = sum(len(p) for p in prompts)
    print(f"\nradix arm skipped prefill compute for {saved} of {total} "
          f"prompt tokens ({saved / total:.0%}) — same tokens out, "
          f"smaller TTFT.")


if __name__ == "__main__":
    main()
