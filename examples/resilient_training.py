"""Fault-tolerant training walkthrough (paddle_tpu.resilience).

Runs on the CPU backend: a deterministic train loop with a real eager
collective checkpoints asynchronously (atomic commit + checksum manifest),
then a seeded fault plan injects a transient collective failure mid-run
AND corrupts the newest on-disk checkpoint.  The RecoverySupervisor
classifies the failure as transient, backs off with jitter, detects the
corruption via the manifest, falls back to the previous valid step, and
the run still finishes every step — surviving both failures it was dealt.

    JAX_PLATFORMS=cpu python examples/resilient_training.py
"""

import os
import tempfile

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.observability import faults
from paddle_tpu.resilience import (
    AsyncCheckpointManager, CollectiveTimeoutError, RecoverySupervisor,
    RetryPolicy, corrupt_checkpoint,
)

TOTAL_STEPS = 8
FAIL_AT = 4      # the collective of step 4 dies (after steps 0..3 trained)

ckpt_dir = tempfile.mkdtemp(prefix="paddle_resilient_")
print(f"checkpoints -> {ckpt_dir}")
mgr = AsyncCheckpointManager(ckpt_dir, max_to_keep=4)

rs = np.random.RandomState(7)
x = paddle.to_tensor(rs.randn(32, 16).astype("float32"))
y = paddle.to_tensor(rs.randint(0, 4, (32,)).astype("int64"))
lossf = nn.CrossEntropyLoss()


def train_fn(start, state):
    """Resumable loop: restore, then train steps [start, TOTAL_STEPS)."""
    paddle.seed(0)
    m = nn.Sequential(nn.Linear(16, 32), nn.Tanh(), nn.Linear(32, 4))
    o = opt.Momentum(learning_rate=0.05, momentum=0.9,
                     parameters=m.parameters())
    if state is not None:
        m.set_state_dict(state["model"])
        o.set_state_dict(state["opt"])
        print(f"  resumed from checkpoint: step {start}")
    for step in range(start, TOTAL_STEPS):
        # a REAL eager collective (8-device CPU mesh) — the injected
        # failure below fires inside this dispatch path
        dist.all_reduce(paddle.to_tensor(np.ones((8, 4), "float32")))
        loss = lossf(m(x), y)
        loss.backward()
        o.step()
        o.clear_grad()
        print(f"  step {step}: loss {float(loss):.4f}")
        # async: snapshot to host now, write + atomic commit in background
        mgr.save(step + 1, {"model": m.state_dict(), "opt": o.state_dict()})
    mgr.wait_until_finished()
    return "trained"


def sabotage():
    """The chaos: damage the newest committed checkpoint, then fail the
    collective the way a dying neighbor rank would."""
    mgr.wait_until_finished()
    victim = corrupt_checkpoint(mgr)
    print(f"  !! corrupted newest checkpoint: {victim}")
    raise CollectiveTimeoutError("injected: all_reduce timed out "
                                 "(simulated preempted neighbor)")


plan = faults.FaultPlan(seed=5).add(
    "collective_hang", fn=sabotage, at_trips={FAIL_AT + 1})

supervisor = RecoverySupervisor(
    mgr,
    policy=RetryPolicy(base_delay=0.05, max_delay=1.0, jitter=0.5, seed=0),
    max_transient_restarts=3)

with plan:   # scoped: whatever happens, the faults disarm on exit
    result = supervisor.run(train_fn)

print(f"result: {result}")
print(f"transient restarts: {supervisor.restarts['transient']}")
print(f"valid checkpoints on disk: {mgr.valid_steps()}")
quarantined = [n for n in os.listdir(ckpt_dir) if ".corrupt-" in n]
print(f"quarantined corrupt checkpoints: {quarantined}")
assert supervisor.restarts["transient"] == 1 and TOTAL_STEPS in mgr.valid_steps()
mgr.close()
print("survived an injected collective failure + checkpoint corruption.")
