"""Tensor-parallel GPT serving: one ServingEngine sharded over a device
mesh (README "Tensor-parallel serving").

Demonstrates ``ServingEngine(mesh=...)``:

- the paged KV pools split on the KV-head dimension and the decoder
  weights split Megatron-style (qkv/ffn1 column-parallel, out_proj/ffn2
  row-parallel) across a ``model`` mesh axis — one SPMD program per
  (phase, bucket) family, scheduling stays host-side and replicated;
- greedy output byte-identical to the unsharded engine (the sharding is
  a placement annotation, not a different computation);
- per-shard capacity accounting: ``bytes_per_page`` halves at mp=2, so
  the same per-chip HBM budget admits twice the resident sequences;
- a dp x mp topology: ``ReplicaPool(devices="auto", mp=2)`` carves the
  device list into mp-sized submeshes behind the prefix-affinity router.

Run (CPU works — two host devices are forced below; on a real TPU slice
drop the XLA_FLAGS line and pass ``mesh=jax.devices()``):

    JAX_PLATFORMS=cpu python examples/serve_gpt_mp.py
"""

import os

os.environ.setdefault(
    "XLA_FLAGS", "--xla_force_host_platform_device_count=2")

import jax                      # noqa: E402  (after XLA_FLAGS)
import numpy as np              # noqa: E402

import paddle_tpu as paddle     # noqa: E402
from paddle_tpu.serving import ServingEngine  # noqa: E402
from paddle_tpu.serving.cluster import ReplicaPool  # noqa: E402
from paddle_tpu.text.models import GPTForCausalLM  # noqa: E402


def main():
    paddle.seed(0)
    model = GPTForCausalLM(vocab_size=1024, hidden_size=128,
                           num_hidden_layers=4, num_attention_heads=4,
                           max_position_embeddings=256).eval()
    rs = np.random.RandomState(0)
    prompts = [rs.randint(1, 1024, (n,)).tolist()
               for n in (12, 24, 40, 64)]
    print(f"devices: {jax.devices()}")

    # --- unsharded reference --------------------------------------------
    with ServingEngine(model, num_slots=4, page_size=16,
                       max_model_len=256) as eng:
        ref = [eng.generate(p, max_new_tokens=24, timeout=600)
               for p in prompts]
        bpp1 = eng.stats()["bytes_per_page"]

    # --- the same engine, sharded over the mesh -------------------------
    with ServingEngine(model, num_slots=4, page_size=16, max_model_len=256,
                       mesh=jax.devices()) as eng:
        hs = [eng.submit(p, max_new_tokens=24) for p in prompts]
        out = [h.result(timeout=600) for h in hs]
        st = eng.stats()
        print(f"mp={st['mp']}: greedy "
              f"{'byte-identical' if out == ref else 'MISMATCH'} "
              f"to the unsharded engine")
        print(f"per-shard bytes/page {st['bytes_per_page']} "
              f"(unsharded {bpp1}) -> same per-chip HBM budget holds "
              f"{bpp1 // st['bytes_per_page']}x the pages")
        bm = eng.block_manager
        budget = 64 * bpp1
        print(f"resident sequences at a {budget // 1024} KiB budget: "
              f"{bm.max_resident_sequences(256, budget_bytes=budget)} "
              f"(shards={bm.shards})")
        print(f"decode traces: {eng.step_traces} "
              f"(one SPMD program for the whole mixed batch)")

    # --- dp x mp: carve the same two devices into two mp=1 replicas, or
    # scale up: with 4+ devices ReplicaPool(devices='auto', mp=2) builds
    # len(devices)/2 sharded replicas behind the router
    with ReplicaPool(model, devices="auto", mp=len(jax.devices()),
                     num_slots=4, page_size=16, max_model_len=256,
                     replica_prefix="mp") as pool:
        got = pool.engines[0].generate(prompts[0], max_new_tokens=24,
                                       timeout=600)
        print(f"pool of {len(pool)} mp={pool.engines[0].stats()['mp']} "
              f"replica(s): "
              f"{'byte-identical' if got == ref[0] else 'MISMATCH'}")


if __name__ == "__main__":
    main()
