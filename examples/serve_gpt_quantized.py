"""Quantized serving: int8 paged KV-cache pages + int8 weights
(README "Quantized serving").

A small GPT is overfit on a cyclic token stream (wide greedy logit gaps,
so rounding error is visible as token flips if the quantization were
sloppy), then the same requests run through the engine three ways:

- reference: ``ServingEngine(model, ...)`` — full-precision pools;
- int8 KV:   ``ServingEngine(model, ..., kv_dtype="int8")`` — int8 page
  pools with parallel per-(page slot, head) scale pools; quant is fused
  into every pool write, dequant into the paged-attention kernels, so no
  full-precision cache copy ever exists in HBM;
- int8 KV + int8 weights: ``weight_dtype="int8"`` additionally converts
  the decoder Linears to Int8Linear in place (int8 x int8 -> int32 MXU
  dots).  The reference arm runs FIRST because the conversion is
  in-place.

Printed at the end: top-1 agreement of each quantized arm against the
reference stream, bytes per KV token / resident-slot occupancy at a fixed
page-pool HBM budget, and the calibration harness's per-layer error
report.

Run (CPU works; a TPU runs the dequant-fused Pallas kernels):

    JAX_PLATFORMS=cpu python examples/serve_gpt_quantized.py
"""

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.optimizer as opt
from paddle_tpu.observability import memory
from paddle_tpu.serving import ServingEngine
from paddle_tpu.serving.quant import calibrate, top1_agreement

from paddle_tpu.text.models import GPTForCausalLM

PAGE = 16
S0, MAX_NEW = 32, 64


def build_model(period=8, train_steps=150):
    """Overfit a small GPT on phase-shifted cycles (heads=2 keeps
    head_dim=64 — the production-shaped ratio where int8 pools fit ~1.9x
    the bf16 slots per HBM byte)."""
    paddle.seed(0)
    m = GPTForCausalLM(vocab_size=128, hidden_size=128, num_hidden_layers=4,
                       num_attention_heads=2, max_position_embeddings=256)
    cyc = (np.arange(256 + 64) % period + 1).astype("int64")
    o = opt.AdamW(learning_rate=3e-3, parameters=m.parameters())
    step = paddle.jit.TrainStep(m, o, loss_fn=None)
    ids = paddle.to_tensor(np.stack([cyc[i:i + 64] for i in range(8)]))
    for _ in range(train_steps):
        step({"input_ids": ids, "labels": ids})
    # drop the training-only device state (AdamW moments, the TrainStep's
    # donated buffers) before serving: the memory ledger reconciles
    # against jax.live_arrays(), and optimizer state would sit there as
    # untracked bytes the serving process never actually needs
    del o, step, ids
    import gc

    gc.collect()
    return m.eval(), cyc, period


def run_engine(model, prompts, **kw):
    engine = ServingEngine(model, num_slots=4, page_size=PAGE,
                           max_model_len=S0 + MAX_NEW, **kw)
    with engine:
        engine.generate(prompts[0], max_new_tokens=4, timeout=600)  # compile
        handles = [engine.submit(p, max_new_tokens=MAX_NEW) for p in prompts]
        outs = [h.result(timeout=600) for h in handles]
        stats = engine.stats()
        # reconcile the device-memory ledger against jax.live_arrays()
        # while the engine (and hence its pool registrations) is alive
        stats["memory_report"] = memory.ledger().report()
    return outs, stats


def main():
    print("overfitting the demo model ...")
    model, cyc, period = build_model()
    prompts = [cyc[i % period:i % period + S0] for i in range(8)]

    print("\n-- calibration harness (no conversion yet) --")
    rep = calibrate(model, prompts[:4], max_new_tokens=16, page_size=PAGE)
    print(f"top-1 agreement on the calibration batch: "
          f"{rep['top1_agreement']:.4f}")
    print(f"per-layer KV round-trip error:   "
          f"{[round(e, 4) for e in rep['per_layer_kv_error']]}")
    worst_w = max(rep["per_layer_weight_error"].items(), key=lambda kv: kv[1])
    print(f"worst weight round-trip error:   {worst_w[0]} = {worst_w[1]:.4f}")

    # reference FIRST: weight conversion below is in-place
    ref, ref_stats = run_engine(model, prompts)
    int8_kv, kv_stats = run_engine(model, prompts, kv_dtype="int8")
    int8_full, full_stats = run_engine(model, prompts, kv_dtype="int8",
                                       weight_dtype="int8")

    print("\n-- accuracy --")
    print(f"int8 KV pools      vs reference: top-1 agreement "
          f"{top1_agreement(ref, int8_kv):.4f}")
    print(f"int8 KV + weights  vs reference: top-1 agreement "
          f"{top1_agreement(ref, int8_full):.4f}")

    print("\n-- occupancy (one fixed page-pool HBM budget) --")
    bpt_ref = ref_stats["kv_bytes_per_token"]
    bpt_q = kv_stats["kv_bytes_per_token"]
    print(f"KV bytes/token: reference {bpt_ref:.0f} "
          f"({ref_stats['pool_dtype']}), int8 {bpt_q:.0f} "
          f"(payload + scale pools) -> {bpt_ref / bpt_q:.2f}x more "
          f"resident tokens per HBM byte")
    tokens = S0 + MAX_NEW
    budget = ref_stats["num_pages"] * ref_stats["bytes_per_page"]
    slots_ref = (budget // ref_stats["bytes_per_page"]) \
        // -(-tokens // PAGE)
    slots_q = (budget // kv_stats["bytes_per_page"]) \
        // -(-tokens // PAGE)
    print(f"resident {tokens}-token slots at that budget: "
          f"{slots_ref} -> {slots_q} ({slots_q / slots_ref:.2f}x)")

    print("\n-- memory ledger (int8 KV + int8 weights arm) --")
    mrep = full_stats["memory_report"]
    for row in mrep["owners"]:
        print(f"  {row['owner']:<22} {row['bytes']:>12,} B  "
              f"replica={row['replica']} device={row['device']}")
    frac = mrep["untracked_frac"]
    print(f"  tracked {mrep['tracked_bytes']:,} B of "
          f"{mrep['live_bytes']:,} B live -> "
          f"untracked_frac {frac:.4f} "
          f"({'OK' if frac <= 0.05 else 'FAIL'}: ledger accounts "
          f"{(1 - frac) * 100:.1f}% of live device bytes)")

    print("\nfirst request, last 12 tokens of each arm:")
    print("  reference:", ref[0][-12:])
    print("  int8 kv:  ", int8_kv[0][-12:])
    print("  int8 all: ", int8_full[0][-12:])


if __name__ == "__main__":
    main()
