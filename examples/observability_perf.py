"""Performance attribution + SLO walkthrough (paddle_tpu.observability).

Runs on the CPU backend: serves a small mixed workload through the
continuous-batching engine under an SLO policy, trains a few fused steps,
then prints the per-program roofline attribution report (which compiled
program spent the device time, and whether it is HBM- or compute-bound
against the configured ceilings), the SLO attainment/goodput summary, and
the live /statusz program table.

    JAX_PLATFORMS=cpu python examples/observability_perf.py
"""

import json
import os
import urllib.request

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
# roofline ceilings: on a real chip these come from the datasheet tables
# (or the bench roofline section's measured numbers); the CPU test mesh
# has neither, so configure the BENCH_r04-measured v5e-through-tunnel
# values explicitly
os.environ.setdefault("PADDLE_PEAK_FLOPS", "126.8e12")
os.environ.setdefault("PADDLE_HBM_GBS", "456")

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.optimizer as opt
from paddle_tpu.observability import perf
from paddle_tpu.serving import ServingEngine, SLOPolicy
from paddle_tpu.text.models.gpt import GPTForCausalLM

# ------------------------------------------------------- serve under SLO
paddle.seed(0)
model = GPTForCausalLM(vocab_size=128, hidden_size=64, num_hidden_layers=2,
                       num_attention_heads=4,
                       max_position_embeddings=128).eval()

policy = SLOPolicy(ttft_s=30.0, itl_s=5.0, e2e_s=120.0, objective=0.95)
engine = ServingEngine(model, num_slots=2, page_size=16, max_model_len=96,
                       slo=policy, telemetry_port=0)
rs = np.random.RandomState(0)
with engine:
    handles = [
        engine.submit(rs.randint(1, 120, (8,)), max_new_tokens=12),
        engine.submit(rs.randint(1, 120, (8,)), max_new_tokens=8,
                      temperature=0.8),
        engine.submit(rs.randint(1, 120, (24,)), max_new_tokens=10),
    ]
    for h in handles:
        h.result(timeout=600)

    print("SLO summary (per replica):")
    print(json.dumps(engine.slo_accountant.summary(), indent=2))

    from paddle_tpu.observability import telemetry

    url = telemetry.get_server().url
    statusz = json.load(urllib.request.urlopen(f"{url}/statusz", timeout=10))
    table = statusz["perf_programs"]
    print(f"\n/statusz perf_programs (ridge "
          f"{table['ridge_flop_per_byte']:.0f} FLOP/byte):")
    for row in table["programs"]:
        print(f"  {row['program']:<16} calls={row['calls']:<5} "
              f"dev_s={row['device_seconds']:.4f} regime={row['regime']}")

# ------------------------------------------------ a few fused train steps
m = GPTForCausalLM(vocab_size=128, hidden_size=64, num_hidden_layers=2,
                   num_attention_heads=4, max_position_embeddings=128)
o = opt.AdamW(learning_rate=1e-3, parameters=m.parameters())
step = paddle.jit.TrainStep(m, o, loss_fn=None)
ids = paddle.to_tensor(rs.randint(1, 120, (4, 32)).astype("int64"))
for _ in range(4):
    step({"input_ids": ids, "labels": ids})

# ------------------------------------------------- the attribution report
# resolve=True runs the pending XLA cost_analysis thunks (a re-lower +
# compile per program family — exactly what a telemetry scrape is NOT
# allowed to do; set PADDLE_PERF_COST=1 to let /statusz scrapes kick the
# resolution on a background thread instead)
print("\n" + perf.report(top=3, resolve=True))
