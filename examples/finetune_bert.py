"""Baseline config #2: BERT/ERNIE sequence-classification fine-tune through
the compiled path (the reference drives this via @to_static; here the fused
TrainStep compiles forward+backward+AdamW into one program).

    python examples/finetune_bert.py [--model ernie|bert] [--epochs 3]
"""

import argparse

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.metric import Accuracy
from paddle_tpu.text import (BertTokenizer, BertForSequenceClassification,
                             ErnieForSequenceClassification)

TOY_SST = [
    ("a triumph of wit and craft", 1),
    ("gorgeous, moving, expertly acted", 1),
    ("one of the year's best films", 1),
    ("sharp writing and a brilliant cast", 1),
    ("dull, lifeless, and painfully long", 0),
    ("a waste of everyone's talent and time", 0),
    ("the plot collapses into nonsense", 0),
    ("clumsy pacing and wooden dialogue", 0),
] * 8


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="ernie", choices=["ernie", "bert"])
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--max-len", type=int, default=32)
    ap.add_argument("--lr", type=float, default=5e-4)
    args = ap.parse_args()

    texts = [t for t, _ in TOY_SST]
    labels = np.array([l for _, l in TOY_SST], dtype="int64")
    tok = BertTokenizer.from_corpus(texts, min_freq=1)
    vocab = ((tok.vocab_size + 7) // 8) * 8
    ids = np.array([tok(t, max_length=args.max_len)["input_ids"] for t in texts],
                   dtype="int64")

    paddle.seed(0)
    cls = ErnieForSequenceClassification if args.model == "ernie" else \
        BertForSequenceClassification
    net = cls(num_classes=2, vocab_size=vocab, hidden_size=128,
              num_hidden_layers=4, num_attention_heads=4,
              intermediate_size=256, max_position_embeddings=args.max_len,
              hidden_dropout_prob=0.1, attention_probs_dropout_prob=0.1)
    model = paddle.Model(net)
    model.prepare(opt.AdamW(learning_rate=args.lr,
                            parameters=net.parameters()),
                  nn.CrossEntropyLoss(), Accuracy())

    from paddle_tpu.io import TensorDataset

    data = TensorDataset([ids, labels])
    model.fit(data, epochs=args.epochs, batch_size=args.batch_size, verbose=1)
    print("final:", model.evaluate(data, batch_size=args.batch_size, verbose=0))


if __name__ == "__main__":
    main()
