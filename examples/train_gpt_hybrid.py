"""Baseline config #5: GPT decoder LM under Fleet hybrid parallelism
(dp x pp x mp over the device mesh; run on CPU with a virtual mesh via
XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu).

    python examples/train_gpt_hybrid.py --dp 2 --pp 2 --mp 2 [--steps 10]
"""

import argparse
import time

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.optimizer as opt
import paddle_tpu.distributed.fleet as fleet
from paddle_tpu.text.models import GPTForCausalLM, GPTForCausalLMPipe


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dp", type=int, default=2)
    ap.add_argument("--pp", type=int, default=2)
    ap.add_argument("--mp", type=int, default=2)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--vocab", type=int, default=512)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--micro", type=int, default=4)
    ap.add_argument("--steps", type=int, default=10)
    args = ap.parse_args()

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": args.dp, "pp_degree": args.pp,
                               "mp_degree": args.mp,
                               "order": ["dp", "pp", "mp"]}
    fleet.init(is_collective=True, strategy=strategy)
    mesh = fleet.get_hybrid_communicate_group().mesh
    print("mesh:", mesh)

    paddle.seed(0)
    lm = GPTForCausalLM(vocab_size=args.vocab, hidden_size=args.hidden,
                        num_hidden_layers=args.layers,
                        num_attention_heads=args.heads,
                        max_position_embeddings=args.seq)
    if args.pp > 1:
        model = GPTForCausalLMPipe(lm, mesh, n_micro=args.micro,
                                   batch_axis="dp" if args.dp > 1 else None)
    else:
        model = lm
    optim = fleet.distributed_optimizer(
        opt.AdamW(learning_rate=3e-4, parameters=model.parameters()))
    step = paddle.jit.TrainStep(model, optim, loss_fn=None)

    B = args.micro * max(args.dp, 1)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(1, args.vocab, (B, args.seq)).astype("int64"))

    loss = step({"input_ids": ids, "labels": ids})
    float(loss)
    t0 = time.time()
    for i in range(args.steps):
        loss = step({"input_ids": ids, "labels": ids})
        print(f"step {i + 1}: loss {float(loss):.4f}")
    dt = (time.time() - t0) / args.steps
    tokens = B * args.seq
    print(f"{tokens / dt:.0f} tokens/sec ({dt * 1e3:.1f} ms/step) on "
          f"dp{args.dp} x pp{args.pp} x mp{args.mp}")


if __name__ == "__main__":
    main()
