"""Llama fine-tuning: the modern decoder recipe end-to-end — fused train
step with gradient accumulation, fp16/bf16 autocast with the traced
GradScaler, padding-masked batches, EMA evaluation weights, and greedy /
top-p generation at the end.

Synthetic corpus by default (next-token objective over random sequences);
tiny config so it runs anywhere, scale the flags up on real hardware.

    python examples/finetune_llama.py [--steps 30] [--accum 2]
                                      [--hidden 256] [--layers 4]
                                      [--amp-dtype bfloat16|float16]
"""

import argparse
import time

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.incubate.optimizer import ExponentialMovingAverage
from paddle_tpu.text.models import LlamaConfig, LlamaForCausalLM


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--seq-len", type=int, default=128)
    ap.add_argument("--accum", type=int, default=2)
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--kv-heads", type=int, default=4)
    ap.add_argument("--vocab", type=int, default=2048)
    ap.add_argument("--lr", type=float, default=3e-4)
    ap.add_argument("--amp-dtype", default="bfloat16",
                    choices=["bfloat16", "float16"])
    args = ap.parse_args()

    paddle.seed(0)
    cfg = LlamaConfig(
        vocab_size=args.vocab, hidden_size=args.hidden,
        intermediate_size=int(args.hidden * 8 / 3) // 16 * 16,
        num_hidden_layers=args.layers, num_attention_heads=args.heads,
        num_key_value_heads=args.kv_heads,
        max_position_embeddings=4 * args.seq_len, tie_word_embeddings=True)
    model = LlamaForCausalLM(cfg)
    n_params = sum(p._value.size for p in model.parameters())
    print(f"llama: {n_params / 1e6:.1f}M params "
          f"(GQA {args.heads}q/{args.kv_heads}kv, SwiGLU, tied head)")

    sched = opt.lr.CosineAnnealingDecay(learning_rate=args.lr,
                                        T_max=args.steps)
    optimizer = opt.AdamW(learning_rate=sched, parameters=model.parameters(),
                          weight_decay=0.01,
                          grad_clip=opt.ClipGradByGlobalNorm(1.0))
    scaler = (paddle.amp.GradScaler(init_loss_scaling=2.0 ** 15)
              if args.amp_dtype == "float16" else None)
    step = paddle.jit.TrainStep(model, optimizer, amp_level="O2",
                                amp_dtype=args.amp_dtype,
                                accumulate_steps=args.accum, scaler=scaler)
    ema = ExponentialMovingAverage(model, decay=0.99)

    rs = np.random.RandomState(0)
    t0 = time.time()
    for i in range(args.steps):
        ids = paddle.to_tensor(
            rs.randint(1, args.vocab,
                       (args.batch_size, args.seq_len)).astype("int64"))
        loss = step({"input_ids": ids, "labels": ids})
        ema.update()
        sched.step()
        if i % 5 == 0 or i == args.steps - 1:
            extra = (f" scale={step.loss_scale:.0f}" if scaler else "")
            print(f"step {i:4d}  loss {float(loss):.4f}  "
                  f"lr {sched.get_lr():.2e}{extra}")
    dt = time.time() - t0
    tok = args.steps * args.batch_size * args.seq_len / dt
    print(f"{dt:.1f}s total, {tok:,.0f} tokens/s")

    # evaluate with EMA weights, then generate
    with ema.apply():
        model.eval()
        prompt = paddle.to_tensor(
            rs.randint(1, args.vocab, (1, 8)).astype("int64"))
        greedy = model.generate(prompt, max_new_tokens=16, temperature=0.0)
        sampled = model.generate(prompt, max_new_tokens=16, temperature=0.8,
                                 top_p=0.9, seed=1)
    print("greedy :", greedy.numpy()[0, -16:].tolist())
    print("sampled:", sampled.numpy()[0, -16:].tolist())


if __name__ == "__main__":
    main()
